"""The hand-written batch_norm/layer_norm backward (ops/nn_ops.py
_batch_norm_grad/_layer_norm_grad — the HBM byte-reduction for ResNet/LM
training, PERF.md) must match the generic vjp-of-forward gradient it
replaced. The generic path is recovered by monkeypatching the op's
grad_fn away before append_backward runs (backward.py consults it at
build time), so both programs differentiate the identical forward."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core.registry import get_op


def _grads(build, monkeypatch, generic, fetch):
    if generic:
        for op_name in ("batch_norm", "layer_norm"):
            monkeypatch.setattr(get_op(op_name), "grad_fn", None)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        loss, feed = build()
        pt.optimizer.SGDOptimizer(learning_rate=0.0).minimize(
            loss, startup_program=startup)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope)
    names = [n for n in fetch if main.global_block.has_var(n)]
    assert names == fetch
    outs = exe.run(main, feed=feed, fetch_list=names, scope=scope)
    return {n: np.asarray(o) for n, o in zip(names, outs)}


def _bn_net(fmt, is_test=False):
    rng = np.random.RandomState(0)
    shape = [8, 6, 5, 4] if fmt == "NHWC" else [8, 4, 6, 5]
    x = layers.data("x", shape=shape[1:])
    x.stop_gradient = False
    y = layers.batch_norm(x, data_layout=fmt, is_test=is_test,
                          param_attr=pt.ParamAttr(name="bn_s"),
                          bias_attr=pt.ParamAttr(name="bn_b"))
    loss = layers.mean(layers.square(y))
    feed = {"x": rng.randn(*shape).astype("float32")}
    return loss, feed


@pytest.mark.parametrize("fmt", ["NHWC", "NCHW"])
def test_batch_norm_grad_matches_generic_vjp(monkeypatch, fmt):
    fetch = ["x@GRAD", "bn_s@GRAD", "bn_b@GRAD"]
    custom = _grads(lambda: _bn_net(fmt), monkeypatch, False, fetch)
    generic = _grads(lambda: _bn_net(fmt), monkeypatch, True, fetch)
    for n in fetch:
        np.testing.assert_allclose(custom[n], generic[n], rtol=2e-5,
                                   atol=2e-6, err_msg=n)


def test_batch_norm_inference_grad_matches_generic_vjp(monkeypatch):
    fetch = ["x@GRAD", "bn_s@GRAD", "bn_b@GRAD"]
    custom = _grads(lambda: _bn_net("NHWC", is_test=True),
                    monkeypatch, False, fetch)
    generic = _grads(lambda: _bn_net("NHWC", is_test=True),
                     monkeypatch, True, fetch)
    for n in fetch:
        np.testing.assert_allclose(custom[n], generic[n], rtol=2e-5,
                                   atol=2e-6, err_msg=n)


def test_batch_norm_inference_running_stat_grads(monkeypatch):
    """is_test batch_norm genuinely depends on its Mean/Variance INPUTS;
    when those are differentiable the custom grad must reproduce the
    generic vjp's nonzero gradients (code-review finding: the first cut
    silently zero-filled them)."""
    def build():
        rng = np.random.RandomState(3)
        x = layers.data("x", shape=[6, 5, 4])
        x.stop_gradient = False
        y = layers.batch_norm(x, data_layout="NHWC", is_test=True,
                              param_attr=pt.ParamAttr(name="bn2_s"),
                              bias_attr=pt.ParamAttr(name="bn2_b"))
        blk = y.block
        # the layer names its running stats <prefix>.mean/.var; mark
        # them differentiable to exercise the Mean/Variance grad path
        for name, var in blk.vars.items():
            if name.endswith(".mean") or name.endswith(".var"):
                var.stop_gradient = False
        loss = layers.mean(layers.square(y))
        feed = {"x": rng.randn(8, 6, 5, 4).astype("float32")}
        return loss, feed

    # find the stat var names from a probe build
    main = pt.Program()
    with pt.program_guard(main, pt.Program()):
        loss, _ = build()
    stats = sorted(n for n in main.global_block.vars
                   if n.endswith(".mean") or n.endswith(".var"))
    assert len(stats) == 2, stats
    fetch = ["x@GRAD"] + [s + "@GRAD" for s in stats]
    custom = _grads(build, monkeypatch, False, fetch)
    generic = _grads(build, monkeypatch, True, fetch)
    for n in fetch:
        assert np.abs(custom[n]).max() > 0, n
        np.testing.assert_allclose(custom[n], generic[n], rtol=2e-5,
                                   atol=2e-6, err_msg=n)


def test_batch_norm_training_stat_update_grads(monkeypatch):
    """The running-stat UPDATE (mean_out/var_out = momentum*old +
    (1-momentum)*batch_stat) is differentiable w.r.t. x and the old
    stats; a loss touching the updated stats must get the same gradients
    from the custom backward as from the generic vjp (code-review
    finding: the first cut raised NotImplementedError here)."""
    def build():
        rng = np.random.RandomState(6)
        x = layers.data("x", shape=[6, 5, 4])
        x.stop_gradient = False
        y = layers.batch_norm(x, data_layout="NHWC",
                              param_attr=pt.ParamAttr(name="bn3_s"),
                              bias_attr=pt.ParamAttr(name="bn3_b"))
        blk = y.block
        stat_vars = [v for n, v in blk.vars.items()
                     if n.endswith(".mean") or n.endswith(".var")]
        assert len(stat_vars) == 2
        reg = None
        for v in stat_vars:
            v.stop_gradient = False
            term = layers.mean(layers.square(v))
            reg = term if reg is None else \
                layers.elementwise_add(reg, term)
        loss = layers.elementwise_add(layers.mean(layers.square(y)), reg)
        feed = {"x": rng.randn(8, 6, 5, 4).astype("float32")}
        return loss, feed

    fetch = ["x@GRAD", "bn3_s@GRAD", "bn3_b@GRAD"]
    custom = _grads(build, monkeypatch, False, fetch)
    generic = _grads(build, monkeypatch, True, fetch)
    for n in fetch:
        assert np.abs(custom[n]).max() > 0, n
        np.testing.assert_allclose(custom[n], generic[n], rtol=2e-5,
                                   atol=2e-6, err_msg=n)


def test_batch_norm_saved_stat_grads(monkeypatch):
    """SavedMean/SavedVariance (batch mean / batch inverse std) are plain
    functions of X; a loss touching them must match the generic vjp."""
    def build():
        rng = np.random.RandomState(12)
        x = layers.data("x", shape=[6, 5, 4])
        x.stop_gradient = False
        y = layers.batch_norm(x, data_layout="NHWC",
                              param_attr=pt.ParamAttr(name="bn4_s"),
                              bias_attr=pt.ParamAttr(name="bn4_b"))
        blk = y.block
        bn_op = [op for op in blk.ops if op.type == "batch_norm"][-1]
        loss = layers.mean(layers.square(y))
        for slot in ("SavedMean", "SavedVariance"):
            sv = blk.vars[bn_op.outputs[slot][0]]
            sv.stop_gradient = False
            loss = layers.elementwise_add(
                loss, layers.mean(layers.square(sv)))
        feed = {"x": rng.randn(8, 6, 5, 4).astype("float32")}
        return loss, feed

    fetch = ["x@GRAD", "bn4_s@GRAD", "bn4_b@GRAD"]
    custom = _grads(build, monkeypatch, False, fetch)
    generic = _grads(build, monkeypatch, True, fetch)
    for n in fetch:
        assert np.abs(custom[n]).max() > 0, n
        np.testing.assert_allclose(custom[n], generic[n], rtol=2e-5,
                                   atol=2e-6, err_msg=n)


def test_batch_norm_stays_recompute_segment_eligible(monkeypatch):
    """grad_fn_is_optimization must keep BN/LN foldable into recompute
    segments: a conv+BN+relu span under recompute_guard still collapses
    to ONE seg_fwd (no shattering at the norm op), and its grads match
    the unguarded build."""
    from paddle_tpu.core.program import recompute_guard

    def build(recompute):
        rng = np.random.RandomState(5)
        x = layers.data("x", shape=[8, 8, 3])
        x.stop_gradient = False
        import contextlib
        ctx = recompute_guard() if recompute else contextlib.nullcontext()
        with ctx:
            h = layers.conv2d(x, num_filters=4, filter_size=3, padding=1,
                              data_format="NHWC",
                              param_attr=pt.ParamAttr(name="cw"),
                              bias_attr=False)
            h = layers.batch_norm(h, data_layout="NHWC", act="relu",
                                  param_attr=pt.ParamAttr(name="bs"),
                                  bias_attr=pt.ParamAttr(name="bb"))
            h2 = layers.layer_norm(
                layers.reshape(h, shape=[-1, 8 * 8 * 4]),
                begin_norm_axis=1,
                param_attr=pt.ParamAttr(name="ls"),
                bias_attr=pt.ParamAttr(name="lb"))
        loss = layers.mean(layers.square(h2))
        feed = {"x": rng.rand(4, 8, 8, 3).astype("float32")}
        return loss, feed

    fetch = ["x@GRAD", "cw@GRAD", "bs@GRAD", "ls@GRAD"]
    plain = _grads(lambda: build(False), monkeypatch, False, fetch)

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        loss, feed = build(True)
        pt.optimizer.SGDOptimizer(learning_rate=0.0).minimize(
            loss, startup_program=startup)
    seg_ops = [op.type for op in main.global_block.ops
               if op.type in ("seg_fwd", "grad_seg")]
    assert seg_ops.count("seg_fwd") == 1, seg_ops
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope)
    outs = exe.run(main, feed=feed, fetch_list=fetch, scope=scope)
    for n, o in zip(fetch, outs):
        np.testing.assert_allclose(np.asarray(o), plain[n], rtol=2e-5,
                                   atol=2e-6, err_msg=n)


def test_per_layer_transformer_remat_matches_plain():
    """transformer_lm(remat=True) on the per-layer path: each block
    collapses into one recompute segment and the training trajectory
    matches the unrematerialized build."""
    from paddle_tpu import models

    def build(remat):
        rng = np.random.RandomState(15)
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            ids = layers.data("ids", shape=[12], dtype="int64")
            tgt = layers.data("tgt", shape=[12], dtype="int64")
            logits = models.transformer_lm(
                ids, vocab_size=48, d_model=16, n_layers=2, num_heads=2,
                max_len=12, remat=remat)
            loss = layers.mean(layers.softmax_with_cross_entropy(
                layers.reshape(logits, shape=[-1, 48]),
                layers.reshape(tgt, shape=[-1, 1])))
            pt.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(
                loss, startup_program=startup)
        feed = {"ids": rng.randint(0, 48, (3, 12)).astype("int64"),
                "tgt": rng.randint(0, 48, (3, 12)).astype("int64")}
        scope = pt.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        ls = [float(np.asarray(exe.run(main, feed=feed,
                                       fetch_list=[loss],
                                       scope=scope)[0]))
              for _ in range(8)]
        segs = sum(1 for op in main.global_block.ops
                   if op.type == "seg_fwd")
        return ls, segs

    plain, segs0 = build(False)
    remat, segs1 = build(True)
    assert segs0 == 0
    assert segs1 == 2, segs1  # one segment per block
    np.testing.assert_allclose(remat, plain, rtol=2e-5, atol=2e-6)


def test_per_layer_remat_tags_explicit_program():
    """remat=True must tag the EXPLICIT main_program, not the ambient
    default (code-review finding: the guard landed on
    default_main_program and remat silently no-opped)."""
    from paddle_tpu import models

    main, startup = pt.Program(), pt.Program()
    ids = layers.data("ids", shape=[8], dtype="int64",
                      main_program=main)
    logits = models.transformer_lm(ids, vocab_size=16, d_model=8,
                                   n_layers=2, num_heads=1, max_len=8,
                                   remat=True, main_program=main,
                                   startup_program=startup)
    loss = layers.mean(logits, main_program=main,
                       startup_program=startup)
    pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(
        loss, startup_program=startup)
    segs = sum(1 for op in main.global_block.ops if op.type == "seg_fwd")
    assert segs == 2, segs


def _ln_net(begin):
    rng = np.random.RandomState(1)
    shape = [4, 7, 6]
    x = layers.data("x", shape=shape[1:])
    x.stop_gradient = False
    y = layers.layer_norm(x, begin_norm_axis=begin,
                          param_attr=pt.ParamAttr(name="ln_s"),
                          bias_attr=pt.ParamAttr(name="ln_b"))
    loss = layers.mean(layers.square(y))
    feed = {"x": rng.randn(*shape).astype("float32")}
    return loss, feed


def _rms_net(begin, shift):
    rng = np.random.RandomState(2)
    shape = [4, 7, 6]
    x = layers.data("x", shape=shape[1:])
    x.stop_gradient = False
    y = layers.rms_norm(x, begin_norm_axis=begin, shift=shift,
                        param_attr=pt.ParamAttr(name="rm_s"),
                        bias_attr=pt.ParamAttr(name="rm_b"))
    loss = layers.mean(layers.square(y))
    feed = {"x": rng.randn(*shape).astype("float32")}
    return loss, feed


@pytest.mark.parametrize("begin,shift", [(1, False), (2, True)])
def test_rms_norm_grad_matches_generic_vjp(monkeypatch, begin, shift):
    fetch = ["x@GRAD", "rm_s@GRAD"] + (["rm_b@GRAD"] if shift else [])
    def gen(generic):
        if generic:
            monkeypatch.setattr(get_op("rms_norm"), "grad_fn", None)
        return _grads(lambda: _rms_net(begin, shift), monkeypatch, False,
                      fetch)
    custom = gen(False)
    generic = gen(True)
    for n in fetch:
        np.testing.assert_allclose(custom[n], generic[n], rtol=2e-5,
                                   atol=2e-6, err_msg=n)


def test_rms_norm_forward_numpy_reference():
    rng = np.random.RandomState(4)
    xv = rng.randn(3, 5).astype("float32")
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[5])
        y = layers.rms_norm(x, begin_norm_axis=1,
                            param_attr=pt.ParamAttr(name="rms_ref_s"))
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope)
    (out,) = exe.run(main, feed={"x": xv}, fetch_list=[y], scope=scope)
    want = xv / np.sqrt((xv ** 2).mean(axis=1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5,
                               atol=1e-6)


def test_transformer_rms_norm_trains():
    rng = np.random.RandomState(9)
    from paddle_tpu import models
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        ids = layers.data("ids", shape=[16], dtype="int64")
        tgt = layers.data("tgt", shape=[16], dtype="int64")
        logits = models.transformer_lm(ids, vocab_size=64, d_model=32,
                                       n_layers=2, num_heads=2, max_len=16,
                                       norm_type="rms_norm")
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.reshape(logits, shape=[-1, 64]),
            layers.reshape(tgt, shape=[-1, 1])))
        pt.optimizer.AdamOptimizer(learning_rate=1e-2).minimize(
            loss, startup_program=startup)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope)
    feed = {"ids": rng.randint(0, 64, (4, 16)).astype("int64"),
            "tgt": rng.randint(0, 64, (4, 16)).astype("int64")}
    losses = []
    for _ in range(25):
        (lv,) = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv)))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    # no LayerNorm shift/mean plane anywhere: the rms blocks create only
    # scale parameters
    ln_ops = [op.type for op in main.global_block.ops
              if op.type == "layer_norm"]
    assert not ln_ops


def test_rms_norm_rejected_on_stacked_path():
    main, startup = pt.Program(), pt.Program()
    from paddle_tpu import models
    with pt.program_guard(main, startup):
        ids = layers.data("ids", shape=[8], dtype="int64")
        with pytest.raises(ValueError, match="layer_norm"):
            models.transformer_lm(ids, vocab_size=32, d_model=16,
                                  n_layers=1, num_heads=1, max_len=8,
                                  norm_type="rms_norm",
                                  pipeline_stack=True)


def _stat_output_net(kind):
    """A net whose loss touches the norm's auxiliary stat OUTPUTS
    (layer_norm Mean/Variance; rms_norm InvRms) — they are plain
    differentiable functions of X and must match the generic vjp."""
    rng = np.random.RandomState(8)
    shape = [4, 6, 5]
    x = layers.data("x", shape=shape[1:])
    x.stop_gradient = False
    helper_prog = x.block.program
    from paddle_tpu.layers.layer_helper import LayerHelper

    helper = LayerHelper(f"{kind}_stat_net", main_program=helper_prog)
    s = helper.create_parameter(pt.ParamAttr(name=f"{kind}_ss"),
                                shape=[5], dtype="float32")
    if kind == "layer_norm":
        outs, _ = helper.append_op(
            "layer_norm", {"X": [x], "Scale": [s]},
            ["Y", "Mean", "Variance"],
            {"epsilon": 1e-5, "begin_norm_axis": 2})
        stats = [outs["Mean"][0], outs["Variance"][0]]
    else:
        outs, _ = helper.append_op(
            "rms_norm", {"X": [x], "Scale": [s]}, ["Y", "InvRms"],
            {"epsilon": 1e-6, "begin_norm_axis": 2})
        stats = [outs["InvRms"][0]]
    loss = layers.mean(layers.square(outs["Y"][0]))
    for st in stats:
        st.stop_gradient = False
        loss = layers.elementwise_add(loss,
                                      layers.mean(layers.square(st)))
    feed = {"x": rng.randn(*shape).astype("float32")}
    return loss, feed


@pytest.mark.parametrize("kind", ["layer_norm", "rms_norm"])
def test_norm_stat_output_grads_match_generic_vjp(monkeypatch, kind):
    fetch = ["x@GRAD", f"{kind}_ss@GRAD"]
    def gen(generic):
        if generic:
            monkeypatch.setattr(get_op(kind), "grad_fn", None)
        return _grads(lambda: _stat_output_net(kind), monkeypatch, False,
                      fetch)
    custom = gen(False)
    generic = gen(True)
    for n in fetch:
        assert np.abs(custom[n]).max() > 0, n
        np.testing.assert_allclose(custom[n], generic[n], rtol=2e-5,
                                   atol=2e-6, err_msg=n)


def test_norm_grads_match_generic_vjp_under_amp(monkeypatch):
    """The custom backward exists FOR the AMP path (bf16 activations, f32
    reduction accumulation): under set_amp(True) both norms must still
    track the generic vjp within bf16 tolerance."""
    def build():
        rng = np.random.RandomState(11)
        x = layers.data("x", shape=[6, 5, 4])
        x.stop_gradient = False
        h = layers.conv2d(x, num_filters=4, filter_size=1,
                          data_format="NHWC",
                          param_attr=pt.ParamAttr(name="amp_cw"),
                          bias_attr=False)
        h = layers.batch_norm(h, data_layout="NHWC", act="relu",
                              param_attr=pt.ParamAttr(name="amp_bs"),
                              bias_attr=pt.ParamAttr(name="amp_bb"))
        h = layers.layer_norm(layers.reshape(h, shape=[-1, 6 * 5 * 4]),
                              begin_norm_axis=1,
                              param_attr=pt.ParamAttr(name="amp_ls"),
                              bias_attr=pt.ParamAttr(name="amp_lb"))
        loss = layers.mean(layers.square(h))
        feed = {"x": rng.rand(8, 6, 5, 4).astype("float32")}
        return loss, feed

    fetch = ["x@GRAD", "amp_cw@GRAD", "amp_bs@GRAD", "amp_bb@GRAD",
             "amp_ls@GRAD", "amp_lb@GRAD"]
    pt.set_amp(True)
    try:
        custom = _grads(build, monkeypatch, False, fetch)
        generic = _grads(build, monkeypatch, True, fetch)
    finally:
        pt.set_amp(False)
    for n in fetch:
        np.testing.assert_allclose(custom[n], generic[n], rtol=2e-2,
                                   atol=2e-3, err_msg=n)


@pytest.mark.parametrize("begin", [1, 2])
def test_layer_norm_grad_matches_generic_vjp(monkeypatch, begin):
    fetch = ["x@GRAD", "ln_s@GRAD", "ln_b@GRAD"]
    custom = _grads(lambda: _ln_net(begin), monkeypatch, False, fetch)
    generic = _grads(lambda: _ln_net(begin), monkeypatch, True, fetch)
    for n in fetch:
        np.testing.assert_allclose(custom[n], generic[n], rtol=2e-5,
                                   atol=2e-6, err_msg=n)

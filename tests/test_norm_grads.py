"""The hand-written batch_norm/layer_norm backward (ops/nn_ops.py
_batch_norm_grad/_layer_norm_grad — the HBM byte-reduction for ResNet/LM
training, PERF.md) must match the generic vjp-of-forward gradient it
replaced. The generic path is recovered by monkeypatching the op's
grad_fn away before append_backward runs (backward.py consults it at
build time), so both programs differentiate the identical forward."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core.registry import get_op


def _grads(build, monkeypatch, generic, fetch):
    if generic:
        for op_name in ("batch_norm", "layer_norm"):
            monkeypatch.setattr(get_op(op_name), "grad_fn", None)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        loss, feed = build()
        pt.optimizer.SGDOptimizer(learning_rate=0.0).minimize(
            loss, startup_program=startup)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope)
    names = [n for n in fetch if main.global_block.has_var(n)]
    assert names == fetch
    outs = exe.run(main, feed=feed, fetch_list=names, scope=scope)
    return {n: np.asarray(o) for n, o in zip(names, outs)}


def _bn_net(fmt, is_test=False):
    rng = np.random.RandomState(0)
    shape = [8, 6, 5, 4] if fmt == "NHWC" else [8, 4, 6, 5]
    x = layers.data("x", shape=shape[1:])
    x.stop_gradient = False
    y = layers.batch_norm(x, data_layout=fmt, is_test=is_test,
                          param_attr=pt.ParamAttr(name="bn_s"),
                          bias_attr=pt.ParamAttr(name="bn_b"))
    loss = layers.mean(layers.square(y))
    feed = {"x": rng.randn(*shape).astype("float32")}
    return loss, feed


@pytest.mark.parametrize("fmt", ["NHWC", "NCHW"])
def test_batch_norm_grad_matches_generic_vjp(monkeypatch, fmt):
    fetch = ["x@GRAD", "bn_s@GRAD", "bn_b@GRAD"]
    custom = _grads(lambda: _bn_net(fmt), monkeypatch, False, fetch)
    generic = _grads(lambda: _bn_net(fmt), monkeypatch, True, fetch)
    for n in fetch:
        np.testing.assert_allclose(custom[n], generic[n], rtol=2e-5,
                                   atol=2e-6, err_msg=n)


def test_batch_norm_inference_grad_matches_generic_vjp(monkeypatch):
    fetch = ["x@GRAD", "bn_s@GRAD", "bn_b@GRAD"]
    custom = _grads(lambda: _bn_net("NHWC", is_test=True),
                    monkeypatch, False, fetch)
    generic = _grads(lambda: _bn_net("NHWC", is_test=True),
                     monkeypatch, True, fetch)
    for n in fetch:
        np.testing.assert_allclose(custom[n], generic[n], rtol=2e-5,
                                   atol=2e-6, err_msg=n)


def test_batch_norm_inference_running_stat_grads(monkeypatch):
    """is_test batch_norm genuinely depends on its Mean/Variance INPUTS;
    when those are differentiable the custom grad must reproduce the
    generic vjp's nonzero gradients (code-review finding: the first cut
    silently zero-filled them)."""
    def build():
        rng = np.random.RandomState(3)
        x = layers.data("x", shape=[6, 5, 4])
        x.stop_gradient = False
        y = layers.batch_norm(x, data_layout="NHWC", is_test=True,
                              param_attr=pt.ParamAttr(name="bn2_s"),
                              bias_attr=pt.ParamAttr(name="bn2_b"))
        blk = y.block
        # the layer names its running stats <prefix>.mean/.var; mark
        # them differentiable to exercise the Mean/Variance grad path
        for name, var in blk.vars.items():
            if name.endswith(".mean") or name.endswith(".var"):
                var.stop_gradient = False
        loss = layers.mean(layers.square(y))
        feed = {"x": rng.randn(8, 6, 5, 4).astype("float32")}
        return loss, feed

    # find the stat var names from a probe build
    main = pt.Program()
    with pt.program_guard(main, pt.Program()):
        loss, _ = build()
    stats = sorted(n for n in main.global_block.vars
                   if n.endswith(".mean") or n.endswith(".var"))
    assert len(stats) == 2, stats
    fetch = ["x@GRAD"] + [s + "@GRAD" for s in stats]
    custom = _grads(build, monkeypatch, False, fetch)
    generic = _grads(build, monkeypatch, True, fetch)
    for n in fetch:
        assert np.abs(custom[n]).max() > 0, n
        np.testing.assert_allclose(custom[n], generic[n], rtol=2e-5,
                                   atol=2e-6, err_msg=n)


def test_batch_norm_stays_recompute_segment_eligible(monkeypatch):
    """grad_fn_is_optimization must keep BN/LN foldable into recompute
    segments: a conv+BN+relu span under recompute_guard still collapses
    to ONE seg_fwd (no shattering at the norm op), and its grads match
    the unguarded build."""
    from paddle_tpu.core.program import recompute_guard

    def build(recompute):
        rng = np.random.RandomState(5)
        x = layers.data("x", shape=[8, 8, 3])
        x.stop_gradient = False
        import contextlib
        ctx = recompute_guard() if recompute else contextlib.nullcontext()
        with ctx:
            h = layers.conv2d(x, num_filters=4, filter_size=3, padding=1,
                              data_format="NHWC",
                              param_attr=pt.ParamAttr(name="cw"),
                              bias_attr=False)
            h = layers.batch_norm(h, data_layout="NHWC", act="relu",
                                  param_attr=pt.ParamAttr(name="bs"),
                                  bias_attr=pt.ParamAttr(name="bb"))
            h2 = layers.layer_norm(
                layers.reshape(h, shape=[-1, 8 * 8 * 4]),
                begin_norm_axis=1,
                param_attr=pt.ParamAttr(name="ls"),
                bias_attr=pt.ParamAttr(name="lb"))
        loss = layers.mean(layers.square(h2))
        feed = {"x": rng.rand(4, 8, 8, 3).astype("float32")}
        return loss, feed

    fetch = ["x@GRAD", "cw@GRAD", "bs@GRAD", "ls@GRAD"]
    plain = _grads(lambda: build(False), monkeypatch, False, fetch)

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        loss, feed = build(True)
        pt.optimizer.SGDOptimizer(learning_rate=0.0).minimize(
            loss, startup_program=startup)
    seg_ops = [op.type for op in main.global_block.ops
               if op.type in ("seg_fwd", "grad_seg")]
    assert seg_ops.count("seg_fwd") == 1, seg_ops
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope)
    outs = exe.run(main, feed=feed, fetch_list=fetch, scope=scope)
    for n, o in zip(fetch, outs):
        np.testing.assert_allclose(np.asarray(o), plain[n], rtol=2e-5,
                                   atol=2e-6, err_msg=n)


def _ln_net(begin):
    rng = np.random.RandomState(1)
    shape = [4, 7, 6]
    x = layers.data("x", shape=shape[1:])
    x.stop_gradient = False
    y = layers.layer_norm(x, begin_norm_axis=begin,
                          param_attr=pt.ParamAttr(name="ln_s"),
                          bias_attr=pt.ParamAttr(name="ln_b"))
    loss = layers.mean(layers.square(y))
    feed = {"x": rng.randn(*shape).astype("float32")}
    return loss, feed


@pytest.mark.parametrize("begin", [1, 2])
def test_layer_norm_grad_matches_generic_vjp(monkeypatch, begin):
    fetch = ["x@GRAD", "ln_s@GRAD", "ln_b@GRAD"]
    custom = _grads(lambda: _ln_net(begin), monkeypatch, False, fetch)
    generic = _grads(lambda: _ln_net(begin), monkeypatch, True, fetch)
    for n in fetch:
        np.testing.assert_allclose(custom[n], generic[n], rtol=2e-5,
                                   atol=2e-6, err_msg=n)

"""Cold-start plane: signature manifests, AOT warmup replay, the
persistent-cache donation guard, /healthz warming, and compile-source
counters (ISSUE 8 — boot-to-first-token without fresh compiles)."""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.core.executor as executor_mod
from paddle_tpu import layers
from paddle_tpu.core import manifest as manifest_mod
from paddle_tpu.core.manifest import ManifestError, SignatureManifest


def _square_program():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        y = layers.mean(layers.fc(x, size=3))
    return main, startup, y


def _train_program():
    """fc + momentum step: donates parameter/accumulator state."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        t = layers.data("t", shape=[1])
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, t)))
        pt.optimizer.MomentumOptimizer(
            learning_rate=0.1, momentum=0.9).minimize(
            loss, startup_program=startup)
    return main, startup, loss


@pytest.fixture
def fresh_cache_wiring(tmp_path):
    """A private --compilation_cache_dir for one test, with the module
    wiring and verdict memo reset on both sides."""
    d = str(tmp_path / "xla_cache")
    pt.set_flags({"compilation_cache_dir": d})
    executor_mod.reset_compilation_cache()
    executor_mod._donation_verdicts.clear()
    yield d
    executor_mod.reset_compilation_cache()
    executor_mod._donation_verdicts.clear()


# ---------------------------------------------------------------------------
# manifest schema + round trip
# ---------------------------------------------------------------------------
class TestManifest:
    def test_record_save_load_roundtrip(self, tmp_path):
        main, startup, y = _square_program()
        scope = pt.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[y], scope=scope)
        exe.run(main, feed={"x": np.ones((4, 4), np.float32)},
                fetch_list=[y], scope=scope)
        assert len(exe.manifest) == 3  # startup + two main signatures
        path = exe.manifest.save(str(tmp_path))
        assert os.path.basename(path) == "warmup_manifest.json"
        loaded = manifest_mod.load(str(tmp_path))
        canon = lambda m: sorted(  # noqa: E731
            json.dumps(s, sort_keys=True) for s in m.signatures())
        assert canon(loaded) == canon(exe.manifest)

    def test_save_merges_existing(self, tmp_path):
        a, b = SignatureManifest(), SignatureManifest()
        a.record("p1", [("x", (2, 4), "float32")], ["y"])
        b.record("p1", [("x", (8, 4), "float32")], ["y"])
        a.save(str(tmp_path))
        b.save(str(tmp_path))  # merge=True folds a's signature back in
        assert len(manifest_mod.load(str(tmp_path))) == 2

    def test_unknown_version_rejected_with_location(self, tmp_path):
        path = tmp_path / "warmup_manifest.json"
        path.write_text(json.dumps({"schema": "paddle_tpu/warmup_manifest",
                                    "version": 99, "signatures": []}))
        with pytest.raises(ManifestError) as ei:
            manifest_mod.load(str(tmp_path))
        msg = str(ei.value)
        assert str(path) in msg and "99" in msg and "version" in msg
        # try_load must stay loud on version problems (only absence is None)
        with pytest.raises(ManifestError):
            manifest_mod.try_load(str(tmp_path))
        assert manifest_mod.try_load(str(tmp_path / "nope")) is None

    def test_malformed_signature_rejected(self, tmp_path):
        path = tmp_path / "warmup_manifest.json"
        path.write_text(json.dumps({
            "schema": "paddle_tpu/warmup_manifest", "version": 1,
            "signatures": [{"program": "p", "feeds": [["x"]],
                            "fetches": ["y"]}]}))
        with pytest.raises(ManifestError, match="signature #0"):
            manifest_mod.load(str(tmp_path))

    def test_replay_compiles_identical_signature_set(self, tmp_path):
        main, startup, y = _square_program()
        scope = pt.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        for n in (2, 4):
            exe.run(main, feed={"x": np.ones((n, 4), np.float32)},
                    fetch_list=[y], scope=scope)
        exe.manifest.save(str(tmp_path))

        exe2 = pt.Executor(pt.CPUPlace())
        scope2 = pt.Scope()
        exe2.run(startup, scope=scope2)
        stats = manifest_mod.replay(
            exe2, [main], scope=scope2,
            manifest=manifest_mod.load(str(tmp_path)))
        # both main signatures compile; the startup digest is skipped
        assert stats["compiled"] == 2 and stats["skipped"] == 1
        misses0 = exe2.cache_stats()["misses"]
        for n in (2, 4):
            exe2.run(main, feed={"x": np.ones((n, 4), np.float32)},
                     fetch_list=[y], scope=scope2)
        assert exe2.cache_stats()["misses"] == misses0, \
            "post-replay traffic must be pure in-process cache hits"

    def test_replay_is_idempotent(self, tmp_path):
        main, startup, y = _square_program()
        scope = pt.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[y], scope=scope)
        exe.manifest.save(str(tmp_path))
        manifest = manifest_mod.load(str(tmp_path))
        again = manifest_mod.replay(exe, [main], scope=scope,
                                    manifest=manifest)
        assert again["compiled"] == 0 and again["already"] == 1

    def test_program_digest_ignores_callsites(self):
        main1, _, _ = _square_program()
        main2, _, _ = _square_program()  # different build line, same shape
        d1 = manifest_mod.program_digest(main1)
        # names embed global uid counters, so only programs built from an
        # identical counter state digest equal — what matters here is that
        # the digest is stable for the SAME program and attr-private data
        # does not perturb it
        assert d1 == manifest_mod.program_digest(main1)
        assert isinstance(manifest_mod.program_digest(main2), str)


# ---------------------------------------------------------------------------
# compile-source counters + spans
# ---------------------------------------------------------------------------
class TestCompileSourceCounters:
    def test_cache_stats_classify_fresh_vs_hit(self):
        main, startup, y = _square_program()
        scope = pt.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        feed = {"x": np.ones((2, 4), np.float32)}
        exe.run(main, feed=feed, fetch_list=[y], scope=scope)
        exe.run(main, feed=feed, fetch_list=[y], scope=scope)
        stats = exe.cache_stats()
        assert stats["fresh_compiles"] == 2  # startup + main
        assert stats["persistent_hits"] == 0
        assert stats["hits"] == 1 and stats["misses"] == 2

    def test_compile_span_carries_source(self):
        from paddle_tpu import trace

        main, startup, y = _square_program()
        scope = pt.Scope()
        exe = pt.Executor(pt.CPUPlace())
        trace.enable(level=1)
        try:
            trace.get_tracer().clear()
            exe.run(startup, scope=scope)
            exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[y], scope=scope)
            compile_spans = [s for s in trace.get_tracer().spans()
                             if s.name == "executor/compile"]
            assert compile_spans
            assert all(s.attrs.get("source") == "fresh"
                       for s in compile_spans)
        finally:
            trace.disable()

    def test_statset_counts_compile_sources(self):
        from paddle_tpu import profiler

        profiler.global_stat.reset()
        main, startup, y = _square_program()
        scope = pt.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[y], scope=scope)
        d = profiler.global_stat.as_dict(
            prefix="executor/compile_cache/fresh_compile")
        assert d and next(iter(d.values()))["calls"] == 2


# ---------------------------------------------------------------------------
# persistent cache: restored-executable donation guard
# ---------------------------------------------------------------------------
class TestRestoredDonationGuard:
    def test_restored_train_step_is_bit_exact(self, fresh_cache_wiring,
                                              tmp_path):
        """THE conftest-documented bug, fixed: a training step whose
        executable is restored from --compilation_cache_dir must produce
        the identical (finite) loss trajectory — previously it read freed
        donated buffers and went NaN."""
        main, startup, loss = _train_program()
        rng = np.random.RandomState(0)
        batches = [(rng.randn(8, 4).astype(np.float32),
                    rng.randn(8, 1).astype(np.float32)) for _ in range(5)]

        def run_all(exe, scope):
            out = []
            for bx, bt in batches:
                (lo,) = exe.run(main, feed={"x": bx, "t": bt},
                                fetch_list=[loss], scope=scope)
                out.append(float(lo))
            return out

        exe = pt.Executor(pt.CPUPlace())
        scope = pt.Scope()
        exe.run(startup, scope=scope)
        ref = run_all(exe, scope)
        assert np.all(np.isfinite(ref))

        # fresh-process equivalent: drop the in-memory executables so the
        # next compile deserializes from the on-disk cache
        import jax

        jax.clear_caches()
        executor_mod._donation_verdicts.clear()
        exe2 = pt.Executor(pt.CPUPlace())
        scope2 = pt.Scope()
        exe2.run(startup, scope=scope2)
        got = run_all(exe2, scope2)
        np.testing.assert_allclose(got, ref, rtol=1e-6)
        stats = exe2.cache_stats()
        assert stats["persistent_hits"] >= 1, stats  # restore path taken
        # CPU restores are denylisted: the donating step must have been
        # routed to its no-donation twin
        assert stats["donation_fallbacks"] >= 1, stats

    def test_save_resume_bit_exact_with_warm_cache(self, fresh_cache_wiring,
                                                   tmp_path):
        """test_master_checkpoint's save/resume scenario WITH the
        persistent cache active — the exact setup the old conftest note
        said NaN'd at step 3."""
        from paddle_tpu.checkpoint import load_checkpoint, save_checkpoint

        main, startup, loss = _train_program()
        rng = np.random.RandomState(0)
        batches = [(rng.randn(8, 4).astype(np.float32),
                    rng.randn(8, 1).astype(np.float32)) for _ in range(8)]
        ckdir = str(tmp_path / "ck")

        exe = pt.Executor(pt.CPUPlace())
        scope = pt.Scope()
        exe.run(startup, scope=scope)
        for bx, bt in batches[:4]:
            exe.run(main, feed={"x": bx, "t": bt}, fetch_list=[loss],
                    scope=scope)
        save_checkpoint(ckdir, scope=scope, step=4)
        ref = [float(exe.run(main, feed={"x": bx, "t": bt},
                             fetch_list=[loss], scope=scope)[0])
               for bx, bt in batches[4:]]

        import jax

        jax.clear_caches()  # resume in a fresh-process equivalent
        executor_mod._donation_verdicts.clear()
        exe2 = pt.Executor(pt.CPUPlace())
        scope2 = pt.Scope()
        exe2.run(startup, scope=scope2)
        load_checkpoint(ckdir, scope=scope2)
        got = [float(exe2.run(main, feed={"x": bx, "t": bt},
                              fetch_list=[loss], scope=scope2)[0])
               for bx, bt in batches[4:]]
        assert np.all(np.isfinite(got))
        np.testing.assert_allclose(got, ref, rtol=1e-6)

    def test_fresh_compiles_trust_donation(self, fresh_cache_wiring):
        """Without a restore, donation stays on (no twin execution, no
        fallback) even with the cache enabled."""
        main, startup, loss = _train_program()
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.Scope()
        exe.run(startup, scope=scope)
        exe.run(main, feed={"x": np.ones((8, 4), np.float32),
                            "t": np.ones((8, 1), np.float32)},
                fetch_list=[loss], scope=scope)
        stats = exe.cache_stats()
        assert stats["donation_fallbacks"] == 0
        assert stats["persistent_hits"] == 0


# ---------------------------------------------------------------------------
# engines + server boot path
# ---------------------------------------------------------------------------
def _save_dense_model(tmp_path):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[6])
        y = layers.fc(x, size=4, act="softmax")
    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    startup.random_seed = 11
    exe.run(startup, scope=scope)
    d = str(tmp_path / "dense")
    pt.io.save_inference_model(d, ["x"], [y], exe, main_program=main,
                               scope=scope)
    return d


class TestEngineWarmStart:
    def test_warmup_persists_manifest_and_replay_precompiles(self, tmp_path):
        from paddle_tpu.serving import InferenceEngine

        d = _save_dense_model(tmp_path)
        eng = InferenceEngine(d, batch_buckets=(1, 2))
        assert eng.warm_start() == 2  # no manifest yet -> execute warmup
        assert os.path.exists(os.path.join(d, "warmup_manifest.json"))

        eng2 = InferenceEngine(d, batch_buckets=(1, 2))
        assert eng2.warm_start() == 2  # manifest replay, no execution
        misses0 = eng2.cache_stats()["misses"]
        x = np.random.RandomState(0).rand(2, 6).astype(np.float32)
        eng2.run({"x": x})
        assert eng2.cache_stats()["misses"] == misses0
        assert eng2.metrics.counter("warmup_replayed") == 2

    def test_bad_manifest_degrades_to_warmup(self, tmp_path):
        from paddle_tpu.serving import InferenceEngine

        d = _save_dense_model(tmp_path)
        with open(os.path.join(d, "warmup_manifest.json"), "w") as f:
            json.dump({"version": 99}, f)
        eng = InferenceEngine(d, batch_buckets=(1, 2))
        with pytest.warns(RuntimeWarning, match="warmup manifest"):
            assert eng.warm_start() == 2  # fell back to execute warmup

    def test_server_warming_healthz(self, tmp_path):
        from paddle_tpu.serving import InferenceEngine, Server

        d = _save_dense_model(tmp_path)
        eng = InferenceEngine(d, batch_buckets=(1, 2))
        gate = threading.Event()

        def slow_warm():
            assert gate.wait(10)
            eng.warm_start()

        srv = Server(eng, batch_buckets=(1, 2), warmup=slow_warm)
        srv.start()
        port = srv.serve_http()
        try:
            assert srv.state == "warming"
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=5)
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert body["state"] == "warming" and body["ok"] is False
            gate.set()
            deadline = time.monotonic() + 30
            while srv.state != "ready" and time.monotonic() < deadline:
                time.sleep(0.02)
            assert srv.state == "ready"
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5)
            assert resp.status == 200
            # the boot gauge landed and the engine serves
            assert srv.metrics.snapshot()["gauges"]["warmup/boot_s"] >= 0
            x = np.random.RandomState(0).rand(6).astype(np.float32)
            srv.submit({"x": x}).result(timeout=30)
            # compile-source dimensions reach the Prometheus exposition
            prom = srv.metrics_prometheus()
            assert "fresh_compiles" in prom and "persistent_hits" in prom
        finally:
            gate.set()
            srv.stop()

    def test_server_default_warmup_uses_engine_warm_start(self, tmp_path):
        from paddle_tpu.serving import InferenceEngine, Server

        d = _save_dense_model(tmp_path)
        eng = InferenceEngine(d, batch_buckets=(1, 2))
        srv = Server(eng, batch_buckets=(1, 2), warmup=True)
        srv.start()
        try:
            deadline = time.monotonic() + 60
            while srv.state != "ready" and time.monotonic() < deadline:
                time.sleep(0.02)
            assert srv.state == "ready"
            assert eng.cache_stats()["entries"] == 2  # both buckets warm
        finally:
            srv.stop()

    def test_generation_engine_manifest_roundtrip(self, tmp_path):
        from paddle_tpu import models
        from paddle_tpu.serving import GenerationEngine

        scope = pt.Scope()
        exe = pt.Executor(pt.TPUPlace())
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            prompt = layers.data("p_save", shape=[8], dtype="int64")
            out_ids = models.transformer_lm_generate(
                prompt, vocab_size=32, d_model=16, n_layers=2, num_heads=2,
                max_len=32, max_new_tokens=4)
        startup.random_seed = 7
        exe.run(startup, scope=scope)
        d = str(tmp_path / "lm")
        pt.io.save_inference_model(d, ["p_save"], [out_ids], exe,
                                   main_program=prog, scope=scope)

        eng = GenerationEngine.from_saved(d, slots=2, prompt_buckets=(8,),
                                          prefill_batch_buckets=(1, 2))
        eng.warm_start()
        prompts = np.random.RandomState(6).randint(
            0, 32, (2, 8)).astype("int64")
        ref = np.stack(eng.generate_all(list(prompts), max_new_tokens=4))

        eng2 = GenerationEngine.from_saved(d, slots=2, prompt_buckets=(8,),
                                           prefill_batch_buckets=(1, 2))
        # 2 prefill batch buckets + decode + the copy-on-write page copy
        assert eng2.warm_from_manifest() == 4
        misses0 = eng2.cache_stats()["misses"]
        got = np.stack(eng2.generate_all(list(prompts), max_new_tokens=4))
        np.testing.assert_array_equal(got, ref)
        assert eng2.cache_stats()["misses"] == misses0


class TestTrainerManifest:
    def _build_trainer(self):
        from paddle_tpu.core import program as prog_mod
        from paddle_tpu.core import scope as scope_mod

        # fresh-boot equivalent inside one process: reset the global
        # programs/scope AND the uid counter so rebuilt programs are
        # name-identical to the first build (what a real process restart
        # gives for free)
        prog_mod.Program._uid_counter = 0
        prog_mod._main_program = prog_mod.Program()
        prog_mod._startup_program = prog_mod.Program()
        scope_mod._global_scope = scope_mod.Scope()
        scope_mod._scope_stack[:] = [scope_mod._global_scope]
        x = layers.data("x", shape=[4])
        t = layers.data("t", shape=[1])
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, t)))
        return pt.trainer.SGD(
            cost=loss,
            optimizer=pt.optimizer.SGDOptimizer(learning_rate=0.1),
            feed_list=[x, t], scope=pt.Scope())

    def test_sgd_resume_bit_exact_with_warm_cache(self, tmp_path,
                                                  fresh_cache_wiring):
        """THE acceptance pin: SGD.train resume with
        --compilation_cache_dir set (manifest replay + restored
        executables + donation guard) reaches bitwise-identical params
        vs an uninterrupted run."""
        from paddle_tpu.resilience import CheckpointConfig

        rng = np.random.RandomState(0)
        rows = [(rng.randn(4).astype(np.float32),
                 rng.randn(1).astype(np.float32)) for _ in range(8)]

        def reader():
            for i in range(0, 8, 4):
                yield rows[i:i + 4]

        quiet = lambda e: None  # noqa: E731

        def params_of(trainer):
            names = sorted(trainer.scope.keys())  # params, lr, RNG stream
            assert any(".w" in n for n in names), names
            return {n: np.asarray(trainer.scope.get(n)) for n in names}

        # uninterrupted 2-pass reference (no checkpointing at all)
        ref_t = self._build_trainer()
        ref_t.train(reader, num_passes=2, event_handler=quiet)
        ref = params_of(ref_t)

        # pass 0 with checkpointing, then a fresh-process-equivalent
        # resume (in-memory executables dropped -> disk restores) for
        # pass 1
        ckdir = str(tmp_path / "ck")

        def config():
            return CheckpointConfig(ckdir, every_n_steps=1,
                                    background=False,
                                    install_signal_handlers=False)

        t1 = self._build_trainer()
        t1.train(reader, num_passes=1, event_handler=quiet,
                 checkpoint=config())
        import jax

        jax.clear_caches()
        executor_mod._donation_verdicts.clear()
        t2 = self._build_trainer()
        t2.train(reader, num_passes=2, event_handler=quiet,
                 checkpoint=config())
        got = params_of(t2)
        assert sorted(got) == sorted(ref)
        for name in ref:
            assert np.isfinite(got[name]).all(), name
            np.testing.assert_array_equal(got[name], ref[name],
                                          err_msg=name)
        # the resume actually took the cold-start path
        assert t2.exe.cache_stats()["persistent_hits"] >= 1

    def test_resume_replays_manifest(self, tmp_path):
        from paddle_tpu.resilience import CheckpointConfig

        ckdir = str(tmp_path / "ck")
        rng = np.random.RandomState(0)
        rows = [(rng.randn(4).astype(np.float32),
                 rng.randn(1).astype(np.float32)) for _ in range(8)]

        def reader():
            for i in range(0, 8, 4):
                yield rows[i:i + 4]

        def config():
            return CheckpointConfig(ckdir, every_n_steps=1,
                                    background=False,
                                    install_signal_handlers=False)

        quiet = lambda e: None  # noqa: E731
        t1 = self._build_trainer()
        t1.train(reader, num_passes=1, event_handler=quiet,
                 checkpoint=config())
        assert os.path.exists(os.path.join(ckdir, "warmup_manifest.json"))

        t2 = self._build_trainer()
        t2.train(reader, num_passes=2, event_handler=quiet,
                 checkpoint=config())
        assert getattr(t2, "_last_replay", None) is not None
        assert t2._last_replay["compiled"] >= 1, t2._last_replay


# ---------------------------------------------------------------------------
# zero fresh compiles across real process boots (slow: subprocesses)
# ---------------------------------------------------------------------------
_BOOT_CHILD = r'''
import json, os, sys
import numpy as np
import paddle_tpu as pt
from paddle_tpu.serving import InferenceEngine
model_dir, cache_dir = sys.argv[1:3]
pt.set_flags({"compilation_cache_dir": cache_dir})
eng = InferenceEngine(model_dir, batch_buckets=(1, 2))
warmed = eng.warm_start()
out, = eng.run({"x": np.ones((2, 6), np.float32)})
print(json.dumps({"warmed": warmed, "out": np.asarray(out).tolist(),
                  **eng.cache_stats()}))
'''


@pytest.mark.slow
def test_second_boot_zero_fresh_compiles(tmp_path):
    """Boot the same artifact in two fresh processes with manifest +
    persistent cache: the second boot must not compile anything fresh."""
    d = _save_dense_model(tmp_path)
    cache = str(tmp_path / "xla_cache")
    child = str(tmp_path / "boot_child.py")
    with open(child, "w") as f:
        f.write(_BOOT_CHILD)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))

    def boot():
        proc = subprocess.run([sys.executable, child, d, cache], env=env,
                              capture_output=True, text=True, timeout=300,
                              cwd=repo)
        assert proc.returncode == 0, proc.stderr[-800:]
        return json.loads(proc.stdout.strip().splitlines()[-1])

    first = boot()
    second = boot()
    assert first["fresh_compiles"] > 0
    assert second["fresh_compiles"] == 0, second
    assert second["persistent_hits"] >= second["warmed"]
    np.testing.assert_allclose(first["out"], second["out"], rtol=1e-6)

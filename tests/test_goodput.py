"""Training observatory (ISSUE 18): goodput/badput accounting, live MFU
gauges, and straggler detection across the elastic plane.

Acceptance pins:
- taxonomy completeness: per-bucket seconds sum to >=99% of the measured
  pass wall on BOTH loop paths, and a run with forced fresh compiles +
  a synchronous checkpoint + an injected transient retry attributes
  nonzero seconds to exactly those buckets;
- straggler pin: 3 concurrent StreamingTrainers on one master, one
  throttled — the master flags it within the run (labeled
  ``trainer_step_seconds``/``trainer_straggler`` series + trace record)
  while the throttle leaves training bitwise-unchanged;
- runlog regression: ``examples_per_sec`` is resolve-ordered under
  ``async_depth>1`` (the dispatch-anchored wall measured only the
  resolve block and OVERSTATED throughput).

Tier-1 budget: module-level shared trainer builders, tiny models; the
async completeness variant and the solo-throttle bitwise leg are
``@pytest.mark.slow``.
"""
import io
import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import dataset, event as evt, layers, profiler, trace
from paddle_tpu.master import Master, MasterClient, MasterServer
from paddle_tpu.online import StreamingTrainer
from paddle_tpu.resilience import CheckpointConfig, FaultPlan
from paddle_tpu.serving.metrics import MetricsRegistry
from paddle_tpu.trace import BUCKETS, GoodputMeter, RunLog
from paddle_tpu.trace.flight import get_recorder
from paddle_tpu.trace.slo import SLO, SLOTracker
from paddle_tpu.trainer import SGD

VOCAB, SLOTS, DD = 128, dataset.ctr.SLOTS, dataset.ctr.DENSE_DIM


# ---------------------------------------------------------------------------
# shared builders
# ---------------------------------------------------------------------------
def _build_fc(dim=16, seed=3):
    main, startup = pt.Program(), pt.Program()
    startup.random_seed = seed
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[dim])
        y = layers.data("y", shape=[1], dtype="int64")
        h = layers.fc(x, size=dim, act="relu")
        logits = layers.fc(h, size=3)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        sgd = SGD(cost=loss,
                  optimizer=pt.optimizer.SGDOptimizer(learning_rate=0.1),
                  feed_list=[x, y], place=pt.CPUPlace(), scope=pt.Scope())
    return sgd


def _rows(n, dim=16, batch=8, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.rand(batch, dim).astype("float32")
    ys = rng.randint(0, 3, size=(batch, 1)).astype("int64")
    rows = [(xs[i], ys[i]) for i in range(batch)]

    def reader():
        for _ in range(n):
            yield rows
    return reader


def _build_ctr(seed=7):
    """Order-seeded CTR bundle (the test_elastic builder): identically
    built bundles initialize bit-identically."""
    main, startup = pt.Program(), pt.Program()
    startup.random_seed = seed
    with pt.program_guard(main, startup):
        ids = layers.data("ids", shape=[SLOTS], dtype="int64")
        dense = layers.data("dense", shape=[DD])
        label = layers.data("label", shape=[1])
        logit = pt.models.wide_deep(ids, dense, vocab_size=VOCAB,
                                    embed_dim=4, hidden_sizes=(8,))
        loss, _ = pt.models.wide_deep_loss(logit, label)
        sgd = SGD(loss, pt.optimizer.SGDOptimizer(learning_rate=0.05),
                  [ids, dense, label], scope=pt.Scope())
    return sgd


def _okeys(scope):
    import re

    def key(name):
        m = re.search(r"_(\d+)$", name)
        return (0, int(m.group(1))) if m else (1, name)
    return sorted(scope.keys(), key=key)


def _assert_scopes_bitwise(a, b):
    ka, kb = _okeys(a), _okeys(b)
    assert len(ka) == len(kb)
    for na, nb in zip(ka, kb):
        np.testing.assert_array_equal(np.asarray(a.get(na)),
                                      np.asarray(b.get(nb)),
                                      err_msg=f"{na} vs {nb}")


# ---------------------------------------------------------------------------
# GoodputMeter unit surface
# ---------------------------------------------------------------------------
class TestGoodputMeter:
    def test_account_measure_move_and_totals(self):
        m = GoodputMeter()
        m.account("device_compute", 0.3)
        m.account("data_wait", 0.1)
        with m.measure("checkpoint_stall"):
            time.sleep(0.002)
        m.move("device_compute", "fresh_compile", 0.1)
        snap = m.snapshot()
        assert snap["buckets"]["device_compute"] == pytest.approx(0.2)
        assert snap["buckets"]["fresh_compile"] == pytest.approx(0.1)
        assert snap["buckets"]["checkpoint_stall"] >= 0.002
        # buckets and total are rounded to 6dp independently: the sum
        # of rounded buckets can drift a few microseconds off the total
        assert snap["total_s"] == pytest.approx(
            sum(snap["buckets"].values()), abs=1e-5)
        assert m.goodput_fraction() == pytest.approx(
            0.2 / snap["total_s"], rel=1e-3)
        with pytest.raises(KeyError):
            m.account("not_a_bucket", 1.0)

    def test_mfu_from_priced_flops(self):
        m = GoodputMeter(peak_flops=1e9)
        assert m.note_step(0.1) is None       # unpriced -> no MFU
        m.set_program_flops(5e7)
        mfu = m.note_step(0.1)                # 5e8 flops/s vs 1e9 peak
        assert mfu == pytest.approx(0.5)
        assert m.mfu_ema == pytest.approx(0.5)
        m.note_step(0.05)                     # 1e9/s -> mfu 1.0
        assert m.mfu == pytest.approx(1.0)
        assert 0.5 < m.mfu_ema < 1.0          # EMA trails
        assert m.steps == 3                   # every step counts, MFU
        #                                       only once priced

    def test_publish_prometheus_series_and_ratio_counters(self):
        reg = MetricsRegistry()
        m = GoodputMeter()
        m.account("device_compute", 0.9)
        m.account("data_wait", 0.1)
        m.publish(reg, job="train")
        snap = reg.snapshot()
        assert snap["counters"]["goodput_good_ms_total"] == 900
        assert snap["counters"]["goodput_total_ms_total"] == 1000
        assert snap["gauges"]["goodput_fraction"] == pytest.approx(0.9)
        text = reg.prometheus_text()
        assert 'bucket="device_compute"' in text
        assert 'job="train"' in text
        # counters are cumulative + monotonic across publishes
        m.account("device_compute", 0.5)
        m.publish(reg, job="train")
        snap2 = reg.snapshot()
        assert snap2["counters"]["goodput_good_ms_total"] == 1400
        assert snap2["counters"]["goodput_total_ms_total"] == 1500

    def test_telemetry_payload(self):
        m = GoodputMeter(peak_flops=1e9)
        m.set_program_flops(1e8)
        m.account("device_compute", 1.0)
        m.note_step(0.2)
        t = m.telemetry(last_step_wall_s=0.25)
        assert t["step_wall_s"] == pytest.approx(0.25)
        assert t["steps"] == 1
        assert t["goodput"] == pytest.approx(1.0)
        assert t["mfu"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# taxonomy completeness (ACCEPTANCE)
# ---------------------------------------------------------------------------
class TestTaxonomyCompleteness:
    def _measured_pass_wall(self, trainer, reader, **kw):
        """Train with a wall clock pinned to the pass window (first
        BeginPass -> last EndPass): the decomposition's denominator."""
        t = {"t0": None, "t1": None}

        def handler(e):
            if isinstance(e, evt.BeginPass) and t["t0"] is None:
                t["t0"] = time.perf_counter()
            elif isinstance(e, evt.EndPass):
                t["t1"] = time.perf_counter()

        trainer.train(reader, event_handler=handler, **kw)
        return t["t1"] - t["t0"]

    def test_sync_buckets_sum_to_99pct_of_wall(self):
        tr = _build_fc()
        tr.train(_rows(2), num_passes=1,
                 event_handler=lambda e: None)  # warm compile/init
        wall = self._measured_pass_wall(tr, _rows(40), num_passes=2)
        snap = tr.goodput.snapshot()
        assert snap["steps"] == 80
        covered = snap["total_s"] / wall
        assert covered >= 0.99, (covered, snap)
        # and nothing is double counted either
        assert covered <= 1.02, (covered, snap)
        # every second lands in a named bucket (sum == total, modulo
        # the independent 6dp rounding of each bucket)
        assert snap["total_s"] == pytest.approx(
            sum(snap["buckets"].values()), abs=1e-5)
        assert set(snap["buckets"]) == set(BUCKETS)

    @pytest.mark.slow  # same contract as the sync pin, async loop
    def test_async_buckets_sum_to_99pct_of_wall(self):
        tr = _build_fc(seed=5)
        tr.train(_rows(2), num_passes=1, async_depth=3,
                 event_handler=lambda e: None)
        wall = self._measured_pass_wall(tr, _rows(40), num_passes=2,
                                        async_depth=3)
        snap = tr.goodput.snapshot()
        covered = snap["total_s"] / wall
        assert covered >= 0.99, (covered, snap)
        assert covered <= 1.02, (covered, snap)

    def test_badput_lands_in_named_buckets(self, tmp_path):
        """Forced fresh compiles (a mid-pass batch-shape change), a
        synchronous checkpoint, and an injected transient executor
        retry each attribute NONZERO seconds to exactly their bucket."""
        tr = _build_fc(seed=9)
        rng = np.random.RandomState(1)

        def reader():  # batch sizes 8 and 12 -> two compiled shapes
            for i in range(8):
                b = 8 if i % 2 == 0 else 12
                xs = rng.rand(b, 16).astype("float32")
                ys = rng.randint(0, 3, size=(b, 1)).astype("int64")
                yield [(xs[j], ys[j]) for j in range(b)]

        ck = CheckpointConfig(str(tmp_path / "ck"), every_n_steps=2,
                              background=False,
                              install_signal_handlers=False)
        with FaultPlan().at(step=3, kind="executor_error").active() \
                as plan:
            tr.train(reader, num_passes=1, checkpoint=ck,
                     event_handler=lambda e: None)
            assert ("executor_error", 3) in plan.fired_log
        b = tr.goodput.snapshot()["buckets"]
        assert b["fresh_compile"] > 0, b
        assert b["checkpoint_stall"] > 0, b
        # the step retry backs off 10ms before retrying -> visible
        assert b["recovery_rollback"] >= 0.005, b
        assert b["device_compute"] > 0 and b["data_wait"] > 0, b

    def test_goodput_false_disables_accounting(self):
        tr = _build_fc(seed=11)
        tr.train(_rows(2), num_passes=1, goodput=False,
                 event_handler=lambda e: None)
        assert tr.goodput is None

    def test_shared_meter_accumulates_across_calls(self):
        tr = _build_fc(seed=13)
        m = GoodputMeter()
        tr.train(_rows(2), num_passes=1, goodput=m,
                 event_handler=lambda e: None)
        s1 = m.total_seconds()
        tr.train(_rows(2), num_passes=1, goodput=m,
                 event_handler=lambda e: None)
        assert m.total_seconds() > s1
        assert tr.goodput is m


# ---------------------------------------------------------------------------
# runlog regression: resolve-ordered walls (ACCEPTANCE satellite)
# ---------------------------------------------------------------------------
class _FakeClock:
    """Stand-in for the ``time`` module inside runlog: a settable
    perf_counter plus a real time() for the header."""

    def __init__(self):
        self.now = 100.0

    def perf_counter(self):
        return self.now

    def time(self):
        return 0.0


class TestRunLogResolveOrdered:
    def _drive(self, clock, rl, script):
        for t, e in script:
            clock.now = t
            rl(e)

    def test_async_reordered_walls_and_throughput(self, monkeypatch):
        """Under ``async_depth>1`` BeginIteration k+1 fires BEFORE
        EndIteration k resolves. The journal wall must be the interval
        between consecutive RESOLVES (0.5s here, 32 ex/s), not the
        dispatch-anchored remainder (0.4s, 40 ex/s — the old
        overstatement)."""
        from paddle_tpu.trace import runlog as runlog_mod

        clock = _FakeClock()
        monkeypatch.setattr(runlog_mod, "time", clock)
        sink = io.StringIO()
        rl = RunLog(sink)
        e0 = evt.EndIteration(0, 0, 1.0, batch_size=16,
                              host_wall_s=0.1, device_wall_s=0.4,
                              mfu=0.5)
        e1 = evt.EndIteration(0, 1, 1.0, batch_size=16,
                              host_wall_s=0.1, device_wall_s=0.4,
                              mfu=0.7)
        self._drive(clock, rl, [
            (100.0, evt.BeginPass(0)),
            (100.0, evt.BeginIteration(0, 0)),   # dispatch 0
            (100.1, evt.BeginIteration(0, 1)),   # dispatch 1 (pipelined)
            (100.5, e0),                         # resolve 0
            (101.0, e1),                         # resolve 1
            (101.0, evt.EndPass(0)),
        ])
        rows = [json.loads(line) for line in
                sink.getvalue().splitlines()]
        iters = [r for r in rows if r["type"] == "iteration"]
        assert iters[0]["wall_ms"] == pytest.approx(500.0)
        assert iters[0]["examples_per_sec"] == pytest.approx(32.0)
        # the regression: dispatch-anchored accounting yielded 400ms/40
        assert iters[1]["wall_ms"] == pytest.approx(500.0)
        assert iters[1]["examples_per_sec"] == pytest.approx(32.0)
        # goodput split + live MFU ride the same rows
        for it in iters:
            assert it["host_wall_ms"] == pytest.approx(100.0)
            assert it["device_wall_ms"] == pytest.approx(400.0)
        assert iters[0]["mfu"] == pytest.approx(0.5)
        assert iters[1]["mfu_ema"] == pytest.approx(
            0.1 * 0.7 + 0.9 * 0.5)

    def test_sync_walls_identical_to_dispatch_anchored(self, monkeypatch):
        """Synchronous runs resolve in dispatch order, so the
        resolve-ordered wall equals the old per-iteration wall."""
        from paddle_tpu.trace import runlog as runlog_mod

        clock = _FakeClock()
        monkeypatch.setattr(runlog_mod, "time", clock)
        sink = io.StringIO()
        rl = RunLog(sink)
        self._drive(clock, rl, [
            (100.0, evt.BeginPass(0)),
            (100.0, evt.BeginIteration(0, 0)),
            (100.2, evt.EndIteration(0, 0, 1.0, batch_size=8)),
            (100.2, evt.BeginIteration(0, 1)),
            (100.5, evt.EndIteration(0, 1, 1.0, batch_size=8)),
        ])
        iters = [json.loads(line) for line in
                 sink.getvalue().splitlines()
                 if json.loads(line)["type"] == "iteration"]
        assert iters[0]["wall_ms"] == pytest.approx(200.0)
        assert iters[1]["wall_ms"] == pytest.approx(300.0)


# ---------------------------------------------------------------------------
# goodput SLO objective (ratio kind over cumulative counters)
# ---------------------------------------------------------------------------
class TestGoodputSLO:
    def test_ratio_objective_burns_on_badput(self):
        slo = SLO(goodput=0.9, target=0.9, windows_s=(10.0, 30.0),
                  burn_thresholds=(2.0, 1.5))
        clock = {"t": 0.0}
        tracker = SLOTracker(slo, clock=lambda: clock["t"])

        def snap(good_ms, total_ms):
            return {"counters": {"goodput_good_ms_total": good_ms,
                                 "goodput_total_ms_total": total_ms}}

        # healthy: 95% goodput
        for i in range(1, 5):
            clock["t"] = i * 5.0
            tracker.sample(snap(950 * i, 1000 * i))
        st = tracker.status()
        assert st["objectives"]["goodput"]["attainment"] \
            == pytest.approx(0.95)
        assert not st["alerting"]
        # collapse: the next windows are pure badput
        for i in range(5, 9):
            clock["t"] = i * 5.0
            tracker.sample(snap(3800, 1000 * i))
        st = tracker.status()
        obj = st["objectives"]["goodput"]
        assert obj["attainment"] < 0.9
        assert all(w["burn_rate"] > 1.5 for w in obj["burn"].values())
        assert obj["alerting"] and st["alerting"]

    def test_objectives_and_to_dict_carry_goodput(self):
        slo = SLO(goodput=0.85)
        obj = slo.objectives()["goodput"]
        assert obj == {"kind": "ratio", "good": "goodput_good_ms_total",
                       "total": "goodput_total_ms_total", "target": 0.85}
        assert slo.to_dict()["goodput"] == 0.85


# ---------------------------------------------------------------------------
# flight recorder covers training (satellite)
# ---------------------------------------------------------------------------
class TestTrainingFlightRecorder:
    def test_trainer_source_registered_and_dumped_on_error(self):
        tr = _build_fc(seed=17)
        tr.train(_rows(3), num_passes=1, event_handler=lambda e: None)
        rec = get_recorder()
        doc = rec.bundle("probe")
        states = [v for k, v in doc["state"].items()
                  if k.startswith("trainer#")]
        assert states, list(doc["state"])
        st = states[-1]
        assert st["position"]["pass_id"] == 0
        assert st["goodput"]["steps"] == 3
        assert len(st["recent_step_walls_s"]) == 3

        # an unhandled step-loop error auto-dumps (in-memory bundle;
        # files only land when $PADDLE_TPU_FLIGHT_DIR is set)
        rec._last_auto_dump = 0.0  # defeat the crash-loop throttle
        with FaultPlan().at(step=2, kind="crash").active():
            with pytest.raises(Exception):
                tr.train(_rows(3), num_passes=1,
                         event_handler=lambda e: None)
        assert rec.last_bundle["reason"] == "trainer_error"
        assert rec.last_bundle["error"] is not None


# ---------------------------------------------------------------------------
# straggler plane: master unit level
# ---------------------------------------------------------------------------
class TestStragglerMaster:
    def _master_with_telemetry(self, walls):
        m = Master(timeout_s=60)
        toks = {tid: m.register_trainer(tid, lease_s=30.0)
                for tid in walls}
        for _ in range(4):
            for tid, w in walls.items():
                m.heartbeat(toks[tid],
                            telemetry={"step_wall_s": w, "steps": 4,
                                       "goodput": 0.8, "mfu": 0.2})
        return m, toks

    def test_skew_detection_and_recovery(self):
        m, toks = self._master_with_telemetry(
            {"fast-a": 0.01, "fast-b": 0.012, "slow": 0.05})
        ts = m.train_status()
        assert ts["stragglers"] == ["slow"]
        assert ts["stragglers_detected_total"] == 1
        assert ts["trainers"]["slow"]["straggler"] is True
        assert ts["skew"] > 2.0
        # catches back up -> flag clears, detection counter does not
        for _ in range(32):
            m.heartbeat(toks["slow"],
                        telemetry={"step_wall_s": 0.011, "steps": 40})
        ts = m.train_status()
        assert ts["stragglers"] == []
        assert ts["stragglers_detected_total"] == 1

    def test_single_trainer_never_flagged(self):
        m, _ = self._master_with_telemetry({"only": 0.5})
        assert m.train_status()["stragglers"] == []

    def test_prometheus_labeled_trainer_series(self):
        m, _ = self._master_with_telemetry(
            {"fast-a": 0.01, "fast-b": 0.012, "slow": 0.05})
        text = m.prometheus_text()
        assert 'trainer_step_seconds{trainer="slow"} 0.05' in text
        assert 'trainer_straggler{trainer="slow"} 1' in text
        assert 'trainer_straggler{trainer="fast-a"} 0' in text
        assert 'trainer_goodput_fraction{trainer="fast-a"} 0.8' in text
        assert 'trainer_mfu{trainer="fast-a"} 0.2' in text
        assert "master_straggler 1" in text
        assert "master_stragglers_detected_total 1" in text

    def test_detection_emits_trace_record_and_stat(self):
        before = profiler.global_stat.as_dict(
            prefix="master/straggler_detected").get(
            "master/straggler_detected", {}).get("total_ms", 0)
        trace.enable(level=1)
        m, _ = self._master_with_telemetry(
            {"fast-a": 0.01, "fast-b": 0.012, "slow": 0.05})
        after = profiler.global_stat.as_dict(
            prefix="master/straggler_detected")[
            "master/straggler_detected"]["total_ms"]
        assert after >= before + 1
        recs = [s for s in trace.get_tracer().spans()
                if s.name == "master/straggler_detected"]
        assert recs and recs[-1].attrs["trainer"] == "slow"
        assert recs[-1].attrs["skew"] > 2.0


# ---------------------------------------------------------------------------
# the 3-trainer straggler pin (ACCEPTANCE)
# ---------------------------------------------------------------------------
def _slow_handler(delay_s):
    def handler(e):
        if isinstance(e, evt.EndIteration):
            time.sleep(delay_s)
    return handler


@pytest.mark.slow  # tier-1 budget (PR 20): the 3-trainer skew drill is
# the heaviest goodput case; the meter/decomposition/MFU contracts stay
# tier-1 via the other goodput tests
def test_straggler_pin_three_trainers(tmp_path):
    """ACCEPTANCE PIN: 3 StreamingTrainers heartbeat one master
    concurrently; one is throttled 6x per step. The master's skew check
    flags exactly the slow trainer DURING the run — exported as the
    labeled ``trainer_straggler`` gauge and a
    ``master/straggler_detected`` trace record — within the K
    heartbeats the run itself takes."""
    descs = dataset.ctr.task_descs(6, records_per_shard=32, vocab=VOCAB)
    srv = MasterServer(timeout_s=30, port=0)
    addr = srv.start()
    seen = {"stragglers": set(), "polls": 0}
    try:
        trainers = {}
        threads = []
        for tid, delay in (("fast-a", 0.0), ("fast-b", 0.0),
                           ("slow-c", 0.03)):
            b = _build_ctr()
            st = StreamingTrainer(
                b, addr, dataset.ctr.task_reader, task_descs=descs,
                batch_size=16,
                checkpoint=CheckpointConfig(
                    # one 2-step task per checkpoint: elastic acks are
                    # deferred until a generation covers them, so the
                    # cadence must divide the task length or the fleet
                    # parks on NO_TASK waiting for acks that never flush
                    str(tmp_path / f"ck_{tid}"), every_n_steps=2,
                    background=False),
                max_passes=1, trainer_id=tid,
                install_signal_handlers=False, telemetry_every_s=0.01)
            trainers[tid] = st
            handler = _slow_handler(delay) if delay else None
            th = threading.Thread(target=st.run,
                                  kwargs={"event_handler": handler})
            threads.append(th)
        for th in threads:
            th.start()
        # poll the detector while the fleet runs: detection must land
        # within the run's own heartbeats, not post-hoc.  Snapshot the
        # prometheus text AT detection time — once fast trainers leave
        # the fleet the 2-trainer nearest-rank median equals the slow
        # trainer's own mean and the gauge legitimately clears.
        flagged_text = ""
        flag_polls: dict = {}
        while any(th.is_alive() for th in threads):
            now = set(srv.master.train_status()["stragglers"])
            seen["stragglers"] |= now
            for tid in now:
                flag_polls[tid] = flag_polls.get(tid, 0) + 1
            if "slow-c" in now and not flagged_text:
                flagged_text = srv.master.prometheus_text()
            seen["polls"] += 1
            time.sleep(0.02)
        for th in threads:
            th.join()
        ts = srv.master.train_status()
        text = srv.master.prometheus_text()
    finally:
        srv.stop()

    # slow-c must be flagged, and dominantly so: threaded trainers on a
    # loaded CPU host can transiently spike a fast trainer over the skew
    # bar for a beat or two, but the throttled one stays flagged
    assert "slow-c" in seen["stragglers"], seen
    others = {t: n for t, n in flag_polls.items() if t != "slow-c"}
    assert all(flag_polls["slow-c"] > n for n in others.values()), \
        flag_polls
    assert ts["stragglers_detected_total"] >= 1
    assert 'trainer_straggler{trainer="slow-c"} 1' in flagged_text
    assert 'trainer_step_seconds{trainer="slow-c"}' in text
    # per-trainer digests carried goodput/MFU telemetry too
    assert ts["trainers"]["slow-c"]["goodput"] is not None
    # each trainer exits at ITS OWN pass boundary and the first
    # PASS_DONE recycles the queue for the rest of the fleet, so the
    # fleet drains the queue a whole number of times (up to one full
    # pass per trainer — how many exactly is a scheduling race)
    done = sum(st.tasks_finished for st in trainers.values())
    assert done >= len(descs) and done % len(descs) == 0, done


@pytest.mark.slow  # the bitwise half of the pin: throttling is pure
# wall time — a throttled run's math is unchanged
def test_throttled_run_bitwise_identical(tmp_path):
    descs = dataset.ctr.task_descs(3, records_per_shard=32, vocab=VOCAB)

    def solo(tag, handler):
        srv = MasterServer(timeout_s=30, port=0)
        addr = srv.start()
        b = _build_ctr()
        st = StreamingTrainer(
            b, addr, dataset.ctr.task_reader, task_descs=descs,
            batch_size=16,
            checkpoint=CheckpointConfig(str(tmp_path / tag),
                                        every_n_steps=2,
                                        background=False),
            max_passes=1, trainer_id=tag,
            install_signal_handlers=False, telemetry_every_s=0.01)
        try:
            st.run(event_handler=handler)
        finally:
            srv.stop()
        return b

    b_fast = solo("fast", None)
    b_slow = solo("slow", _slow_handler(0.02))
    _assert_scopes_bitwise(b_fast.scope, b_slow.scope)


# ---------------------------------------------------------------------------
# streaming trainer exposes its meter (observatory glue)
# ---------------------------------------------------------------------------
def test_streaming_trainer_goodput_state(tmp_path):
    descs = dataset.ctr.task_descs(2, records_per_shard=32, vocab=VOCAB)
    srv = MasterServer(timeout_s=30, port=0)
    addr = srv.start()
    b = _build_ctr()
    st = StreamingTrainer(
        b, addr, dataset.ctr.task_reader, task_descs=descs,
        batch_size=16,
        checkpoint=CheckpointConfig(str(tmp_path / "ck"),
                                    every_n_steps=2, background=False),
        max_passes=1, trainer_id="obs", install_signal_handlers=False,
        telemetry_every_s=0.01)
    try:
        stats = st.run()
    finally:
        srv.stop()
    assert st.goodput is not None
    snap = st.goodput.snapshot()
    # the elastic buckets the plain trainer never touches are live here
    assert snap["buckets"]["master_wait"] > 0, snap
    assert snap["buckets"]["checkpoint_stall"] > 0, snap
    assert stats is not None
    # state() surfaces the same waterfall for /metrics + flight dumps
    assert st.state()["goodput"]["total_s"] == pytest.approx(
        snap["total_s"], rel=0.2)
    # and the flight recorder can see it
    doc = get_recorder().bundle("probe")
    states = [v for k, v in doc["state"].items()
              if k.startswith("streaming_trainer#")]
    assert states and states[-1]["trainer_id"] == "obs"


# ---------------------------------------------------------------------------
# trace_summary --goodput waterfall (tool glue)
# ---------------------------------------------------------------------------
def test_trace_summary_goodput_waterfall(tmp_path):
    import sys

    tr = _build_fc(seed=21)
    path = str(tmp_path / "run.jsonl")
    with RunLog(path) as rl:
        tr.train(_rows(6), num_passes=1, event_handler=lambda e: None,
                 run_log=rl)
    sys.path.insert(0, "tools")
    try:
        import trace_summary
    finally:
        sys.path.pop(0)
    out = trace_summary.summarize_goodput(path)
    assert "device_compute" in out and "goodput:" in out
    assert "MFU" in out
    # the per-trainer skew table renders from a master exposition
    mm = tmp_path / "master.txt"
    mm.write_text('trainer_step_seconds{trainer="a"} 0.01\n'
                  'trainer_step_seconds{trainer="b"} 0.012\n'
                  'trainer_step_seconds{trainer="c"} 0.06\n'
                  'trainer_straggler{trainer="c"} 1\n')
    out = trace_summary.summarize_goodput(path, master_metrics=str(mm))
    assert "STRAG" in out and "5.00x" in out

"""Paged KV cache pins: token-exactness vs the dense slot table on mixed
greedy batches, prefix sharing (stored-once pages, copy-on-write on
divergence, refcounted release), Sarathi-style chunked-prefill fairness,
typed pool backpressure, and the zero-recompile steady state over the
chunked/shared/COW paths."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models
from paddle_tpu.serving import (CacheExhaustedError, DynamicBatcher,
                                GenerationEngine, LMSpec,
                                PagedGenerationEngine, Request)

VOCAB, D, L, H, MAXLEN = 32, 16, 2, 2, 64

# weight cache: the LM startup compiles once per (seed, variant); scopes
# share the immutable weight arrays (decode never writes them — only the
# engines' own cache tensors are donated), which keeps this file's many
# fresh-engine tests off the startup-compile hot path
_WEIGHTS = {}


def _init_lm_scope(seed=7, **lm_kwargs):
    key = (seed, tuple(sorted(lm_kwargs.items())))
    exe = pt.Executor(pt.TPUPlace())
    if key not in _WEIGHTS:
        scope = pt.Scope()
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            prompt = layers.data("p_init", shape=[8], dtype="int64")
            models.transformer_lm_generate(
                prompt, vocab_size=VOCAB, d_model=D, n_layers=L,
                num_heads=H, max_len=MAXLEN, max_new_tokens=1, **lm_kwargs)
        startup.random_seed = seed
        exe.run(startup, scope=scope)
        _WEIGHTS[key] = {n: scope.get(n) for n in scope.keys()}
    scope = pt.Scope()
    for n, v in _WEIGHTS[key].items():
        scope.set(n, v)
    return scope, exe


def _reference_decode(scope, exe, prompts, max_new, **lm_kwargs):
    tp = prompts.shape[1]
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        prompt = layers.data(f"p_ref{tp}_{max_new}", shape=[tp],
                             dtype="int64")
        out_ids = models.transformer_lm_generate(
            prompt, vocab_size=VOCAB, d_model=D, n_layers=L, num_heads=H,
            max_len=MAXLEN, max_new_tokens=max_new, **lm_kwargs)
    got, = exe.run(prog, feed={f"p_ref{tp}_{max_new}": prompts},
                   fetch_list=[out_ids], scope=scope)
    return np.asarray(got)


def _spec(**kw):
    return LMSpec(vocab_size=VOCAB, d_model=D, n_layers=L, num_heads=H,
                  max_len=MAXLEN, **kw)


# ---------------------------------------------------------------------------
# token-exactness vs the dense slot table
# ---------------------------------------------------------------------------
class TestPagedParity:
    def test_paged_vs_dense_mixed_length_greedy_batch(self):
        """THE tentpole acceptance pin: a bs>=8 mixed-length greedy
        workload through the paged engine emits exactly the dense slot
        table's tokens (same weights, same prompts, same horizons)."""
        scope_d, exe = _init_lm_scope(7)
        scope_p, _ = _init_lm_scope(7)
        rng = np.random.RandomState(0)
        lens = [3, 5, 8, 11, 6, 14, 2, 16]  # mixed lengths, bs=8
        prompts = [rng.randint(0, VOCAB, (n,)).astype("int64")
                   for n in lens]
        dense = GenerationEngine(_spec(), scope_d, slots=8,
                                 kv_cache="dense",
                                 prompt_buckets=(4, 8, 16))
        paged = GenerationEngine(_spec(), scope_p, slots=8, page_size=8,
                                 prompt_buckets=(4, 8, 16))
        assert isinstance(paged, PagedGenerationEngine)
        assert not isinstance(dense, PagedGenerationEngine)
        got_d = dense.generate_all(prompts, max_new_tokens=5)
        got_p = paged.generate_all(prompts, max_new_tokens=5)
        # the dense leg is itself pinned one-shot-exact in
        # tests/test_serving.py, so dense equality IS ground truth
        for a, b in zip(got_d, got_p):
            np.testing.assert_array_equal(a, b)
        assert paged.metrics.counter("completed") == len(lens)
        # every page released on finish (sharing retains prefix pages)
        assert paged.pool.pages_in_use() == len(paged.prefix_index)

    @pytest.mark.slow
    def test_gqa_rope_paged_parity(self):
        """Per-row rotary offsets in the paged chunk prefill (each batch
        row resumes at its own absolute position) vs the dense path."""
        scope_d, _ = _init_lm_scope(5, use_rope=True, num_kv_heads=1)
        scope_p, _ = _init_lm_scope(5, use_rope=True, num_kv_heads=1)
        rng = np.random.RandomState(2)
        prompts = [rng.randint(0, VOCAB, (n,)).astype("int64")
                   for n in (5, 12)]
        dense = GenerationEngine(_spec(use_rope=True, num_kv_heads=1),
                                 scope_d, slots=2, kv_cache="dense",
                                 prompt_buckets=(16,),
                                 prefill_batch_buckets=(2,))
        paged = GenerationEngine(_spec(use_rope=True, num_kv_heads=1),
                                 scope_p, slots=2, page_size=4,
                                 prompt_buckets=(16,),
                                 prefill_batch_buckets=(2,))
        got_d = dense.generate_all(prompts, max_new_tokens=4)
        got_p = paged.generate_all(prompts, max_new_tokens=4)
        for a, b in zip(got_d, got_p):
            np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# prefix sharing
# ---------------------------------------------------------------------------
class TestPrefixSharing:
    def test_shared_system_prompt_stored_once_token_exact(self):
        """Three requests share a 2-page system prompt: tokens match the
        sharing-disabled engine exactly, prefix_hit_tokens counts the
        skipped prefill, live sharers hold the SAME physical pages
        (sub-linear pool growth), and finish releases refcounts down to
        the index-retained prefix."""
        scope_a, _ = _init_lm_scope(7)
        scope_b, _ = _init_lm_scope(7)
        rng = np.random.RandomState(4)
        ps = 8
        sys_prompt = rng.randint(0, VOCAB, (2 * ps,)).astype("int64")
        tails = [rng.randint(0, VOCAB, (n,)).astype("int64")
                 for n in (3, 5, 7)]
        prompts = [np.concatenate([sys_prompt, t]) for t in tails]
        plain = GenerationEngine(_spec(), scope_a, slots=4, page_size=ps,
                                 prefix_sharing=False,
                                 prompt_buckets=(8, 16, 32))
        shared = GenerationEngine(_spec(), scope_b, slots=4, page_size=ps,
                                  prompt_buckets=(8, 16, 32))
        ref = plain.generate_all(prompts, max_new_tokens=4)
        assert plain.metrics.counter("prefix_hit_tokens") == 0

        # first request populates the index...
        got0 = shared.generate_all([prompts[0]], max_new_tokens=4)
        np.testing.assert_array_equal(got0[0], ref[0])
        assert shared.metrics.counter("prefix_hit_tokens") == 0
        base_pages = shared.pool.pages_in_use()
        # ...the next two (admitted TOGETHER) share its system pages
        got12 = shared.generate_all(prompts[1:], max_new_tokens=4)
        np.testing.assert_array_equal(got12[0], ref[1])
        np.testing.assert_array_equal(got12[1], ref[2])
        assert shared.metrics.counter("prefix_hit_tokens") == 2 * 2 * ps
        assert shared.metrics.counter("prefix_hits") == 2
        # stored once: two extra sequences of 3 pages each grew the pool
        # by their UNSHARED pages only
        peak = shared.metrics.snapshot()["gauges"]["mem/kv_pages_in_use"]
        assert peak <= base_pages + 2 * 2  # tail page + one gen page each
        # refcounted release: only index-held prefix pages stay resident
        assert shared.pool.pages_in_use() == len(shared.prefix_index)
        assert shared.pool.stats()["shared"] == 0  # no live sharers left

    def test_full_prompt_hit_takes_copy_on_write(self):
        """A repeated IDENTICAL prompt full-hits the prefix cache: zero
        prefill tokens, identical output, and the first generated token
        triggers exactly the copy-on-write path (the shared tail page is
        about to be written) — pinned via kv_cow_copies and the cached
        page's survival for a THIRD identical request."""
        scope, _ = _init_lm_scope(7)
        rng = np.random.RandomState(6)
        prompt = rng.randint(0, VOCAB, (11,)).astype("int64")  # 1.375 pages
        eng = GenerationEngine(_spec(), scope, slots=2, page_size=8,
                               prompt_buckets=(8, 16))
        first = eng.generate_all([prompt], max_new_tokens=4)[0]
        assert eng.metrics.counter("kv_cow_copies") == 0
        prefills0 = eng.metrics.counter("prefills")
        second = eng.generate_all([prompt], max_new_tokens=4)[0]
        np.testing.assert_array_equal(second, first)
        # full hit: the whole prompt was served from cached pages
        assert eng.metrics.counter("prefix_hit_tokens") == prompt.size
        assert eng.metrics.counter("prefills") == prefills0  # none ran
        assert eng.metrics.counter("kv_cow_copies") >= 1
        third = eng.generate_all([prompt], max_new_tokens=4)[0]
        np.testing.assert_array_equal(third, first)
        assert eng.metrics.counter("prefix_hit_tokens") == 2 * prompt.size

    @pytest.mark.slow
    def test_swap_params_invalidates_prefix_cache(self):
        """Rolling weight updates drop cached prefixes — K/V computed
        with the old weights must never serve the new ones."""
        scope, _ = _init_lm_scope(7)
        eng = GenerationEngine(_spec(), scope, slots=2, page_size=8)
        prompt = np.arange(10, dtype=np.int64) % VOCAB
        eng.generate_all([prompt], max_new_tokens=3)
        assert len(eng.prefix_index) > 0
        eng.swap_params(_init_lm_scope(8)[0])
        assert len(eng.prefix_index) == 0
        assert eng.pool.pages_in_use() == 0


# ---------------------------------------------------------------------------
# chunked prefill
# ---------------------------------------------------------------------------
class TestChunkedPrefill:
    def test_decode_ticks_interleave_with_long_prefill(self):
        """THE fairness pin: while a near-Tmax prompt prefills, the
        in-flight stream keeps emitting a token per tick — per-tick
        prefill work is bounded by prefill_chunk, so decode latency
        cannot spike by a whole-prompt prefill."""
        scope, exe = _init_lm_scope(7)
        rng = np.random.RandomState(9)
        short = rng.randint(0, VOCAB, (6,)).astype("int64")
        long_p = rng.randint(0, VOCAB, (48,)).astype("int64")  # 6 chunks
        ref_short = _reference_decode(scope, exe, short[None], 10)[0]
        ref_long = _reference_decode(scope, exe, long_p[None], 4)[0]
        eng = GenerationEngine(_spec(), scope, slots=2, page_size=8,
                               prefill_chunk=8, prompt_buckets=(8, 16))
        r_short = Request({"prompt": short}, {"max_new_tokens": 10}, None)
        r_long = Request({"prompt": long_p}, {"max_new_tokens": 4}, None)
        eng.admit([r_short])
        eng.decode_tick()
        eng.admit([r_long])  # enters the chunked-prefill state
        short_progress = []
        while eng.prefilling:  # the long prompt is streaming in
            eng.prefill_tick()
            eng.decode_tick()
            st = eng._slots[[i for i in range(eng.slots)
                             if eng._slots[i] is not None
                             and eng._slots[i].state == "decode"][0]]
            short_progress.append(len(st.generated))
        # every interleaved tick advanced the short stream by one token
        assert short_progress == sorted(short_progress)
        assert len(short_progress) >= 5  # 48/8 = 6 chunks ran
        assert short_progress[-1] > short_progress[0]
        while eng.active:
            eng.prefill_tick()
            eng.decode_tick()
        np.testing.assert_array_equal(r_short.future.result(1), ref_short)
        np.testing.assert_array_equal(r_long.future.result(1), ref_long)
        # per-chunk latency is the bounded unit of prefill work
        snap = eng.metrics.snapshot()
        assert snap["counters"]["prefill_chunks"] == 6
        assert "prefill_chunk_ms" in snap["latency"]


# ---------------------------------------------------------------------------
# pool backpressure
# ---------------------------------------------------------------------------
class TestBackpressure:
    def test_request_that_can_never_fit_fails_typed(self):
        scope, _ = _init_lm_scope(7)
        eng = GenerationEngine(_spec(), scope, slots=2, page_size=8,
                               n_pages=3, prompt_buckets=(8, 16, 32))
        big = Request({"prompt": np.arange(30, dtype=np.int64) % VOCAB},
                      {"max_new_tokens": 4}, None)  # needs 5 of 2 pages
        assert eng.admit([big]) == 0
        with pytest.raises(CacheExhaustedError) as ei:
            big.future.result(timeout=1)
        assert ei.value.pages_needed == 5 and ei.value.pages_free == 2
        assert eng.free_slots == 2  # no slot leaked
        # a fitting request still serves
        small = eng.generate_all([np.arange(6, dtype=np.int64)],
                                 max_new_tokens=2)
        assert small[0].size == 8

    @pytest.mark.slow
    def test_transient_pressure_defers_not_fails(self):
        """Two requests that EACH fit but not TOGETHER: the second is
        deferred until the first finishes — backpressure, not a
        mid-decode failure."""
        scope, _ = _init_lm_scope(7)
        eng = GenerationEngine(_spec(), scope, slots=2, page_size=8,
                               n_pages=3, prefix_sharing=False,
                               prompt_buckets=(8, 16))
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, VOCAB, (10,)).astype("int64")
                   for _ in range(2)]  # 2 pages each, pool holds 2
        got = eng.generate_all(prompts, max_new_tokens=4)
        assert all(g.size == 14 for g in got)
        assert eng.metrics.counter("admission_deferred") >= 1
        assert eng.metrics.counter("cache_exhausted") == 0
        assert eng.pool.pages_in_use() == 0

    def test_deferred_surfaces_through_serve_step(self):
        """The server path: pool-blocked requests wait in the engine's
        deferred line while serve_step keeps decode moving; everyone
        completes once pages free up."""
        scope, _ = _init_lm_scope(7)
        eng = GenerationEngine(_spec(), scope, slots=3, page_size=8,
                               n_pages=3, prefix_sharing=False,
                               prompt_buckets=(8, 16))
        batcher = DynamicBatcher(buckets=(1, 2, 4), max_wait_ms=1)
        rng = np.random.RandomState(5)
        futs = [batcher.submit(
            {"prompt": rng.randint(0, VOCAB, (9,)).astype("int64")},
            max_new_tokens=3) for _ in range(3)]
        for _ in range(200):
            eng.serve_step(batcher, idle_wait_s=0)
            if all(f.done() for f in futs):
                break
        for f in futs:
            assert f.result(timeout=1).size == 12
        assert eng.metrics.counter("admission_deferred") >= 1


# ---------------------------------------------------------------------------
# compile-cache steady state
# ---------------------------------------------------------------------------
class TestZeroRecompile:
    @pytest.mark.slow
    def test_paged_zero_recompiles_incl_chunked_shared_cow(self):
        """Warmup covers every paged shape — chunk widths x batch
        buckets, decode, AND the copy-on-write page copy — so a workload
        exercising chunked prefill, prefix hits, and COW adds zero
        compile-cache misses."""
        scope, _ = _init_lm_scope(7)
        eng = GenerationEngine(_spec(), scope, slots=4, page_size=8,
                               prefill_chunk=16, prompt_buckets=(8, 16),
                               prefill_batch_buckets=(1, 2, 4))
        eng.warmup()
        misses0 = eng.cache_stats()["misses"]
        rng = np.random.RandomState(31)
        prompts = [rng.randint(0, VOCAB, (rng.randint(2, 15),))
                   .astype("int64") for _ in range(8)]
        prompts.append(rng.randint(0, VOCAB, (40,)).astype("int64"))
        got = eng.generate_all(prompts, max_new_tokens=5)
        # the chunked long prompt decodes token-exact (vs the one-shot
        # reference) straight off the streaming-prefill pages
        ref = _reference_decode(scope, _init_lm_scope(7)[1],
                                prompts[-1][None], 5)[0]
        np.testing.assert_array_equal(got[-1], ref)
        eng.generate_all([prompts[0]], max_new_tokens=5)  # full hit + COW
        stats = eng.cache_stats()
        assert stats["misses"] == misses0, stats
        assert stats["hits"] > 0
        assert eng.metrics.counter("prefill_chunks") >= 3
        assert eng.metrics.counter("kv_cow_copies") >= 1
        assert eng.metrics.counter("prefix_hit_tokens") > 0

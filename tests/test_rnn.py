"""RNN tests: scan-based LSTM/GRU vs explicit numpy recurrences + an
end-to-end sentiment-style training smoke (embedding -> lstm -> pool -> fc),
mirroring the reference book test understand_sentiment
(/root/reference/python/paddle/v2/fluid/tests/book/
test_understand_sentiment_lstm.py)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core.registry import get_op


def sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def np_lstm(x, w, bias, lengths, h0=None, c0=None):
    """x [b,T,4h] pre-projected; gate order (c, i, f, o) per lstm_op.cc."""
    b, T, four_h = x.shape
    h_dim = four_h // 4
    h = np.zeros((b, h_dim), np.float32) if h0 is None else h0
    c = np.zeros((b, h_dim), np.float32) if c0 is None else c0
    hs = np.zeros((b, T, h_dim), np.float32)
    cs = np.zeros((b, T, h_dim), np.float32)
    for t in range(T):
        gates = x[:, t] + h @ w + bias
        gc, gi, gf, go = np.split(gates, 4, axis=-1)
        i, f, o = sigmoid(gi), sigmoid(gf), sigmoid(go)
        c_new = f * c + i * np.tanh(gc)
        h_new = o * np.tanh(c_new)
        alive = (t < lengths)[:, None]
        h = np.where(alive, h_new, h)
        c = np.where(alive, c_new, c)
        hs[:, t] = np.where(alive, h_new, 0)
        cs[:, t] = np.where(alive, c_new, 0)
    return hs, cs, h, c


def np_gru(x, w, bias, lengths):
    """x [b,T,3h]; w [:, :2h] = (update, reset), [:, 2h:] = candidate."""
    b, T, three_h = x.shape
    h_dim = three_h // 3
    h = np.zeros((b, h_dim), np.float32)
    hs = np.zeros((b, T, h_dim), np.float32)
    wg, wc = w[:, : 2 * h_dim], w[:, 2 * h_dim:]
    for t in range(T):
        xt = x[:, t] + bias
        xg, xc = xt[:, : 2 * h_dim], xt[:, 2 * h_dim:]
        g = sigmoid(xg + h @ wg)
        u, r = g[:, :h_dim], g[:, h_dim:]
        cand = np.tanh(xc + (r * h) @ wc)
        h_new = (1 - u) * h + u * cand  # gru_op.cc:142
        alive = (t < lengths)[:, None]
        h = np.where(alive, h_new, h)
        hs[:, t] = np.where(alive, h_new, 0)
    return hs, h


def run_op(op_type, ins, attrs=None):
    import jax.numpy as jnp
    ins = {k: [jnp.asarray(a) for a in v] for k, v in ins.items()}
    return get_op(op_type).fn(attrs or {}, ins)


class TestLSTMOp:
    def setup_method(self, _):
        rng = np.random.RandomState(0)
        self.b, self.T, self.h = 3, 6, 4
        self.x = rng.randn(self.b, self.T, 4 * self.h).astype(np.float32) * 0.5
        self.w = rng.randn(self.h, 4 * self.h).astype(np.float32) * 0.3
        self.bias = rng.randn(1, 4 * self.h).astype(np.float32) * 0.1
        self.lengths = np.array([6, 3, 5], np.int32)

    def test_matches_numpy(self):
        outs = run_op("lstm", {"Input": [self.x], "Weight": [self.w],
                               "Bias": [self.bias], "Length": [self.lengths]})
        hs, cs, h, c = np_lstm(self.x, self.w, self.bias[0], self.lengths)
        np.testing.assert_allclose(np.asarray(outs["Hidden"][0]), hs,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(outs["Cell"][0]), cs,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(outs["LastH"][0]), h,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(outs["LastC"][0]), c,
                                   rtol=1e-4, atol=1e-5)

    def test_reverse_full_lengths(self):
        full = np.full((self.b,), self.T, np.int32)
        outs = run_op("lstm", {"Input": [self.x], "Weight": [self.w],
                               "Bias": [self.bias], "Length": [full]},
                      {"is_reverse": True})
        hs_rev, _, _, _ = np_lstm(self.x[:, ::-1], self.w, self.bias[0], full)
        np.testing.assert_allclose(np.asarray(outs["Hidden"][0]),
                                   hs_rev[:, ::-1], rtol=1e-4, atol=1e-5)

    def test_lstm_unit(self):
        rng = np.random.RandomState(2)
        gates = rng.randn(2, 4 * self.h).astype(np.float32)
        c_prev = rng.randn(2, self.h).astype(np.float32)
        outs = run_op("lstm_unit", {"X": [gates], "C_prev": [c_prev]})
        # reference gate layout (i, f, o, g): lstm_unit_op.h:63-66
        gi, gf, go, gc = np.split(gates, 4, axis=-1)
        c = sigmoid(gf) * c_prev + sigmoid(gi) * np.tanh(gc)
        h = sigmoid(go) * np.tanh(c)
        np.testing.assert_allclose(np.asarray(outs["C"][0]), c, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(outs["H"][0]), h, rtol=1e-5)


class TestGRUOp:
    def test_matches_numpy(self):
        rng = np.random.RandomState(1)
        b, T, h = 3, 5, 4
        x = rng.randn(b, T, 3 * h).astype(np.float32) * 0.5
        w = rng.randn(h, 3 * h).astype(np.float32) * 0.3
        bias = rng.randn(1, 3 * h).astype(np.float32) * 0.1
        lengths = np.array([5, 2, 4], np.int32)
        outs = run_op("gru", {"Input": [x], "Weight": [w], "Bias": [bias],
                              "Length": [lengths]})
        hs, hlast = np_gru(x, w, bias[0], lengths)
        np.testing.assert_allclose(np.asarray(outs["Hidden"][0]), hs,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(outs["LastH"][0]), hlast,
                                   rtol=1e-4, atol=1e-5)

    def test_gru_unit(self):
        rng = np.random.RandomState(3)
        b, h = 2, 4
        xt = rng.randn(b, 3 * h).astype(np.float32)
        hp = rng.randn(b, h).astype(np.float32)
        w = rng.randn(h, 3 * h).astype(np.float32) * 0.3
        outs = run_op("gru_unit",
                      {"Input": [xt], "HiddenPrev": [hp], "Weight": [w]})
        g = sigmoid(xt[:, : 2 * h] + hp @ w[:, : 2 * h])
        u, r = g[:, :h], g[:, h:]
        cand = np.tanh(xt[:, 2 * h:] + (r * hp) @ w[:, 2 * h:])
        ref = (1 - u) * hp + u * cand
        np.testing.assert_allclose(np.asarray(outs["Hidden"][0]), ref,
                                   rtol=1e-5, atol=1e-6)


class TestSentimentTraining:
    def test_lstm_classifier_learns(self):
        """Tiny understand_sentiment: label = (first word id < vocab/2)."""
        vocab, emb_dim, hid = 20, 8, 8
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            words = layers.data("words", shape=[1], dtype="int64", lod_level=1)
            label = layers.data("label", shape=[1], dtype="int64")
            emb = layers.embedding(words, size=[vocab, emb_dim])
            emb.seq_len = words.seq_len
            proj = layers.fc(emb, size=4 * hid, num_flatten_dims=2,
                             bias_attr=False)
            h_seq, _ = layers.dynamic_lstm(proj, size=4 * hid)
            pooled = layers.sequence_pool(h_seq, "max")
            logits = layers.fc(pooled, size=2)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, label))
            opt = pt.optimizer.AdamOptimizer(learning_rate=0.05)
            opt.minimize(loss, startup_program=startup)

        scope = pt.Scope()
        exe = pt.Executor(pt.TPUPlace())
        exe.run(startup, scope=scope)

        rng = np.random.RandomState(0)
        b, T = 16, 7
        losses = []
        for step in range(30):
            lengths = rng.randint(1, T + 1, size=b).astype(np.int32)
            ids = rng.randint(0, vocab, size=(b, T)).astype(np.int64)
            y = (ids[:, 0] < vocab // 2).astype(np.int64)[:, None]
            (lo,) = exe.run(main, feed={"words": ids, "words@len": lengths,
                                        "label": y},
                            fetch_list=[loss], scope=scope)
            losses.append(float(lo))
        assert losses[-1] < losses[0] * 0.5, losses

"""The stacked (scan/pipeline) transformer and the per-layer encoder path
are two implementations of the same block; with identical weights they
must produce identical logits. Guards the pair against silent drift."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models

VOCAB, D, L, H, T, FF = 32, 16, 3, 2, 12, 64


def _build(pipeline_stack):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        ids = layers.data("ids", shape=[T], dtype="int64")
        logits = models.transformer_lm(ids, vocab_size=VOCAB, d_model=D,
                                       n_layers=L, num_heads=H, d_ff=FF,
                                       max_len=T,
                                       pipeline_stack=pipeline_stack)
    return main, startup, logits


@pytest.mark.slow  # tier-1 budget (PR 20): full stacked-vs-per-layer
# parity sweep; stack correctness stays tier-1 via test_attention and
# the sharded-stack tests
def test_stacked_matches_per_layer_with_copied_weights():
    exe = pt.Executor(pt.TPUPlace())

    # per-layer model: initialize, then read its weights in creation order
    scope_a = pt.Scope()
    main_a, startup_a, logits_a = _build(False)
    exe.run(startup_a, scope=scope_a)
    params_a = [p.name for p in main_a.global_block.all_parameters()]

    def val(name):
        return np.asarray(scope_a.get(name))

    # creation order per encoder layer: ln1 s/b, qkv w, out w, ln2 s/b,
    # ff w1, ff b1, ff w2, ff b2 — then the final ln s/b and head w.
    per_layer = [n for n in params_a if n not in ("tok_emb", "pos_emb")]
    assert len(per_layer) == L * 10 + 3, per_layer
    stack_vals = {k: [] for k in ("ln1_s", "ln1_b", "qkv_w", "out_w",
                                  "ln2_s", "ln2_b", "ff_w1", "ff_b1",
                                  "ff_w2", "ff_b2")}
    order = ["ln1_s", "ln1_b", "qkv_w", "out_w", "ln2_s", "ln2_b",
             "ff_w1", "ff_b1", "ff_w2", "ff_b2"]
    for i in range(L):
        chunk = per_layer[i * 10:(i + 1) * 10]
        for key, name in zip(order, chunk):
            stack_vals[key].append(val(name))
    fin_s, fin_b, head_w = per_layer[-3:]

    # stacked model in a fresh scope; overwrite its weights with A's
    scope_b = pt.Scope()
    main_b, startup_b, logits_b = _build(True)
    exe.run(startup_b, scope=scope_b)
    for key in order:
        stacked = np.stack(stack_vals[key], axis=0)
        name = f"lm_stack.stack_{key}"
        assert np.asarray(scope_b.get(name)).shape == stacked.shape, \
            (name, stacked.shape, np.asarray(scope_b.get(name)).shape)
        scope_b.set(name, stacked)
    scope_b.set("tok_emb", val("tok_emb"))
    scope_b.set("pos_emb", val("pos_emb"))
    scope_b.set("final_ln.scale", val(fin_s))
    scope_b.set("final_ln.bias", val(fin_b))
    scope_b.set("lm_head.w", val(head_w))

    rng = np.random.RandomState(0)
    ids = rng.randint(0, VOCAB, (4, T)).astype("int64")
    out_a, = exe.run(main_a, feed={"ids": ids}, fetch_list=[logits_a],
                     scope=scope_a)
    out_b, = exe.run(main_b, feed={"ids": ids}, fetch_list=[logits_b],
                     scope=scope_b)
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(out_a),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow  # tier-1 budget: "dots" remat numerics also pinned by test_norm_grads per-layer remat
def test_stack_remat_policies_match_numerically():
    """remat=False / True / "dots" (selective save-dots policy) are pure
    memory-schedule choices — identical losses through training steps."""
    def run(remat):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            ids = layers.data("ids", shape=[T], dtype="int64")
            logits = models.transformer_lm(
                ids, vocab_size=VOCAB, d_model=D, n_layers=L,
                num_heads=H, d_ff=FF, max_len=T, pipeline_stack=True,
                remat=remat)
            nxt = layers.data("nxt", shape=[T], dtype="int64")
            loss = layers.mean(
                layers.softmax_with_cross_entropy(
                    logits, layers.reshape(nxt, shape=[0, T, 1])))
            pt.optimizer.AdamOptimizer(learning_rate=1e-2).minimize(
                loss, startup_program=startup)
        main.random_seed = startup.random_seed = 13
        scope = pt.Scope()
        exe = pt.Executor(pt.TPUPlace())
        exe.run(startup, scope=scope)
        rng = np.random.RandomState(0)
        ids_v = rng.randint(0, VOCAB, size=(2, T)).astype("int64")
        feed = {"ids": ids_v, "nxt": np.roll(ids_v, -1, 1)}
        return [float(np.asarray(exe.run(main, feed=feed,
                                         fetch_list=[loss],
                                         scope=scope)[0]))
                for _ in range(4)]

    plain = run(False)
    full = run(True)
    dots = run("dots")
    assert np.isfinite(plain).all()
    np.testing.assert_allclose(full, plain, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(dots, plain, rtol=1e-5, atol=1e-6)
    assert plain[-1] < plain[0]

"""paddle_tpu.image preprocessing utilities (reference v2/image.py API)."""
import io
import os
import tarfile

import numpy as np
import pytest

from paddle_tpu import image


def _png_bytes(arr):
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return buf.getvalue()


@pytest.fixture
def img():
    rng = np.random.RandomState(0)
    return rng.randint(0, 255, size=(48, 64, 3), dtype=np.uint8)


def test_load_roundtrip(tmp_path, img):
    p = str(tmp_path / "x.png")
    with open(p, "wb") as f:
        f.write(_png_bytes(img))
    got = image.load_image(p)
    np.testing.assert_array_equal(got, img)
    gray = image.load_image(p, is_color=False)
    assert gray.ndim == 2 and gray.shape == (48, 64)
    np.testing.assert_array_equal(image.load_image_bytes(_png_bytes(img)),
                                  img)


def test_resize_short_keeps_aspect(img):
    out = image.resize_short(img, 24)  # shorter edge 48 -> 24
    assert out.shape[:2] == (24, 32)
    tall = image.resize_short(img.transpose(1, 0, 2), 24)
    assert tall.shape[:2] == (32, 24)


def test_crops_and_flip(img):
    c = image.center_crop(img, 32)
    assert c.shape == (32, 32, 3)
    np.testing.assert_array_equal(c, img[8:40, 16:48])
    r = image.random_crop(img, 32, rng=np.random.RandomState(3))
    assert r.shape == (32, 32, 3)
    np.testing.assert_array_equal(image.left_right_flip(img),
                                  img[:, ::-1])
    with pytest.raises(ValueError):
        image.center_crop(img, 100)


def test_to_chw(img):
    chw = image.to_chw(img)
    assert chw.shape == (3, 48, 64)
    gray = image.to_chw(img[:, :, 0])
    assert gray.shape == (1, 48, 64)


def test_simple_transform_train_eval(img):
    ev = image.simple_transform(img, 32, 24, is_train=False,
                                mean=np.array([1.0, 2.0, 3.0]))
    assert ev.shape == (3, 24, 24) and ev.dtype == np.float32
    tr = image.simple_transform(img, 32, 24, is_train=True,
                                rng=np.random.RandomState(0))
    assert tr.shape == (3, 24, 24)


def test_batch_images_from_tar(tmp_path, img):
    tar_path = str(tmp_path / "imgs.tar")
    with tarfile.open(tar_path, "w") as tf:
        for i in range(5):
            b = _png_bytes(img)
            info = tarfile.TarInfo(name=f"img_{i}.png")
            info.size = len(b)
            tf.addfile(info, io.BytesIO(b))
    labels = {f"img_{i}.png": i % 3 for i in range(5)}
    out = image.batch_images_from_tar(tar_path, "t", labels,
                                      num_per_batch=2)
    names = open(os.path.join(out, "batch_names.txt")).read().split()
    assert len(names) == 3  # 2 + 2 + 1
    import pickle

    first = pickle.load(open(os.path.join(out, names[0]), "rb"))
    assert len(first["data"]) == 2 and first["label"] == [0, 1]
    got = image.load_image_bytes(first["data"][0])
    np.testing.assert_array_equal(got, img)

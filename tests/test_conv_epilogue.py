"""Fused conv1x1+BN+act(+residual) epilogue (ops/fusion_ops.py,
kernels/conv_epilogue.py): numerical parity against the separate
conv2d -> batch_norm -> elementwise_add -> relu ops, forward AND through
training steps (the backward is the hand-written XLA chain), plus the
kernel-level pallas interpret-mode checks."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _train_net(fused, is_test=False, residual=True, steps=3, lr=0.1):
    pt.flags.FLAGS.fused_conv_epilogue = fused
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[4, 4, 6])
        if fused:
            y = layers.conv1x1_bn_act(
                x, 8, act="relu", is_test=is_test,
                residual=layers.conv1x1_bn_act(x, 8, act=None,
                                               is_test=is_test)
                if residual else None)
        else:
            def cbn(inp, act):
                c = layers.conv2d(inp, num_filters=8, filter_size=1,
                                  bias_attr=False, data_format="NHWC")
                return layers.batch_norm(c, act=act, is_test=is_test,
                                         data_layout="NHWC")

            # residual branch FIRST: parameter creation order must match
            # the fused build (kwargs evaluate before the call) so the
            # same startup seed draws identical inits
            r = cbn(x, None) if residual else None
            y = cbn(x, None)
            if residual:
                y = layers.elementwise_add(y, r)
            y = layers.relu(y)
        loss = layers.mean(y * y)
        if not is_test:
            pt.optimizer.SGDOptimizer(learning_rate=lr).minimize(
                loss, startup_program=startup)
    main.random_seed = startup.random_seed = 7
    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(2, 4, 4, 6).astype("float32")}
    vals = [float(np.asarray(exe.run(main, feed=feed, fetch_list=[loss],
                                     scope=scope)[0]))
            for _ in range(steps)]
    return vals, scope


def test_fused_forward_matches_unfused_training_mode():
    """Same seeds -> identical parameter init; the fused op must produce
    the same loss trajectory (fwd + bwd + BN running-stat updates) as
    the separate ops."""
    try:
        fused, s1 = _train_net(fused=True)
    finally:
        pt.flags.FLAGS.fused_conv_epilogue = False
    plain, s2 = _train_net(fused=False)
    assert np.isfinite(fused).all() and np.isfinite(plain).all()
    np.testing.assert_allclose(fused, plain, rtol=2e-4, atol=2e-5)
    assert fused[-1] < fused[0]  # it trains


def test_fused_inference_mode_matches():
    try:
        fused, _ = _train_net(fused=True, is_test=True, steps=1)
    finally:
        pt.flags.FLAGS.fused_conv_epilogue = False
    plain, _ = _train_net(fused=False, is_test=True, steps=1)
    np.testing.assert_allclose(fused, plain, rtol=2e-4, atol=2e-5)


def test_fused_without_residual_matches():
    try:
        fused, _ = _train_net(fused=True, residual=False)
    finally:
        pt.flags.FLAGS.fused_conv_epilogue = False
    plain, _ = _train_net(fused=False, residual=False)
    np.testing.assert_allclose(fused, plain, rtol=2e-4, atol=2e-5)


@pytest.mark.slow  # tier-1 budget: EXPERIMENTAL flag path awaiting its chip A/B
def test_resnet_block_under_flag_trains():
    """A bottleneck stack builds with the fused ops and its loss
    decreases; the program actually contains conv1x1_bn_act ops."""
    from paddle_tpu import models

    pt.flags.FLAGS.fused_conv_epilogue = True
    try:
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            img = layers.data("img", shape=[8, 8, 3])
            lbl = layers.data("lbl", shape=[1], dtype="int64")
            logits = models.resnet_imagenet(img, num_classes=5, depth=50)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, lbl))
            pt.optimizer.MomentumOptimizer(
                learning_rate=0.05, momentum=0.9).minimize(
                loss, startup_program=startup)
        types = {op.type for op in main.global_block.ops}
        assert "conv1x1_bn_act" in types
    finally:
        pt.flags.FLAGS.fused_conv_epilogue = False
    main.random_seed = startup.random_seed = 11
    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(1)
    feed = {"img": rng.rand(4, 8, 8, 3).astype("float32"),
            "lbl": rng.randint(0, 5, size=(4, 1)).astype("int64")}
    vals = [float(np.asarray(exe.run(main, feed=feed, fetch_list=[loss],
                                     scope=scope)[0]))
            for _ in range(6)]
    assert np.isfinite(vals).all()
    assert vals[-1] < vals[0], vals


def test_kernels_interpret_mode_parity():
    """kernels/conv_epilogue.py pallas paths (interpret mode) vs jnp."""
    import jax.numpy as jnp

    from paddle_tpu.kernels import conv_epilogue as ke

    rng = np.random.RandomState(3)
    R, I, O = 512, 128, 128  # tiles at block_r >= 128
    x = jnp.asarray(rng.randn(R, I).astype(np.float32))
    w = jnp.asarray(rng.randn(I, O).astype(np.float32) * 0.1)
    res = jnp.asarray(rng.randn(R, O).astype(np.float32))
    scale = jnp.asarray(rng.rand(O).astype(np.float32) + 0.5)
    shift = jnp.asarray(rng.randn(O).astype(np.float32))

    y_ref = x @ w
    y_raw, stats = ke.conv1x1_stats(x, w, interpret=True)
    np.testing.assert_allclose(np.asarray(y_raw), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(np.asarray(stats[0]),
                               np.asarray(y_ref.sum(0)), rtol=1e-4,
                               atol=1e-2)
    np.testing.assert_allclose(np.asarray(stats[1]),
                               np.asarray((y_ref * y_ref).sum(0)),
                               rtol=1e-4, atol=1e-1)

    full = ke.conv1x1_epilogue(x, w, scale, shift, residual=res,
                               act="relu", interpret=True)
    want = np.maximum(np.asarray(y_ref) * np.asarray(scale)
                      + np.asarray(shift) + np.asarray(res), 0.0)
    np.testing.assert_allclose(np.asarray(full), want, rtol=1e-5,
                               atol=1e-4)

    app = ke.scale_shift_act(y_raw, scale, shift, residual=res,
                             act="relu", interpret=True)
    np.testing.assert_allclose(np.asarray(app), want, rtol=1e-5,
                               atol=1e-4)


def test_kernel_fallback_on_untileable_shapes():
    """R not a multiple of 128 -> the XLA fallback path, same numbers."""
    import jax.numpy as jnp

    from paddle_tpu.kernels import conv_epilogue as ke

    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(100, 32).astype(np.float32))
    w = jnp.asarray(rng.randn(32, 16).astype(np.float32))
    y_raw, stats = ke.conv1x1_stats(x, w, interpret=True)
    np.testing.assert_allclose(np.asarray(y_raw), np.asarray(x @ w),
                               rtol=1e-5, atol=1e-4)


def test_fused_op_under_dp_mesh_matches_single_device():
    """The fused op must compose with GSPMD: a dp-sharded mesh run is
    numerically identical (up to reduction order) to single-device —
    the Pallas kernels fall back to the XLA composition on the CPU mesh,
    but the op boundary, BN stats, and running-stat writebacks all ride
    the sharded executor path the driver's dryrun exercises."""
    import jax

    from paddle_tpu.parallel import data_parallel_plan, make_mesh

    pt.flags.FLAGS.fused_conv_epilogue = True
    try:
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", shape=[4, 4, 6])
            lbl = layers.data("lbl", shape=[1], dtype="int64")
            y = layers.conv1x1_bn_act(
                x, 8, act="relu",
                residual=layers.conv1x1_bn_act(x, 8, act=None))
            pooled = layers.pool2d(y, pool_size=4, pool_stride=4,
                                   data_format="NHWC")
            logits = layers.fc(layers.reshape(pooled, shape=[-1, 8]),
                               size=3)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, lbl))
            pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(
                loss, startup_program=startup)
    finally:
        pt.flags.FLAGS.fused_conv_epilogue = False
    main.random_seed = startup.random_seed = 17
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(16, 4, 4, 6).astype("float32"),
            "lbl": rng.randint(0, 3, (16, 1)).astype("int64")}

    def run(exe):
        scope = pt.Scope()
        exe.run(startup, scope=scope)
        return [float(np.asarray(exe.run(main, feed=feed,
                                         fetch_list=[loss],
                                         scope=scope)[0]))
                for _ in range(4)]

    ref = run(pt.Executor(pt.TPUPlace()))
    mesh = make_mesh({"dp": 8})
    got = run(pt.Executor(mesh=mesh, plan=data_parallel_plan(mesh)))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-6)
    assert ref[-1] < ref[0]

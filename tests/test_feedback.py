"""paddle_tpu.feedback — the serve->log->join->train->publish loop (PR 17).

Pins: the crash-safe impression log (bounded buffer, torn-tail
walk-back), the windowed outcome joiner's exactly-once example
contract under every edge case (duplicate outcome first-wins,
outcome-before-impression parked, window-expiry negatives, restart
with a discarded open tail), the compactor's drained-queue + durable
manifest exactly-once feed, the SparseLifecycle deterministic re-init
pin, the capacity-bounded a2a embedding exchange (bitwise vs gather,
in-graph overflow fallback), the movielens varlen CTR path, and THE
acceptance pin: a live 2-replica fleet serves, outcomes post back over
HTTP, a StreamingTrainer trains on EXACTLY the logged traffic, the
Publisher rolls a generation back into the fleet token-exact with zero
failed requests — plus the chaos leg (joiner killed mid-window + torn
log tail: bounded, counted loss; never a duplicated training example).

Tier-1 budget: the CTR builder is shared; redundant HTTP-surface
variants are ``@pytest.mark.slow``.
"""
import itertools
import json
import os
import struct
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, dataset, io
from paddle_tpu.feedback import (Compactor, FeedbackHook, ImpressionLog,
                                 OutcomeJoiner, read_records,
                                 sealed_segments, task_desc, task_reader)
from paddle_tpu.feedback.log import segment_meta

import jax.numpy as jnp

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VOCAB, SLOTS, DD = 512, dataset.ctr.SLOTS, dataset.ctr.DENSE_DIM


def _build_ctr(vocab=VOCAB, embed_dim=4, hidden=(8,), lr=0.05,
               optimizer="adagrad", seed=7):
    main, startup = pt.Program(), pt.Program()
    startup.random_seed = seed
    with pt.program_guard(main, startup):
        ids = layers.data("ids", shape=[SLOTS], dtype="int64")
        dense = layers.data("dense", shape=[DD])
        label = layers.data("label", shape=[1])
        logit = pt.models.wide_deep(ids, dense, vocab_size=vocab,
                                    embed_dim=embed_dim,
                                    hidden_sizes=hidden)
        loss, prob = pt.models.wide_deep_loss(logit, label)
        opt = (pt.optimizer.AdagradOptimizer(learning_rate=lr)
               if optimizer == "adagrad"
               else pt.optimizer.SGDOptimizer(learning_rate=lr))
        sgd = pt.trainer.SGD(loss, opt, [ids, dense, label],
                             scope=pt.Scope())
    return {"sgd": sgd, "main": main, "startup": startup, "loss": loss,
            "prob": prob}


class _Clock:
    """Deterministic time source for window/TTL tests."""

    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)


def _row(rng):
    return {"ids": rng.randint(0, VOCAB, size=SLOTS).astype(np.int64),
            "dense": rng.rand(DD).astype(np.float32)}


def _log_impressions(dirname, n, *, segment_records=8, clock=None,
                     rid_prefix="r", rng_seed=0):
    """n hook-shaped impressions through a real ImpressionLog; returns
    the rids in append order (the log's single writer preserves it)."""
    rng = np.random.RandomState(rng_seed)
    kw = {"segment_records": segment_records, "flush_s": 0.002}
    if clock is not None:
        kw["clock"] = clock
    log = ImpressionLog(str(dirname), **kw)
    hook = FeedbackHook(log, clock=clock or time.time)
    rids = []
    for i in range(n):
        rid = f"{rid_prefix}{i}"
        assert hook.on_served(rid, _row(rng), [float(i)])
        rids.append(rid)
    log.close()
    return rids


def _wait_logged(log, n, timeout=10.0):
    """The serving tap appends AFTER set_result — a waiter can race it,
    so tests settle the log before sealing."""
    deadline = time.monotonic() + timeout
    while log.stats()["logged"] < n and time.monotonic() < deadline:
        time.sleep(0.005)
    assert log.stats()["logged"] == n, log.stats()


def _sealed_examples(joined_dir):
    """Every example across every SEALED joined segment (the only ones
    the training plane can ever see)."""
    out = []
    for path in sealed_segments(str(joined_dir)):
        out.extend(rec for _, rec in read_records(path))
    return out


# ---------------------------------------------------------------------------
# impression log (unit)
# ---------------------------------------------------------------------------
class TestImpressionLog:
    def test_segments_seal_in_order(self, tmp_path):
        d = tmp_path / "log"
        rids = _log_impressions(d, 20, segment_records=8)
        paths = sealed_segments(str(d))
        # close() seals the 4-record remainder too
        assert [segment_meta(p)["records"] for p in paths] == [8, 8, 4]
        seen = [rec["rid"] for p in paths for _, rec in read_records(p)]
        assert seen == rids
        # features survive the numpy->json round trip in feed shape
        first = next(read_records(paths[0]))[1]
        assert len(first["features"]["ids"]) == SLOTS
        assert len(first["features"]["dense"]) == DD

    def test_bounded_buffer_sheds_and_counts(self, tmp_path):
        log = ImpressionLog(str(tmp_path / "log"), buffer_records=4096)
        try:
            # force the full-buffer branch deterministically
            log._buffer_records = 0
            assert log.append({"rid": "x"}) is False
            s = log.stats()
            assert s["dropped"] == 1 and s["logged"] == 0
        finally:
            log.close()

    def test_torn_tail_walk_back(self, tmp_path):
        """A crashed writer's .open tail: complete records are re-sealed
        (torn=True), the ragged tail bytes are counted and discarded."""
        d = tmp_path / "log"
        d.mkdir()
        rec = json.dumps({"rid": "ok", "t": 1.0}).encode()
        with open(d / "seg-000000.open", "wb") as fh:
            fh.write(struct.pack("<I", len(rec)))
            fh.write(rec)
            fh.write(struct.pack("<I", 999))   # length of a record...
            fh.write(b'{"rid": "to')           # ...that never landed
        log = ImpressionLog(str(d))
        try:
            s = log.stats()
            assert s["torn_recovered"] == 1
            assert s["torn_lost_bytes"] == 4 + 11
        finally:
            log.close()
        paths = sealed_segments(str(d))
        assert len(paths) == 1
        meta = segment_meta(paths[0])
        assert meta["torn"] is True and meta["lost_bytes"] == 15
        assert [r["rid"] for _, r in read_records(paths[0])] == ["ok"]


# ---------------------------------------------------------------------------
# outcome joiner edge cases (the satellite-4 matrix)
# ---------------------------------------------------------------------------
class TestOutcomeJoiner:
    def test_duplicate_outcome_first_wins(self, tmp_path):
        clk = _Clock()
        rids = _log_impressions(tmp_path / "log", 2, clock=clk)
        j = OutcomeJoiner(str(tmp_path / "log"), str(tmp_path / "joined"),
                          window_s=30.0, clock=clk)
        j.poll_once()
        assert j.post_outcome(rids[0], 1.0) == "joined"
        assert j.post_outcome(rids[0], 0.0) == "duplicate"
        assert j.stats()["duplicate_outcomes"] == 1
        clk.advance(31.0)
        j.poll_once()          # rids[1] expires negative
        j.seal()
        ex = {e["rid"]: e for e in _sealed_examples(tmp_path / "joined")}
        assert ex[rids[0]]["label"] == 1.0     # the FIRST outcome stuck
        assert ex[rids[1]]["label"] == 0.0
        assert len(ex) == 2

    def test_outcome_before_impression_parks_then_joins(self, tmp_path):
        clk = _Clock()
        rids = _log_impressions(tmp_path / "log", 1, clock=clk)
        j = OutcomeJoiner(str(tmp_path / "log"), str(tmp_path / "joined"),
                          window_s=30.0, clock=clk)
        # the outcome races ahead of the impression ingest (normal on a
        # busy HTTP plane)
        assert j.post_outcome(rids[0], {"label": 1.0,
                                        "dwell_ms": 840}) == "parked"
        j.poll_once()
        s = j.stats()
        assert s["joined"] == 1 and s["parked_joins"] == 1
        j.seal()
        (ex,) = _sealed_examples(tmp_path / "joined")
        assert ex["label"] == 1.0
        assert ex["outcome"] == {"dwell_ms": 840}   # extras ride along

    def test_window_expiry_emits_negatives(self, tmp_path):
        clk = _Clock()
        rids = _log_impressions(tmp_path / "log", 4, clock=clk)
        j = OutcomeJoiner(str(tmp_path / "log"), str(tmp_path / "joined"),
                          window_s=10.0, clock=clk)
        j.poll_once()
        assert j.stats()["pending"] == 4
        clk.advance(10.5)
        j.poll_once()
        assert j.stats()["expired_negatives"] == 4
        j.seal()
        ex = _sealed_examples(tmp_path / "joined")
        assert sorted(e["rid"] for e in ex) == sorted(rids)
        assert all(e["label"] == 0.0 and e["t_outcome"] is None
                   for e in ex)

    def test_parked_outcome_ttl_expires_as_orphan(self, tmp_path):
        clk = _Clock()
        j = OutcomeJoiner(str(tmp_path / "log"), str(tmp_path / "joined"),
                          window_s=10.0, park_ttl_s=5.0, clock=clk)
        assert j.post_outcome("never-served", 1.0) == "parked"
        clk.advance(6.0)
        j.poll_once()
        s = j.stats()
        assert s["orphan_outcomes"] == 1 and s["parked"] == 0

    def test_restart_replays_without_duplicates(self, tmp_path):
        """Kill/restart between polls: sealed coverage is honored, the
        open tail is discarded (counted) and its sources re-emit —
        every impression lands in EXACTLY one sealed example."""
        clk = _Clock()
        rids = _log_impressions(tmp_path / "log", 12, clock=clk)
        j1 = OutcomeJoiner(str(tmp_path / "log"),
                           str(tmp_path / "joined"), window_s=10.0,
                           segment_records=5, clock=clk)
        for rid in rids[:7]:
            assert j1.post_outcome(rid, 1.0) == "parked"
        j1.poll_once()   # 7 joins -> one sealed segment of 5, 2 open
        # j1 dies here: no seal(), its pending window evaporates
        j2 = OutcomeJoiner(str(tmp_path / "log"),
                           str(tmp_path / "joined"), window_s=10.0,
                           segment_records=5, clock=clk)
        assert j2.stats()["discarded_open_examples"] == 2
        j2.poll_once()
        # 5 covered by the sealed segment; 7 re-ingest (2 discarded
        # joins + 5 never-pended), all in the partially-covered segment
        # count as replays
        assert j2.stats()["pending"] == 7
        clk.advance(11.0)
        j2.poll_once()
        j2.seal()
        ex = _sealed_examples(tmp_path / "joined")
        assert sorted(e["rid"] for e in ex) == sorted(rids)   # no dupes
        assert len(ex) == 12
        # bounded, counted loss: the 2 discarded positives re-expired
        # as negatives
        assert sum(e["label"] for e in ex) == 5


# ---------------------------------------------------------------------------
# compactor / feeder (unit + master integration)
# ---------------------------------------------------------------------------
def _joined_segments(tmp_path, n, *, segment_records=4, rid_prefix="r"):
    clk = _Clock()
    _log_impressions(tmp_path / "log", n, clock=clk,
                     rid_prefix=rid_prefix)
    j = OutcomeJoiner(str(tmp_path / "log"), str(tmp_path / "joined"),
                      window_s=0.0, segment_records=segment_records,
                      clock=clk)
    j.poll_once()     # window 0: everything expires negative at once
    j.seal()
    return str(tmp_path / "joined")


class TestCompactor:
    def test_task_reader_replays_ctr_shaped_rows(self, tmp_path):
        joined = _joined_segments(tmp_path, 6, segment_records=3)
        (d0, d1) = [task_desc(p, segment_meta(p)["records"])
                    for p in sealed_segments(joined)]
        rows = list(task_reader(d0))
        assert len(rows) == 3
        ids, dense, label = rows[0]
        assert ids.dtype == np.int64 and ids.shape == (SLOTS,)
        assert dense.dtype == np.float32 and dense.shape == (DD,)
        assert label.shape == (1,)
        # a desc is self-sufficient: replay is exact (master
        # requeue-on-timeout depends on this)
        again = list(task_reader(d0))
        for (a, b, c), (x, y, z) in zip(rows, again):
            np.testing.assert_array_equal(a, x)
            np.testing.assert_array_equal(b, y)
            np.testing.assert_array_equal(c, z)
        assert list(task_reader(d1))[0][0].shape == (SLOTS,)

    def test_enqueue_exactly_once_and_drained_gate(self, tmp_path):
        from paddle_tpu.master import MasterClient, MasterServer

        joined = _joined_segments(tmp_path, 8, segment_records=4)
        srv = MasterServer(timeout_s=10, port=0)
        addr = srv.start()
        try:
            client = MasterClient(addr)
            comp = Compactor(joined)
            descs = comp.enqueue(client)
            assert len(descs) == 2
            assert all(d.startswith("ctrlog:4:") for d in descs)
            assert client.counts()["todo"] == 2
            # drained gate: the queue holds work -> a new segment must
            # NOT replace it (set_dataset clears the master's queue)
            more = _Clock()
            _log_impressions(tmp_path / "log", 4, clock=more,
                             rid_prefix="m")
            j = OutcomeJoiner(str(tmp_path / "log"), joined,
                              window_s=0.0, segment_records=4,
                              clock=more)
            j.poll_once()
            j.seal()
            assert comp.enqueue(client) == []
            assert comp.stats()["backlog_segments"] == 1
            # restart: the durable manifest survives — already-fed
            # segments never feed twice
            comp2 = Compactor(joined)
            assert comp2.stats()["segments_enqueued"] == 2
            assert [d for d in comp2.pending_descs()
                    ] == comp.pending_descs()
            assert len(comp2.pending_descs()) == 1
            client.close()
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# serving-side hook (Server / MultiTenantServer taps)
# ---------------------------------------------------------------------------
def _serve_engine(bundle, seed):
    from paddle_tpu.serving import InferenceEngine

    serve_prog = io.prune_program(bundle["main"], ["ids", "dense"],
                                  [bundle["prob"].name])
    scope = pt.Scope()
    bundle["startup"].random_seed = seed
    pt.Executor(pt.TPUPlace()).run(bundle["startup"], scope=scope)
    return InferenceEngine(program=serve_prog,
                           feed_names=["ids", "dense"],
                           fetch_names=[bundle["prob"].name], scope=scope,
                           batch_buckets=(4,), place=pt.CPUPlace())


class TestServingTap:
    def test_server_submit_logs_impression_with_version(self, tmp_path):
        from paddle_tpu.serving import Server

        bundle = _build_ctr()
        log = ImpressionLog(str(tmp_path / "log"), flush_s=0.002)
        joiner = OutcomeJoiner(str(tmp_path / "log"),
                               str(tmp_path / "joined"), window_s=60.0)
        hook = FeedbackHook(log, joiner=joiner,
                            version_source=lambda: 42)
        rng = np.random.RandomState(1)
        row = _row(rng)
        with Server(_serve_engine(bundle, 11),
                    batch_buckets=(1, 4)) as srv:
            srv.attach_feedback(hook)
            fut = srv.submit(dict(row))
            res = fut.result(timeout=30)
            rid = fut.request_id
        assert rid
        _wait_logged(log, 1)
        log.seal()
        (path,) = sealed_segments(str(tmp_path / "log"))
        (rec,) = [r for _, r in read_records(path)]
        assert rec["rid"] == rid
        assert rec["weights_version"] == 42
        np.testing.assert_array_equal(rec["features"]["ids"],
                                      row["ids"])
        served = np.asarray(rec["served"][0], np.float32)
        np.testing.assert_allclose(served.ravel(),
                                   np.asarray(res[0]).ravel(),
                                   rtol=1e-6)
        # the outcome plane closes on the same rid
        assert joiner.post_outcome(rid, 1.0) in ("joined", "parked")
        log.close()

    def test_multitenant_impressions_carry_tenant(self, tmp_path):
        from paddle_tpu.serving.tenancy import (ModelRegistry,
                                                MultiTenantServer)

        bundle = _build_ctr()
        reg = ModelRegistry()
        reg.register("ctr-a", [_serve_engine(bundle, 11)])
        reg.register("ctr-b", [_serve_engine(bundle, 12)])
        log = ImpressionLog(str(tmp_path / "log"), flush_s=0.002)
        hook = FeedbackHook(log)
        srv = MultiTenantServer(reg)
        srv.start()
        try:
            srv.attach_feedback(hook)
            rng = np.random.RandomState(2)
            srv.submit(_row(rng), model="ctr-b").result(timeout=30)
            srv.submit(_row(rng)).result(timeout=30)  # default tenant
        finally:
            srv.stop()
        _wait_logged(log, 2)
        log.seal()
        recs = [r for p in sealed_segments(str(tmp_path / "log"))
                for _, r in read_records(p)]
        assert sorted(r["model"] for r in recs) == ["ctr-a", "ctr-b"]
        log.close()

    @pytest.mark.slow
    def test_server_http_request_id_and_outcome(self, tmp_path):
        """Redundant with the fleet e2e's HTTP leg: the single-Server
        JSON surface returns request_id and accepts /v1/outcome."""
        from paddle_tpu.serving import Server

        bundle = _build_ctr()
        log = ImpressionLog(str(tmp_path / "log"), flush_s=0.002)
        joiner = OutcomeJoiner(str(tmp_path / "log"),
                               str(tmp_path / "joined"), window_s=60.0)
        hook = FeedbackHook(log, joiner=joiner)
        rng = np.random.RandomState(3)
        row = _row(rng)
        with Server(_serve_engine(bundle, 11),
                    batch_buckets=(1, 4)) as srv:
            srv.attach_feedback(hook)
            port = srv.serve_http()
            body = json.dumps({"inputs": {
                "ids": row["ids"].tolist(),
                "dense": row["dense"].tolist()}}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/infer", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                out = json.load(r)
            rid = out["request_id"]
            body = json.dumps({"request_id": rid,
                               "outcome": 1.0}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/outcome", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                assert json.load(r)["status"] in ("joined", "parked")
        log.close()


# ---------------------------------------------------------------------------
# THE acceptance pin: the loop closes end to end on a live fleet
# ---------------------------------------------------------------------------
def test_feedback_loop_end_to_end_live_fleet(tmp_path):
    """ACCEPTANCE PIN: a 2-replica fleet serves a request storm with the
    feedback hook attached; outcomes post back over POST /v1/outcome;
    the joiner emits exactly one example per impression; the compactor
    feeds ONLY logged traffic to the master; a StreamingTrainer
    consumes it; the Publisher rolls the new generation into the SAME
    fleet token-exact — zero failed requests, and the next impression
    records the new weights_version (the loop observably closed)."""
    from paddle_tpu.master import MasterClient, MasterServer
    from paddle_tpu.online import Publisher, StreamingTrainer
    from paddle_tpu.resilience import CheckpointConfig
    from paddle_tpu.serving.fleet import Fleet
    from paddle_tpu.trace.slo import SLO

    bundle = _build_ctr()
    log = ImpressionLog(str(tmp_path / "log"), segment_records=16,
                        flush_s=0.002)
    joiner = OutcomeJoiner(str(tmp_path / "log"),
                           str(tmp_path / "joined"), window_s=0.2,
                           park_ttl_s=30.0, segment_records=16)
    hook = FeedbackHook(log, joiner=joiner)

    srv = MasterServer(timeout_s=10, port=0)
    addr = srv.start()
    ck = str(tmp_path / "ck")
    engines = [_serve_engine(bundle, s) for s in (21, 22)]
    fleet = Fleet(engines, hedge=False,
                  slo=SLO(freshness_s=60.0, availability=0.99))
    pub = Publisher(fleet, ck)
    fleet.attach_feedback(hook)

    N_PER_THREAD, failed, served = 24, [], []
    lock = threading.Lock()

    def storm(tid):
        rng = np.random.RandomState(100 + tid)
        for i in range(N_PER_THREAD):
            row = _row(rng)
            try:
                fut = fleet.submit(dict(row), timeout_ms=20_000)
                fut.result(timeout=30)
                with lock:
                    served.append((fut.request_id, i % 3 == 0))
            except Exception as exc:  # noqa: BLE001 - the pin
                failed.append(repr(exc))

    with fleet:
        port = fleet.serve_http()
        threads = [threading.Thread(target=storm, args=(t,))
                   for t in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert failed == []                       # zero failed requests
        assert len(served) == 2 * N_PER_THREAD
        assert all(rid for rid, _ in served)      # every reply carried one
        _wait_logged(log, 2 * N_PER_THREAD)
        log.seal()

        # outcomes post back over the fleet's own HTTP plane
        clicked = [rid for rid, c in served if c]
        for rid in clicked:
            body = json.dumps({"request_id": rid,
                               "outcome": 1.0}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/outcome", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                assert json.load(r)["status"] == "parked"
        joiner.poll_once()
        assert joiner.stats()["parked_joins"] == len(clicked)
        time.sleep(0.3)                 # the rest age past the window
        joiner.poll_once()
        joiner.seal()
        stats = joiner.stats()
        assert stats["joined"] + stats["expired_negatives"] == 48

        examples = _sealed_examples(tmp_path / "joined")
        assert len(examples) == 48                # exactly one each
        assert len({e["rid"] for e in examples}) == 48
        assert sum(e["label"] for e in examples) == len(clicked)

        # compactor feeds the drained master queue, durably
        client = MasterClient(addr)
        comp = Compactor(str(tmp_path / "joined"))
        descs = comp.enqueue(client)
        assert descs and all(d.startswith("ctrlog:") for d in descs)
        assert comp.stats()["examples_enqueued"] == 48
        client.close()

        # the trainer consumes ONLY the logged traffic: it never seeds
        # its own dataset (task_descs=None) and trains one pass
        st = StreamingTrainer(
            bundle["sgd"], addr, task_reader, task_descs=None,
            batch_size=16,
            checkpoint=CheckpointConfig(ck, every_n_steps=1,
                                        background=False),
            max_passes=1)
        state = st.run()
        assert state["tasks_finished"] == len(descs)
        assert state["steps"] == 48 // 16

        step = pub.poll_once()                    # the roll back in
        assert step is not None and pub.generations == 1

        # token-exact: the fleet now serves the trained checkpoint
        reference = _serve_engine(bundle, 99)
        reference.swap_params(ck)
        rng = np.random.RandomState(7)
        probe = _row(rng)
        want = np.asarray(reference.run(
            {"ids": probe["ids"][None], "dense": probe["dense"][None]})[0])
        fut = fleet.submit(dict(probe))
        got = np.asarray(fut.result(timeout=30)[0])
        np.testing.assert_array_equal(got.ravel(), want.ravel())

        # ...and THAT impression records the published weights version:
        # the loop's next cycle knows which weights served it
        _wait_logged(log, 2 * N_PER_THREAD + 1)
        log.seal()
        last_seg = sealed_segments(str(tmp_path / "log"))[-1]
        last = [r for _, r in read_records(last_seg)][-1]
        assert last["rid"] == fut.request_id
        assert last["weights_version"] == step
    log.close()
    srv.stop()


def test_feedback_loop_chaos_joiner_kill_and_torn_tail(tmp_path):
    """CHAOS PIN: the joiner is killed mid-window AND the impression
    log has a torn tail — the loop loses a bounded, counted set of
    outcomes (label flips to negative on replay) and tail bytes, but
    NEVER duplicates a training example."""
    clk = _Clock()
    rids = _log_impressions(tmp_path / "log", 32, segment_records=8,
                            clock=clk)
    # a crashed serving host left a ragged .open tail: one whole record
    # plus a partial write
    rec = json.dumps({"rid": "torn-0", "t": clk(), "features":
                      {"ids": [1] * SLOTS, "dense": [0.0] * DD},
                      "served": [0.5]}).encode()
    with open(tmp_path / "log" / "seg-000004.open", "wb") as fh:
        fh.write(struct.pack("<I", len(rec)))
        fh.write(rec)
        fh.write(struct.pack("<I", 777))
        fh.write(b'{"rid": "lost-forever"')
    relog = ImpressionLog(str(tmp_path / "log"), clock=clk)
    s = relog.stats()
    relog.close()
    assert s["torn_recovered"] == 1          # walked back to the last
    assert s["torn_lost_bytes"] > 0          # clean record; loss counted
    all_rids = rids + ["torn-0"]

    j1 = OutcomeJoiner(str(tmp_path / "log"), str(tmp_path / "joined"),
                       window_s=10.0, segment_records=5, clock=clk)
    for rid in rids[:12]:
        assert j1.post_outcome(rid, 1.0) == "parked"
    j1.poll_once()
    # j1 is KILLED here: 12 joins emitted (10 sealed, 2 in the open
    # tail), 21 impressions pending in memory — all of that state dies

    j2 = OutcomeJoiner(str(tmp_path / "log"), str(tmp_path / "joined"),
                       window_s=10.0, segment_records=5, clock=clk)
    assert j2.stats()["discarded_open_examples"] == 2
    j2.poll_once()
    assert j2.stats()["pending"] == 23       # 21 lost-pending + 2 redone
    assert j2.stats()["replayed"] > 0
    clk.advance(11.0)
    j2.poll_once()
    j2.seal()

    examples = _sealed_examples(tmp_path / "joined")
    seen = [e["rid"] for e in examples]
    assert len(seen) == len(set(seen))        # NEVER a duplicate
    assert sorted(seen) == sorted(all_rids)   # and nothing vanished
    # bounded, counted loss: exactly the 2 discarded positives came
    # back as negatives; everything else kept its label
    assert sum(e["label"] for e in examples) == 10
    assert j2.stats()["expired_negatives"] == 23


# ---------------------------------------------------------------------------
# sparse lifecycle (satellite: admit-by-touch + TTL-evict)
# ---------------------------------------------------------------------------
class TestSparseLifecycle:
    def test_admit_evict_deterministic_reinit_pin(self):
        """THE PIN: evict -> re-admit reinitializes the row BYTE-EQUAL
        to its first admission (row_init is pure in (seed, id))."""
        from paddle_tpu.online import SparseLifecycle

        b = _build_ctr(seed=3)
        scope = b["sgd"].scope
        pt.Executor(pt.TPUPlace()).run(b["startup"], scope=scope)
        table = sorted(k for k in scope.keys()
                       if "embedding" in k and ".w" in k
                       and not k.endswith("_acc"))[0]
        # an optimizer accumulator riding the table must reset too
        acc = table + "_moment_acc"
        scope.set(acc, jnp.ones_like(scope.get(table)[:, :1]) * 7.0)
        lc = SparseLifecycle(table, admit_touches=2, ttl_steps=1,
                             seed=11)
        rng = np.random.RandomState(0)
        batch = [(np.array([7] * SLOTS, np.int64),
                  rng.rand(DD).astype(np.float32),
                  np.zeros(1, np.float32))]
        lc.after_batch(batch, scope, step=1)      # touch 1: suppressed
        assert lc.stats()["suppressed"] == 1
        np.testing.assert_array_equal(np.asarray(scope.get(table)[7]),
                                      lc.row_init(7))
        lc.after_batch(batch, scope, step=2)      # touch 2: admitted
        assert lc.stats()["admitted"] == 1
        first_admit = np.asarray(scope.get(table)[7]).copy()
        np.testing.assert_array_equal(first_admit, lc.row_init(7))
        # training mutates the row; an admitted row is left alone
        scope.set(table, scope.get(table).at[7].add(0.5))
        lc.after_batch(batch, scope, step=3)
        assert np.asarray(scope.get(table)[7])[0] != first_admit[0]
        # TTL sweep: untouched past ttl_steps -> evicted, row AND
        # accumulator reset
        lc.on_task_end(scope, step=5)
        assert lc.stats()["evicted"] == 1
        np.testing.assert_array_equal(np.asarray(scope.get(table)[7]),
                                      lc.row_init(7))
        assert np.asarray(scope.get(acc))[7].item() == 0.0
        # re-admission: byte-equal to the first admission
        lc.after_batch(batch, scope, step=6)
        lc.after_batch(batch, scope, step=7)
        np.testing.assert_array_equal(np.asarray(scope.get(table)[7]),
                                      first_admit)

    def test_out_of_vocab_ids_ignored(self):
        from paddle_tpu.online import SparseLifecycle

        b = _build_ctr(seed=4)
        scope = b["sgd"].scope
        pt.Executor(pt.TPUPlace()).run(b["startup"], scope=scope)
        table = sorted(k for k in scope.keys()
                       if "embedding" in k and ".w" in k
                       and not k.endswith("_acc"))[0]
        lc = SparseLifecycle(table, admit_touches=1, ttl_steps=10)
        batch = [(np.array([VOCAB] * SLOTS, np.int64),  # the sentinel
                  np.zeros(DD, np.float32), np.zeros(1, np.float32))]
        lc.after_batch(batch, scope, step=1)
        assert lc.stats()["tracked"] == 0

    def test_streaming_trainer_drives_lifecycle(self, tmp_path):
        """The trainer calls the hooks at batch/task boundaries."""
        from paddle_tpu.master import MasterServer
        from paddle_tpu.online import SparseLifecycle, StreamingTrainer
        from paddle_tpu.resilience import CheckpointConfig

        srv = MasterServer(timeout_s=10, port=0)
        addr = srv.start()
        try:
            b = _build_ctr()
            scope = b["sgd"].scope
            # the lifecycle binds to the wide_deep embedding table
            pt.Executor(pt.TPUPlace()).run(b["startup"], scope=scope)
            table = sorted(k for k in scope.keys()
                           if "embedding" in k and ".w" in k
                           and not k.endswith("_acc"))[0]
            lc = SparseLifecycle(table, admit_touches=1, ttl_steps=0)
            descs = dataset.ctr.task_descs(2, records_per_shard=32,
                                           vocab=VOCAB)
            st = StreamingTrainer(
                b["sgd"], addr, dataset.ctr.task_reader,
                task_descs=descs, batch_size=16,
                checkpoint=CheckpointConfig(str(tmp_path / "ck"),
                                            every_n_steps=8,
                                            background=False),
                max_passes=1, sparse_lifecycle=lc)
            state = st.run()
            assert state["steps"] == 4
            s = lc.stats()
            assert s["admitted"] > 0      # every first touch admits
            assert s["evicted"] > 0       # ttl 0 sweeps stale ids at
        finally:                          # task boundaries
            srv.stop()


# ---------------------------------------------------------------------------
# capacity-bounded a2a exchange (satellite: sharded-embedding scatter)
# ---------------------------------------------------------------------------
class TestA2AExchange:
    def test_a2a_bitwise_matches_gather_and_serial(self, cpu_mesh_dp_mp):
        from paddle_tpu.parallel.sharded_embedding import vp_scatter_add

        mesh = cpu_mesh_dp_mp
        V, D, n = 64, 8, 16
        rng = np.random.RandomState(3)
        w = jnp.asarray(rng.rand(V, D).astype(np.float32))
        # merged-SelectedRows shape: unique rows up front, height
        # sentinels padding the static tail
        rows = jnp.asarray(np.concatenate(
            [rng.choice(V, size=10, replace=False),
             np.full(6, V)]).astype(np.int32))
        vals = jnp.asarray(rng.rand(n, D).astype(np.float32))
        want = np.asarray(w.at[rows].add(vals, mode="drop"))
        got_a2a = np.asarray(vp_scatter_add(w, rows, vals, mesh,
                                            exchange="a2a"))
        got_gat = np.asarray(vp_scatter_add(w, rows, vals, mesh,
                                            exchange="gather"))
        np.testing.assert_array_equal(got_a2a, want)
        np.testing.assert_array_equal(got_gat, want)
        # auto mode picks a2a for divisible add-mode streams
        got_auto = np.asarray(vp_scatter_add(w, rows, vals, mesh))
        np.testing.assert_array_equal(got_auto, want)

    def test_a2a_overflow_falls_back_in_graph(self, cpu_mesh_dp_mp):
        """A stream skewed onto one owner overflows a tight capacity;
        the mesh-uniform spill predicate reroutes to the gather
        exchange INSIDE the compiled step — still bitwise exact."""
        from paddle_tpu.parallel.sharded_embedding import vp_scatter_add

        mesh = cpu_mesh_dp_mp
        V, D, n = 64, 8, 16
        rng = np.random.RandomState(5)
        w = jnp.asarray(rng.rand(V, D).astype(np.float32))
        # every real row owned by shard 0 -> its buckets overflow
        rows = jnp.asarray(np.concatenate(
            [np.arange(12, dtype=np.int32),
             np.full(4, V, np.int32)]))
        vals = jnp.asarray(rng.rand(n, D).astype(np.float32))
        got = np.asarray(vp_scatter_add(w, rows, vals, mesh,
                                        exchange="a2a",
                                        capacity_factor=0.25))
        want = np.asarray(w.at[rows].add(vals, mode="drop"))
        np.testing.assert_array_equal(got, want)

    def test_exchange_bytes_model_cuts_by_shard_count(self):
        from paddle_tpu.parallel.sharded_embedding import (a2a_capacity,
                                                           exchange_bytes)

        for nmp in (2, 4, 8):
            bw = exchange_bytes(1 << 16, nmp, width=64,
                                capacity_factor=1.0)
            # at capacity_factor 1 the a2a ships each row exactly once:
            # the wire cut is exactly the shard count
            assert bw["gather"] // bw["a2a"] == nmp
        # capacity is clamped to the local slice
        assert a2a_capacity(8, 8, capacity_factor=100.0) == 1


# ---------------------------------------------------------------------------
# movielens varlen CTR (satellite: id-LISTS through the varlen plane)
# ---------------------------------------------------------------------------
def test_movielens_varlen_ctr_smoke():
    """movielens ratings as varlen CTR impressions: ragged id lists ->
    bucket_by_length -> lod_level=1 embedding + sequence_pool tower;
    synthetic fallback, no network."""
    from paddle_tpu.reader import decorator

    ml = dataset.movielens
    V = ml.ctr_vocab_size()
    rows = list(itertools.islice(ml.ctr_train()(), 128))
    lens = {len(r[0]) for r in rows}
    assert len(lens) > 1                      # genuinely ragged
    assert max(int(r[0].max()) for r in rows) < V
    assert all(r[1].shape == (ml.CTR_DENSE_DIM,) for r in rows[:4])
    labels = {float(r[2][0]) for r in rows}
    assert labels <= {0.0, 1.0} and len(labels) == 2

    main, startup = pt.Program(), pt.Program()
    startup.random_seed = 11
    with pt.program_guard(main, startup):
        ids = layers.data("ids", shape=[1], dtype="int64", lod_level=1)
        dense = layers.data("dense", shape=[ml.CTR_DENSE_DIM])
        label = layers.data("label", shape=[1])
        emb = layers.embedding(ids, size=[V, 8], is_sparse=True)
        emb.seq_len = ids.seq_len
        pooled = layers.sequence_pool(emb, "average")
        feat = layers.concat([pooled, dense], axis=1)
        h = layers.fc(feat, size=16, act="relu")
        logit = layers.fc(h, size=1)
        loss, prob = pt.models.wide_deep_loss(logit, label)
        sgd = pt.trainer.SGD(
            loss, pt.optimizer.AdagradOptimizer(learning_rate=0.05),
            [ids, dense, label], scope=pt.Scope(), pad_to_multiple=8)

    reader = decorator.bucket_by_length(lambda: iter(rows),
                                        batch_size=16, seed=0,
                                        pad_to_multiple=8)
    costs = []

    def handler(e):
        if isinstance(e, pt.event.EndIteration):
            costs.append(e.cost)

    sgd.train(reader, num_passes=2, event_handler=handler)
    assert len(costs) == 16
    assert all(np.isfinite(c) for c in costs)


# ---------------------------------------------------------------------------
# loopctl (operator surface)
# ---------------------------------------------------------------------------
def test_loopctl_reports_stage_lag(tmp_path, capsys):
    import importlib.util

    joined = _joined_segments(tmp_path, 6, segment_records=3)
    spec = importlib.util.spec_from_file_location(
        "loopctl", os.path.join(_REPO, "tools", "loopctl.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main(["--log-dir", str(tmp_path / "log"),
                   "--joined-dir", joined, "--json"])
    assert rc == 0
    status = json.loads(capsys.readouterr().out)
    assert status["backlog_segments"] == 2     # sealed, not yet fed
    assert status["log_lag_s"] is not None
    assert status["join_lag_s"] is not None
    assert status["torn_segments"] == 0
    # table mode renders the same stages
    rc = mod.main(["--log-dir", str(tmp_path / "log"),
                   "--joined-dir", joined])
    out = capsys.readouterr().out
    assert rc == 0 and "STAGE" in out and "join" in out

"""paddle_tpu.serving.disagg: prefill/decode split pools + KV handoff.

Pins the disaggregation contracts:

1. HANDOFF IS MIGRATION — tokens are byte-identical to a unified
   engine at every pool shape; the decode pool's ``prefills`` counter
   stays 0 (never a recompute) and the prefill pool never runs a
   decode step (role purity);
2. SAME-PROCESS is a refcount transfer through ONE shared page pool
   (``DisaggEngine.build``); separate-pool legs move serialized page
   ranges instead — both drain the source pool clean;
3. CROSS-PROCESS handoffs ride ``POST /v1/adopt`` on the existing
   HTTP surface (``RemoteDecodeLeg``) and the SOURCE request's future
   resolves with the remote decode's tokens — the client never sees
   the pool boundary;
4. schema/page-shape mismatches are a typed BadRequestError, never
   silent cache corruption.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models
from paddle_tpu.decoding import SamplingParams
from paddle_tpu.serving import GenerationEngine, LMSpec, Server
from paddle_tpu.serving.batcher import Request
from paddle_tpu.serving.disagg import (HANDOFF_V, DecodePool, DisaggEngine,
                                       PrefillPool, RemoteDecodeLeg,
                                       install_handoff)
from paddle_tpu.serving.errors import BadRequestError

VOCAB, D, L, H, MAXLEN = 32, 16, 2, 2, 32
SEED = 7
MAXNEW = 6
PROMPTS = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [2, 3, 4]]
# the last request decodes SAMPLED: the handoff must carry the decode
# policy (temperature/top_k/seed) so migration is invisible to it too
SAMPLING = [None, None, None,
            SamplingParams(temperature=0.7, top_k=4, seed=11)]

_WEIGHTS = {}


def _lm_scope(seed=SEED):
    exe = pt.Executor(pt.TPUPlace())
    if seed not in _WEIGHTS:
        scope = pt.Scope()
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            prompt = layers.data("p_init", shape=[8], dtype="int64")
            models.transformer_lm_generate(
                prompt, vocab_size=VOCAB, d_model=D, n_layers=L,
                num_heads=H, max_len=MAXLEN, max_new_tokens=1)
        startup.random_seed = seed
        exe.run(startup, scope=scope)
        _WEIGHTS[seed] = {n: scope.get(n) for n in scope.keys()}
    scope = pt.Scope()
    for n, v in _WEIGHTS[seed].items():
        scope.set(n, v)
    return scope


def _spec():
    return LMSpec(vocab_size=VOCAB, d_model=D, n_layers=L, num_heads=H,
                  max_len=MAXLEN)


def _engine(**kw):
    return GenerationEngine(_spec(), _lm_scope(), slots=4, page_size=8,
                            kv_cache="paged", **kw)


def _reqs():
    return [Request({"prompt": p},
                    {"max_new_tokens": MAXNEW, "sampling_params": sp},
                    None)
            for p, sp in zip(PROMPTS, SAMPLING)]


def _results(reqs, timeout=60):
    return [np.asarray(r.future.result(timeout=timeout)) for r in reqs]


@pytest.fixture(scope="module")
def reference():
    """The unified-engine tokens every split shape must reproduce."""
    uni = _engine()
    outs = uni.generate_all(PROMPTS, max_new_tokens=MAXNEW,
                            sampling=SAMPLING)
    return [np.asarray(o) for o in outs]


def _assert_disagg_matches(dis, reference, *, timeout=60):
    reqs = _reqs()
    dis._drive(reqs)
    for got, want in zip(_results(reqs, timeout=timeout), reference):
        np.testing.assert_array_equal(got, want)


def _counters(obj) -> dict:
    snap = obj.metrics.snapshot() if hasattr(obj, "metrics") else obj
    return snap.get("counters", snap)


def _drained(eng) -> int:
    """Pages still referenced once the prefix index's deliberate
    retention (finished prompts cached for reuse) is dropped — 0 means
    no migration leaked a refcount in either direction."""
    if eng.prefix_index is not None:
        eng.prefix_index.clear()
    return eng.pool.pages_in_use()


def _role_purity(prefill_engines, decode_engines):
    """The split's whole point: prefill legs never decode, decode legs
    never prefill (so a migration was never a recompute)."""
    for eng in prefill_engines:
        assert _counters(eng).get("decode_steps", 0) == 0
    for eng in decode_engines:
        assert _counters(eng).get("prefills", 0) == 0


# ---------------------------------------------------------------------------
# same-process: ONE shared page pool, migration by refcount
# ---------------------------------------------------------------------------
class TestSharedPoolHandoff:
    def test_tokens_byte_identical_and_roles_pure(self, reference):
        dis = DisaggEngine.build(_spec(), prefill_replicas=1,
                                 decode_replicas=1, scope=_lm_scope(),
                                 slots=4, page_size=8)
        _assert_disagg_matches(dis, reference)
        pf = _counters(dis.prefill.engines[0])
        de = _counters(dis.decode.engines[0])
        assert pf.get("kv_handoffs_out") == len(PROMPTS)
        assert de.get("kv_handoffs_in") == len(PROMPTS)
        assert pf.get("kv_handoff_pages", 0) >= len(PROMPTS)
        _role_purity(dis.prefill.engines, dis.decode.engines)
        # every migration moved pages, and finishing released them all:
        # the shared pool drains clean (no refcount leak either way)
        for eng in dis.engines:
            assert _drained(eng) == 0
        assert _counters(dis.prefill.engines[0]).get("kv_migrations") \
            == len(PROMPTS)

    @pytest.mark.slow
    def test_pool_shape_2x2(self, reference):
        # redundant shape variant: same contract, more legs
        dis = DisaggEngine.build(_spec(), prefill_replicas=2,
                                 decode_replicas=2, scope=_lm_scope(),
                                 slots=4, page_size=8)
        _assert_disagg_matches(dis, reference)
        _role_purity(dis.prefill.engines, dis.decode.engines)
        assert sum(_counters(e).get("kv_handoffs_in", 0)
                   for e in dis.decode.engines) == len(PROMPTS)


# ---------------------------------------------------------------------------
# separate pools in one process: serialized page ranges
# ---------------------------------------------------------------------------
class TestSerializedHandoff:
    def test_separate_pool_migration_moves_bytes(self, reference):
        eng_a, eng_b = _engine(), _engine()   # distinct scopes + pools
        assert eng_a.pool is not eng_b.pool
        dis = DisaggEngine(PrefillPool([eng_a]), DecodePool([eng_b]))
        _assert_disagg_matches(dis, reference)
        b = _counters(eng_b)
        assert b.get("kv_handoffs_in") == len(PROMPTS)
        assert b.get("kv_handoff_pages", 0) >= len(PROMPTS)
        _role_purity([eng_a], [eng_b])
        # the exporter released its page claims to the bytes
        assert _drained(eng_a) == 0
        assert _drained(eng_b) == 0

    def test_handoff_schema_and_shape_typed(self):
        eng = _engine()
        req = Request({"prompt": [1]}, {}, None)
        with pytest.raises(BadRequestError, match="schema"):
            install_handoff(eng, {"v": HANDOFF_V + 1}, req)
        with pytest.raises(BadRequestError, match="page_size"):
            install_handoff(eng, {"v": HANDOFF_V,
                                  "page_size": eng.page_size * 2}, req)
        with pytest.raises(BadRequestError, match="context"):
            install_handoff(eng, {"v": HANDOFF_V,
                                  "page_size": eng.page_size,
                                  "prompt": [1] * MAXLEN,
                                  "max_new": MAXLEN}, req)

    def test_remote_only_decode_needs_a_leg(self):
        with pytest.raises(ValueError, match="decode leg"):
            DisaggEngine(PrefillPool([_engine()]), DecodePool([]))


# ---------------------------------------------------------------------------
# cross-process: POST /v1/adopt over the HTTP replica leg
# ---------------------------------------------------------------------------
class TestRemoteAdopt:
    def test_handoff_rides_v1_adopt(self, reference):
        decode_eng = _engine()
        srv = Server([decode_eng])
        srv.start()
        port = srv.serve_http(port=0)
        try:
            pre = _engine()
            dis = DisaggEngine(
                PrefillPool([pre]), DecodePool([]),
                remote_decode=[RemoteDecodeLeg(
                    f"http://127.0.0.1:{port}")])
            _assert_disagg_matches(dis, reference)
            _role_purity([pre], [decode_eng])
            de = _counters(decode_eng)
            assert de.get("kv_handoffs_in") == len(PROMPTS)
            assert _drained(pre) == 0
            assert _drained(decode_eng) == 0
        finally:
            srv.stop()

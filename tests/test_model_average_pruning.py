"""ModelAverage + StaticPruningHook tests (reference
paddle/parameter/AverageOptimizer.h, ParameterUpdaterHook.cpp)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.param_attr import Hook, StaticPruningHook


class TestModelAverage:
    def test_apply_uses_window_average_and_restores(self):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", shape=[4])
            y = layers.data("y", shape=[1])
            pred = layers.fc(x, size=1, bias_attr=False,
                             param_attr=pt.ParamAttr(name="ma_w"))
            loss = layers.mean(layers.square_error_cost(pred, y))
            pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(
                loss, startup_program=startup)
            ma = pt.optimizer.ModelAverage(min_average_window=2,
                                           max_average_window=1000)
        scope = pt.Scope()
        exe = pt.Executor(pt.TPUPlace())
        exe.run(startup, scope=scope)
        rng = np.random.RandomState(0)
        w_hist = []
        for _ in range(10):
            xb = rng.randn(16, 4).astype(np.float32)
            yb = (xb @ np.array([[1.0], [2.0], [-1.0], [0.5]],
                                np.float32))
            exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss],
                    scope=scope)
            w_hist.append(np.asarray(scope.get_numpy("ma_w")).copy())
        live = np.asarray(scope.get_numpy("ma_w")).copy()
        expected_avg = np.mean(w_hist, axis=0)
        with ma.apply(scope):
            applied = np.asarray(scope.get_numpy("ma_w"))
            np.testing.assert_allclose(applied, expected_avg, rtol=1e-4)
        restored = np.asarray(scope.get_numpy("ma_w"))
        np.testing.assert_array_equal(restored, live)

    def test_below_min_window_keeps_live_params(self):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", shape=[3])
            y = layers.data("y", shape=[1])
            pred = layers.fc(x, size=1, bias_attr=False)
            loss = layers.mean(layers.square_error_cost(pred, y))
            pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(
                loss, startup_program=startup)
            ma = pt.optimizer.ModelAverage(min_average_window=100)
        scope = pt.Scope()
        exe = pt.Executor(pt.TPUPlace())
        exe.run(startup, scope=scope)
        xb = np.ones((4, 3), np.float32)
        yb = np.ones((4, 1), np.float32)
        exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss],
                scope=scope)
        names = [n for n in scope.keys() if n.endswith("@MA_sum_1")]
        assert names  # accumulators exist
        pname = names[0].replace("@MA_sum_1", "")
        live = np.asarray(scope.get_numpy(pname)).copy()
        with ma.apply(scope):
            np.testing.assert_array_equal(
                np.asarray(scope.get_numpy(pname)), live)

    def test_window_rotation(self):
        """After num_1 hits max_average_window, sum_2 takes the history."""
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", shape=[2])
            y = layers.data("y", shape=[1])
            pred = layers.fc(x, size=1, bias_attr=False,
                             param_attr=pt.ParamAttr(name="rot_w"))
            loss = layers.mean(layers.square_error_cost(pred, y))
            pt.optimizer.SGDOptimizer(learning_rate=0.0).minimize(
                loss, startup_program=startup)
            pt.optimizer.ModelAverage(min_average_window=1,
                                      max_average_window=3)
        scope = pt.Scope()
        exe = pt.Executor(pt.TPUPlace())
        exe.run(startup, scope=scope)
        xb = np.ones((2, 2), np.float32)
        yb = np.ones((2, 1), np.float32)
        for _ in range(4):  # rotation fires at step 3
            exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss],
                    scope=scope)
        n1 = float(np.asarray(scope.get_numpy("rot_w@MA_num_1"))[0])
        n2 = float(np.asarray(scope.get_numpy("rot_w@MA_num_2"))[0])
        assert n2 == 3.0 and n1 == 1.0, (n1, n2)


class TestStaticPruning:
    def test_mask_sparsity_and_persistence(self):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", shape=[10])
            y = layers.data("y", shape=[1])
            pred = layers.fc(
                x, size=10, bias_attr=False,
                param_attr=pt.ParamAttr(
                    name="prune_w",
                    update_hooks=Hook("pruning", sparsity_ratio=0.7)))
            out = layers.fc(pred, size=1, bias_attr=False)
            loss = layers.mean(layers.square_error_cost(out, y))
            pt.optimizer.SGDOptimizer(learning_rate=0.05).minimize(
                loss, startup_program=startup)
        scope = pt.Scope()
        exe = pt.Executor(pt.TPUPlace())
        exe.run(startup, scope=scope)
        w0 = np.asarray(scope.get_numpy("prune_w"))
        sparsity0 = (w0 == 0).mean()
        assert 0.65 <= sparsity0 <= 0.75, sparsity0  # pruned at init
        zero_mask = w0 == 0
        rng = np.random.RandomState(0)
        for _ in range(5):
            xb = rng.randn(8, 10).astype(np.float32)
            yb = rng.randn(8, 1).astype(np.float32)
            exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss],
                    scope=scope)
        w5 = np.asarray(scope.get_numpy("prune_w"))
        # pruned entries stay exactly zero through training; others move
        assert (w5[zero_mask] == 0).all()
        assert np.abs(w5[~zero_mask] - w0[~zero_mask]).max() > 0

    def test_hook_factory_validates(self):
        import pytest

        with pytest.raises(ValueError):
            Hook("unknown")
        with pytest.raises(ValueError):
            StaticPruningHook(1.5)

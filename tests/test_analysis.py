"""paddle_tpu.analysis: whole-program shape/dtype checker, structural
verifier, lint-rule registry, and the registry-plane satellites
(memoized infer_outputs, get_op nearest-match errors)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import analysis, layers, models
from paddle_tpu.core import registry
from paddle_tpu.core.program import BATCH_DIM_SENTINEL


def _build(fn):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        out = fn()
    return main, startup, out


# ==========================================================================
# Whole-program checker: model-zoo programs validate with zero errors
# ==========================================================================
class TestModelZooClean:
    """Acceptance: every zoo program checks clean — zero false positives."""

    def _check(self, main, startup, feeds, fetches):
        res = analysis.check_program(main, feeds, fetches)
        assert not [i for i in res.issues if i.severity == analysis.ERROR]
        analysis.check_program(startup)
        return res

    def test_resnet50_training_program(self):
        def build():
            img = layers.data("img", shape=[32, 32, 3], dtype="float32")
            logits = models.resnet_imagenet(img, num_classes=10, depth=50)
            label = layers.data("label", shape=[1], dtype="int64")
            loss = layers.mean(
                layers.cross_entropy(layers.softmax(logits), label))
            pt.optimizer.MomentumOptimizer(
                learning_rate=0.1, momentum=0.9).minimize(loss)
            return loss

        main, startup, loss = _build(build)
        res = self._check(main, startup, ["img", "label"], [loss.name])
        # inferred types cover the whole program, batch stays symbolic
        assert res.shape_of(loss.name) == ()
        assert res.types["img"].shape[0] == BATCH_DIM_SENTINEL

    def test_transformer_training_program(self):
        def build():
            ids = layers.data("ids", shape=[16], dtype="int64")
            tgt = layers.data("tgt", shape=[16], dtype="int64")
            logits = models.transformer_lm(
                ids, vocab_size=97, d_model=32, n_layers=2, num_heads=4,
                max_len=32)
            loss = layers.mean(layers.softmax_with_cross_entropy(
                layers.reshape(logits, shape=[-1, 97]),
                layers.reshape(tgt, shape=[-1, 1])))
            pt.optimizer.AdamOptimizer(learning_rate=1e-3).minimize(loss)
            return loss

        main, startup, loss = _build(build)
        self._check(main, startup, ["ids", "tgt"], [loss.name])

    def test_rnn_lstm_training_program(self):
        def build():
            ids = layers.data("ids", shape=[12], dtype="int64")
            emb = layers.embedding(ids, size=[50, 8])
            proj = layers.fc(emb, size=4 * 16, num_flatten_dims=2)
            h_seq, _ = layers.dynamic_lstm(proj, size=4 * 16)
            pooled = layers.sequence_pool(h_seq, pool_type="max")
            logits = layers.fc(pooled, size=2, act="softmax")
            label = layers.data("label", shape=[1], dtype="int64")
            loss = layers.mean(layers.cross_entropy(logits, label))
            pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
            return loss

        main, startup, loss = _build(build)
        self._check(main, startup, ["ids", "label"], [loss.name])

    def test_ctr_wide_deep_training_program(self):
        def build():
            ids = layers.data("ids", shape=[5], dtype="int64")
            dense = layers.data("dense", shape=[4], dtype="float32")
            label = layers.data("label", shape=[1], dtype="float32")
            logit = models.wide_deep(ids, dense, vocab_size=1000,
                                     embed_dim=8)
            loss, prob = models.wide_deep_loss(logit, label)
            pt.optimizer.AdamOptimizer(learning_rate=1e-3).minimize(loss)
            return loss, prob

        main, startup, (loss, prob) = _build(build)
        self._check(main, startup, ["ids", "dense", "label"],
                    [loss.name, prob.name])

    def test_recompute_segment_program(self):
        """seg_fwd/grad_seg special ops go through the abstract
        handlers, not jax.eval_shape."""
        def build():
            img = layers.data("img", shape=[8, 8, 3], dtype="float32")
            with pt.recompute_guard():
                y = layers.fc(layers.reshape(img, shape=[-1, 192]),
                              size=32, act="relu")
            logits = layers.fc(y, size=10)
            label = layers.data("label", shape=[1], dtype="int64")
            loss = layers.mean(
                layers.cross_entropy(layers.softmax(logits), label))
            pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
            return loss

        main, startup, loss = _build(build)
        assert any(op.type == "seg_fwd" for op in main.global_block.ops)
        self._check(main, startup, ["img", "label"], [loss.name])

    def test_generation_program(self):
        def build():
            prompt = layers.data("prompt", shape=[8], dtype="int64")
            return models.transformer_lm_generate(
                prompt, vocab_size=97, d_model=32, n_layers=2,
                num_heads=4, max_len=32, max_new_tokens=8)

        main, startup, out_ids = _build(build)
        self._check(main, startup, ["prompt"], [out_ids.name])


# ==========================================================================
# Pinned failure modes: located build-time errors, not JAX trace errors
# ==========================================================================
class TestLocatedErrors:
    def test_declared_shape_mismatch_names_op_slot_callsite(self):
        """Acceptance pin: a shape-mismatched program fails at build
        time with op index + callsite + slot in the message."""
        main = pt.Program()
        b = main.global_block
        b.create_var(name="x", shape=[-1, 4], dtype="float32",
                     is_data=True)
        b.create_parameter(name="w", shape=[4, 10], dtype="float32")
        b.create_var(name="y", shape=[-1, 8], dtype="float32")  # wrong
        b.append_op("mul", {"X": ["x"], "Y": ["w"]}, {"Out": ["y"]},
                    {"_callsite": "model.py:42"})
        with pytest.raises(analysis.ProgramCheckError) as ei:
            analysis.infer_program(main, ["x"], ["y"])
        msg = str(ei.value)
        assert "op #0" in msg and "'mul'" in msg
        assert "model.py:42" in msg
        assert "'Out'" in msg and "'y'" in msg
        assert "(-1, 10)" in msg and "(-1, 8)" in msg
        assert ei.value.op_index == 0 and ei.value.slot == "Out"

    def test_kernel_rejection_is_located(self):
        """An op whose kernel rejects its abstract inputs reports the op
        context and input signatures, not a bare JAX error."""
        main = pt.Program()
        b = main.global_block
        b.create_var(name="x", shape=[-1, 4], dtype="float32",
                     is_data=True)
        b.create_parameter(name="w", shape=[5, 10], dtype="float32")
        b.create_var(name="y", shape=None, dtype="float32")
        b.append_op("mul", {"X": ["x"], "Y": ["w"]}, {"Out": ["y"]},
                    {"_callsite": "model.py:7"})
        with pytest.raises(analysis.ProgramCheckError) as ei:
            analysis.infer_program(main, ["x"], ["y"])
        msg = str(ei.value)
        assert "shape inference failed" in msg
        assert "op #0" in msg and "model.py:7" in msg
        assert "inputs:" in msg

    def test_dangling_input_is_located(self):
        main = pt.Program()
        b = main.global_block
        b.create_var(name="mid", shape=[-1, 4], dtype="float32")
        b.create_var(name="y", shape=None, dtype="float32")
        b.append_op("relu", {"X": ["mid"]}, {"Out": ["y"]})
        with pytest.raises(analysis.ProgramCheckError) as ei:
            analysis.infer_program(main, [], ["y"])
        assert "produced by no earlier op" in str(ei.value)
        assert ei.value.var == "mid"

    def test_annotation_fills_unknown_shapes(self):
        main = pt.Program()
        b = main.global_block
        b.create_var(name="x", shape=[-1, 4], dtype="float32",
                     is_data=True)
        y = b.create_var(name="y", shape=None, dtype="float32")
        b.append_op("relu", {"X": ["x"]}, {"Out": ["y"]})
        analysis.infer_program(main, ["x"], ["y"], annotate=True)
        assert y.shape == (-1, 4)


# ==========================================================================
# Structural verifier rules
# ==========================================================================
class TestVerifierRules:
    def _lint(self, program, feeds=(), fetches=(), scope=None, rules=None):
        return analysis.run_lint(program, feeds, fetches, scope=scope,
                                 rules=rules)

    def test_unknown_op(self):
        main = pt.Program()
        main.global_block.append_op("definitely_not_an_op", {}, {})
        issues = self._lint(main, rules=["unknown-op"])
        assert issues and issues[0].severity == analysis.ERROR

    def test_use_before_def_error_for_declared_var(self):
        main = pt.Program()
        b = main.global_block
        b.create_var(name="mid", shape=[4], dtype="float32")
        b.create_var(name="y", shape=[4], dtype="float32")
        b.append_op("relu", {"X": ["mid"]}, {"Out": ["y"]})
        with pytest.raises(analysis.ProgramVerifyError) as ei:
            analysis.verify_program(main, [], ["y"])
        assert ei.value.issues[0].rule == "use-before-def"

    def test_duplicate_output_within_one_op(self):
        main = pt.Program()
        b = main.global_block
        b.create_var(name="x", shape=[4], dtype="float32", is_data=True)
        b.create_var(name="y", shape=[4], dtype="float32")
        b.append_op("topk", {"X": ["x"]}, {"Out": ["y"], "Indices": ["y"]})
        issues = self._lint(main, ["x"], ["y"],
                            rules=["duplicate-output"])
        assert issues and issues[0].severity == analysis.ERROR

    def test_dead_output_warns_only_when_whole_op_dead(self):
        main = pt.Program()
        b = main.global_block
        b.create_var(name="x", shape=[4], dtype="float32", is_data=True)
        b.create_var(name="y", shape=[4], dtype="float32")
        b.create_var(name="z", shape=[4], dtype="float32")
        b.append_op("relu", {"X": ["x"]}, {"Out": ["y"]})
        b.append_op("tanh", {"X": ["x"]}, {"Out": ["z"]})
        issues = self._lint(main, ["x"], ["y"], rules=["dead-output"])
        assert len(issues) == 1
        assert issues[0].severity == analysis.WARNING
        assert issues[0].op_type == "tanh"

    def test_optional_input_contract(self):
        main = pt.Program()
        b = main.global_block
        b.create_var(name="x", shape=[4], dtype="float32", is_data=True)
        b.create_var(name="y", shape=[4], dtype="float32")
        op = b.append_op("relu", {"X": ["x"]}, {"Out": ["y"]})
        op.inputs["Mystery"] = []  # empty, undeclared-optional slot
        issues = self._lint(main, ["x"], ["y"],
                            rules=["optional-input-contract"])
        assert issues and issues[0].slot == "Mystery"

    def test_rng_determinism_lint(self):
        def build():
            x = layers.data("x", shape=[4], dtype="float32")
            return layers.dropout(x, dropout_prob=0.5)

        main, startup, y = _build(build)
        issues = self._lint(main, ["x"], [y.name], rules=["rng-no-seed"])
        assert issues and issues[0].severity == analysis.WARNING
        main.random_seed = 7
        assert not self._lint(main, ["x"], [y.name],
                              rules=["rng-no-seed"])

    def test_fetch_donated_state_hazard(self):
        main = pt.Program()
        b = main.global_block
        b.create_parameter(name="p", shape=[4], dtype="float32")
        b.create_var(name="g", shape=[4], dtype="float32", is_data=True)
        b.append_op("elementwise_add", {"X": ["p"], "Y": ["g"]},
                    {"Out": ["p"]})
        issues = self._lint(main, ["g"], ["p"],
                            rules=["fetch-donated-state"])
        assert issues and "donat" in issues[0].message

    def test_fetch_never_produced(self):
        main = pt.Program()
        with pytest.raises(analysis.ProgramVerifyError):
            analysis.verify_program(main, [], ["ghost"])

    def test_async_overlap_check(self):
        def prog():
            p = pt.Program()
            b = p.global_block
            b.create_parameter(name="shared", shape=[4], dtype="float32")
            b.create_var(name="x", shape=[4], dtype="float32",
                         is_data=True)
            b.append_op("elementwise_add", {"X": ["shared"], "Y": ["x"]},
                        {"Out": ["shared"]})
            return p

        issues = analysis.check_async_overlap(
            [(prog(), ["x"], []), (prog(), ["x"], [])])
        assert issues and "shared" in issues[0].message
        assert not analysis.check_async_overlap([(prog(), ["x"], [])])

    def test_custom_rule_registry_mirrors_pass_registry(self):
        class NoTanh(analysis.LintRule):
            name = "no-tanh-test-rule"

            def check(self, program, ctx):
                for block in program.blocks:
                    for i, op in enumerate(block.ops):
                        if op.type == "tanh":
                            yield analysis.LintIssue(
                                rule=self.name,
                                severity=analysis.WARNING,
                                message="tanh is banned here",
                                op_index=i, op_type="tanh")

        analysis.register_rule(NoTanh)
        try:
            assert "no-tanh-test-rule" in analysis.registered_rules()
            main = pt.Program()
            b = main.global_block
            b.create_var(name="x", shape=[4], dtype="float32",
                         is_data=True)
            b.create_var(name="y", shape=[4], dtype="float32")
            b.append_op("tanh", {"X": ["x"]}, {"Out": ["y"]})
            issues = analysis.run_lint(main, ["x"], ["y"],
                                       rules=["no-tanh-test-rule"])
            assert len(issues) == 1 and issues[0].op_type == "tanh"
        finally:
            from paddle_tpu.analysis import lint as lint_mod

            lint_mod._RULE_REGISTRY.pop("no-tanh-test-rule", None)

    def test_verify_program_with_scope_accepts_scope_state(self):
        """Scope-resident state (KV caches) resolves inputs the program
        itself never declares."""
        main = pt.Program()
        b = main.global_block
        b.create_var(name="x", shape=[4], dtype="float32", is_data=True)
        b.create_var(name="y", shape=[4], dtype="float32")
        b.append_op("elementwise_add", {"X": ["x"], "Y": ["cache"]},
                    {"Out": ["y"]})
        scope = pt.Scope()
        scope.set("cache", np.zeros([4], np.float32))
        analysis.verify_program(main, ["x"], ["y"], scope=scope)
        with pytest.raises(analysis.ProgramVerifyError):
            analysis.verify_program(main, ["x"], ["y"], scope=pt.Scope())


# ==========================================================================
# Registry satellites
# ==========================================================================
class TestRegistrySatellites:
    def test_get_op_error_truncates_and_suggests(self):
        with pytest.raises(KeyError) as ei:
            registry.get_op("softmax_with_crossentropy")
        msg = str(ei.value)
        assert "did you mean" in msg
        assert "softmax_with_cross_entropy" in msg
        # the full registry (hundreds of names) is NOT dumped
        assert len(msg) < 600
        assert "registered_ops()" in msg

    def test_infer_outputs_memoized_with_counters(self):
        import jax
        import jax.numpy as jnp

        registry.clear_infer_cache()
        sds = jax.ShapeDtypeStruct((3, 5), jnp.float32)
        r1 = registry.infer_outputs("relu", {}, {"X": [sds]})
        r2 = registry.infer_outputs("relu", {}, {"X": [sds]})
        assert r1["Out"][0].shape == (3, 5)
        assert r2["Out"][0].shape == (3, 5)
        stats = registry.infer_cache_stats()
        assert stats["hits"] == 1 and stats["misses"] == 1
        # callsite metadata must not split cache entries
        registry.infer_outputs("relu", {"_callsite": "a.py:1"},
                               {"X": [sds]})
        assert registry.infer_cache_stats()["hits"] == 2
        # different signature is a distinct entry
        registry.infer_outputs(
            "relu", {}, {"X": [jax.ShapeDtypeStruct((7,), jnp.float32)]})
        assert registry.infer_cache_stats()["misses"] == 2

    def test_infer_cache_counters_in_profiler_statset(self):
        from paddle_tpu import profiler

        import jax
        import jax.numpy as jnp

        profiler.global_stat.reset()
        registry.clear_infer_cache()
        sds = jax.ShapeDtypeStruct((2, 2), jnp.float32)
        registry.infer_outputs("tanh", {}, {"X": [sds]})
        registry.infer_outputs("tanh", {}, {"X": [sds]})
        names = [row[0] for row in profiler.global_stat.table()]
        assert "registry/infer_cache/hit" in names
        assert "registry/infer_cache/miss" in names
        assert profiler.global_stat.kind_of(
            "registry/infer_cache/hit") == "count"

    def test_layer_build_reuses_cache(self):
        registry.clear_infer_cache()

        def build():
            x = layers.data("x", shape=[16], dtype="float32")
            h = x
            for _ in range(4):  # identical signatures -> cache hits
                h = layers.fc(h, size=16, act="relu")
            return h

        _build(build)
        stats = registry.infer_cache_stats()
        assert stats["hits"] > 0

    def test_mutating_cached_result_does_not_poison_cache(self):
        import jax
        import jax.numpy as jnp

        registry.clear_infer_cache()
        sds = jax.ShapeDtypeStruct((3,), jnp.float32)
        r1 = registry.infer_outputs("relu", {}, {"X": [sds]})
        r1["Out"].append("garbage")
        r1["Extra"] = ["junk"]
        r2 = registry.infer_outputs("relu", {}, {"X": [sds]})
        assert list(r2.keys()) == ["Out"] and len(r2["Out"]) == 1

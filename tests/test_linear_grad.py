"""Fused linear backward (kernels/linear_grad.py): kernel logic validated
in Pallas interpret mode on CPU (the real-chip run lives in
tests/tpu_tier.py::fused_linear_backward_matches_xla), plus the
custom-vjp plumbing and the VMEM-budget fallback decisions."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import paddle_tpu.kernels.linear_grad as lg


def _run_kernel_interpret(x, dy, w, blk):
    R, I = x.shape
    O = w.shape[1]
    nsteps = R // blk
    return pl.pallas_call(
        functools.partial(lg._linear_bwd_kernel, nsteps=nsteps,
                          precision=None),
        grid=(nsteps,),
        in_specs=[pl.BlockSpec((blk, I), lambda i: (i, 0)),
                  pl.BlockSpec((blk, O), lambda i: (i, 0)),
                  pl.BlockSpec((I, O), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((blk, I), lambda i: (i, 0)),
                   pl.BlockSpec((I, O), lambda i: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((R, I), x.dtype),
                   jax.ShapeDtypeStruct((I, O), w.dtype)],
        scratch_shapes=[pltpu.VMEM((I, O), jnp.float32)],
        interpret=True,
    )(x, dy, w)


@pytest.mark.parametrize("R,I,O", [(1024, 256, 64), (512, 128, 128),
                                   (2048, 64, 256)])
def test_kernel_matches_reference_dots(R, I, O):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(R, I), jnp.bfloat16)
    w = jnp.asarray(rng.randn(I, O), jnp.bfloat16)
    dy = jnp.asarray(rng.randn(R, O), jnp.bfloat16)
    blk = lg._pick_block(R, I, O, 2, 2, 2)
    assert blk > 0 and R % blk == 0
    dx, dw = _run_kernel_interpret(x, dy, w, blk)
    dxr = (dy.astype(jnp.float32) @ w.astype(jnp.float32).T)
    dwr = (x.astype(jnp.float32).T @ dy.astype(jnp.float32))
    sx = float(jnp.max(jnp.abs(dxr))) + 1e-9
    sw = float(jnp.max(jnp.abs(dwr))) + 1e-9
    assert float(jnp.max(jnp.abs(dx.astype(jnp.float32) - dxr))) < 2e-2 * sx
    assert float(jnp.max(jnp.abs(dw.astype(jnp.float32) - dwr))) < 2e-2 * sw


def test_vmem_budget_fallback_decisions():
    # vocab-sized head: weight-resident footprint alone exceeds the budget
    assert lg._pick_block(16384, 1024, 16384, 2, 2, 2) == 0
    # transformer FFN no longer fits: XLA's 16 MB scoped-vmem limit for
    # custom calls is the binding constraint (measured on chip — a 44 MB
    # claim is a hard compile error), so [1024, 4096]-sized weight
    # residency (32 MB fixed) must fall back to the XLA dots
    assert lg._pick_block(16384, 1024, 4096, 2, 2, 2) == 0
    # qkv/out-proj-sized weights still fit
    assert lg._pick_block(16384, 1024, 1024, 2, 2, 2) > 0
    # untileable R
    assert lg._pick_block(1000, 128, 128, 2, 2, 2) == 0


def test_custom_vjp_matches_plain_dot_grads():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(256, 64), jnp.float32)
    w = jnp.asarray(rng.randn(64, 32), jnp.float32)

    def f_fused(x, w):
        return jnp.sum(jnp.tanh(lg.linear2d(x, w)))

    def f_plain(x, w):
        return jnp.sum(jnp.tanh(x @ w))

    gx, gw = jax.grad(f_fused, argnums=(0, 1))(x, w)
    gx2, gw2 = jax.grad(f_plain, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx2), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw2), rtol=1e-5,
                               atol=1e-6)

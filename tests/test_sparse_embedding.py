"""Sparse (SelectedRows) embedding path + Wide&Deep CTR flagship.

Mirrors the reference's sparse coverage: lookup_table_op's SelectedRows
gradient (/root/reference/paddle/operators/lookup_table_op.cc:59, tested in
fluid test_lookup_table_op.py), sparse optimizer kernels
(test_sgd_op.py TestSparseSGDOp, adagrad/adam sparse tests), and the
CompareSparse trainer tests (/root/reference/paddle/trainer/tests/
test_CompareSparse.cpp) which assert sparse == dense training results.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core.selected_rows import SelectedRows

import jax
import jax.numpy as jnp


def test_selected_rows_to_dense_and_merge():
    rows = jnp.array([3, 1, 3, 7], jnp.int32)
    vals = jnp.arange(8, dtype=jnp.float32).reshape(4, 2)
    sr = SelectedRows(rows, vals, height=8)
    dense = np.asarray(sr.to_dense())
    expect = np.zeros((8, 2), np.float32)
    for r, v in zip(np.asarray(rows), np.asarray(vals)):
        expect[r] += v
    np.testing.assert_allclose(dense, expect)

    m = sr.merged()
    # merged keeps static length; padding slots carry the height sentinel
    np.testing.assert_allclose(np.asarray(m.to_dense()), expect)
    mrows = np.asarray(m.rows)
    uniq = sorted(set(np.asarray(rows).tolist()))
    assert mrows[:len(uniq)].tolist() == uniq
    assert (mrows[len(uniq):] == 8).all()


def test_selected_rows_add_and_scale():
    a = SelectedRows(jnp.array([0, 2], jnp.int32),
                     jnp.ones((2, 3), jnp.float32), height=4)
    b = SelectedRows(jnp.array([2, 3], jnp.int32),
                     2 * jnp.ones((2, 3), jnp.float32), height=4)
    s = a + b
    assert isinstance(s, SelectedRows)
    dense = np.asarray(s.to_dense())
    assert dense[2].tolist() == [3.0, 3.0, 3.0]
    scaled = np.asarray((0.5 * a).to_dense())
    assert scaled[0].tolist() == [0.5, 0.5, 0.5]


def _train_embedding(is_sparse, optimizer_fn, steps=4, vocab=50, dim=8):
    """Train a one-embedding bow classifier; return final weight table."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        ids = layers.data("ids", shape=[5], dtype="int64")
        label = layers.data("label", shape=[1], dtype="int64")
        emb = layers.embedding(ids, size=[vocab, dim], is_sparse=is_sparse)
        bow = layers.reshape(emb, [-1, 5 * dim])
        logits = layers.fc(bow, size=2)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        optimizer_fn().minimize(loss, startup_program=startup)
    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    emb_name = [k for k in scope.keys() if "embedding" in k and ".w" in k][0]
    losses = []
    for _ in range(steps):
        idb = rng.randint(0, vocab, size=(8, 5)).astype(np.int64)
        lb = rng.randint(0, 2, size=(8, 1)).astype(np.int64)
        out, = exe.run(main, feed={"ids": idb, "label": lb},
                       fetch_list=[loss], scope=scope)
        losses.append(float(out))
    return np.asarray(scope.get(emb_name)), losses


@pytest.mark.parametrize("opt", [
    lambda: pt.optimizer.SGDOptimizer(learning_rate=0.1),
    lambda: pt.optimizer.AdagradOptimizer(learning_rate=0.1),
])
def test_sparse_training_matches_dense(opt):
    """sgd/adagrad row-sparse updates are exactly the dense update restricted
    to touched rows (test_CompareSparse.cpp's contract)."""
    w_dense, l_dense = _train_embedding(False, opt)
    w_sparse, l_sparse = _train_embedding(True, opt)
    np.testing.assert_allclose(w_sparse, w_dense, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(l_sparse, l_dense, rtol=2e-5)


def test_sparse_adam_touched_rows_match_manual():
    """Lazy Adam: touched rows follow the dense formula; untouched rows (and
    their moments) stay exactly put."""
    vocab, dim = 20, 4
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        ids = layers.data("ids", shape=[3], dtype="int64")
        emb = layers.embedding(ids, size=[vocab, dim], is_sparse=True)
        loss = layers.mean(emb)
        pt.optimizer.AdamOptimizer(learning_rate=0.01).minimize(
            loss, startup_program=startup)
    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    exe.run(startup, scope=scope)
    emb_name = [k for k in scope.keys() if "embedding" in k and ".w" in k][0]
    w0 = np.asarray(scope.get(emb_name)).copy()
    idb = np.array([[2, 5, 5]], np.int64)  # row 5 repeated: grads accumulate
    exe.run(main, feed={"ids": idb}, scope=scope)
    w1 = np.asarray(scope.get(emb_name))

    # manual lazy-adam for the touched rows
    g = np.zeros_like(w0)
    n = idb.size
    for i in idb.ravel():
        g[i] += 1.0 / (n * dim)
    touched = [2, 5]
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    m1 = (1 - b1) * g
    m2 = (1 - b2) * g ** 2
    lr_t = lr * np.sqrt(1 - b2) / (1 - b1)
    expect = w0.copy()
    for r in touched:
        expect[r] -= lr_t * m1[r] / (np.sqrt(m2[r]) + eps)
    np.testing.assert_allclose(w1, expect, rtol=1e-5, atol=1e-7)
    untouched = [i for i in range(vocab) if i not in touched]
    np.testing.assert_array_equal(w1[untouched], w0[untouched])


def test_sparse_grad_is_selected_rows_not_dense():
    """The sparse path must emit a SelectedRows, not a [V, D] array."""
    from paddle_tpu.core.registry import get_op

    opdef = get_op("lookup_table")
    w = jnp.ones((1000, 4), jnp.float32)
    ids = jnp.array([[1], [7]], jnp.int32)
    og = jnp.ones((2, 4), jnp.float32)
    grads = opdef.grad_fn({"is_sparse": True}, {"W": [w], "Ids": [ids]},
                          {}, {"Out": [og]})
    gw = grads["W"][0]
    assert isinstance(gw, SelectedRows)
    assert gw.values.shape == (2, 4)  # no [V, D] materialization
    assert gw.height == 1000


def test_sparse_padding_idx_gets_no_update():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        ids = layers.data("ids", shape=[4], dtype="int64")
        emb = layers.embedding(ids, size=[10, 3], is_sparse=True,
                               padding_idx=0)
        loss = layers.mean(emb)
        pt.optimizer.SGDOptimizer(learning_rate=1.0).minimize(
            loss, startup_program=startup)
    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    exe.run(startup, scope=scope)
    emb_name = [k for k in scope.keys() if "embedding" in k and ".w" in k][0]
    w0 = np.asarray(scope.get(emb_name)).copy()
    exe.run(main, feed={"ids": np.array([[0, 0, 3, 4]], np.int64)},
            scope=scope)
    w1 = np.asarray(scope.get(emb_name))
    np.testing.assert_array_equal(w1[0], w0[0])  # padding row untouched
    assert not np.allclose(w1[3], w0[3])


def test_sparse_grad_accumulation_densifies():
    """Gradient accumulation over a sparse param: the ``acc += grad``
    elementwise add takes the SelectedRows' dense view (regression: it
    used to crash on y.ndim), off-step runs leave the param untouched,
    and the k-th run applies the mean."""
    vocab, dim = 32, 4
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        ids = layers.data("ids", shape=[3], dtype="int64")
        emb = layers.embedding(ids, size=[vocab, dim], is_sparse=True)
        loss = layers.mean(emb)
        pt.optimizer.SGDOptimizer(learning_rate=0.5).minimize(
            loss, startup_program=startup, accumulate_steps=2)
    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    exe.run(startup, scope=scope)
    emb_name = [k for k in scope.keys()
                if "embedding" in k and ".w" in k and "_acc" not in k][0]
    w0 = np.asarray(scope.get(emb_name)).copy()
    feed = {"ids": np.array([[1, 2, 3]], np.int64)}
    exe.run(main, feed=feed, scope=scope)
    np.testing.assert_array_equal(np.asarray(scope.get(emb_name)), w0)
    exe.run(main, feed=feed, scope=scope)  # k-th run: the mean applies
    w2 = np.asarray(scope.get(emb_name))
    assert not np.allclose(w2[[1, 2, 3]], w0[[1, 2, 3]])
    np.testing.assert_array_equal(w2[0], w0[0])


def test_sum_op_mixes_sparse_and_dense():
    """Grad fan-out: embedding used twice -> sum of two SelectedRows stays
    sparse; mixing with a dense contribution densifies."""
    from paddle_tpu.core.registry import get_op

    sum_fn = get_op("sum").fn
    a = SelectedRows(jnp.array([1], jnp.int32), jnp.ones((1, 2)), 4)
    b = SelectedRows(jnp.array([3], jnp.int32), jnp.ones((1, 2)), 4)
    r = sum_fn({}, {"X": [a, b]})["Out"][0]
    assert isinstance(r, SelectedRows)
    d = jnp.ones((4, 2), jnp.float32)
    r2 = sum_fn({}, {"X": [a, d]})["Out"][0]
    assert not isinstance(r2, SelectedRows)
    np.testing.assert_allclose(np.asarray(r2)[1], [2.0, 2.0])


# ---------------------------------------------------------------------------
# Wide&Deep CTR flagship (BASELINE.json configs[5])
# ---------------------------------------------------------------------------
def _ctr_batch(rng, batch, slots, vocab, dense_dim):
    # Zipf-ish id traffic: most lookups hit a small hot set (real CTR data),
    # so per-id embeddings are learnable within a short test run, while the
    # table itself stays high-dimensional (the sparse path under test).
    hot = rng.randint(0, 200, size=(batch, slots))
    cold = rng.randint(0, vocab, size=(batch, slots))
    ids = np.where(rng.rand(batch, slots) < 0.9, hot, cold).astype(np.int64)
    dense = rng.rand(batch, dense_dim).astype(np.float32)
    # clickiness depends on a few "magic" id buckets + one dense feature
    signal = (ids % 7 == 3).sum(1) * 0.8 + dense[:, 0] * 2.0 - 2.2
    prob = 1.0 / (1.0 + np.exp(-signal))
    label = (rng.rand(batch) < prob).astype(np.float32)[:, None]
    return ids, dense, label


def test_wide_deep_ctr_trains_large_vocab():
    """The CTR book test: vocab 1e5 sparse embeddings, AUC improves, loss
    falls — with SelectedRows grads (never a [V, D] buffer) on every step."""
    vocab, slots, dense_dim, batch = 100_000, 8, 4, 64
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        ids = layers.data("ids", shape=[slots], dtype="int64")
        dense = layers.data("dense", shape=[dense_dim])
        label = layers.data("label", shape=[1])
        logit = pt.models.wide_deep(ids, dense, vocab_size=vocab,
                                    embed_dim=8, hidden_sizes=(32, 16))
        loss, prob = pt.models.wide_deep_loss(logit, label)
        auc = pt.evaluator.Auc(prob, label, main_program=main,
                               startup_program=startup)
        pt.optimizer.AdagradOptimizer(learning_rate=0.05).minimize(
            loss, startup_program=startup)
    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    first = last = None
    for step in range(60):
        if step == 40:  # measure AUC on the trained model only
            auc.reset(exe, scope)
        idb, db, lb = _ctr_batch(rng, batch, slots, vocab, dense_dim)
        out, = exe.run(main, feed={"ids": idb, "dense": db, "label": lb},
                       fetch_list=[loss], scope=scope)
        if first is None:
            first = float(out)
        last = float(out)
    assert last < first, (first, last)
    assert auc.eval(exe, scope) > 0.65


@pytest.mark.slow  # tier-1 budget (PR 20): dp x mp CTR training sweep;
# sharded_embedding correctness stays tier-1 via the unit tests above
# and the large-vocab train test
def test_wide_deep_ctr_vocab_sharded_mesh():
    """CTR under dp x mp: vocab dim sharded over mp (the ICI replacement for
    the sparse pserver), batch over dp; loss matches single-device run."""
    import jax as _jax
    from paddle_tpu.parallel import make_mesh, vocab_sharded_plan

    if len(_jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    vocab, slots, dense_dim, batch = 1024, 4, 3, 16

    def build():
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            ids = layers.data("ids", shape=[slots], dtype="int64")
            dense = layers.data("dense", shape=[dense_dim])
            label = layers.data("label", shape=[1])
            logit = pt.models.wide_deep(ids, dense, vocab_size=vocab,
                                        embed_dim=4, hidden_sizes=(16,))
            loss, _ = pt.models.wide_deep_loss(logit, label)
            pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(
                loss, startup_program=startup)
        return main, startup, loss

    rng = np.random.RandomState(1)
    batches = [_ctr_batch(rng, batch, slots, vocab, dense_dim)
               for _ in range(3)]

    def run(mesh, plan):
        main, startup, loss = build()
        scope = pt.Scope()
        exe = pt.Executor(mesh=mesh, plan=plan)
        exe.run(startup, scope=scope)
        outs = []
        for idb, db, lb in batches:
            o, = exe.run(main, feed={"ids": idb, "dense": db, "label": lb},
                         fetch_list=[loss], scope=scope)
            outs.append(float(o))
        return outs

    single = run(None, None)
    mesh = make_mesh({"dp": 2, "mp": 2}, devices=_jax.devices()[:4])
    sharded = run(mesh, vocab_sharded_plan(mesh))
    np.testing.assert_allclose(sharded, single, rtol=1e-4, atol=1e-6)


def test_sparse_embedding_trains_under_data_parallel_mesh():
    """The SelectedRows sparse-gradient path composes with the dp sharding
    plan: the CTR shape (ragged id-lists -> embedding-sum -> head) trains
    over the 8-device mesh, GSPMD handling the gradient exchange the
    reference routed through its sparse pserver updaters."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.parallel import make_mesh

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        ids = layers.data("ids", shape=[1], dtype="int64", lod_level=1)
        y = layers.data("y", shape=[1], dtype="int64")
        emb = layers.embedding(ids, size=[1000, 16], is_sparse=True)
        emb.seq_len = ids.seq_len
        pooled = layers.sequence_pool(emb, "sum")
        logits = layers.fc(pooled, size=2)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        pt.optimizer.AdamOptimizer(learning_rate=1e-2).minimize(
            loss, startup_program=startup)

    exe = pt.Executor(mesh=make_mesh({"dp": 8}))
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    feed = {"ids": rng.randint(0, 1000, size=(16, 5)).astype(np.int64),
            "ids@len": rng.randint(1, 6, size=16).astype(np.int32),
            "y": rng.randint(0, 2, size=(16, 1)).astype(np.int64)}
    losses = [float(exe.run(main, feed=feed, fetch_list=[loss],
                            scope=scope)[0]) for _ in range(4)]
    assert losses[-1] < losses[0], losses

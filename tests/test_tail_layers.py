"""The final v1 layer-name tail (ops/tail_ops.py + v2 wrappers):
sub_seq, switch_order, scale_sub_region, selective_fc, lambda_cost,
cross_entropy_with_selfnorm, img_cmrnorm, 3-D conv/pool wrappers,
conv_projection — checked against hand-computed references."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers as L
from paddle_tpu.v2 import layer as l2


def _run(fetches, feed, main, startup, seed=None):
    if seed is not None:
        main.random_seed = startup.random_seed = seed
    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    exe.run(startup, scope=scope)
    outs = exe.run(main, feed=feed, fetch_list=list(fetches), scope=scope)
    return [np.asarray(o) for o in outs]


def test_sub_seq_slices_rows():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = L.data("x", shape=[4, 3])
        off = L.data("off", shape=[1], dtype="int64")
        sz = L.data("sz", shape=[1], dtype="int64")
        out = l2.sub_seq(x, off, sz)
    rng = np.random.RandomState(0)
    xv = rng.rand(2, 4, 3).astype("float32")
    o, = _run([out], {"x": xv, "off": np.array([[1], [0]], "int64"),
                      "sz": np.array([[2], [3]], "int64")}, main, startup)
    np.testing.assert_allclose(o[0, :2], xv[0, 1:3], rtol=1e-6)
    assert np.abs(o[0, 2:]).max() == 0  # masked past size
    np.testing.assert_allclose(o[1, :3], xv[1, :3], rtol=1e-6)


def test_switch_order_nchw_to_nhwc():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = L.data("x", shape=[3, 4, 5])  # C,H,W
        out = l2.switch_order(x)
        out2 = l2.switch_order(x, reshape_axis=2)
    xv = np.random.RandomState(0).rand(2, 3, 4, 5).astype("float32")
    o, o2 = _run([out, out2], {"x": xv}, main, startup)
    np.testing.assert_allclose(o, xv.transpose(0, 2, 3, 1), rtol=1e-6)
    assert o2.shape == (2, 4 * 5, 3)


def test_scale_sub_region_matches_loop():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = L.data("x", shape=[2, 4, 4])
        idx = L.data("idx", shape=[6], dtype="int64")
        out = l2.scale_sub_region(x, idx, value=3.0)
    rng = np.random.RandomState(1)
    xv = rng.rand(2, 2, 4, 4).astype("float32")
    iv = np.array([[1, 1, 2, 3, 1, 2], [2, 2, 1, 4, 3, 4]], "int64")
    o, = _run([out], {"x": xv, "idx": iv}, main, startup)
    want = xv.copy()
    for b in range(2):
        c0, c1, h0, h1, w0, w1 = iv[b]
        want[b, c0 - 1:c1, h0 - 1:h1, w0 - 1:w1] *= 3.0
    np.testing.assert_allclose(o, want, rtol=1e-6)


def test_selective_fc_masks_unselected():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = L.data("x", shape=[6])
        sel = L.data("sel", shape=[4])
        out = l2.selective_fc(x, sel, 4)
    rng = np.random.RandomState(0)
    o, = _run([out], {"x": rng.rand(3, 6).astype("float32"),
                      "sel": np.array([[1, 0, 1, 0]] * 3, "float32")},
              main, startup, seed=3)
    assert np.abs(o[:, 1]).max() == 0 and np.abs(o[:, 3]).max() == 0
    assert np.abs(o[:, 0]).max() > 0


def test_lambda_cost_orders_scores():
    """Perfectly ordered scores cost less than inverted ones. Reference
    argument order (CostLayer.cpp LambdaCost): the FIRST argument is the
    model's score output, the second the ground-truth relevance."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        sc = L.data("sc", shape=[5])
        rel = L.data("rel", shape=[5])
        cost = l2.lambda_cost(sc, rel, NDCG_num=5)
    relv = np.array([[3, 2, 1, 0, 0]], "float32")
    good = np.array([[5, 4, 3, 2, 1]], "float32")
    bad = np.array([[1, 2, 3, 4, 5]], "float32")
    g, = _run([cost], {"rel": relv, "sc": good}, main, startup)
    b, = _run([cost], {"rel": relv, "sc": bad}, main, startup)
    assert float(g[0]) < float(b[0])


def test_lambda_cost_max_sort_size_gates_anchor_only():
    """Truncated-sort mode (LambdaCost::calcGrad): only the HIGHER-
    relevance anchor must rank inside the top max_sort_size; pairs whose
    partner ranks outside still contribute — so the truncated cost sits
    strictly between zero and the untruncated cost when relevant items
    rank below the cut."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        sc = L.data("sc", shape=[6])
        rel = L.data("rel", shape=[6])
        full = l2.lambda_cost(sc, rel, NDCG_num=6)
        cut = l2.lambda_cost(sc, rel, NDCG_num=6, max_sort_size=2)
    # scores rank items as [s0 s1 | s2 s3 s4 s5]; the only relevant item
    # (rel=2) sits at rank 2 — OUTSIDE the top-2 cut
    relv = np.array([[0, 0, 2, 0, 0, 1]], "float32")
    scv = np.array([[6, 5, 4, 3, 2, 1]], "float32")
    f, c = _run([full, cut], {"rel": relv, "sc": scv}, main, startup)
    # both anchors (ranks 2 and 5) are outside the top-2: truncation
    # must zero the cost even though partners rank inside
    assert float(f[0]) > 0
    assert float(c[0]) == 0
    # move the rel=2 anchor into the cut (rank 0): its pairs against ALL
    # lower-relevance partners count, including partners beyond the cut
    scv2 = np.array([[1, 5, 6, 3, 2, 4]], "float32")
    f2, c2 = _run([full, cut], {"rel": relv, "sc": scv2}, main, startup)
    assert 0 < float(c2[0]) < float(f2[0]) + 1e-6
    # with the pair-side (old, wrong) gating, the rank-0 anchor's pairs
    # against partners ranked >= 2 would vanish; anchor-side gating
    # keeps them: the truncated cost must count pairs whose partner is
    # outside the cut. rel=1 at rank 5 contributes nothing (anchor out).
    # Hand-count: anchor rank0 pairs vs the five rel<2 partners all
    # survive => cut == those pairs' sum under the full delta/loss —
    # equality with a full cost computed on a list where the OTHER
    # anchor (rel=1) is removed is checked structurally instead:
    relv3 = np.array([[0, 0, 2, 0, 0, 0]], "float32")
    f3, c3 = _run([full, cut], {"rel": relv3, "sc": scv2}, main, startup)
    np.testing.assert_allclose(float(c3[0]), float(f3[0]), rtol=1e-6)


def test_cross_entropy_with_selfnorm_formula():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = L.data("x", shape=[4])
        lbl = L.data("lbl", shape=[1], dtype="int64")
        out = l2.cross_entropy_with_selfnorm(
            x, lbl, softmax_selfnorm_alpha=0.2)
    xv = np.array([[0.2, 0.3, 0.4, 0.3]], "float32")  # Z = 1.2
    o, = _run([out], {"x": xv, "lbl": np.array([[2]], "int64")},
              main, startup)
    z = 1.2
    want = -np.log(0.4) + np.log(z) + 0.2 * np.log(z) ** 2
    np.testing.assert_allclose(float(o[0]), want, rtol=1e-5)


def test_img_cmrnorm_and_3d_wrappers_build_and_run():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        img = L.data("img", shape=[6, 6, 4])  # NHWC
        norm = l2.img_cmrnorm(img, size=3)
        vol = L.data("vol", shape=[2, 5, 6, 6])  # NCDHW
        c3 = l2.img_conv3d(vol, 3, 4, padding=1, act="relu")
        p3 = l2.img_pool3d(c3, 2, stride=2)
    rng = np.random.RandomState(0)
    o1, o2, o3 = _run([norm, c3, p3],
                      {"img": rng.rand(2, 6, 6, 4).astype("float32"),
                       "vol": rng.rand(2, 2, 5, 6, 6).astype("float32")},
                      main, startup, seed=1)
    assert o1.shape == (2, 6, 6, 4)
    assert o2.shape == (2, 4, 5, 6, 6)
    assert o3.shape == (2, 4, 2, 3, 3)
    assert np.isfinite(o1).all() and np.isfinite(o3).all()


def test_conv_projection_in_mixed_layer():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        img = L.data("img", shape=[6, 6, 3])
        mix = l2.mixed_layer(size=0, input=[
            l2.conv_projection(img, 3, 4, padding=1)])
    o, = _run([mix], {"img": np.random.RandomState(0).rand(
        2, 6, 6, 3).astype("float32")}, main, startup, seed=2)
    assert o.shape == (2, 6, 6, 4)


def test_v1_namespace_carries_the_tail():
    from paddle_tpu.v1 import helpers

    for name in ("selective_fc_layer", "lambda_cost",
                 "cross_entropy_with_selfnorm", "sub_seq_layer",
                 "switch_order_layer", "scale_sub_region_layer",
                 "img_cmrnorm_layer", "img_conv3d_layer",
                 "img_pool3d_layer", "conv_projection", "conv_operator"):
        assert name in helpers._EXPORTS, name


def test_full_trainer_config_helpers_namespace_parity():
    """Every name in the reference trainer_config_helpers modules'
    __all__ (layers, networks, evaluators, optimizers, attrs, poolings,
    activations) exists in the v1 namespace — SURVEY row 29 closed
    structurally, not by sampling."""
    import os
    import re

    import pytest

    ref_dir = "/root/reference/python/paddle/trainer_config_helpers"
    if not os.path.isdir(ref_dir):
        pytest.skip("reference tree not present")
    from paddle_tpu.v1 import helpers as H

    missing = {}
    for mod in ("layers", "networks", "evaluators", "optimizers",
                "attrs", "poolings", "activations"):
        src = open(f"{ref_dir}/{mod}.py").read()
        m = re.search(r"__all__ = \[(.*?)\]", src, re.S)
        names = re.findall(r"[\"']([A-Za-z_0-9]+)[\"']", m.group(1))
        miss = [n for n in names if n not in H._EXPORTS]
        if miss:
            missing[mod] = miss
    assert not missing, missing


def test_tensor_layer_bilinear_product():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        a = L.data("a", shape=[3])
        b = L.data("b", shape=[4])
        from paddle_tpu.v1 import helpers as H

        out = H.tensor_layer(a, b, size=5)
    rng = np.random.RandomState(0)
    av, bv = rng.rand(2, 3).astype("f4"), rng.rand(2, 4).astype("f4")
    o, = _run([out], {"a": av, "b": bv}, main, startup, seed=1)
    assert o.shape == (2, 5)
    assert np.isfinite(o).all()


def test_sub_nested_seq_gathers_subsequences():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = L.data("x", shape=[3, 4, 2])  # [b, S=3, T=4, d=2]
        idx = L.data("idx", shape=[2], dtype="int64")
        from paddle_tpu.v1 import helpers as H

        out = H.sub_nested_seq_layer(x, idx)
    rng = np.random.RandomState(0)
    xv = rng.rand(2, 3, 4, 2).astype("f4")
    iv = np.array([[2, 0], [1, -1]], "int64")
    o, = _run([out], {"x": xv, "idx": iv}, main, startup)
    np.testing.assert_allclose(o[0, 0], xv[0, 2], rtol=1e-6)
    np.testing.assert_allclose(o[0, 1], xv[0, 0], rtol=1e-6)
    np.testing.assert_allclose(o[1, 0], xv[1, 1], rtol=1e-6)
    assert np.abs(o[1, 1]).max() == 0  # -1 selects nothing


def test_lstmemory_group_and_gru_group_train_shapes():
    """The step-visible LSTM/GRU composites (reference networks.py
    lstmemory_group / gru_group) run inside recurrent_group."""
    from paddle_tpu.v1 import helpers as H

    prev = H._CTX
    H._CTX = H.ParseContext()
    try:
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = L.data("x", shape=[5, 6])
            lstm_seq = H.lstmemory_group(x, size=4, name="lg")
            gru_in = L.data("g", shape=[5, 9])
            gru_seq = H.gru_group(gru_in, size=3, name="gg")
    finally:
        H._CTX = prev
    rng = np.random.RandomState(0)
    o1, o2 = _run([lstm_seq, gru_seq],
                  {"x": rng.rand(2, 5, 6).astype("f4"),
                   "g": rng.rand(2, 5, 9).astype("f4")},
                  main, startup, seed=4)
    assert o1.shape == (2, 5, 4)
    assert o2.shape == (2, 5, 3)
    assert np.isfinite(o1).all() and np.isfinite(o2).all()


def test_seq_slice_and_crop_reference_contracts():
    from paddle_tpu.v1 import helpers as H

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = L.data("x", shape=[5, 2])
        st = L.data("st", shape=[1], dtype="int64")
        en = L.data("en", shape=[1], dtype="int64")
        sl = H.seq_slice_layer(x, starts=st, ends=en)
        img = L.data("img", shape=[3, 6, 6])  # NCHW-ish [C,H,W]
        cr = H.crop_layer(img, offset=[1, 2], axis=2, shape=[4, 3])
    rng = np.random.RandomState(0)
    xv = rng.rand(2, 5, 2).astype("f4")
    iv = rng.rand(2, 3, 6, 6).astype("f4")
    o_sl, o_cr = _run([sl, cr], {
        "x": xv, "st": np.array([[1], [0]], "int64"),
        "en": np.array([[4], [2]], "int64"), "img": iv}, main, startup)
    # [start, end): row 0 gets elements 1..3 (len 3), row 1 gets 0..1
    np.testing.assert_allclose(o_sl[0, :3], xv[0, 1:4], rtol=1e-6)
    assert np.abs(o_sl[1, 2:]).max() == 0
    assert o_cr.shape == (2, 3, 4, 3)
    np.testing.assert_allclose(o_cr, iv[:, :, 1:5, 2:5], rtol=1e-6)


def test_detection_output_keep_top_k_caps_globally():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        from paddle_tpu.layers.layer_helper import LayerHelper

        sc = L.data("sc", shape=[8, 3])
        bx = L.data("bx", shape=[8, 4])
        helper = LayerHelper("det")
        out5 = helper.simple_op(
            "detection_output", {"Scores": [sc], "Boxes": [bx]},
            {"nms_threshold": 0.45, "nms_top_k": 8, "keep_top_k": 5,
             "score_threshold": 0.01})
    rng = np.random.RandomState(0)
    scores = rng.rand(1, 8, 3).astype("f4")
    boxes = np.sort(rng.rand(1, 8, 2, 2), axis=2).reshape(1, 8, 4) \
        .astype("f4")
    o, = _run([out5], {"sc": scores, "bx": boxes}, main, startup)
    assert o.shape[1] == 5  # the global cross-class cap

"""paddle_tpu.serving.tenancy: multi-tenant model registry + one /v1.

Pins the multi-tenancy contracts:

1. ROUTING — requests route on their ``model``/``tenant`` field into
   the named tenant's own queue and engines; absent means the default
   tenant; unknown ids are a typed ModelNotFoundError (HTTP 404 on the
   wire, mapped BACK to the typed error by HttpReplica), never a silent
   fall-through;
2. ISOLATION — per-tenant admission quotas (QueueFullError), per-tenant
   sampling defaults, per-tenant labeled gauges and SLO burn-rate
   planes on ONE shared registry;
3. TENANT-SCOPED ROLLS — ``swap_params(tenant=...)`` / a tenant-scoped
   ``online.Publisher`` roll one tenant to a new weight generation
   while the other tenant keeps serving token-exact with zero failed
   requests, and the ``weights_version{tenant=...}`` gauges move
   independently;
4. the 2-replica FLEET STORM — two models behind one fleet under
   concurrent mixed traffic: zero failed requests, zero cross-tenant
   interference in sampled tokens, zero steady-state fresh compiles.
"""
import json
import os
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models
from paddle_tpu.decoding import SamplingParams
from paddle_tpu.serving import (Fleet, GenerationEngine, HttpReplica,
                                LMSpec, QueueFullError)
from paddle_tpu.serving.errors import ModelNotFoundError
from paddle_tpu.serving.tenancy import (ModelRegistry, MultiTenantServer,
                                        Tenant)
from paddle_tpu.trace.slo import SLO

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VOCAB, D, L, H, MAXLEN = 32, 16, 2, 2, 32
SEED_RANKER, SEED_CHAT = 7, 13

# startup-compile cache: weights initialized once per seed, shared as
# immutable arrays across fresh scopes (tier-1 budget)
_WEIGHTS = {}


def _lm_scope(seed):
    exe = pt.Executor(pt.TPUPlace())
    if seed not in _WEIGHTS:
        scope = pt.Scope()
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            prompt = layers.data("p_init", shape=[8], dtype="int64")
            models.transformer_lm_generate(
                prompt, vocab_size=VOCAB, d_model=D, n_layers=L,
                num_heads=H, max_len=MAXLEN, max_new_tokens=1)
        startup.random_seed = seed
        exe.run(startup, scope=scope)
        _WEIGHTS[seed] = {n: scope.get(n) for n in scope.keys()}
    scope = pt.Scope()
    for n, v in _WEIGHTS[seed].items():
        scope.set(n, v)
    return scope


def _spec():
    return LMSpec(vocab_size=VOCAB, d_model=D, n_layers=L, num_heads=H,
                  max_len=MAXLEN)


def _engine(seed, **kw):
    # narrow bucket grids so warmup() covers every steady-state shape
    # with a handful of compiles (tier-1 budget)
    return GenerationEngine(_spec(), _lm_scope(seed), slots=4,
                            page_size=8, kv_cache="paged",
                            prompt_buckets=(8,),
                            prefill_batch_buckets=(1, 2, 4), **kw)


def _registry(slo=None):
    """Two resident models: 'ranker' (greedy default) and 'chat' (a
    seeded sampled default — deterministic, but different weights AND
    different decode behavior)."""
    reg = ModelRegistry()
    reg.register("ranker", [_engine(SEED_RANKER)], slo=slo)
    reg.register("chat", [_engine(SEED_CHAT)],
                 sampling=SamplingParams(temperature=0.7, top_k=8,
                                         seed=5), slo=slo)
    return reg


PROMPT = [1, 2, 3]


@pytest.fixture(scope="module")
def mts():
    srv = MultiTenantServer(_registry())
    srv.start()
    yield srv
    srv.stop()


# ---------------------------------------------------------------------------
# registry + tenant (unit)
# ---------------------------------------------------------------------------
class TestRegistry:
    def test_registry_contract(self):
        eng = _engine(SEED_RANKER)
        reg = ModelRegistry()
        t = reg.register("a", [eng])
        assert reg.default is t and reg.resolve(None) is t
        assert "a" in reg and reg.names() == ("a",)
        with pytest.raises(ValueError):
            reg.register("a", [eng])
        with pytest.raises(ModelNotFoundError):
            reg.get("nope")
        # prebuilt tenant under a mismatched name is an error
        with pytest.raises(ValueError):
            reg.register("b", tenant=t)

    def test_tenant_namespace_and_sampling_defaults(self):
        eng = _engine(SEED_RANKER)
        sp = SamplingParams(temperature=0.5, top_k=4, seed=9)
        t = Tenant("canary", eng, sampling=sp, max_pending=2)
        # the tenant name became the engine's manifest/compile namespace
        assert eng.namespace == "canary"
        assert "canary" in eng.manifest_name
        assert eng.default_sampling is sp
        assert eng.temperature == 0.5 and eng.top_k == 4
        # quota: the tenant's own queue bound, typed
        t.batcher.submit({"prompt": PROMPT})
        t.batcher.submit({"prompt": PROMPT})
        with pytest.raises(QueueFullError):
            t.batcher.submit({"prompt": PROMPT})
        t.batcher.close()

    def test_fleetctl_renders_tenant_table(self):
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        try:
            import fleetctl
        finally:
            sys.path.pop(0)
        status = {
            "replicas": [], "pending": 0, "fleet": {},
            "tenants": [
                {"tenant": "ranker", "queue_depth": 2, "active": 1,
                 "pages_in_use": 6, "weights_version": 5.0,
                 "slo_max_burn": 0.5, "slo_alerting": False,
                 "paused": False},
                {"tenant": "chat", "queue_depth": 0, "active": 0,
                 "pages_in_use": 0, "weights_version": 0.0,
                 "slo_max_burn": None, "slo_alerting": False,
                 "paused": True},
            ],
        }
        table = fleetctl.render_status_table(status)
        assert "tenant" in table and "ranker" in table and "chat" in table
        assert "0.5x" in table            # SLO burn column
        assert "paused" in table          # chat's state column
        assert "5" in table               # weights version


# ---------------------------------------------------------------------------
# the multi-tenant server
# ---------------------------------------------------------------------------
class TestMultiTenantServer:
    def test_routing_defaults_and_typed_404(self, mts):
        a = mts.submit({"prompt": PROMPT}, model="ranker",
                       max_new_tokens=4).result(timeout=30)
        b = mts.submit({"prompt": PROMPT}, model="chat",
                       max_new_tokens=4).result(timeout=30)
        d = mts.submit({"prompt": PROMPT},
                       max_new_tokens=4).result(timeout=30)
        # default tenant is the first registered; tenants really serve
        # from their OWN weights/sampling (outputs differ)
        np.testing.assert_array_equal(d, a)
        assert not np.array_equal(a, b)
        # chat's sampled default carries a pinned seed: deterministic
        b2 = mts.submit({"prompt": PROMPT}, model="chat",
                        max_new_tokens=4).result(timeout=30)
        np.testing.assert_array_equal(b, b2)
        nf0 = mts.metrics.counter("model_not_found")
        with pytest.raises(ModelNotFoundError):
            mts.submit({"prompt": PROMPT}, model="nope")
        assert mts.metrics.counter("model_not_found") == nf0 + 1

    def test_tenant_status_rows_and_labeled_gauges(self, mts):
        rows = {r["tenant"]: r for r in mts.tenant_status()}
        assert set(rows) == {"ranker", "chat"}
        for row in rows.values():
            for key in ("queue_depth", "active", "pages_in_use",
                        "weights_version", "completed", "failed",
                        "paused", "max_pending"):
                assert key in row
        prom = mts.metrics_prometheus()
        assert 'tenant_queue_depth{tenant="ranker"}' in prom
        assert 'weights_version{tenant="chat"}' in prom
        snap = mts.metrics_snapshot()
        assert {r["tenant"] for r in snap["tenants"]} == {"ranker",
                                                          "chat"}

    def test_tenant_scoped_swap_other_tenant_serves_through(self, mts):
        before_r = mts.submit({"prompt": PROMPT}, model="ranker",
                              max_new_tokens=4).result(timeout=30)
        before_c = mts.submit({"prompt": PROMPT}, model="chat",
                              max_new_tokens=4).result(timeout=30)
        swaps0 = mts.metrics.counter("tenant_swaps")
        new = _lm_scope(99)
        mts.swap_params({k: np.asarray(new.get(k)) for k in new.keys()},
                        tenant="chat")
        after_c = mts.submit({"prompt": PROMPT}, model="chat",
                             max_new_tokens=4).result(timeout=30)
        after_r = mts.submit({"prompt": PROMPT}, model="ranker",
                             max_new_tokens=4).result(timeout=30)
        # chat rolled; ranker byte-identical (its engines, queue and
        # pages were never touched)
        assert not np.array_equal(after_c, before_c)
        np.testing.assert_array_equal(after_r, before_r)
        assert mts.metrics.counter("tenant_swaps") == swaps0 + 1
        rows = {r["tenant"]: r for r in mts.tenant_status()}
        assert rows["chat"]["weights_version"] > 0
        assert not rows["chat"]["paused"]  # resumed after the roll
        # roll back so later tests see the module fixture's weights
        old = _lm_scope(SEED_CHAT)
        mts.swap_params({k: np.asarray(old.get(k)) for k in old.keys()},
                        tenant="chat")

    def test_plain_server_answers_tenant_swap_typed(self):
        from paddle_tpu.serving import Server

        eng = _engine(SEED_RANKER)
        srv = Server([eng])
        with pytest.raises(ModelNotFoundError):
            srv.swap_params({}, tenant="whoever")

    def test_http_model_routing_404_and_replica_mapping(self, mts):
        """Satellite pin: unknown model/tenant is HTTP 404 on /v1/*,
        and HttpReplica maps the 404 BACK to ModelNotFoundError (which
        the fleet treats as give-up — every replica serves the same
        registry, retrying elsewhere only burns attempts)."""
        port = mts.serve_http(port=0)
        base = f"http://127.0.0.1:{port}"

        def post(body):
            req = urllib.request.Request(
                base + "/v1/generate", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())

        out = post({"prompt": PROMPT, "model": "chat",
                    "max_new_tokens": 4})
        want = mts.submit({"prompt": PROMPT}, model="chat",
                          max_new_tokens=4).result(timeout=30)
        np.testing.assert_array_equal(np.asarray(out["ids"]), want)
        # the "tenant" alias routes identically
        out2 = post({"prompt": PROMPT, "tenant": "chat",
                     "max_new_tokens": 4})
        np.testing.assert_array_equal(np.asarray(out2["ids"]), want)
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            post({"prompt": PROMPT, "model": "nope"})
        assert exc_info.value.code == 404
        detail = json.loads(exc_info.value.read())["error"]
        assert "nope" in detail and "ranker" in detail
        # the typed round-trip through a fleet leg
        rep = HttpReplica(base)
        att = rep.begin({"prompt": PROMPT}, {"model": "nope"}, 5_000.0)
        with pytest.raises(ModelNotFoundError):
            att.future.result(timeout=10)


# ---------------------------------------------------------------------------
# the 2-replica fleet: storm + tenant-scoped publisher roll
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tenant_fleet():
    slo = SLO(ttft_ms=10_000.0, availability=0.9)
    servers = [MultiTenantServer(_registry(slo=slo)) for _ in range(2)]
    for eng in _fleet_engines(servers):
        eng.warmup()  # settle every steady-state shape before counting
    fleet = Fleet(servers, hedge=False, default_timeout_ms=60_000.0)
    fleet.start()
    yield fleet, servers
    fleet.stop()


def _fleet_engines(servers):
    return [eng for srv in servers for eng in srv.engines]


@pytest.mark.slow  # tier-1 budget (PR 20): the 2-replica x 2-model
# fleet fixture alone costs ~50 s of warmup; the tenancy contracts
# (routing, quotas, labeled gauges, tenant-scoped swap) stay tier-1 via
# TestRegistry/TestMultiTenantServer above
class TestTenantFleet:
    def test_two_model_storm_no_interference_no_recompiles(
            self, tenant_fleet):
        """ACCEPTANCE PIN: two models on one 2-replica fleet under a
        concurrent mixed storm — zero failed requests, every sampled
        token stream identical to its quiet-fleet reference (zero
        cross-tenant interference), zero steady-state fresh compiles,
        and per-tenant SLO burn-rate gauges on /fleet/status."""
        fleet, servers = tenant_fleet
        rng = np.random.RandomState(0)
        jobs = []      # (model, prompt, meta)
        for i in range(12):
            model = ("ranker", "chat")[i % 2]
            prompt = rng.randint(0, VOCAB, (4 + i % 3,)).tolist()
            meta = {"model": model, "max_new_tokens": 4}
            if model == "chat":
                # explicit per-request seed: output is a pure function
                # of (request, seed) whichever replica serves it
                meta.update(temperature=0.7, top_k=8, seed=100 + i)
            jobs.append((prompt, meta))
        # quiet reference pass (also settles every compile)
        want = [fleet.submit({"prompt": p}, **dict(m)).result(timeout=60)
                for p, m in jobs]
        compiles0 = sum(e.cache_stats()["fresh_compiles"]
                        for e in _fleet_engines(servers))
        failed, results = [], {}
        lock = threading.Lock()

        def storm(ids):
            for i in ids:
                p, m = jobs[i]
                try:
                    got = fleet.submit({"prompt": p},
                                       **dict(m)).result(timeout=60)
                    with lock:
                        results.setdefault(i, []).append(got)
                except Exception as exc:  # noqa: BLE001 - the pin
                    failed.append(repr(exc))

        threads = [threading.Thread(target=storm,
                                    args=(range(k, 12, 3),))
                   for k in range(3)]
        for _ in range(2):          # two storm waves
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            threads = [threading.Thread(target=storm,
                                        args=(range(k, 12, 3),))
                       for k in range(3)]
        assert failed == []
        for i, (p, m) in enumerate(jobs):
            for got in results[i]:
                np.testing.assert_array_equal(got, want[i])
        # zero steady-state fresh compiles per tenant
        assert sum(e.cache_stats()["fresh_compiles"]
                   for e in _fleet_engines(servers)) == compiles0
        # per-tenant SLO plane on the fleet status
        status = fleet.status()
        rows = {r["tenant"]: r for r in status["tenants"]}
        assert set(rows) == {"ranker", "chat"}
        for row in rows.values():
            assert row["slo"] is not None
            assert not row["slo_alerting"]
            assert row["failed"] == 0
        prom = servers[0].metrics_prometheus()
        assert 'slo_burn_rate{objective="availability",tenant="ranker"' \
            in prom
        # unknown model through the fleet: typed give-up, no retry storm
        att0 = fleet.metrics.counter("attempts")
        with pytest.raises(ModelNotFoundError):
            fleet.submit({"prompt": PROMPT},
                         model="nope").result(timeout=30)
        assert fleet.metrics.counter("attempts") == att0 + 1

    def test_publisher_rolls_one_tenant_while_other_serves(
            self, tenant_fleet, tmp_path):
        """Satellite pin: a tenant-scoped Publisher rolls 'ranker' to a
        new checkpoint generation while 'chat' storms — chat stays
        token-exact throughout with ZERO failed requests, ranker's
        outputs move to the new generation, and the
        weights_version{tenant=...} gauges move independently."""
        from paddle_tpu import checkpoint as ckpt_mod
        from paddle_tpu.online import Publisher

        fleet, servers = tenant_fleet
        ck = str(tmp_path / "ranker-ck")
        ckpt_mod.save_checkpoint(ck, scope=_lm_scope(99), step=5)

        chat_meta = {"model": "chat", "max_new_tokens": 4,
                     "temperature": 0.7, "top_k": 8, "seed": 42}
        want_chat = fleet.submit({"prompt": PROMPT},
                                 **dict(chat_meta)).result(timeout=60)
        before_rank = fleet.submit(
            {"prompt": PROMPT}, model="ranker",
            max_new_tokens=4).result(timeout=60)

        pub = Publisher(fleet, ck, verify=False, pin=False,
                        tenant="ranker")
        assert fleet.tenant_publishers["ranker"] is pub
        assert fleet.publisher is None  # untenanted slot untouched

        stop, failed, served = threading.Event(), [], [0]

        def storm():
            while not stop.is_set():
                try:
                    got = fleet.submit(
                        {"prompt": PROMPT},
                        **dict(chat_meta)).result(timeout=60)
                    np.testing.assert_array_equal(got, want_chat)
                    served[0] += 1
                except Exception as exc:  # noqa: BLE001 - the pin
                    failed.append(repr(exc))

        threads = [threading.Thread(target=storm) for _ in range(2)]
        for t in threads:
            t.start()
        try:
            step = pub.poll_once()
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert step == 5
        assert failed == []                    # chat: zero downtime
        assert served[0] > 0
        after_rank = fleet.submit(
            {"prompt": PROMPT}, model="ranker",
            max_new_tokens=4).result(timeout=60)
        assert not np.array_equal(after_rank, before_rank)
        # independent weights gauges: ranker at the published step,
        # chat untouched — on the fleet registry AND per-replica rows
        status = fleet.status()
        rows = {r["tenant"]: r for r in status["tenants"]}
        assert rows["ranker"]["weights_version"] == 5.0
        assert rows["chat"]["weights_version"] == 0.0
        assert rows["ranker"]["weights"]["tenant"] == "ranker"
        assert rows["ranker"]["weights"]["published_step"] == 5
        labeled = fleet.metrics.snapshot()["labeled"]
        assert labeled["weights_version"]['{tenant="ranker"}'] == 5.0

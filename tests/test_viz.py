"""net_drawer + Ploter tests (reference fluid net_drawer.py, v2 plot)."""
import os

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.net_drawer import draw_graph
from paddle_tpu.plot import Ploter


def test_draw_graph_emits_dot(tmp_path):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[4])
        h = layers.fc(x, size=3, act="relu")
        loss = layers.mean(h)
    pt.append_backward(loss)
    p = str(tmp_path / "g.dot")
    dot = draw_graph(main, path=p)
    assert dot.startswith("digraph Program {") and dot.endswith("}")
    assert '"x"' in dot and "mul" in dot and "relu" in dot
    assert "grad" in dot  # backward section present
    assert open(p).read() == dot


def test_ploter_png_and_summary(tmp_path):
    pl = Ploter("train_cost", "test_cost")
    for i in range(10):
        pl.append("train_cost", i, 1.0 / (i + 1))
    pl.append("test_cost", 0, 0.5)
    png = str(tmp_path / "curve.png")
    summary = pl.plot(png)
    assert os.path.getsize(png) > 500
    assert "train_cost: n=10" in summary and "test_cost: n=1" in summary
    try:
        pl.append("nope", 0, 0.0)
        assert False
    except KeyError:
        pass
    pl.reset()
    assert pl.series("train_cost") == []

"""paddle_tpu.transpiler: pass framework + standard pass library.

The acceptance surface: transpiled programs are numerically faithful
(dropout→scale and DCE bit-exact; BN folding within fp32 tolerance on
conv and fc models), the fusion rewriter reaches the fused kernels from
primitive-op programs with ≥20% fewer block ops on the demo CNN and a
primitive-attention transformer block, per-pass timing/op-delta stats
are visible via the profiler StatSet snapshot, and transpiled programs
round-trip program_to_dict / the C machine."""
import math

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import io as pio
from paddle_tpu import layers, models, profiler
from paddle_tpu import transpiler as T
from paddle_tpu.layers.layer_helper import LayerHelper


def _run(prog, feed, fetches, scope=None):
    exe = pt.Executor(pt.CPUPlace())
    return exe.run(prog, feed=feed, fetch_list=fetches, scope=scope)


def _init(main_startup):
    scope = pt.Scope()
    pt.Executor(pt.CPUPlace()).run(main_startup, scope=scope)
    return scope


# --------------------------------------------------------------------------
# Framework
# --------------------------------------------------------------------------
class TestFramework:
    def test_registry_and_custom_pass(self):
        class NopPass(T.Pass):
            name = "test_nop_pass_xyz"

            def apply(self, program, ctx):
                ctx.note("ran")

        if "test_nop_pass_xyz" not in T.registered_passes():
            T.register_pass(NopPass)
        p = T.get_pass("test_nop_pass_xyz")
        assert isinstance(p, NopPass)
        for std in ["dead_op_elimination", "constant_fold",
                    "fold_batch_norm", "fuse_patterns", "dropout_to_scale",
                    "canonicalize_is_test", "expand_recompute_segments"]:
            assert std in T.registered_passes()
        # PassManager accepts registered names as well as instances
        pm = T.PassManager(["test_nop_pass_xyz"])
        pm.run(pt.Program(), [], [])
        assert pm.last_notes == ["ran"]

    def test_stats_visible_in_profiler_statset(self):
        stats = profiler.StatSet()
        pm = T.PassManager([T.DeadOpElimination()], stat_set=stats)
        main = pt.Program()
        with pt.program_guard(main, pt.Program()):
            x = layers.data("x", shape=[4])
            y = layers.fc(x, size=2)
            dead = layers.fc(x, size=3)  # noqa: F841 — sliced away
        pm.run(main, ["x"], [y.name])
        snap = stats.as_dict(prefix="transpiler/")
        assert "transpiler/pass/dead_op_elimination" in snap
        # add_count stores op deltas so the ms-scaled column reads the
        # raw count: the dead fc (mul + add) gives delta -2
        delta = snap["transpiler/delta/dead_op_elimination"]["total_ms"]
        assert delta == pytest.approx(-2.0)
        assert pm.results[0].op_delta == -2
        assert pm.stats()[0]["pass"] == "dead_op_elimination"

    def test_ir_dump_hook(self, tmp_path):
        main = pt.Program()
        with pt.program_guard(main, pt.Program()):
            x = layers.data("x", shape=[4])
            y = layers.fc(x, size=2)
            layers.fc(x, size=3)
        pm = T.PassManager([T.DeadOpElimination()],
                           dump_hook=T.ir_dump_hook(str(tmp_path / "ir")))
        pm.run(main, ["x"], [y.name])
        dumps = sorted((tmp_path / "ir").iterdir())
        assert len(dumps) == 2  # before + after for the one changing pass
        assert "mul" in dumps[0].read_text()


# --------------------------------------------------------------------------
# Faithfulness: dropout→scale + DCE bit-exact
# --------------------------------------------------------------------------
class TestDropoutAndDCE:
    @pytest.mark.slow  # tier-1 budget (PR 20): full bit-exact A/B sweep;
    # the dropout->scale and DCE rewrites stay tier-1 via the structural
    # tests in this class
    def test_bit_exact_vs_untranspiled_is_test(self):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", shape=[6])
            h = layers.fc(x, size=16, act="relu")
            h = layers.dropout(h, dropout_prob=0.3)
            y = layers.fc(h, size=4)
            label = layers.data("label", shape=[4])
            loss = layers.mean(layers.square_error_cost(y, label))
            pt.optimizer.SGD(0.1).minimize(loss, startup_program=startup)
        scope = _init(startup)
        # the untranspiled is_test program: plain slice of the training
        # program with is_test flipped (no rewrites)
        test_prog = pio.prune_program(main, ["x"], [y.name], for_test=True)
        xv = np.random.rand(3, 6).astype(np.float32)
        (ref,) = _run(test_prog, {"x": xv}, [y], scope=scope)

        pm = T.inference_pipeline()
        prog = pm.run(main.clone(), ["x"], [y.name],
                      scope=pt.Scope(parent=scope))
        types = [op.type for op in prog.global_block.ops]
        assert "dropout" not in types and "scale" in types
        assert "sgd" not in types and "grad" not in types
        (out,) = _run(prog, {"x": xv}, [y], scope=scope)
        np.testing.assert_array_equal(out, ref)  # bit-exact

    def test_dropout_kept_when_mask_is_consumed(self):
        main = pt.Program()
        with pt.program_guard(main, pt.Program()):
            x = layers.data("x", shape=[4])
            helper = LayerHelper("d")
            outs, _ = helper.append_op(
                "dropout", {"X": [x]}, ["Out", "Mask"],
                {"dropout_prob": 0.5, "is_test": True})
            y = layers.elementwise_add(outs["Out"][0], outs["Mask"][0])
        pm = T.PassManager([T.DropoutToScale()])
        pm.run(main, ["x"], [y.name])
        assert [op.type for op in main.global_block.ops][0] == "dropout"

    def test_dce_preserve_state_writes(self):
        main = pt.Program()
        scope = pt.Scope()
        with pt.program_guard(main, pt.Program()):
            x = layers.data("x", shape=[4])
            helper = LayerHelper("s")
            state = helper.block.create_var(name="cache_state", shape=[4],
                                            persistable=True)
            helper.append_op("scale", {"X": [x]}, {"Out": [state]},
                             {"scale": 2.0})
            y = layers.scale(x, scale=3.0)
        import jax.numpy as jnp

        scope.set("cache_state", jnp.zeros(4))
        # with preservation the unfetched state write survives
        prog = main.clone()
        T.PassManager([T.DeadOpElimination()]).run(
            prog, ["x"], [y.name], scope=scope, preserve_state_writes=True)
        assert len(prog.global_block.ops) == 2
        # without it the write is dead code
        prog2 = main.clone()
        T.PassManager([T.DeadOpElimination()]).run(
            prog2, ["x"], [y.name], scope=scope)
        assert len(prog2.global_block.ops) == 1


# --------------------------------------------------------------------------
# BN folding
# --------------------------------------------------------------------------
class TestFoldBatchNorm:
    def _nontrivial_stats(self, scope):
        import jax.numpy as jnp

        rng = np.random.RandomState(7)
        for n in list(scope.keys()):
            if "mean" in n:
                scope.set(n, jnp.asarray(
                    rng.rand(*scope.get_numpy(n).shape).astype(np.float32)))
            if "variance" in n:
                scope.set(n, jnp.asarray(
                    (0.5 + rng.rand(*scope.get_numpy(n).shape))
                    .astype(np.float32)))

    @pytest.mark.parametrize("fmt", ["NHWC", "NCHW"])
    def test_conv_bn_folds_fp32_tolerance(self, fmt):
        shape = [6, 6, 3] if fmt == "NHWC" else [3, 6, 6]
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            img = layers.data("img", shape=shape)
            c = layers.conv2d(img, num_filters=8, filter_size=3, padding=1,
                              bias_attr=False, data_format=fmt)
            y = layers.batch_norm(c, act="relu", is_test=True,
                                  data_layout=fmt)
        scope = _init(startup)
        self._nontrivial_stats(scope)
        xv = np.random.rand(2, *shape).astype(np.float32)
        (ref,) = _run(main, {"img": xv}, [y], scope=scope)

        work = pt.Scope(parent=scope)
        prog = T.inference_pipeline().run(main.clone(), ["img"], [y.name],
                                          scope=work)
        types = [op.type for op in prog.global_block.ops]
        assert "batch_norm" not in types
        assert types == ["conv2d", "elementwise_add", "relu"]
        conv = prog.global_block.ops[0]
        assert conv.attrs.get("__bn_folded__") is True
        (out,) = _run(prog, {"img": xv}, [y], scope=work)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_fc_bias_bn_folds_through_existing_add(self):
        """mul → elementwise_add(bias) → batch_norm collapses onto the
        existing add (bias folded through the BN affine)."""
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", shape=[12])
            h = layers.fc(x, size=16)  # mul + bias add
            y = layers.batch_norm(h, is_test=True)
        scope = _init(startup)
        self._nontrivial_stats(scope)
        xv = np.random.rand(4, 12).astype(np.float32)
        (ref,) = _run(main, {"x": xv}, [y], scope=scope)

        work = pt.Scope(parent=scope)
        prog = T.inference_pipeline().run(main.clone(), ["x"], [y.name],
                                          scope=work)
        types = [op.type for op in prog.global_block.ops]
        assert types == ["mul", "elementwise_add"]
        (out,) = _run(prog, {"x": xv}, [y], scope=work)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_training_bn_does_not_fold(self):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            img = layers.data("img", shape=[4, 4, 3])
            c = layers.conv2d(img, num_filters=4, filter_size=1,
                              bias_attr=False, data_format="NHWC")
            y = layers.batch_norm(c, is_test=False, data_layout="NHWC")
        scope = _init(startup)
        prog = main.clone()
        T.PassManager([T.FoldBatchNorm()]).run(
            prog, ["img"], [y.name], scope=pt.Scope(parent=scope))
        assert any(op.type == "batch_norm"
                   for op in prog.global_block.ops)

    def test_shared_conv_output_not_folded(self):
        """A conv output consumed by BN AND something else must survive."""
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            img = layers.data("img", shape=[4, 4, 3])
            c = layers.conv2d(img, num_filters=4, filter_size=1,
                              bias_attr=False, data_format="NHWC")
            b = layers.batch_norm(c, is_test=True, data_layout="NHWC")
            y = layers.elementwise_add(b, c)  # second consumer of c
        scope = _init(startup)
        work = pt.Scope(parent=scope)
        prog = main.clone()
        T.PassManager([T.FoldBatchNorm()]).run(prog, ["img"], [y.name],
                                               scope=work)
        assert any(op.type == "batch_norm"
                   for op in prog.global_block.ops)


# --------------------------------------------------------------------------
# Constant folding
# --------------------------------------------------------------------------
class TestConstantFolding:
    def test_param_subgraph_folds_and_matches(self):
        """The transformer position-table slice: feed-independent, folds
        to a precomputed persistable var."""
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            ids = layers.data("ids", shape=[5], dtype="int64")
            tok = layers.embedding(ids, size=[11, 8])
            helper = LayerHelper("cf")
            table = helper.create_parameter(
                pt.ParamAttr(name="pos_table"), shape=[32, 8],
                dtype="float32")
            pos = helper.simple_op("slice", {"X": [table]},
                                   {"axes": [0], "starts": [0],
                                    "ends": [5]})
            y = helper.simple_op("elementwise_add", {"X": [tok],
                                                     "Y": [pos]})
        scope = _init(startup)
        feed = {"ids": np.random.randint(0, 11, size=(2, 5))
                .astype(np.int64)}
        (ref,) = _run(main, feed, [y], scope=scope)
        work = pt.Scope(parent=scope)
        prog = main.clone()
        pm = T.PassManager([T.ConstantFolding()])
        pm.run(prog, ["ids"], [y.name], scope=work)
        types = [op.type for op in prog.global_block.ops]
        assert "slice" not in types
        (out,) = _run(prog, feed, [y], scope=work)
        np.testing.assert_array_equal(out, ref)

    def test_params_stay_live_without_fold_params(self):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", shape=[4])
            helper = LayerHelper("cf2")
            table = helper.create_parameter(
                pt.ParamAttr(name="t2"), shape=[8, 4], dtype="float32")
            s = helper.simple_op("slice", {"X": [table]},
                                 {"axes": [0], "starts": [0], "ends": [2]})
            y = helper.simple_op("reduce_sum", {"X": [s]}, {"dim": [0]})
        scope = _init(startup)
        prog = main.clone()
        T.PassManager([T.ConstantFolding(fold_params=False)]).run(
            prog, ["x"], [y.name], scope=pt.Scope(parent=scope))
        assert any(op.type == "slice" for op in prog.global_block.ops)


# --------------------------------------------------------------------------
# Fusion rewrites
# --------------------------------------------------------------------------
class TestFusePatterns:
    def test_conv_bn_residual_relu_fuses_and_matches(self):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            img = layers.data("img", shape=[4, 4, 8])
            c = layers.conv2d(img, num_filters=8, filter_size=1,
                              bias_attr=False, data_format="NHWC")
            b = layers.batch_norm(c, is_test=True, data_layout="NHWC")
            a = layers.elementwise_add(b, img)
            y = layers.relu(a)
        scope = _init(startup)
        xv = np.random.rand(2, 4, 4, 8).astype(np.float32)
        (ref,) = _run(main, {"img": xv}, [y], scope=scope)
        prog = main.clone()
        T.PassManager([T.FusePatterns(epilogue=True)]).run(
            prog, ["img"], [y.name])
        ops = prog.global_block.ops
        assert [o.type for o in ops] == ["conv1x1_bn_act"]
        assert ops[0].attrs["act"] == "relu"
        assert ops[0].input("Residual") == "img"
        assert ops[0].attrs["__fused_from__"] == [
            "conv2d", "batch_norm", "elementwise_add", "relu"]
        (out,) = _run(prog, {"img": xv}, [y], scope=scope)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_nonunit_conv_does_not_fuse(self):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            img = layers.data("img", shape=[6, 6, 3])
            c = layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                              bias_attr=False, data_format="NHWC")
            y = layers.batch_norm(c, is_test=True, data_layout="NHWC")
        prog = main.clone()
        T.PassManager([T.FusePatterns(epilogue=True)]).run(
            prog, ["img"], [y.name])
        assert any(op.type == "batch_norm" for op in prog.global_block.ops)

    def test_demo_cnn_op_reduction_at_least_20pct(self):
        """The fusion rewriter on the demo CNN (ResNet-50): ≥20% fewer
        block ops, fused epilogue present."""
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            img = layers.data("img", shape=[32, 32, 3])
            logits = models.resnet_imagenet(img, num_classes=10, depth=50,
                                            is_test=True)
        prog = pio.prune_program(main, ["img"], [logits.name])
        before = len(prog.global_block.ops)
        T.PassManager([T.FusePatterns(epilogue=True)]).run(
            prog, ["img"], [logits.name])
        after = len(prog.global_block.ops)
        fused = sum(1 for op in prog.global_block.ops
                    if op.type == "conv1x1_bn_act")
        assert fused >= 30
        assert after <= 0.8 * before, (before, after)

    def _primitive_attention_block(self, main, startup, T_len=8, d=16,
                                   heads=2):
        """A transformer block with attention written in PRIMITIVE ops
        (matmul/scale/softmax/matmul) — what a hand-ported model or an
        imported graph looks like before the rewriter."""
        with pt.program_guard(main, startup):
            x = layers.data("x", shape=[T_len, d])
            helper = LayerHelper("prim")
            hd = d // heads

            def heads_split(t):
                t = layers.reshape(t, [-1, T_len, heads, hd])
                return layers.transpose(t, [0, 2, 1, 3])

            q = heads_split(layers.fc(x, size=d, num_flatten_dims=2,
                                      bias_attr=False))
            k = heads_split(layers.fc(x, size=d, num_flatten_dims=2,
                                      bias_attr=False))
            v = heads_split(layers.fc(x, size=d, num_flatten_dims=2,
                                      bias_attr=False))
            s = helper.simple_op("matmul", {"X": [q], "Y": [k]},
                                 {"transpose_Y": True})
            s = helper.simple_op("scale", {"X": [s]},
                                 {"scale": 1.0 / math.sqrt(hd)})
            p = helper.simple_op("softmax", {"X": [s]})
            ctxv = helper.simple_op("matmul", {"X": [p], "Y": [v]})
            ctxv = layers.transpose(ctxv, [0, 2, 1, 3])
            ctxv = layers.reshape(ctxv, [-1, T_len, d])
            o = layers.fc(ctxv, size=d, num_flatten_dims=2,
                          bias_attr=False)
            y = layers.elementwise_add(x, o)
        return x, y

    def test_primitive_attention_transformer_fuses_and_matches(self):
        main, startup = pt.Program(), pt.Program()
        x, y = self._primitive_attention_block(main, startup)
        scope = _init(startup)
        xv = np.random.rand(2, 8, 16).astype(np.float32)
        (ref,) = _run(main, {"x": xv}, [y], scope=scope)

        prog = pio.prune_program(main, ["x"], [y.name])
        before = len(prog.global_block.ops)
        pm = T.inference_pipeline()
        work = pt.Scope(parent=scope)
        pm.run(prog, ["x"], [y.name], scope=work)
        after = len(prog.global_block.ops)
        types = [op.type for op in prog.global_block.ops]
        assert "scaled_dot_product_attention" in types
        assert "softmax" not in types
        assert after < before  # matmul+scale+softmax+matmul -> one op
        (out,) = _run(prog, {"x": xv}, [y], scope=work)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_transformer_op_reduction_at_least_20pct(self):
        """Two transformer layers over head-space tensors ([B, H, T, D],
        the layout the repo's own attention ops use): the rewriter takes
        every layer's primitive attention to the fused op with ≥20% fewer
        block ops overall."""
        H, T_len, hd = 2, 8, 8
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", shape=[H, T_len, hd])
            helper = LayerHelper("tfm")
            h = x
            for i in range(2):
                s = helper.simple_op("matmul", {"X": [h], "Y": [h]},
                                     {"transpose_Y": True})
                s = helper.simple_op("scale", {"X": [s]},
                                     {"scale": 1.0 / math.sqrt(hd)})
                p = helper.simple_op("softmax", {"X": [s]})
                ctxv = helper.simple_op("matmul", {"X": [p], "Y": [h]})
                h = helper.simple_op("elementwise_add",
                                     {"X": [h], "Y": [ctxv]})
                w = helper.create_parameter(
                    pt.ParamAttr(name=f"ff_w{i}"), shape=[hd, hd],
                    dtype="float32")
                ff = helper.simple_op("matmul", {"X": [h], "Y": [w]})
                ff = helper.simple_op("gelu", {"X": [ff]})
                h = helper.simple_op("elementwise_add",
                                     {"X": [h], "Y": [ff]})
        scope = _init(startup)
        xv = np.random.rand(2, H, T_len, hd).astype(np.float32)
        (ref,) = _run(main, {"x": xv}, [h], scope=scope)
        prog = pio.prune_program(main, ["x"], [h.name])
        before = len(prog.global_block.ops)
        work = pt.Scope(parent=scope)
        T.inference_pipeline().run(prog, ["x"], [h.name], scope=work)
        after = len(prog.global_block.ops)
        fused = sum(1 for op in prog.global_block.ops
                    if op.type == "scaled_dot_product_attention")
        assert fused == 2
        assert after <= 0.8 * before, (before, after)
        (out,) = _run(prog, {"x": xv}, [h], scope=work)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


# --------------------------------------------------------------------------
# Round-trip + deployment satellites
# --------------------------------------------------------------------------
class TestRoundTrip:
    def test_transpiled_program_dict_roundtrip(self):
        """Rewritten fused ops, folded weights and pass-metadata attrs
        survive program_to_dict/program_from_dict."""
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            img = layers.data("img", shape=[4, 4, 8])
            c = layers.conv2d(img, num_filters=8, filter_size=1,
                              bias_attr=False, data_format="NHWC")
            b = layers.batch_norm(c, is_test=True, data_layout="NHWC")
            h = layers.relu(b)
            f = layers.fc(h, size=6)
            y = layers.batch_norm(f, is_test=True)
        scope = _init(startup)
        work = pt.Scope(parent=scope)
        prog = T.inference_pipeline(epilogue=True).run(
            main.clone(), ["img"], [y.name], scope=work)
        types = [op.type for op in prog.global_block.ops]
        assert "conv1x1_bn_act" in types          # fused op
        assert any(op.attrs.get("__folded_from__") == "batch_norm"
                   for op in prog.global_block.ops)

        back = pio.program_from_dict(pio.program_to_dict(prog))
        assert [op.type for op in back.global_block.ops] == types
        assert [op.attrs for op in back.global_block.ops] == \
            [op.attrs for op in prog.global_block.ops]
        xv = np.random.rand(2, 4, 4, 8).astype(np.float32)
        (a,) = _run(prog, {"img": xv}, [y.name], scope=work)
        (bk,) = _run(back, {"img": xv}, [y.name], scope=work)
        np.testing.assert_array_equal(a, bk)

    def test_c_machine_loads_transpiled_model(self, tmp_path):
        """save_inference_model (transpiling) artifacts still load and
        serve through the native C machine."""
        import shutil

        if shutil.which("g++") is None:
            pytest.skip("no C++ toolchain")
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            img = layers.data("img", shape=[6, 6, 3])
            c = layers.conv2d(img, num_filters=8, filter_size=1,
                              bias_attr=False, data_format="NHWC")
            h = layers.batch_norm(c, act="relu", is_test=True,
                                  data_layout="NHWC")
            h = layers.dropout(h, dropout_prob=0.25, is_test=True)
            y = layers.fc(h, size=4)
        scope = _init(startup)
        d = str(tmp_path / "m")
        exe = pt.Executor(pt.CPUPlace())
        pio.save_inference_model(d, ["img"], [y], exe, main_program=main,
                                 scope=scope)
        meta = pio.read_inference_model_meta(d)
        saved_types = [o["type"] for o in
                       meta["program"]["blocks"][0]["ops"]]
        assert "batch_norm" not in saved_types  # folded at save time
        assert "dropout" not in saved_types     # rewritten to scale
        xv = np.random.rand(2, 6, 6, 3).astype(np.float32)
        load_scope = pt.Scope()
        prog, feeds, fetches = pio.load_inference_model(d, exe,
                                                        scope=load_scope)
        (ref,) = exe.run(prog, feed={"img": xv}, fetch_list=fetches,
                         scope=load_scope)
        from paddle_tpu.capi import InferenceMachine

        with InferenceMachine(d) as machine:
            (got,) = machine.run({"img": xv})
        np.testing.assert_allclose(got, np.asarray(ref), rtol=2e-3,
                                   atol=1e-5)


class TestQuantizeAfterFolding:
    def test_strictly_more_bytes_quantize_after_folding(self, tmp_path):
        """conv+BN model where the conv rides the fused epilogue op: raw
        quantization cannot touch the filter (not a conv2d Filter slot);
        the deployment pipeline folds/lowers it back to plain conv2d and
        strictly more parameter bytes quantize."""
        import json
        import os

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            img = layers.data("img", shape=[4, 4, 8])
            h = layers.conv1x1_bn_act(img, num_filters=32, act="relu",
                                      is_test=True)
            y = layers.fc(h, size=10)
        scope = _init(startup)
        d = str(tmp_path / "m")
        exe = pt.Executor(pt.CPUPlace())
        pio.save_inference_model(d, ["img"], [y], exe, main_program=main,
                                 scope=scope)

        def quant_bytes(qdir):
            with open(os.path.join(qdir, "__quant__.json")) as f:
                return sum(int(np.prod(r["shape"])) for r in json.load(f))

        q_raw = str(tmp_path / "q_raw")
        raw_names = pio.quantize_inference_model(d, q_raw, min_elems=64,
                                                 transpile=False)
        q_opt = str(tmp_path / "q_opt")
        opt_names = pio.quantize_inference_model(d, q_opt, min_elems=64)
        assert quant_bytes(q_opt) > quant_bytes(q_raw), (raw_names,
                                                         opt_names)
        # the folded conv filter is the newly-eligible weight
        assert any("@bnfold" in n or "conv" in n for n in opt_names)

        # and the quantized artifact still matches the f32 model closely
        xv = np.random.rand(2, 4, 4, 8).astype(np.float32)
        (ref,) = _run(main, {"img": xv}, [y], scope=scope)
        import shutil

        if shutil.which("g++") is not None:
            from paddle_tpu.capi import InferenceMachine

            with InferenceMachine(q_opt) as machine:
                (got,) = machine.run({"img": xv})
            assert np.abs(got - np.asarray(ref)).max() < 2e-2


# --------------------------------------------------------------------------
# Serving integration
# --------------------------------------------------------------------------
class TestServingTranspile:
    def test_inference_engine_publishes_pass_stats(self, tmp_path):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", shape=[4])
            h = layers.dropout(layers.fc(x, size=8, act="relu"), 0.5)
            y = layers.fc(h, size=2)
        scope = _init(startup)
        d = str(tmp_path / "m")
        exe = pt.Executor(pt.CPUPlace())
        pio.save_inference_model(d, ["x"], [y], exe, main_program=main,
                                 scope=scope, transpile=False)
        from paddle_tpu.serving import InferenceEngine

        eng = InferenceEngine(model_dir=d, batch_buckets=[2])
        gauges = eng.metrics.snapshot()["gauges"]
        assert any(k.startswith("transpile/") for k in gauges), gauges
        assert gauges["transpile/total_ms"] >= 0
        # the engine's program was transpiled: inference dropout is gone
        assert not any(op.type == "dropout"
                       for op in eng.program.global_block.ops)
        out = eng.run({"x": np.random.rand(2, 4).astype(np.float32)})
        assert out[0].shape == (2, 2)

    def test_generation_engine_publishes_pass_stats(self):
        from paddle_tpu.serving.generation import GenerationEngine, LMSpec

        spec = LMSpec(vocab_size=17, d_model=16, n_layers=1, num_heads=2,
                      d_ff=32, max_len=16)
        eng = GenerationEngine(spec, slots=2, max_seq_len=8)
        gauges = eng.metrics.snapshot()["gauges"]
        assert any(k.startswith("transpile/decode/") for k in gauges), \
            gauges


class TestTrainerTranspile:
    def test_sgd_transpile_trains_and_tests(self):
        # default programs: the SGD trainer owns default_startup_program
        x = layers.data("x", shape=[4])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=16, act="relu")
        logits = layers.fc(h, size=2)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        rng = np.random.RandomState(0)

        def reader():
            for _ in range(3):
                yield [(rng.rand(4).astype(np.float32),
                        np.array([int(rng.randint(2))])) for _ in range(8)]

        sgd = pt.trainer.SGD(loss, pt.optimizer.SGDOptimizer(0.1),
                             [x, label], place=pt.CPUPlace(),
                             transpile=True)
        costs = []
        sgd.train(reader, num_passes=1,
                  event_handler=lambda e: costs.append(e))
        res = sgd.test(reader)
        assert np.isfinite(res.cost)

"""One-attach chip session: the round-3 measurement queue in ONE process.

The dev tunnel tolerates a single attached process and drops without
warning, so everything chip-side — the real-chip test tier, the ResNet
fused-backward A/B, the transformer MFU grid, the varlen LSTM bench, and
the per-op profile — runs sequentially here, each experiment wrapped in
its own SIGALRM watchdog and appended as one JSON line to
``CHIP_SESSION_r3.jsonl`` the moment it finishes. A tunnel drop costs the
remaining experiments, never the finished ones.

Usage:  PYTHONPATH=/root/repo:<tunnel-site> python tools/chip_session.py
"""
import json
import os
import signal
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "CHIP_SESSION_r3.jsonl")


def emit(record):
    record["ts"] = round(time.time(), 1)
    with open(OUT, "a") as f:
        f.write(json.dumps(record) + "\n")
        f.flush()
        os.fsync(f.fileno())
    print(json.dumps(record), flush=True)


# BaseException so per-check `except Exception` guards inside experiments
# cannot swallow the watchdog and leave the session unprotected.
class Timeout(BaseException):
    pass


def _alarm(_sig, _frm):
    raise Timeout()


# Set after paddle_tpu imports; every experiment re-asserts AMP because
# two tpu_tier checks flip it off on exit (the r3 session measured every
# post-tier experiment in f32 — a clean 2x ResNet slowdown — before this).
_PT = None

_SKIP = set(filter(None, os.environ.get("CHIP_SKIP", "").split(",")))

# experiment() returns this for a CHIP_SKIP skip so callers' None-checks
# (fallback experiments) don't fire on an operator-requested skip.
SKIPPED = object()


def experiment(name, fn, seconds=1200):
    if name in _SKIP:
        print(f"skip {name} (CHIP_SKIP)", flush=True)
        return SKIPPED
    signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(seconds)
    t0 = time.time()
    try:
        if _PT is not None:
            _PT.set_amp(True)
        result = fn()
        emit({"experiment": name, "ok": True,
              "seconds": round(time.time() - t0, 1), "result": result})
        return result
    except Timeout:
        emit({"experiment": name, "ok": False,
              "seconds": round(time.time() - t0, 1), "error": "timeout"})
    except Exception as exc:  # noqa: BLE001 - keep the session alive
        emit({"experiment": name, "ok": False,
              "seconds": round(time.time() - t0, 1),
              "error": repr(exc)[:500]})
    finally:
        signal.alarm(0)
    return None


def probe_tpu(session=None):
    """Shared session preamble: probe the TPU backend in a disposable
    child first — a downed tunnel HANGS backend init in uninterruptible
    C code (the xla_env notes; SIGALRM cannot fire mid-call) — then emit
    the probe row. Returns the jax module on success, None on failure
    (caller should exit nonzero)."""
    import subprocess

    detail = ""
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=180)
        platform = (probe.stdout or "").strip().splitlines()[-1] \
            if probe.returncode == 0 and probe.stdout.strip() else None
        if platform is None:
            tail = (probe.stderr or "").strip().splitlines()[-3:]
            detail = f" rc={probe.returncode}: " + " | ".join(tail)
    except subprocess.TimeoutExpired:
        platform = None
        detail = " (probe timed out after 180s)"
    if platform is None or platform == "cpu":
        emit({"experiment": "probe", "ok": False,
              "error": f"no TPU backend (probe got {platform!r}; "
                       f"tunnel down or hung){detail}"[:500]})
        return None

    import jax

    dev = jax.devices()[0]
    result = {"platform": dev.platform, "kind": dev.device_kind}
    if session:
        result["session"] = session
    emit({"experiment": "probe", "ok": dev.platform != "cpu",
          "result": result})
    return None if dev.platform == "cpu" else jax


def build_resnet50_train(pt, layers, models):
    """The canonical ResNet-50 bs256 A/B program (one definition so the
    A and B sides of every session measure the same graph)."""
    main_prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_prog, startup):
        images = layers.data("images", shape=[224, 224, 3])
        label = layers.data("label", shape=[1], dtype="int64")
        logits = models.resnet_imagenet(images, num_classes=1000,
                                        depth=50)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.MomentumOptimizer(
            learning_rate=0.1, momentum=0.9).minimize(
            loss, startup_program=startup)
    return main_prog, startup, loss


def resnet50_bs256_step(jax, pt, layers, models, bench, peak,
                        batch=256, steps=20, extra=None):
    """Measure the canonical ResNet-50 bs256 train step (img/s, ms, MFU).
    ONE definition of the timing + MFU math so every session's A and B
    sides are comparable."""
    import numpy as np

    main_prog, startup, loss = build_resnet50_train(pt, layers, models)
    rng = np.random.RandomState(0)
    feed = {"images": rng.rand(batch, 224, 224, 3).astype("float32"),
            "label": rng.randint(0, 1000, (batch, 1)).astype("int64")}
    sec = bench._time_train_steps(jax, pt, main_prog, startup, loss,
                                  feed, warmup=3, steps=steps)
    flops = bench.RESNET50_TRAIN_FLOPS_224
    out = {"img_per_sec": round(batch / sec, 1),
           "ms_per_step": round(sec * 1e3, 2),
           "mfu": round(flops * batch / sec / peak, 4) if peak else None}
    out.update(extra or {})
    return out


def transformer_lm_step(jax, pt, layers, models, bench, peak,
                        bs=8, d=1024, H=8, L=8, vocab=16384,
                        fused_head=False, extra=None):
    """Measure the canonical transformer LM train step (tokens/s, MFU).
    ONE definition of the probe schema so journal rows from different
    sessions stay comparable."""
    tok_s, flops_s = bench.bench_transformer_step(
        jax, pt, layers, models, bs=bs, d=d, H=H, L=L, vocab=vocab,
        fused_head=fused_head)
    out = {"tokens_per_sec": round(tok_s),
           "mfu": round(flops_s / peak, 4) if peak else None,
           "d_model": d, "d_head": d // H, "bs": bs}
    if vocab != 16384:
        out["vocab"] = vocab
    if fused_head:
        out["fused_head"] = True
    out.update(extra or {})
    return out


def resnet50_profile(pt, layers, models, logdir):
    """Per-op xprof profile of the canonical ResNet-50 bs256 train step."""
    import numpy as np

    from paddle_tpu import profiler

    main_prog, startup, loss = build_resnet50_train(pt, layers, models)
    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    feed = {"images": rng.rand(256, 224, 224, 3).astype("float32"),
            "label": rng.randint(0, 1000, (256, 1)).astype("int64")}
    for _ in range(3):
        exe.run(main_prog, feed=feed, fetch_list=[loss], scope=scope)
    with profiler.xprof_trace(logdir):
        for _ in range(5):
            o, = exe.run(main_prog, feed=feed, fetch_list=[loss],
                         scope=scope, return_numpy=False)
        np.asarray(o)
    return profiler.framework_op_stats(logdir, top=12)


def main():
    if probe_tpu() is None:
        return 1
    import jax

    import bench
    import paddle_tpu as pt
    from paddle_tpu import layers, models

    global _PT
    _PT = pt

    dev = jax.devices()[0]
    peak = bench._peak_flops(dev.device_kind)

    def mfu(flops_per_sec):
        return round(flops_per_sec / peak, 4) if peak else None

    pt.set_amp(True)

    # 1. Real-chip tier (validates the fused kernels before we bench them).
    def run_tier():
        sys.path.insert(0, os.path.join(REPO, "tests"))
        import tpu_tier

        out = {}
        for fn in tpu_tier.CHECKS:
            try:
                out[fn.__name__] = {"ok": True, "detail": str(fn() or "")}
            except Exception as exc:  # noqa: BLE001
                out[fn.__name__] = {"ok": False, "detail": repr(exc)[:300]}
        return out

    experiment("tpu_tier", run_tier, seconds=1500)

    # 2. ResNet-50 bs256. (The round-3 fused-linear-backward A/B is gone:
    #    the kernel lost on chip and was removed in round 5.)
    def resnet_step(batch=256, steps=20):
        return resnet50_bs256_step(jax, pt, layers, models, bench, peak,
                                   batch=batch, steps=steps)

    experiment("resnet50_bs256", resnet_step)

    # 3. Transformer MFU grid: d_head via heads (d1024: H8 -> 128, H16 -> 64).
    def lm(heads):
        return transformer_lm_step(
            jax, pt, layers, models, bench, peak, d=1024, H=heads)

    experiment("lm_h8", lambda: lm(8))
    experiment("lm_h16", lambda: lm(16))

    # 3b. Stacked scan-over-layers variant (pipeline_stack=True on one
    #     chip): same math, one compiled block body — measures the
    #     compile-time and step-time cost/benefit of the stacked form.
    def lm_stacked():
        import numpy as np
        # fused off (loses under the 16 MB scoped-vmem limit) and remat on:
        # the scan-over-layers body otherwise saves [L, bs, T, d]-sized
        # activations per layer and OOMs HBM at these shapes.
        pass  # fused linear backward removed in round 5 (lost its chip A/B)
        bs, T, vocab, d, Lh = 8, 2048, 16384, 1024, 8
        main_prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(main_prog, startup):
            ids = layers.data("ids", shape=[T], dtype="int64")
            tgt = layers.data("tgt", shape=[T], dtype="int64")
            logits = models.transformer_lm(
                ids, vocab_size=vocab, d_model=d, n_layers=Lh, num_heads=8,
                max_len=T, pipeline_stack=True, remat=True)
            loss = layers.mean(layers.softmax_with_cross_entropy(
                layers.reshape(logits, shape=[-1, vocab]),
                layers.reshape(tgt, shape=[-1, 1])))
            pt.optimizer.AdamOptimizer(learning_rate=1e-4).minimize(
                loss, startup_program=startup)
        rng = np.random.RandomState(0)
        feed = {"ids": rng.randint(0, vocab, (bs, T)).astype("int64"),
                "tgt": rng.randint(0, vocab, (bs, T)).astype("int64")}
        t0 = time.perf_counter()
        sec = bench._time_train_steps(jax, pt, main_prog, startup, loss,
                                      feed, steps=10)
        wall = time.perf_counter() - t0
        flops = bench.transformer_train_flops(bs, T, d, Lh, vocab)
        return {"tokens_per_sec": round(bs * T / sec),
                "mfu": mfu(flops / sec),
                "compile_plus_run_wall_s": round(wall, 1)}

    experiment("lm_stacked_scan", lm_stacked)

    # 3c. Serving: KV-cache decode throughput (tokens/sec generated);
    #     kv_heads < heads A/Bs the GQA cache-bandwidth win.
    def lm_decode(kv_heads=None):
        import numpy as np
        bs, Tp, N, vocab, d, Lh = 8, 1024, 128, 16384, 1024, 8
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            prompt = layers.data("prompt", shape=[Tp], dtype="int64")
            out_ids = models.transformer_lm_generate(
                prompt, vocab_size=vocab, d_model=d, n_layers=Lh,
                num_heads=8, num_kv_heads=kv_heads, max_len=Tp + N,
                max_new_tokens=N)
        scope = pt.Scope()
        exe = pt.Executor(pt.TPUPlace())
        exe.run(startup, scope=scope)
        rng = np.random.RandomState(0)
        feed = {"prompt": rng.randint(0, vocab, (bs, Tp)).astype("int64")}
        o, = exe.run(prog, feed=feed, fetch_list=[out_ids], scope=scope)
        np.asarray(o)  # close compile + warmup
        t0 = time.perf_counter()
        steps = 3
        for _ in range(steps):
            o, = exe.run(prog, feed=feed, fetch_list=[out_ids],
                         scope=scope, return_numpy=False)
        np.asarray(o)
        sec = (time.perf_counter() - t0) / steps
        return {"decode_tokens_per_sec": round(bs * N / sec),
                "ms_per_token_batch": round(sec / N * 1e3, 3),
                "config": f"bs{bs} prefill{Tp} decode{N} "
                          f"kv{kv_heads or 8}"}

    experiment("lm_decode_throughput", lm_decode)
    experiment("lm_decode_throughput_gqa2", lambda: lm_decode(2))

    # 3d. Self-speculative decode (draft head = copied target head — a
    #     deployment would distill it; measures the verify-round win).
    def lm_spec_decode():
        import numpy as np
        bs, Tp, N, vocab, d, Lh = 8, 1024, 128, 16384, 1024, 8
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            prompt = layers.data("prompt", shape=[Tp], dtype="int64")
            out_ids, rounds = models.transformer_lm_speculative_generate(
                prompt, vocab_size=vocab, d_model=d, n_layers=Lh,
                num_heads=8, max_len=Tp + N + 8, max_new_tokens=N,
                draft_layers=2, gamma=4)
        scope = pt.Scope()
        exe = pt.Executor(pt.TPUPlace())
        exe.run(startup, scope=scope)
        scope.set("draft_head.w", scope.get("lm_head.w"))
        scope.set("draft_ln.scale", scope.get("final_ln.scale"))
        scope.set("draft_ln.bias", scope.get("final_ln.bias"))
        rng = np.random.RandomState(0)
        import jax as _jax
        feed = {"prompt": _jax.device_put(
            rng.randint(0, vocab, (bs, Tp)).astype("int64"))}
        o, r = exe.run(prog, feed=feed, fetch_list=[out_ids, rounds],
                       scope=scope)
        np.asarray(o)
        t0 = time.perf_counter()
        steps = 3
        for _ in range(steps):
            o, r = exe.run(prog, feed=feed, fetch_list=[out_ids, rounds],
                           scope=scope, return_numpy=False)
        np.asarray(o)
        sec = (time.perf_counter() - t0) / steps
        return {"decode_tokens_per_sec": round(bs * N / sec),
                "verify_rounds": int(np.asarray(r)[0]),
                "config": f"bs{bs} prefill{Tp} decode{N} draft2 gamma4 "
                          "(untrained weights: rounds ~= worst case)"}

    experiment("lm_spec_decode", lm_spec_decode)

    # 4. Varlen LSTM (the reference RNN benchmark's ragged semantics).
    pass  # fused linear backward removed in round 5 (lost its chip A/B)
    experiment("lstm_varlen",
               lambda: bench.bench_lstm_varlen(jax, pt, layers))
    experiment("lstm_fixed",
               lambda: {"ms_per_batch":
                        round(bench.bench_lstm_step(jax, pt, layers), 2)})

    # 5. bs16 inference through the saved-model path (three BASELINE.md
    #    "Infer Speed" rows).
    for name in bench.INFER_BASELINES:
        experiment(f"infer_{name}",
                   lambda n=name: bench.bench_inference(jax, pt, layers,
                                                        models, n))

    # 6. Per-op profile of the winning ResNet config.
    def profile_resnet():
        # the winning (unfused) config — the fused kernel lost the A/B
        pass  # fused linear backward removed in round 5 (lost its chip A/B)
        return resnet50_profile(pt, layers, models,
                                "/tmp/chip_session_trace")

    experiment("profile_resnet_unfused", profile_resnet, seconds=1500)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""proglint — static analysis over built programs and saved models.

Runs the paddle_tpu.analysis battery (structural program verifier,
whole-program shape/dtype inference, lint rules) over saved inference
models and/or the demo program topologies, plus the op-registry
conformance audit. Exits nonzero when any error-severity finding
survives — the CI lint gate (tests/test_proglint_gate.py) pins this.

Usage (repo root, CPU backend):

    JAX_PLATFORMS=cpu python tools/proglint.py MODEL_DIR [MODEL_DIR ...]
    JAX_PLATFORMS=cpu python tools/proglint.py --demo quick_start \
                                               --demo serving_lm
    JAX_PLATFORMS=cpu python tools/proglint.py --audit
    JAX_PLATFORMS=cpu python tools/proglint.py --demo quick_start \
                                               --mem --budget 8e9
    ... [--no-shapes] [--json] [--warnings-as-errors] [--rules r1,r2]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEMOS = ("quick_start", "serving_lm", "serving_tenancy", "wide_deep",
         "nmt")


# --------------------------------------------------------------------------
# --mesh dp=4,mp=2: lint/price a SHARDED program per-device. The mesh is
# ABSTRACT (no real devices needed — static analysis only), so a 1-CPU
# box lints the dp=256 program it will deploy.
# --------------------------------------------------------------------------
def parse_mesh(spec: str):
    """``dp=4,mp=2`` -> {"dp": 4, "mp": 2} (the --mesh flag grammar)."""
    axes = {}
    for part in spec.split(","):
        if not part:
            continue
        name, _, size = part.partition("=")
        if not size:
            raise SystemExit(f"--mesh: bad axis {part!r} "
                             f"(want name=size,name=size)")
        axes[name.strip()] = int(size)
    if not axes:
        raise SystemExit("--mesh: no axes given")
    return axes


def build_plan(mesh_axes, plan_kind: str = "auto"):
    """A canned ShardingPlan over an abstract mesh. ``auto`` picks
    megatron when a model axis exists, pure data-parallel otherwise."""
    from paddle_tpu import parallel

    mesh = parallel.make_abstract_mesh(mesh_axes)
    if plan_kind == "auto":
        plan_kind = "megatron" if mesh_axes.get("mp", 1) > 1 else "dp"
    builders = {
        "dp": parallel.data_parallel_plan,
        "megatron": parallel.megatron_plan,
        "zero": parallel.zero_plan,
        "vocab": parallel.vocab_sharded_plan,
        "expert": parallel.expert_parallel_plan,
    }
    return builders[plan_kind](mesh)


# --------------------------------------------------------------------------
# Targets: each yields (tag, program, feed_names, fetch_names, scope)
# --------------------------------------------------------------------------
def load_saved_model(dirname: str):
    from paddle_tpu import io as io_mod
    from paddle_tpu.io import program_from_dict, read_inference_model_meta

    payload = read_inference_model_meta(dirname)
    program = program_from_dict(payload["program"])
    scope = None
    if os.path.isdir(os.path.join(dirname, "params")):
        scope = io_mod._load_saved_params(dirname)
    yield (dirname, program, payload["feed_names"], payload["fetch_names"],
           scope)


def _import_demo_module(name: str):
    import importlib.util

    path = os.path.join(REPO, "demos", f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"demos.{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def build_demo(name: str):
    """Build the named demo's program topologies (no training, no data)
    and yield lint targets — the same graphs the demo scripts train and
    serve, constructed through the demo's own builder where it has one."""
    import paddle_tpu as pt
    from paddle_tpu import layers, models

    if name == "quick_start":
        qs = _import_demo_module("quick_start")
        for config in ("lr", "cnn", "lstm"):
            main, startup = pt.Program(), pt.Program()
            with pt.program_guard(main, startup):
                cost, _output = qs.build(config, word_dim=1000)
            feeds = [v.name for v in main.global_block.vars.values()
                     if v.is_data]
            yield (f"quick_start[{config}]", main, feeds, [cost.name], None)
            yield (f"quick_start[{config}]/startup", startup, [], [], None)
    elif name == "serving_lm":
        # the demo's two programs: the training step and the frozen
        # KV-cache generation graph it saves for the serving engine
        T = 16
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            ids = layers.data("ids", shape=[T], dtype="int64")
            tgt = layers.data("tgt", shape=[T], dtype="int64")
            logits = models.transformer_lm(
                ids, vocab_size=97, d_model=32, n_layers=2, num_heads=4,
                max_len=64, pipeline_stack=True)
            loss = layers.mean(layers.softmax_with_cross_entropy(
                layers.reshape(logits, shape=[-1, 97]),
                layers.reshape(tgt, shape=[-1, 1])))
            pt.optimizer.AdamOptimizer(learning_rate=3e-3).minimize(
                loss, startup_program=startup)
        yield ("serving_lm[train]", main, ["ids", "tgt"], [loss.name], None)
        yield ("serving_lm[train]/startup", startup, [], [], None)
        gen, gen_startup = pt.Program(), pt.Program()
        with pt.program_guard(gen, gen_startup):
            prompt = layers.data("prompt", shape=[8], dtype="int64")
            out_ids = models.transformer_lm_generate(
                prompt, vocab_size=97, d_model=32, n_layers=2, num_heads=4,
                max_len=64, max_new_tokens=8)
        yield ("serving_lm[generate]", gen, ["prompt"], [out_ids.name],
               None)
        # the continuous-batching engine's PAGED decode step, WITH its
        # scope: memplan/proglint --mem price the page pool + block
        # tables as the resident KV state (what the engine's
        # mem_budget gate checks at build time)
        from paddle_tpu.serving import GenerationEngine, LMSpec

        eng = GenerationEngine(
            LMSpec(vocab_size=97, d_model=32, n_layers=2, num_heads=4,
                   max_len=64), slots=4, page_size=16)
        dprog, douts = eng._decode_prog
        yield ("serving_lm[paged_decode]", dprog,
               list(eng._decode_feed_names),
               [v.name for v in eng._fetches(douts)], eng.scope)
    elif name == "serving_tenancy":
        # the multi-tenant topology: TWO resident models of different
        # widths registered behind one /v1 surface — each tenant's
        # paged decode step lints WITH its own scope (its page pool +
        # block tables priced separately), pinning that two
        # compile-cache namespaces coexist in one serving process
        from paddle_tpu.serving import GenerationEngine, LMSpec
        from paddle_tpu.serving.tenancy import ModelRegistry

        reg = ModelRegistry()
        for tenant, (vocab, dm) in (("ranker", (97, 32)),
                                    ("chat", (61, 48))):
            eng = GenerationEngine(
                LMSpec(vocab_size=vocab, d_model=dm, n_layers=2,
                       num_heads=4, max_len=64),
                slots=4, page_size=16)
            reg.register(tenant, [eng])
        for t in reg:
            eng = t.engines[0]
            dprog, douts = eng._decode_prog
            yield (f"serving_tenancy[{t.name}/decode]", dprog,
                   list(eng._decode_feed_names),
                   [v.name for v in eng._fetches(douts)], eng.scope)
    elif name == "nmt":
        # the encoder-decoder (seq2seq) topology: the teacher-forced
        # TRAINING graph plus the serving engine's admission-time
        # encoder and cross-attention decode step WITH the engine scope,
        # so --mem prices the cross-KV slot cache [L, S+1, Hkv, Ts, dh]
        # next to the self-attention page pool
        VS, VT = 48, 52
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            src = layers.data("src", shape=[12], dtype="int64")
            slen = layers.data("slen", shape=[], dtype="int32")
            tgt_in = layers.data("tgt_in", shape=[10], dtype="int64")
            tgt_next = layers.data("tgt_next", shape=[10], dtype="int64")
            logits = models.transformer_nmt_teacher(
                src, slen, tgt_in, src_vocab_size=VS, tgt_vocab_size=VT,
                d_model=32, n_layers=2, num_heads=4,
                max_src_len=16, max_tgt_len=32)
            loss = layers.mean(layers.softmax_with_cross_entropy(
                layers.reshape(logits, shape=[-1, VT]),
                layers.reshape(tgt_next, shape=[-1, 1])))
            pt.optimizer.AdamOptimizer(learning_rate=3e-3).minimize(
                loss, startup_program=startup)
        yield ("nmt[train]", main, ["src", "slen", "tgt_in", "tgt_next"],
               [loss.name], None)
        yield ("nmt[train]/startup", startup, [], [], None)
        from paddle_tpu.decoding import (Seq2SeqGenerationEngine,
                                         Seq2SeqSpec)

        eng = Seq2SeqGenerationEngine(
            Seq2SeqSpec(src_vocab_size=VS, tgt_vocab_size=VT,
                        d_model=32, n_layers=2, num_heads=4,
                        max_src_len=16, max_tgt_len=32),
            slots=4, page_size=8, beam_width=4)
        eprog, eok = eng._encode_prog(16)
        yield ("nmt[encode]", eprog,
               ["serving.src", "serving.src_n", "serving.src_row"],
               [eok.name], eng.scope)
        dprog, douts = eng._decode_prog
        yield ("nmt[cross_decode]", dprog, list(eng._decode_feed_names),
               [v.name for v in eng._fetches(douts)], eng.scope)
    elif name == "wide_deep":
        # the online-CTR topology (demos/online_ctr.py): sparse high-dim
        # embeddings whose SelectedRows grads feed the row-granular
        # sparse_* optimizer ops — with --mesh dp=4,mp=2 --plan vocab
        # the [V, D] tables price PER DEVICE (vocab_sharded_plan)
        from paddle_tpu.dataset import ctr

        vocab = 100_000
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            ids = layers.data("ids", shape=[ctr.SLOTS], dtype="int64")
            dense = layers.data("dense", shape=[ctr.DENSE_DIM])
            label = layers.data("label", shape=[1])
            logit = models.wide_deep(ids, dense, vocab_size=vocab,
                                     embed_dim=16, hidden_sizes=(64, 32))
            loss, prob = models.wide_deep_loss(logit, label)
            pt.optimizer.AdagradOptimizer(learning_rate=0.05).minimize(
                loss, startup_program=startup)
        yield ("wide_deep[train]", main, ["ids", "dense", "label"],
               [loss.name, prob.name], None)
        yield ("wide_deep[train]/startup", startup, [], [], None)
        from paddle_tpu import io as io_mod

        serve = io_mod.prune_program(main, ["ids", "dense"], [prob.name])
        yield ("wide_deep[serve]", serve, ["ids", "dense"], [prob.name],
               None)
    else:
        raise SystemExit(f"unknown --demo {name!r} (have: {DEMOS})")


# --------------------------------------------------------------------------
def lint_target(tag, program, feed_names, fetch_names, scope,
                check_shapes: bool, rules: Optional[List[str]],
                mem: bool = False, budget: Optional[float] = None,
                batch: int = 16, plan=None):
    """Returns (issues, fatal): lint findings plus any checker error
    (already located) surfaced as an error-severity issue."""
    from paddle_tpu import analysis

    issues = analysis.run_lint(program, feed_names, fetch_names,
                               scope=scope, rules=rules)
    if plan is not None and not any(i.severity == analysis.ERROR
                                    for i in issues):
        # sharding plane: resolve every persistable var through the plan
        # — a rule set that cannot fit a var (ShardingPlanError) is an
        # error-severity finding naming var + rules, at lint time
        from paddle_tpu.parallel import ShardingPlanError
        from paddle_tpu.transpiler import shard_program

        try:
            shard_program(program, plan, feed_names, fetch_names,
                          scope=scope)
        except ShardingPlanError as exc:
            issues.append(analysis.LintIssue(
                rule="sharding-plan", severity=analysis.ERROR,
                message=str(exc)))
    if check_shapes and not any(i.severity == analysis.ERROR
                                for i in issues):
        try:
            result = analysis.infer_program(program, feed_names,
                                            fetch_names, scope=scope,
                                            annotate=False)
            issues.extend(result.issues)
        except analysis.ProgramCheckError as exc:
            issues.append(analysis.LintIssue(
                rule="shape-check", severity=analysis.ERROR,
                message=str(exc), block_idx=exc.block_idx,
                op_index=exc.op_index, op_type=exc.op_type,
                callsite=exc.callsite, slot=exc.slot, var=exc.var))
    if mem and not any(i.severity == analysis.ERROR for i in issues):
        # peak-HBM plane: informational watermark per target; an
        # exceeded --budget is an error-severity finding (nonzero exit)
        try:
            m = analysis.analyze_memory(program, feed_names, fetch_names,
                                        scope=scope, batch_size=batch,
                                        plan=plan)
        except Exception as exc:
            issues.append(analysis.LintIssue(
                rule="memory-analysis", severity=analysis.ERROR,
                message=f"{type(exc).__name__}: {exc}"))
        else:
            top = ", ".join(
                f"{t.name} ({t.bytes / 1e6:.1f} MB)" for t in m.top(3))
            severity = analysis.WARNING
            verdict = ""
            if budget is not None and m.peak_bytes > budget:
                severity = analysis.ERROR
                verdict = (f" EXCEEDS budget {budget / 1e9:.3f} GB;"
                           f" top live: {top}")
            scope_note = ""
            if m.mesh_axes:
                axes = "x".join(f"{a}={s}"
                                for a, s in m.mesh_axes.items())
                scope_note = f" PER DEVICE over [{axes}]"
                if m.collectives is not None:
                    scope_note += (f", collectives "
                                   f"{m.collective_bytes / 1e6:.1f} "
                                   f"MB/step")
            issues.append(analysis.LintIssue(
                rule="memory-budget", severity=severity,
                message=f"static peak HBM {m.peak_bytes / 1e9:.3f} GB"
                        f"{scope_note} "
                        f"at batch={batch} (resident "
                        f"{m.resident_bytes / 1e9:.3f} GB, est "
                        f"{m.estimated_step_seconds() * 1e3:.2f} ms/step"
                        f"){verdict}",
                op_index=m.peak_op_index, op_type=m.peak_op_type))
    return issues


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="proglint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("model_dirs", nargs="*",
                    help="save_inference_model directories to lint")
    ap.add_argument("--demo", action="append", default=[],
                    choices=list(DEMOS),
                    help="lint a demo's program topologies (repeatable)")
    ap.add_argument("--audit", action="store_true",
                    help="run the op-registry conformance audit")
    ap.add_argument("--no-shapes", action="store_true",
                    help="structural rules only (skip whole-program "
                         "shape/dtype inference)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated lint rule subset (default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--warnings-as-errors", action="store_true",
                    help="exit nonzero on warnings too")
    ap.add_argument("--mem", action="store_true",
                    help="run the static peak-HBM/liveness analyzer per "
                         "target (reported as a memory-budget finding)")
    ap.add_argument("--budget", type=float, default=None,
                    help="with --mem: peak-HBM budget in bytes — a "
                         "target whose static peak exceeds it is an "
                         "error (nonzero exit)")
    ap.add_argument("--batch", type=int, default=16,
                    help="with --mem: batch size for -1 dims (default 16)")
    ap.add_argument("--mesh", default=None,
                    help="lint the program as SHARDED over a named mesh "
                         "(e.g. --mesh dp=4,mp=2): plan rules resolved "
                         "per var (misfits are error findings), --mem "
                         "prices per-device bytes + collectives")
    ap.add_argument("--plan", default="auto", dest="plan_kind",
                    choices=("auto", "dp", "megatron", "zero", "vocab",
                             "expert"),
                    help="with --mesh: canned ShardingPlan (auto = "
                         "megatron when mp>1, else dp)")
    args = ap.parse_args(argv)
    if not args.model_dirs and not args.demo and not args.audit:
        ap.error("nothing to lint: give MODEL_DIR(s), --demo, or --audit")
    plan = build_plan(parse_mesh(args.mesh), args.plan_kind) \
        if args.mesh else None

    from paddle_tpu import analysis

    rules = args.rules.split(",") if args.rules else None
    report = []
    n_errors = n_warnings = 0

    targets = []
    for d in args.model_dirs:
        targets.append(("model", d))
    for d in args.demo:
        targets.append(("demo", d))

    for kind, name in targets:
        try:
            gen = (load_saved_model(name) if kind == "model"
                   else build_demo(name))
            entries = list(gen)
        except Exception as exc:
            # unreadable/corrupted artifact: that IS a lint failure
            issue = analysis.LintIssue(
                rule="load-failure", severity=analysis.ERROR,
                message=f"{type(exc).__name__}: {exc}")
            report.append((f"{name}", [issue]))
            n_errors += 1
            continue
        for tag, program, feeds, fetches, scope in entries:
            issues = lint_target(tag, program, feeds, fetches, scope,
                                 check_shapes=not args.no_shapes,
                                 rules=rules, mem=args.mem,
                                 budget=args.budget, batch=args.batch,
                                 plan=plan)
            n_errors += sum(i.severity == analysis.ERROR for i in issues)
            n_warnings += sum(i.severity == analysis.WARNING
                              for i in issues)
            report.append((tag, issues))

    if args.audit:
        issues = analysis.audit_op_registry()
        n_errors += sum(i.severity == analysis.ERROR for i in issues)
        n_warnings += sum(i.severity == analysis.WARNING for i in issues)
        report.append(("<op-registry-audit>", issues))

    if args.as_json:
        print(json.dumps(
            {"targets": [{"target": tag,
                          "issues": [i.as_dict() for i in issues]}
                         for tag, issues in report],
             "errors": n_errors, "warnings": n_warnings}, indent=1))
    else:
        for tag, issues in report:
            status = ("clean" if not issues else
                      f"{sum(i.severity == analysis.ERROR for i in issues)}"
                      f" error(s), "
                      f"{sum(i.severity == analysis.WARNING for i in issues)}"
                      f" warning(s)")
            print(f"== {tag}: {status}")
            for i in issues:
                print("   " + i.format())
        print(f"proglint: {n_errors} error(s), {n_warnings} warning(s) "
              f"over {len(report)} target(s)")

    if n_errors or (args.warnings_as_errors and n_warnings):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

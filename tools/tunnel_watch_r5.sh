#!/bin/bash
# Round-5 tunnel watcher: probe the TPU tunnel; when it answers, run every
# command queued in tools/chip_queue_r5.txt (one shell command per line,
# '#' comments skipped), then a full bench.py refresh (sidecar-durable).
# Re-runs the queue from the top whenever it gains NEW lines after a pass.
# Journal: /tmp/tunnel_watch_r5.log
cd /root/repo
PY="${PYTHON:-/opt/venv/bin/python}"
QUEUE=tools/chip_queue_r5.txt
DONE_MARK=/tmp/chip_queue_r5.done   # lines already executed
touch "$DONE_MARK"
{
  echo "tunnel_watch_r5 start $(date -u +%FT%TZ)"
  for i in $(seq 1 320); do
    if timeout -k 5 120 "$PY" -c "import jax; d=jax.devices()[0]; import sys; sys.exit(0 if d.platform!='cpu' else 1)" 2>/dev/null; then
      echo "tunnel up at $(date -u +%FT%TZ) (probe $i)"
      ran_any=0
      while IFS= read -r line; do
        case "$line" in ''|'#'*) continue;; esac
        if grep -qxF -- "$line" "$DONE_MARK"; then continue; fi
        echo ">>> $line"
        timeout 4000 bash -c "$line" < /dev/null
        echo "<<< rc=$? $(date -u +%FT%TZ)"
        echo "$line" >> "$DONE_MARK"
        ran_any=1
      done < "$QUEUE"
      if [ "$ran_any" = 1 ]; then
        echo "queue pass done — bench refresh"
        timeout 5600 "$PY" bench.py > /tmp/bench_refresh_r5.json 2>/tmp/bench_refresh_r5.err
        echo "bench rc=$? at $(date -u +%FT%TZ)"
      fi
      sleep 120
    else
      sleep 130
    fi
  done
  echo "watcher window over $(date -u +%FT%TZ)"
} >> /tmp/tunnel_watch_r5.log 2>&1

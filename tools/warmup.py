#!/usr/bin/env python
"""Pre-warm a saved model artifact from its signature manifest.

Boots the right serving engine for a ``save_inference_model`` directory
(GenerationEngine for stacked-LM decode programs, InferenceEngine
otherwise), replays the artifact's ``warmup_manifest.json`` — AOT
``.lower().compile()`` of every recorded signature, no execution — and
(re)persists the manifest. Point ``--compilation_cache_dir`` at the
volume your replicas mount and every compile lands on disk: the replicas
then boot with ZERO fresh compiles (bench.py bench_cold_start measures
the win; PERF.md records it).

    python tools/warmup.py MODEL_DIR [--compilation_cache_dir DIR]
        [--batch-buckets 1,2,4,8] [--seq-buckets 64,128]
        [--slots N] [--prompt-buckets 8,16] [--max-seq-len N]

Without a manifest (first warmup of a fresh artifact) the engine falls
back to execute-based warmup and WRITES the manifest, so running this
tool once per artifact is enough to make every later boot warm. Prints
one JSON report line.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _csv_ints(s):
    return tuple(int(x) for x in s.split(",") if x)


def main(argv):
    import paddle_tpu as pt

    rest = pt.parse_flags(list(argv))
    opts = {"batch-buckets": None, "seq-buckets": None, "slots": "8",
            "prompt-buckets": None, "max-seq-len": None}
    args = []
    i = 0
    while i < len(rest):
        tok = rest[i]
        if tok.startswith("--") and tok[2:].split("=")[0] in opts:
            body = tok[2:]
            name, eq, val = body.partition("=")
            if not eq:
                i += 1
                val = rest[i]
            opts[name] = val
        else:
            args.append(tok)
        i += 1
    if not args:
        print(__doc__, file=sys.stderr)
        return 2
    model_dir = args[0]
    if not os.path.isdir(model_dir):
        print(f"error: {model_dir!r} is not a saved-model directory",
              file=sys.stderr)
        return 2

    from paddle_tpu.io import read_inference_model_meta
    from paddle_tpu.serving import InferenceEngine
    from paddle_tpu.serving.generation import (_DECODE_OPS,
                                               GenerationEngine)

    t0 = time.perf_counter()
    meta = read_inference_model_meta(model_dir)
    ops = meta["program"]["blocks"][0]["ops"]
    is_generation = any(op["type"] in _DECODE_OPS for op in ops)
    if is_generation:
        kw = {"slots": int(opts["slots"])}
        if opts["prompt-buckets"]:
            kw["prompt_buckets"] = _csv_ints(opts["prompt-buckets"])
        if opts["max-seq-len"]:
            kw["max_seq_len"] = int(opts["max-seq-len"])
        engine = GenerationEngine.from_saved(model_dir, **kw)
    else:
        kw = {}
        if opts["batch-buckets"]:
            kw["batch_buckets"] = _csv_ints(opts["batch-buckets"])
        if opts["seq-buckets"]:
            kw["seq_buckets"] = _csv_ints(opts["seq-buckets"])
        engine = InferenceEngine(model_dir, **kw)
    warmed = engine.warm_start()
    stats = engine.cache_stats()
    report = {
        "model_dir": model_dir,
        "kind": "generation" if is_generation else "inference",
        "signatures_warm": warmed,
        "fresh_compiles": stats["fresh_compiles"],
        "persistent_hits": stats["persistent_hits"],
        "compilation_cache_dir": pt.FLAGS.compilation_cache_dir or None,
        "manifest": os.path.join(model_dir, "warmup_manifest.json"),
        "seconds": round(time.perf_counter() - t0, 3),
    }
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

#!/usr/bin/env python
"""loopctl — inspect a running serve->log->join->train->publish loop.

Reads the feedback loop's on-disk state (impression-log dir, joined
dir, trainer checkpoint dir) and optionally a live fleet's
``/fleet/status`` for the publish stage, then prints per-stage lag —
the operator's view of the ``freshness_s`` SLO:

    loopctl.py --log-dir /data/impressions --joined-dir /data/joined \
        [--ckpt-dir /ckpt/run1] [--url http://host:port] [--json]

Stages:
    log      age of the newest SEALED impression segment (+ drop count)
    join     age of the newest sealed joined segment, pending window
    train    newest checkpoint generation + its age; with --runlog
             (the trainer's RunLog journal) also goodput % and MFU
    publish  fleet weights block (published step / staleness) when
             --url is given

Exit status: 0 on success, 1 when a stage directory is unreadable.
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.request

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root


def _fleet_weights(url: str):
    with urllib.request.urlopen(f"{url}/fleet/status", timeout=10) as r:
        return json.load(r).get("weights")


def _runlog_goodput(path: str):
    """Goodput fraction + MFU EMA from a trainer RunLog journal: the
    newest pass_end's cumulative ``goodput/*`` StatSet mirror and the
    newest iteration's ``mfu_ema`` gauge."""
    buckets = {}
    mfu_ema = None
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                row = json.loads(line)
                if row.get("type") == "pass_end":
                    for name, s in (row.get("stat_set") or {}).items():
                        if name.startswith("goodput/"):
                            buckets[name[len("goodput/"):]] = \
                                float(s.get("total_ms", 0.0))
                elif row.get("type") == "iteration" \
                        and row.get("mfu_ema") is not None:
                    mfu_ema = float(row["mfu_ema"])
    except (OSError, ValueError) as exc:
        return {"error": str(exc)}
    total = sum(buckets.values())
    out = {"mfu": mfu_ema}
    if total > 0:
        out["goodput"] = round(
            buckets.get("device_compute", 0.0) / total, 4)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--log-dir", required=True)
    ap.add_argument("--joined-dir", required=True)
    ap.add_argument("--ckpt-dir")
    ap.add_argument("--runlog",
                    help="trainer RunLog journal: adds goodput %% / MFU "
                         "to the train row")
    ap.add_argument("--url", help="fleet HTTP plane for the publish row")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    from paddle_tpu.feedback import loop_status
    from paddle_tpu.feedback.log import sealed_segments, segment_meta

    try:
        status = loop_status(args.log_dir, args.joined_dir,
                             ckpt_dir=args.ckpt_dir)
    except OSError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    # per-stage extras: torn/drop accounting from segment metas
    torn = lost = 0
    for p in sealed_segments(args.log_dir):
        try:
            m = segment_meta(p)
        except (OSError, ValueError):
            continue
        torn += int(bool(m.get("torn")))
        lost += int(m.get("lost_bytes") or 0)
    status["torn_segments"] = torn
    status["torn_lost_bytes"] = lost
    if args.runlog:
        status["goodput"] = _runlog_goodput(args.runlog)
    if args.url:
        try:
            status["publish"] = _fleet_weights(args.url.rstrip("/"))
        except Exception as exc:  # noqa: BLE001 - report, don't crash
            status["publish_error"] = str(exc)

    if args.as_json:
        print(json.dumps(status, indent=1, sort_keys=True))
        return 0

    def row(stage, lag, extra=""):
        lag = "-" if lag is None else f"{lag:9.3f}s"
        print(f"{stage:<8} {lag:>10}  {extra}")

    print(f"{'STAGE':<8} {'LAG':>10}")
    row("log", status.get("log_lag_s"),
        f"torn={torn} lost_bytes={lost}")
    row("join", status.get("join_lag_s"),
        f"backlog={status.get('backlog_segments')} "
        f"fed_examples={status.get('examples_enqueued')}")
    gp = status.get("goodput") or {}
    gp_extra = ""
    if gp.get("goodput") is not None:
        gp_extra += f" goodput={100.0 * gp['goodput']:.1f}%"
    if gp.get("mfu") is not None:
        gp_extra += f" mfu={gp['mfu']:.4f}"
    if args.ckpt_dir:
        row("train", status.get("train_lag_s"),
            f"step={status.get('trained_step')}" + gp_extra)
    elif gp_extra:
        row("train", None, gp_extra.strip())
    pub = status.get("publish")
    if pub:
        row("publish", pub.get("staleness_s"),
            f"step={pub.get('published_step')} "
            f"generations={pub.get('generations')}")
    elif status.get("publish_error"):
        row("publish", None, f"error: {status['publish_error']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

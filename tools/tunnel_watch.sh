#!/bin/bash
# Watch for the TPU tunnel's return; when it answers, run the queued
# r3c2 chip session (ResNet custom-BN A/B + profile) and then a full
# bench.py refresh. One-shot: exits after the session runs (or after
# ~11h of probing). Journal: /tmp/tunnel_watch.log
cd /root/repo
PY="${PYTHON:-/opt/venv/bin/python}"
{
  echo "tunnel_watch start $(date -u +%FT%TZ)"
  for i in $(seq 1 260); do
    if timeout -k 5 120 "$PY" -c "import jax; d=jax.devices()[0]; import sys; sys.exit(0 if d.platform!='cpu' else 1)" 2>/dev/null; then
      echo "tunnel up at $(date -u +%FT%TZ) (probe $i) — running r3c2"
      timeout 6600 "$PY" tools/chip_session_r3c2.py
      echo "r3c2 rc=$? — running bench refresh"
      timeout 3000 "$PY" bench.py > /tmp/bench_refresh.json 2>/tmp/bench_refresh.err
      echo "bench rc=$? at $(date -u +%FT%TZ)"
      exit 0
    fi
    sleep 140
  done
  echo "tunnel never returned; giving up $(date -u +%FT%TZ)"
} >> /tmp/tunnel_watch.log 2>&1

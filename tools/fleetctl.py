#!/usr/bin/env python
"""fleetctl — operate a running paddle_tpu serving fleet over HTTP.

Talks to ``Fleet.serve_http`` (or, with ``--replica-url``, directly to a
single ``Server.serve_http`` replica's /admin plane). Deliberately
stdlib-only — no paddle_tpu import — so it runs from any box that can
reach the fleet.

    fleetctl.py --url http://host:port status [--table]
    fleetctl.py --url http://host:port drain r1
    fleetctl.py --url http://host:port resume r1
    fleetctl.py --url http://host:port update-weights /ckpt/run1
    fleetctl.py --url http://host:port chaos 'replica_crash@1,slow_replica@2'
    fleetctl.py --url http://host:port metrics [--prom]
    fleetctl.py --url http://host:port flightdump [--out bundle.json]
    fleetctl.py --url http://host:port generate --prompt 1,2,3 \
        [--src 4,5,6] [--max-new-tokens N] [--temperature T] [--top-k K] \
        [--top-p P] [--seed S] [--stop 7,8] [--beam-size K] \
        [--length-penalty A] [--return-beams] [--eos-id E]

``generate`` drives the /v1/generate data plane with the full
decode-platform request schema — per-request sampling (temperature /
top-k / top-p / seed), stop token-sequences, and beam search; flags you
omit keep the fleet's default (greedy) behavior byte-identical.

``status`` reports, per replica, health/breaker/inflight plus the decode
latency columns (TTFT/TPOT p50/p99 from the replica's histograms) and,
when the fleet declares an SLO, per-objective attainment, error-budget
remaining, and multi-window burn rates (``--table`` renders the same
data as a terminal table). With ``--master host:port`` (the training
master's JSON-lines TCP plane) ``status`` also prints a TRAIN row:
fleet goodput %, MFU, step-time skew, and flagged stragglers.
``flightdump`` fetches the fleet's flight recorder bundle (recent
spans + metric history + engine state).

Exit status: 0 on success, 1 on an HTTP/transport error (the body's
``error`` field is printed to stderr).
"""
from __future__ import annotations

import argparse
import json
import socket
import sys
import urllib.error
import urllib.request


def master_call(addr: str, timeout: float = 10.0, **req):
    """One JSON-lines request/response round trip to the training
    master (it speaks newline-delimited JSON over TCP, not HTTP)."""
    host, _, port = addr.rpartition(":")
    with socket.create_connection((host or "127.0.0.1", int(port)),
                                  timeout=timeout) as s:
        s.sendall((json.dumps(req) + "\n").encode())
        f = s.makefile("r", encoding="utf-8")
        line = f.readline()
    resp = json.loads(line or "{}")
    if not resp.get("ok", False):
        raise RuntimeError(resp.get("error") or "master error")
    return resp


def render_train_row(train: dict) -> str:
    """One-line training-observatory summary from the master's
    train_status aggregate (goodput %, MFU, step-time skew,
    flagged stragglers)."""
    gp = train.get("goodput")
    mfu = train.get("mfu")
    skew = train.get("skew")
    stragglers = train.get("stragglers") or []
    parts = [f"trainers={len(train.get('trainers') or {})}"]
    if gp is not None:
        parts.append(f"goodput={100.0 * gp:.1f}%")
    if mfu is not None:
        parts.append(f"mfu={mfu:.4f}")
    if skew is not None:
        parts.append(f"p99/p50={skew:g}x")
    parts.append("stragglers=" + (",".join(stragglers) if stragglers
                                  else "none"))
    return f"{'TRAIN':<10} " + " ".join(parts)


def call(url: str, method: str = "GET", body: dict | None = None,
         timeout: float = 120.0, raw: bool = False):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        payload = r.read()
    return payload.decode() if raw else json.loads(payload or b"{}")


def _fmt_ms(v):
    return "-" if v is None else f"{v:.1f}"


def render_status_table(status: dict) -> str:
    """Human view of /fleet/status: one row per replica with the
    TTFT/TPOT columns, then the SLO/burn-rate block."""
    head = (f"{'replica':<10}{'state':<12}{'breaker':<10}{'inflight':>9}"
            f"{'ttft p50':>10}{'ttft p99':>10}{'tpot p50':>10}"
            f"{'tpot p99':>10}")
    lines = [head, "-" * len(head)]
    for rep in status.get("replicas", []):
        lines.append(
            f"{rep.get('name', '?'):<10}"
            f"{(rep.get('health') or {}).get('state', '?'):<12}"
            f"{rep.get('breaker', '?'):<10}"
            f"{rep.get('inflight', 0):>9}"
            f"{_fmt_ms(rep.get('ttft_p50_ms')):>10}"
            f"{_fmt_ms(rep.get('ttft_p99_ms')):>10}"
            f"{_fmt_ms(rep.get('tpot_p50_ms')):>10}"
            f"{_fmt_ms(rep.get('tpot_p99_ms')):>10}")
    fleet_row = status.get("fleet") or {}
    lines.append(
        f"{'FLEET':<10}{'':<12}{'':<10}{status.get('pending', 0):>9}"
        f"{_fmt_ms(fleet_row.get('ttft_p50_ms')):>10}"
        f"{_fmt_ms(fleet_row.get('ttft_p99_ms')):>10}"
        f"{_fmt_ms(fleet_row.get('tpot_p50_ms')):>10}"
        f"{_fmt_ms(fleet_row.get('tpot_p99_ms')):>10}")
    tenants = status.get("tenants")
    if tenants:
        lines.append("")
        thead = (f"{'tenant':<12}{'queue':>7}{'active':>8}{'pages':>8}"
                 f"{'weights':>9}{'slo burn':>10}{'state':>10}")
        lines.append(thead)
        lines.append("-" * len(thead))
        for t in tenants:
            burn = t.get("slo_max_burn")
            state = ("paused" if t.get("paused")
                     else "ALERT" if t.get("slo_alerting") else "ok")
            lines.append(
                f"{t.get('tenant', '?'):<12}"
                f"{t.get('queue_depth', 0):>7}"
                f"{t.get('active', 0):>8}"
                f"{t.get('pages_in_use', 0):>8}"
                f"{t.get('weights_version', 0):>9g}"
                f"{('-' if burn is None else f'{burn:g}x'):>10}"
                f"{state:>10}")
    weights = status.get("weights")
    if weights:
        lines.append("")
        lines.append(
            f"WEIGHTS    version={weights.get('published_step')} "
            f"latest={weights.get('latest_step')} "
            f"staleness={weights.get('staleness_s')}s "
            f"generations={weights.get('generations')}"
            + (f"  last_error={weights['last_error']}"
               if weights.get("last_error") else ""))
    slo = status.get("slo")
    if slo:
        lines.append("")
        lines.append("SLO " + ("** ALERTING **" if slo.get("alerting")
                               else "(healthy)"))
        for name, obj in sorted((slo.get("objectives") or {}).items()):
            burns = ", ".join(
                f"{win}={w.get('burn_rate')}x"
                for win, w in sorted((obj.get("burn") or {}).items()))
            thr = obj.get("threshold_ms")
            thr_s = obj.get("threshold_s")
            lines.append(
                f"  {name:<14}"
                + (f"<{thr:g}ms " if thr is not None else
                   f"<{thr_s:g}s " if thr_s is not None else "")
                + f"target={obj.get('target')} "
                  f"attainment={obj.get('attainment')} "
                  f"budget_remaining={obj.get('error_budget_remaining')} "
                  f"burn[{burns}]"
                + ("  << ALERT" if obj.get("alerting") else ""))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fleetctl", description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--url", required=True,
                    help="fleet base URL (Fleet.serve_http)")
    ap.add_argument("--master", default=None,
                    help="training master host:port (JSON-lines TCP); "
                         "status gains a TRAIN row — fleet goodput %%, "
                         "MFU, and flagged stragglers")
    ap.add_argument("--timeout", type=float, default=120.0)
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("status", help="replica health, breakers, "
                       "TTFT/TPOT columns, SLO burn rates")
    p.add_argument("--table", action="store_true",
                   help="render a terminal table instead of JSON")
    p = sub.add_parser("drain", help="drain one replica (healthz -> 503)")
    p.add_argument("replica", help="replica name (r0) or index")
    p.add_argument("--no-wait", action="store_true",
                   help="return before in-flight work finishes")
    p = sub.add_parser("resume", help="rejoin a drained replica")
    p.add_argument("replica")
    p = sub.add_parser("update-weights",
                       help="rolling swap from a checkpoint directory")
    p.add_argument("checkpoint_dir")
    p.add_argument("--no-verify", action="store_true",
                   help="skip the warm-start manifest verify step")
    p.add_argument("--tenant", default=None,
                   help="roll only this tenant on multi-tenant replicas "
                        "(others keep serving; no replica drains)")
    p = sub.add_parser("chaos",
                       help="install a fault plan, e.g. replica_crash@1")
    p.add_argument("plan")
    p = sub.add_parser("metrics", help="fleet metrics snapshot")
    p.add_argument("--prom", action="store_true",
                   help="Prometheus text exposition instead of JSON")
    p = sub.add_parser("flightdump",
                       help="fetch the fleet's flight-recorder bundle")
    p.add_argument("--out", default=None,
                   help="write the bundle here instead of stdout")
    p = sub.add_parser("generate",
                       help="submit one /v1/generate request (sampling/"
                            "stop/beam fields included)")
    p.add_argument("--prompt", default=None,
                   help="comma-separated prompt token ids")
    p.add_argument("--model", default=None,
                   help="model/tenant id on multi-tenant replicas "
                        "(unknown ids are HTTP 404)")
    p.add_argument("--src", default=None,
                   help="comma-separated SOURCE ids (seq2seq engines)")
    p.add_argument("--max-new-tokens", type=int, default=None)
    p.add_argument("--eos-id", type=int, default=None)
    p.add_argument("--temperature", type=float, default=None)
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--top-p", type=float, default=None)
    p.add_argument("--seed", type=int, default=None,
                   help="per-request seed: sampled output becomes a "
                        "pure function of (request, seed)")
    p.add_argument("--stop", action="append", default=None,
                   help="stop token-sequence, comma-separated "
                        "(repeatable)")
    p.add_argument("--beam-size", type=int, default=None)
    p.add_argument("--length-penalty", type=float, default=None)
    p.add_argument("--return-beams", action="store_true", default=None)
    args = ap.parse_args(argv)

    def _replica(value):
        return int(value) if value.isdigit() else value

    try:
        if args.cmd == "status":
            out = call(args.url + "/fleet/status", timeout=args.timeout)
            if args.master:
                try:
                    out["train"] = master_call(
                        args.master, op="train_status")["train"]
                except (OSError, RuntimeError, ValueError) as exc:
                    out["train_error"] = str(exc)
            if args.table:
                print(render_status_table(out))
                if out.get("train") is not None:
                    print()
                    print(render_train_row(out["train"]))
                elif out.get("train_error"):
                    print(f"\nTRAIN      unreachable: {out['train_error']}")
                return 0
        elif args.cmd == "drain":
            out = call(args.url + "/fleet/drain", "POST",
                       {"replica": _replica(args.replica),
                        "wait": not args.no_wait}, timeout=args.timeout)
        elif args.cmd == "resume":
            out = call(args.url + "/fleet/resume", "POST",
                       {"replica": _replica(args.replica)},
                       timeout=args.timeout)
        elif args.cmd == "update-weights":
            body = {"checkpoint_dir": args.checkpoint_dir,
                    "verify": not args.no_verify}
            if args.tenant is not None:
                body["tenant"] = args.tenant
            out = call(args.url + "/fleet/update_weights", "POST",
                       body, timeout=args.timeout)
        elif args.cmd == "chaos":
            out = call(args.url + "/fleet/chaos", "POST",
                       {"plan": args.plan}, timeout=args.timeout)
        elif args.cmd == "metrics":
            if args.prom:
                print(call(args.url + "/metrics?format=prom",
                           timeout=args.timeout, raw=True))
                return 0
            out = call(args.url + "/metrics", timeout=args.timeout)
        elif args.cmd == "generate":
            if args.prompt is None and args.src is None:
                ap.error("generate needs --prompt and/or --src")
            body = {}
            if args.prompt is not None:
                body["prompt"] = [int(t) for t in
                                  args.prompt.split(",") if t]
            if args.src is not None:
                body["src"] = [int(t) for t in args.src.split(",") if t]
            if args.stop is not None:
                body["stop"] = [[int(t) for t in s.split(",") if t]
                                for s in args.stop]
            for flag, key in (("model", "model"),
                              ("max_new_tokens", "max_new_tokens"),
                              ("eos_id", "eos_id"),
                              ("temperature", "temperature"),
                              ("top_k", "top_k"), ("top_p", "top_p"),
                              ("seed", "seed"),
                              ("beam_size", "beam_size"),
                              ("length_penalty", "length_penalty"),
                              ("return_beams", "return_beams")):
                v = getattr(args, flag)
                if v is not None:
                    body[key] = v
            out = call(args.url + "/v1/generate", "POST", body,
                       timeout=args.timeout)
        elif args.cmd == "flightdump":
            out = call(args.url + "/fleet/flightdump",
                       timeout=args.timeout)
            if args.out:
                with open(args.out, "w") as f:
                    json.dump(out, f)
                print(f"wrote {args.out} "
                      f"({len(out.get('trace', {}).get('spans', []))} "
                      "spans)")
                return 0
        else:  # unreachable (required=True)
            return 2
    except urllib.error.HTTPError as exc:
        try:
            detail = json.loads(exc.read() or b"{}").get("error", "")
        except ValueError:
            detail = ""
        print(f"fleetctl: HTTP {exc.code}: {detail}", file=sys.stderr)
        return 1
    except urllib.error.URLError as exc:
        print(f"fleetctl: {args.url} unreachable: {exc.reason}",
              file=sys.stderr)
        return 1
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())

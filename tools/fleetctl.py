#!/usr/bin/env python
"""fleetctl — operate a running paddle_tpu serving fleet over HTTP.

Talks to ``Fleet.serve_http`` (or, with ``--replica-url``, directly to a
single ``Server.serve_http`` replica's /admin plane). Deliberately
stdlib-only — no paddle_tpu import — so it runs from any box that can
reach the fleet.

    fleetctl.py --url http://host:port status
    fleetctl.py --url http://host:port drain r1
    fleetctl.py --url http://host:port resume r1
    fleetctl.py --url http://host:port update-weights /ckpt/run1
    fleetctl.py --url http://host:port chaos 'replica_crash@1,slow_replica@2'
    fleetctl.py --url http://host:port metrics [--prom]

Exit status: 0 on success, 1 on an HTTP/transport error (the body's
``error`` field is printed to stderr).
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request


def call(url: str, method: str = "GET", body: dict | None = None,
         timeout: float = 120.0, raw: bool = False):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        payload = r.read()
    return payload.decode() if raw else json.loads(payload or b"{}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fleetctl", description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--url", required=True,
                    help="fleet base URL (Fleet.serve_http)")
    ap.add_argument("--timeout", type=float, default=120.0)
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status", help="replica health, breakers, counters")
    p = sub.add_parser("drain", help="drain one replica (healthz -> 503)")
    p.add_argument("replica", help="replica name (r0) or index")
    p.add_argument("--no-wait", action="store_true",
                   help="return before in-flight work finishes")
    p = sub.add_parser("resume", help="rejoin a drained replica")
    p.add_argument("replica")
    p = sub.add_parser("update-weights",
                       help="rolling swap from a checkpoint directory")
    p.add_argument("checkpoint_dir")
    p.add_argument("--no-verify", action="store_true",
                   help="skip the warm-start manifest verify step")
    p = sub.add_parser("chaos",
                       help="install a fault plan, e.g. replica_crash@1")
    p.add_argument("plan")
    p = sub.add_parser("metrics", help="fleet metrics snapshot")
    p.add_argument("--prom", action="store_true",
                   help="Prometheus text exposition instead of JSON")
    args = ap.parse_args(argv)

    def _replica(value):
        return int(value) if value.isdigit() else value

    try:
        if args.cmd == "status":
            out = call(args.url + "/fleet/status", timeout=args.timeout)
        elif args.cmd == "drain":
            out = call(args.url + "/fleet/drain", "POST",
                       {"replica": _replica(args.replica),
                        "wait": not args.no_wait}, timeout=args.timeout)
        elif args.cmd == "resume":
            out = call(args.url + "/fleet/resume", "POST",
                       {"replica": _replica(args.replica)},
                       timeout=args.timeout)
        elif args.cmd == "update-weights":
            out = call(args.url + "/fleet/update_weights", "POST",
                       {"checkpoint_dir": args.checkpoint_dir,
                        "verify": not args.no_verify},
                       timeout=args.timeout)
        elif args.cmd == "chaos":
            out = call(args.url + "/fleet/chaos", "POST",
                       {"plan": args.plan}, timeout=args.timeout)
        elif args.cmd == "metrics":
            if args.prom:
                print(call(args.url + "/metrics?format=prom",
                           timeout=args.timeout, raw=True))
                return 0
            out = call(args.url + "/metrics", timeout=args.timeout)
        else:  # unreachable (required=True)
            return 2
    except urllib.error.HTTPError as exc:
        try:
            detail = json.loads(exc.read() or b"{}").get("error", "")
        except ValueError:
            detail = ""
        print(f"fleetctl: HTTP {exc.code}: {detail}", file=sys.stderr)
        return 1
    except urllib.error.URLError as exc:
        print(f"fleetctl: {args.url} unreachable: {exc.reason}",
              file=sys.stderr)
        return 1
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Round-5 chip session: the measurement queue behind the tunnel watcher.

Agenda (VERDICT r4 tasks 2/4/5/7):
1. ResNet-50 bs256 A/B over the NEW fused conv epilogue
   (--fused_conv_epilogue, ops/fusion_ops.py) — train and also the
   bf16 inference row where the fusion never materializes the raw conv
   output. The target from PERF.md's roofline: >= 36% MFU at bs256.
2. The carried ResNet custom-BN-backward row (the r3c A/B tail the
   tunnel drop cost — custom norm backwards are default now, so this is
   simply the fresh baseline the epilogue A/B compares against).
3. Stacked-scan selective-remat A/B (kernels in layers/attention.py,
   --scan_remat_policy): all-or-nothing remat vs save-dots at d1024.
4. Self-speculative decode A/B vs plain KV decode (models/gpt_modern)
   on a briefly-trained model at temp 0.
5. Headline MFU re-confirmation for BENCH_r05: d2048 H16 wide config
   (55.9% in r3) and d1024 H8.

Each experiment journals one line to CHIP_SESSION_r5.jsonl as it
finishes; a tunnel drop never costs completed rows.
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import chip_session as cs  # noqa: E402

cs.OUT = os.path.join(REPO, "CHIP_SESSION_r5.jsonl")


def main():
    jax = cs.probe_tpu("r5: conv epilogue + remat + spec decode")
    if jax is None:
        return 1

    import bench
    import paddle_tpu as pt
    from paddle_tpu import layers, models

    cs._PT = pt
    peak = bench._peak_flops(jax.devices()[0].device_kind)
    pt.set_amp(True)

    # 0. On-chip correctness of the new kernels before measuring them.
    def tier(check_name):
        sys.path.insert(0, os.path.join(REPO, "tests"))
        import tpu_tier

        return {"detail": getattr(tpu_tier, check_name)()}

    cs.experiment("tier_conv_epilogue_parity",
                  lambda: tier("conv_epilogue_matches_unfused"),
                  seconds=600)

    # 1. ResNet-50 bs256 conv-epilogue A/B (flag flips the BUILD).
    def resnet(fused):
        pt.flags.FLAGS.fused_conv_epilogue = fused
        try:
            return cs.resnet50_bs256_step(
                jax, pt, layers, models, bench, peak,
                extra={"fused_conv_epilogue": fused})
        finally:
            pt.flags.FLAGS.fused_conv_epilogue = False

    base = cs.experiment("resnet50_bs256_epilogue_off",
                         lambda: resnet(False), seconds=900)
    cs.experiment("resnet50_bs256_epilogue_on",
                  lambda: resnet(True), seconds=900)

    # 1b. bf16 inference row A/B (the single-pass fusion path).
    def infer(fused):
        pt.flags.FLAGS.fused_conv_epilogue = fused
        try:
            import numpy as np

            main_prog, startup = pt.Program(), pt.Program()
            with pt.program_guard(main_prog, startup):
                images = layers.data("images", shape=[224, 224, 3])
                logits = models.resnet_imagenet(images, num_classes=1000,
                                                depth=50, is_test=True)
            scope = pt.Scope()
            exe = pt.Executor(pt.TPUPlace())
            exe.run(startup, scope=scope)
            rng = np.random.RandomState(0)
            feed = {"images": rng.rand(16, 224, 224, 3)
                    .astype("float32")}
            import time

            for _ in range(3):
                exe.run(main_prog, feed=feed, fetch_list=[logits],
                        scope=scope)
            t0 = time.perf_counter()
            for _ in range(30):
                o, = exe.run(main_prog, feed=feed, fetch_list=[logits],
                             scope=scope, return_numpy=False)
            np.asarray(o)
            sec = (time.perf_counter() - t0) / 30
            return {"img_per_sec": round(16 / sec, 1),
                    "fused_conv_epilogue": fused}
        finally:
            pt.flags.FLAGS.fused_conv_epilogue = False

    cs.experiment("resnet50_infer_bs16_epilogue_off",
                  lambda: infer(False), seconds=600)
    cs.experiment("resnet50_infer_bs16_epilogue_on",
                  lambda: infer(True), seconds=600)

    # 3. Stacked-scan remat A/B: all-or-nothing vs the save-dots policy.
    def lm_stacked(remat):
        import time

        import numpy as np

        bs, T, vocab, d, Lh = 8, 2048, 16384, 1024, 8
        main_prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(main_prog, startup):
            ids = layers.data("ids", shape=[T], dtype="int64")
            tgt = layers.data("tgt", shape=[T], dtype="int64")
            logits = models.transformer_lm(
                ids, vocab_size=vocab, d_model=d, n_layers=Lh,
                num_heads=8, max_len=T, pipeline_stack=True, remat=remat)
            loss = layers.mean(layers.softmax_with_cross_entropy(
                layers.reshape(logits, shape=[-1, vocab]),
                layers.reshape(tgt, shape=[-1, 1])))
            pt.optimizer.AdamOptimizer(learning_rate=1e-4).minimize(
                loss, startup_program=startup)
        rng = np.random.RandomState(0)
        feed = {"ids": rng.randint(0, vocab, (bs, T)).astype("int64"),
                "tgt": rng.randint(0, vocab, (bs, T)).astype("int64")}
        t0 = time.perf_counter()
        sec = bench._time_train_steps(jax, pt, main_prog, startup, loss,
                                      feed, steps=10)
        wall = time.perf_counter() - t0
        flops = bench.transformer_train_flops(bs, T, d, Lh, vocab)
        return {"tokens_per_sec": round(bs * T / sec),
                "mfu": round(flops / sec / peak, 4) if peak else None,
                "remat": str(remat),
                "compile_plus_run_wall_s": round(wall, 1)}

    cs.experiment("lm_stacked_remat_full", lambda: lm_stacked(True),
                  seconds=900)
    cs.experiment("lm_stacked_remat_dots", lambda: lm_stacked("dots"),
                  seconds=900)

    # 4. Self-speculative decode A/B at temp 0: train the stack briefly on
    #    a learnable pattern, distill the draft head (copy the real head;
    #    the k-layer trunk still differs), then time spec vs plain decode.
    def spec_decode_ab():
        import time

        import numpy as np

        vocab, d, Lh, H = 2048, 512, 8, 8
        Tp, N, bs = 128, 128, 8
        maxlen = Tp + N + 8
        main_prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(main_prog, startup):
            ids = layers.data("ids", shape=[maxlen - 1], dtype="int64")
            tgt = layers.data("tgt", shape=[maxlen - 1], dtype="int64")
            logits = models.transformer_lm(
                ids, vocab_size=vocab, d_model=d, n_layers=Lh,
                num_heads=H, max_len=maxlen, pipeline_stack=True)
            loss = layers.mean(layers.softmax_with_cross_entropy(
                layers.reshape(logits, shape=[-1, vocab]),
                layers.reshape(tgt, shape=[-1, 1])))
            pt.optimizer.AdamOptimizer(learning_rate=3e-4).minimize(
                loss, startup_program=startup)
        scope = pt.Scope()
        exe = pt.Executor(pt.TPUPlace())
        exe.run(startup, scope=scope)
        rng = np.random.RandomState(0)
        seq = (rng.randint(0, vocab, (64, 1))
               + 7 * np.arange(maxlen)) % vocab
        feed = {"ids": seq[:, :-1].astype("int64"),
                "tgt": seq[:, 1:].astype("int64")}
        for _ in range(150):
            exe.run(main_prog, feed=feed, fetch_list=[loss], scope=scope)

        prog, startup2 = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup2):
            prompt = layers.data("ps", shape=[Tp], dtype="int64")
            plain = models.transformer_lm_generate(
                prompt, vocab_size=vocab, d_model=d, n_layers=Lh,
                num_heads=H, max_len=maxlen, max_new_tokens=N)
            spec, rounds = models.transformer_lm_speculative_generate(
                prompt, vocab_size=vocab, d_model=d, n_layers=Lh,
                num_heads=H, max_len=maxlen, max_new_tokens=N,
                draft_layers=2, gamma=4)
        trained = {k: np.asarray(scope.get(k)) for k in scope.keys()}
        exe.run(startup2, scope=scope)
        for k, v in trained.items():
            scope.set(k, v)
        scope.set("draft_head.w", np.asarray(scope.get("lm_head.w")))
        scope.set("draft_ln.scale",
                  np.asarray(scope.get("final_ln.scale")))
        scope.set("draft_ln.bias", np.asarray(scope.get("final_ln.bias")))
        p = ((rng.randint(0, vocab, (bs, 1)) + 7 * np.arange(Tp))
             % vocab).astype("int64")

        def timed(fetches):
            for _ in range(2):
                exe.run(prog, feed={"ps": p}, fetch_list=fetches,
                        scope=scope)
            t0 = time.perf_counter()
            for _ in range(5):
                outs = exe.run(prog, feed={"ps": p}, fetch_list=fetches,
                               scope=scope, return_numpy=False)
            got = [np.asarray(o) for o in outs]
            return (time.perf_counter() - t0) / 5, got

        sec_plain, (g_plain,) = timed([plain])
        sec_spec, (g_spec, r) = timed([spec, rounds])
        assert (g_spec == g_plain).all(), "spec decode diverged"
        return {"plain_s": round(sec_plain, 3),
                "spec_s": round(sec_spec, 3),
                "speedup": round(sec_plain / sec_spec, 3),
                "verify_rounds": int(r[0]), "plain_rounds": N,
                "tokens_per_sec_spec": round(bs * N / sec_spec)}

    cs.experiment("spec_decode_ab", spec_decode_ab, seconds=1400)

    # 5. Headline MFU rows for BENCH_r05.
    cs.experiment(
        "lm_wide_d2048_h16",
        lambda: cs.transformer_lm_step(jax, pt, layers, models, bench,
                                       peak, bs=8, d=2048, H=16),
        seconds=700)
    cs.experiment(
        "lm_d1024_h8",
        lambda: cs.transformer_lm_step(jax, pt, layers, models, bench,
                                       peak),
        seconds=700)

    # 6. Flash-attention block-size sweep at d1024 H8 (PERF.md: the
    #    d1024 residual gap is partly the flash kernel's in-kernel
    #    softmax VPU work — bigger K blocks amortize it; the sweep says
    #    whether the 512x512 default leaves MFU on the table).
    def lm_blocks(bq, bk):
        from paddle_tpu.kernels import flash_attention as fa

        prev = (fa.DEFAULT_BLOCK_Q, fa.DEFAULT_BLOCK_K)
        fa.DEFAULT_BLOCK_Q, fa.DEFAULT_BLOCK_K = bq, bk
        try:
            return cs.transformer_lm_step(
                jax, pt, layers, models, bench, peak,
                extra={"block_q": bq, "block_k": bk})
        finally:
            fa.DEFAULT_BLOCK_Q, fa.DEFAULT_BLOCK_K = prev

    for bq, bk in ((256, 512), (512, 1024), (1024, 512), (1024, 1024)):
        cs.experiment(f"lm_d1024_blocks_q{bq}_k{bk}",
                      lambda bq=bq, bk=bk: lm_blocks(bq, bk),
                      seconds=600)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""r3c2: the tail of the r3c A/B that the tunnel drop cost — ResNet-50
bs256 with the custom batch_norm backward, plus its per-op profile and
two wide-grid transformer MFU probes. (The LM custom-LN rows already
landed: d1024 48.1%->49.2%, d2048 55.8%->55.9%, CHIP_SESSION_r3.jsonl.)
Run by tools/tunnel_watch.sh when the tunnel returns. Uses the shared
tools/chip_session scaffolding (journal, watchdog, probe, builders)."""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import chip_session as cs  # noqa: E402


def main():
    jax = cs.probe_tpu("r3c2: ResNet custom-BN A/B")
    if jax is None:
        return 1

    import bench
    import paddle_tpu as pt
    from paddle_tpu import layers, models

    cs._PT = pt
    peak = bench._peak_flops(jax.devices()[0].device_kind)
    pt.set_amp(True)
    pass  # fused linear backward removed in round 5 (lost its chip A/B)

    # On-chip correctness first: the custom norm backwards vs generic
    # vjp under bf16 (the new tier check, run standalone to keep this
    # session short).
    def tier(check_name):
        sys.path.insert(0, os.path.join(REPO, "tests"))
        import tpu_tier

        return {"detail": getattr(tpu_tier, check_name)()}

    cs.experiment("tier_norm_backward_parity",
                  lambda: tier("norm_backward_matches_generic_vjp"),
                  seconds=600)
    cs.experiment("tier_fused_head_parity",
                  lambda: tier("fused_head_matches_unfused"),
                  seconds=600)

    cs.experiment(
        "resnet50_bs256_custombn",
        lambda: cs.resnet50_bs256_step(jax, pt, layers, models, bench,
                                       peak,
                                       extra={"norm_grad": "custom"}),
        seconds=900)

    # Wide-grid MFU probes past the 55.9% d2048 row: more tokens per step
    # at d2048, and a d3072 config (d_head 128 via H24) — both keep the
    # MXU-native head width and fatten the FFN contractions further.
    def lm(bs, d, H):
        return cs.transformer_lm_step(jax, pt, layers, models, bench,
                                      peak, bs=bs, d=d, H=H,
                                      extra={"norm_grad": "custom"})

    cs.experiment("lm_d2048_bs16", lambda: lm(16, 2048, 16), seconds=700)
    cs.experiment("lm_d3072_bs4", lambda: lm(4, 3072, 24), seconds=700)

    # Chunked fused head+loss (layers.fused_head_cross_entropy): A/B at
    # the bench vocab, then a 131k vocab that the naive [tokens, vocab]
    # logits path could not hold (16k tokens x 131k bf16 = 4 GB + grad).
    cs.experiment(
        "lm_d1024_fusedhead",
        lambda: cs.transformer_lm_step(jax, pt, layers, models, bench,
                                       peak, fused_head=True,
                                       extra={"norm_grad": "custom"}),
        seconds=700)
    cs.experiment(
        "lm_v131k_fusedhead",
        lambda: cs.transformer_lm_step(jax, pt, layers, models, bench,
                                       peak, vocab=131072,
                                       fused_head=True,
                                       extra={"norm_grad": "custom"}),
        seconds=900)

    cs.experiment(
        "profile_resnet_custombn",
        lambda: cs.resnet50_profile(pt, layers, models,
                                    "/tmp/chip_session_trace_r3c2"),
        seconds=1200)
    return 0


if __name__ == "__main__":
    sys.exit(main())

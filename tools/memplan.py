"""memplan — static memory planning & roofline analysis for programs.

Runs the paddle_tpu.analysis.memory liveness/peak-HBM analyzer and the
per-op cost model over saved inference models and/or the demo program
topologies: prints the peak watermark, the top-N live tensors at the
peak (with producing op + user callsite), the per-op-type roofline table
(FLOPs, HBM bytes, arithmetic intensity vs the v5e ridge, estimated
time), and — for training programs — the remat advisor's ranked
``recompute_guard`` candidates. With ``--budget`` it exits nonzero when
the static peak exceeds the budget (the same gate
``SGD.train(mem_budget=...)`` applies at build time).

Usage (repo root, CPU backend):

    JAX_PLATFORMS=cpu python tools/memplan.py MODEL_DIR [--batch 16]
    JAX_PLATFORMS=cpu python tools/memplan.py --demo quick_start \
        --batch 32 --top 12 --budget 8e9
    ... [--json] [--no-roofline] [--no-advice]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_proglint():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "proglint", os.path.join(REPO, "tools", "proglint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def plan_target(tag, program, feed_names, fetch_names, scope, args,
                plan=None):
    """Analyze one target; returns a JSON-safe dict."""
    from paddle_tpu import analysis

    entry = {"target": tag, "batch": args.batch}
    try:
        mem = analysis.analyze_memory(program, feed_names, fetch_names,
                                      scope=scope, batch_size=args.batch,
                                      plan=plan)
    except Exception as exc:
        entry["error"] = f"{type(exc).__name__}: {exc}"
        return entry
    if mem.mesh_axes:
        entry["mesh"] = mem.mesh_axes
        entry["per_device"] = True
        if mem.collectives is not None:
            entry["collective_bytes"] = mem.collective_bytes
            entry["collectives_by_kind"] = mem.collectives.bytes_by_kind()
            entry["per_device_state_bytes"] = \
                mem.collectives.per_device_state_bytes
    entry.update({
        "peak_bytes": mem.peak_bytes,
        "resident_bytes": mem.resident_bytes,
        "peak_op_index": mem.peak_op_index,
        "peak_op_type": mem.peak_op_type,
        "total_flops": mem.total_flops,
        "total_hbm_bytes": mem.total_hbm_bytes,
        "intensity": mem.intensity,
        "est_step_ms": mem.estimated_step_seconds() * 1e3,
        "top": [dataclasses_asdict(t) for t in mem.top(args.top)],
    })
    if not args.no_roofline:
        entry["roofline"] = mem.roofline_rows()
    if not args.no_advice:
        entry["advice"] = [a.format() for a in
                           analysis.advise_recompute(program, mem)]
    if args.budget is not None:
        entry["budget_bytes"] = args.budget
        entry["over_budget"] = mem.peak_bytes > args.budget
    entry["_report"] = mem.format_report(args.top)
    return entry


def dataclasses_asdict(t):
    import dataclasses

    return dataclasses.asdict(t)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="memplan", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("model_dirs", nargs="*",
                    help="save_inference_model directories to analyze")
    ap.add_argument("--demo", action="append", default=[],
                    help="analyze a demo's program topologies "
                         "(quick_start, serving_lm, wide_deep; "
                         "repeatable)")
    ap.add_argument("--batch", type=int, default=16,
                    help="batch size substituted for -1 dims (default 16)")
    ap.add_argument("--top", type=int, default=10,
                    help="top-N live tensors to list (default 10)")
    ap.add_argument("--budget", type=float, default=None,
                    help="peak-HBM budget in bytes; exit nonzero when any "
                         "target's static peak exceeds it")
    ap.add_argument("--mesh", default=None,
                    help="price the program PER DEVICE over a named mesh "
                         "(e.g. --mesh dp=4,mp=2): sharded dims divide, "
                         "plan collectives (psum/all-to-all wire bytes) "
                         "are added to the report")
    ap.add_argument("--plan", default="auto",
                    choices=("auto", "dp", "megatron", "zero", "vocab",
                             "expert"),
                    help="with --mesh: the canned ShardingPlan to price "
                         "under (auto = megatron when mp>1, else dp)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--no-roofline", action="store_true")
    ap.add_argument("--no-advice", action="store_true")
    args = ap.parse_args(argv)
    if not args.model_dirs and not args.demo:
        ap.error("nothing to analyze: give MODEL_DIR(s) or --demo")

    proglint = _load_proglint()
    plan = proglint.build_plan(proglint.parse_mesh(args.mesh),
                               args.plan) if args.mesh else None
    targets = []
    failures = 0
    for d in args.model_dirs:
        try:
            targets.extend(proglint.load_saved_model(d))
        except Exception as exc:
            print(f"== {d}: load failure: {type(exc).__name__}: {exc}",
                  file=sys.stderr)
            failures += 1
    for d in args.demo:
        targets.extend(proglint.build_demo(d))

    report = []
    over = 0
    for tag, program, feeds, fetches, scope in targets:
        entry = plan_target(tag, program, feeds, fetches, scope, args,
                            plan=plan)
        report.append(entry)
        if entry.get("error"):
            failures += 1
        if entry.get("over_budget"):
            over += 1

    if args.as_json:
        slim = [{k: v for k, v in e.items() if k != "_report"}
                for e in report]
        print(json.dumps({"targets": slim, "over_budget": over,
                          "failures": failures}, indent=1))
    else:
        for e in report:
            print(f"== {e['target']}")
            if e.get("error"):
                print(f"   analysis failed: {e['error']}")
                continue
            for line in e["_report"].splitlines():
                print("   " + line)
            if not args.no_roofline and e.get("roofline"):
                print("   hottest op groups (static roofline):")
                for r in e["roofline"][:6]:
                    print(f"     {r['op']:<26} x{r['count']:<4} "
                          f"{r['flops'] / 1e9:>10.2f} GF "
                          f"{r['bytes'] / 1e9:>8.3f} GB  "
                          f"int {r['intensity']:>8.1f}  [{r['bound']}] "
                          f"~{r['est_ms']:.3f} ms")
            if e.get("advice"):
                print("   remat advisor:")
                for a in e["advice"]:
                    print("     " + a)
            if e.get("over_budget"):
                print(f"   OVER BUDGET: peak "
                      f"{e['peak_bytes'] / 1e9:.3f} GB > "
                      f"{e['budget_bytes'] / 1e9:.3f} GB")
        print(f"memplan: {len(report)} target(s), {over} over budget, "
              f"{failures} failure(s)")
    return 1 if (over or failures) else 0


if __name__ == "__main__":
    sys.exit(main())

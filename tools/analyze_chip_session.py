"""Summarise CHIP_SESSION_r3.jsonl into a PERF.md-ready markdown table.

Usage:  python tools/analyze_chip_session.py [path]
Reads the incremental chip-session journal (tools/chip_session.py) and
prints per-experiment results plus the headline A/B deltas (fused linear
backward on/off, d_head 64 vs 128, GQA decode), so a returning tunnel
session turns into PERF.md prose in one read.
"""
import json
import os
import sys


def load(path):
    recs = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                recs.append(json.loads(line))
    return recs


def fmt(result):
    if not isinstance(result, dict):
        return str(result)
    return ", ".join(f"{k}={v}" for k, v in result.items()
                     if k != "config")


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "CHIP_SESSION_r3.jsonl")
    recs = load(path)
    by = {}
    print("| experiment | ok | s | result |")
    print("|---|---|---|---|")
    for r in recs:
        name = r.get("experiment", "?")
        by[name] = r
        if name == "tpu_tier" and r.get("ok") and isinstance(
                r.get("result"), dict):
            n_ok = sum(1 for v in r["result"].values() if v.get("ok"))
            cell = f"{n_ok}/{len(r['result'])} checks pass"
            bad = [k for k, v in r["result"].items() if not v.get("ok")]
            if bad:
                cell += " (FAIL: " + ", ".join(bad) + ")"
        else:
            cell = fmt(r.get("result")) if r.get("ok") \
                else (r.get("error") or "")[:80]
        print(f"| {name} | {'y' if r.get('ok') else 'N'} | "
              f"{r.get('seconds', '')} | {cell} |")

    def mfu(name):
        r = by.get(name, {})
        return (r.get("result") or {}).get("mfu") if r.get("ok") else None

    def toks(name):
        r = by.get(name, {})
        return (r.get("result") or {}).get("decode_tokens_per_sec") \
            if r.get("ok") else None

    print()
    pairs = [
        ("ResNet-50 fused linear bwd", "resnet50_bs256_fused_off",
         "resnet50_bs256_fused_on", mfu),
        ("LM fused linear bwd (d128)", "lm_h8_fused_off",
         "lm_h8_fused_on", mfu),
        ("LM d_head 64 -> 128 (fused)", "lm_h16_fused_on",
         "lm_h8_fused_on", mfu),
        ("LM per-layer -> stacked scan", "lm_h8_fused_on",
         "lm_stacked_scan", mfu),
        ("decode GQA kv8 -> kv2", "lm_decode_throughput",
         "lm_decode_throughput_gqa2", toks),
        ("decode plain -> speculative", "lm_decode_throughput",
         "lm_spec_decode", toks),
    ]
    for label, a, b, metric in pairs:
        va, vb = metric(a), metric(b)
        if va is not None and vb is not None and va:
            print(f"- {label}: {va} -> {vb} "
                  f"({(vb - va) / va * 100:+.1f}%)")
        else:
            print(f"- {label}: incomplete ({a}={va}, {b}={vb})")


if __name__ == "__main__":
    main()

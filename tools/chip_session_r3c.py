"""r3c chip session: A/B the hand-written batch_norm/layer_norm backward
(ops/nn_ops.py _batch_norm_grad/_layer_norm_grad) that removes the f32
activation residuals the generic vjp pinned across forward->backward.
Candidates it should move: ResNet-50 (BN-bound byte stream, 30.45% MFU
unfused) and the transformer LM (two LNs/block; d1024 at 48.1%, d2048 at
55.8%). Reuses tools/chip_session's journal + watchdog scaffolding.

Usage: python tools/chip_session_r3c.py
"""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import chip_session as cs  # noqa: E402


def main():
    detail = ""
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=180)
        platform = (probe.stdout or "").strip().splitlines()[-1] \
            if probe.returncode == 0 and probe.stdout.strip() else None
        if platform is None:
            tail = (probe.stderr or "").strip().splitlines()[-3:]
            detail = f" rc={probe.returncode}: " + " | ".join(tail)
    except subprocess.TimeoutExpired:
        platform = None
        detail = " (probe timed out after 180s)"
    if platform is None or platform == "cpu":
        cs.emit({"experiment": "probe", "ok": False,
                 "error": f"no TPU backend (probe got {platform!r}; "
                          f"tunnel down or hung){detail}"[:500]})
        return 1

    import jax

    dev = jax.devices()[0]
    cs.emit({"experiment": "probe", "ok": dev.platform != "cpu",
             "result": {"platform": dev.platform, "kind": dev.device_kind,
                        "session": "r3c: custom norm backward"}})
    if dev.platform == "cpu":
        return 1

    import numpy as np

    import bench
    import paddle_tpu as pt
    from paddle_tpu import layers, models

    cs._PT = pt
    peak = bench._peak_flops(dev.device_kind)
    pt.set_amp(True)
    pt.flags.FLAGS.fused_linear_grad = False

    def lm(bs, d=1024, H=8):
        tok_s, flops_s = bench.bench_transformer_step(
            jax, pt, layers, models, bs=bs, d=d, H=H)
        return {"tokens_per_sec": round(tok_s),
                "mfu": round(flops_s / peak, 4) if peak else None,
                "d_model": d, "bs": bs, "norm_grad": "custom"}

    cs.experiment("lm_h8_customln", lambda: lm(8), seconds=600)
    cs.experiment("lm_d2048_customln", lambda: lm(8, d=2048, H=16),
                  seconds=700)

    def resnet_step(batch=256, steps=20):
        main_prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(main_prog, startup):
            images = layers.data("images", shape=[224, 224, 3])
            label = layers.data("label", shape=[1], dtype="int64")
            logits = models.resnet_imagenet(images, num_classes=1000,
                                            depth=50)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, label))
            pt.optimizer.MomentumOptimizer(
                learning_rate=0.1, momentum=0.9).minimize(
                loss, startup_program=startup)
        rng = np.random.RandomState(0)
        feed = {"images": rng.rand(batch, 224, 224, 3).astype("float32"),
                "label": rng.randint(0, 1000, (batch, 1)).astype("int64")}
        sec = bench._time_train_steps(jax, pt, main_prog, startup, loss,
                                      feed, warmup=3, steps=steps)
        flops = bench.RESNET50_TRAIN_FLOPS_224
        return {"img_per_sec": round(batch / sec, 1),
                "ms_per_step": round(sec * 1e3, 2),
                "mfu": round(flops * batch / sec / peak, 4) if peak
                else None,
                "norm_grad": "custom"}

    cs.experiment("resnet50_bs256_custombn", resnet_step, seconds=900)

    # Per-op profile with the custom BN backward: did the convert /
    # normalize byte streams actually shrink?
    def profile_resnet():
        from paddle_tpu import profiler

        main_prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(main_prog, startup):
            images = layers.data("images", shape=[224, 224, 3])
            label = layers.data("label", shape=[1], dtype="int64")
            logits = models.resnet_imagenet(images, num_classes=1000,
                                            depth=50)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, label))
            pt.optimizer.MomentumOptimizer(
                learning_rate=0.1, momentum=0.9).minimize(
                loss, startup_program=startup)
        scope = pt.Scope()
        exe = pt.Executor(pt.TPUPlace())
        exe.run(startup, scope=scope)
        rng = np.random.RandomState(0)
        feed = {"images": rng.rand(256, 224, 224, 3).astype("float32"),
                "label": rng.randint(0, 1000, (256, 1)).astype("int64")}
        for _ in range(3):
            exe.run(main_prog, feed=feed, fetch_list=[loss], scope=scope)
        logdir = "/tmp/chip_session_trace_r3c"
        with profiler.xprof_trace(logdir):
            for _ in range(5):
                o, = exe.run(main_prog, feed=feed, fetch_list=[loss],
                             scope=scope, return_numpy=False)
            np.asarray(o)
        return profiler.framework_op_stats(logdir, top=12)

    cs.experiment("profile_resnet_custombn", profile_resnet, seconds=1500)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""r3c chip session: A/B the hand-written batch_norm/layer_norm backward
(ops/nn_ops.py _batch_norm_grad/_layer_norm_grad) that removes the f32
activation residuals the generic vjp pinned across forward->backward.
Candidates it should move: ResNet-50 (BN-bound byte stream, 30.45% MFU
unfused) and the transformer LM (two LNs/block; d1024 at 48.1%, d2048 at
55.8%). Reuses tools/chip_session's journal + watchdog scaffolding.

Usage: python tools/chip_session_r3c.py
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import chip_session as cs  # noqa: E402


def main():
    jax = cs.probe_tpu('r3c: custom norm backward')
    if jax is None:
        return 1

    import bench
    import paddle_tpu as pt
    from paddle_tpu import layers, models

    cs._PT = pt
    peak = bench._peak_flops(jax.devices()[0].device_kind)
    pt.set_amp(True)
    pass  # fused linear backward removed in round 5 (lost its chip A/B)

    def lm(bs, d=1024, H=8):
        return cs.transformer_lm_step(jax, pt, layers, models, bench,
                                      peak, bs=bs, d=d, H=H,
                                      extra={"norm_grad": "custom"})

    cs.experiment("lm_h8_customln", lambda: lm(8), seconds=600)
    cs.experiment("lm_d2048_customln", lambda: lm(8, d=2048, H=16),
                  seconds=700)

    cs.experiment(
        "resnet50_bs256_custombn",
        lambda: cs.resnet50_bs256_step(jax, pt, layers, models, bench,
                                       peak,
                                       extra={"norm_grad": "custom"}),
        seconds=900)

    # Per-op profile with the custom BN backward: did the convert /
    # normalize byte streams actually shrink?
    cs.experiment(
        "profile_resnet_custombn",
        lambda: cs.resnet50_profile(pt, layers, models,
                                    "/tmp/chip_session_trace_r3c"),
        seconds=1500)
    return 0


if __name__ == "__main__":
    sys.exit(main())

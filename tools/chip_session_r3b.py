"""Focused r3b chip session: exactly the items STATUS.md queued for the
next tunnel window, in priority order so an early tunnel drop costs the
least important ones. Reuses tools/chip_session's scaffolding (SIGALRM
watchdog per experiment, crash-proof JSONL journal).

Queue (STATUS.md "Still queued for the next tunnel window"):
  1. the three bf16 saved-model inference benches (io.py '|V2' fix landed
     in be25baa — these rows died on it in the first session),
  2. transformer bs16 at d_head=128 — the >=50% MFU candidate (bs8 sits
     at 48.1%; bs32 OOMs),
  3. a wider d2048 config (d_head 128, fatter MXU tiles) as the second
     MFU candidate,
  4. the unfused ResNet per-op profile.
The full bench.py refresh runs as its own process after this exits (the
tunnel tolerates one attached process).

Usage: python tools/chip_session_r3b.py   (tunnel env already in shell)
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import chip_session as cs  # noqa: E402  (journal + watchdog scaffolding)


def main():
    jax = cs.probe_tpu('r3b')
    if jax is None:
        return 1

    import bench
    import paddle_tpu as pt
    from paddle_tpu import layers, models

    cs._PT = pt
    peak = bench._peak_flops(jax.devices()[0].device_kind)
    pt.set_amp(True)

    # 1. The three bf16 saved-model inference rows (BASELINE.md "Infer
    #    Speed"; first session hit the '|V2' np.save bug, now fixed).
    for name in bench.INFER_BASELINES:
        cs.experiment(f"infer_{name}",
                      lambda n=name: bench.bench_inference(
                          jax, pt, layers, models, n),
                      seconds=420)

    # 2. Transformer MFU candidates, fused backward off (won the bs8 A/B).
    def lm(bs, d=1024, H=8, L=8):
        pass  # fused linear backward removed in round 5 (lost its chip A/B)
        return cs.transformer_lm_step(jax, pt, layers, models, bench,
                                      peak, bs=bs, d=d, H=H, L=L)

    r16 = cs.experiment("lm_h8_bs16", lambda: lm(16), seconds=600)
    if r16 is None:
        cs.experiment("lm_h8_bs12", lambda: lm(12), seconds=600)

    r2048 = cs.experiment("lm_d2048_bs8",
                          lambda: lm(8, d=2048, H=16), seconds=700)
    if r2048 is None:
        cs.experiment("lm_d2048_bs4", lambda: lm(4, d=2048, H=16),
                      seconds=700)

    # 3. Per-op profile of the winning (unfused) ResNet config.
    def profile_resnet():
        pass  # fused linear backward removed in round 5 (lost its chip A/B)
        return cs.resnet50_profile(pt, layers, models,
                                   "/tmp/chip_session_trace_r3b")

    cs.experiment("profile_resnet_unfused", profile_resnet, seconds=1500)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Focused r3b chip session: exactly the items STATUS.md queued for the
next tunnel window, in priority order so an early tunnel drop costs the
least important ones. Reuses tools/chip_session's scaffolding (SIGALRM
watchdog per experiment, crash-proof JSONL journal).

Queue (STATUS.md "Still queued for the next tunnel window"):
  1. the three bf16 saved-model inference benches (io.py '|V2' fix landed
     in be25baa — these rows died on it in the first session),
  2. transformer bs16 at d_head=128 — the >=50% MFU candidate (bs8 sits
     at 48.1%; bs32 OOMs),
  3. a wider d2048 config (d_head 128, fatter MXU tiles) as the second
     MFU candidate,
  4. the unfused ResNet per-op profile.
The full bench.py refresh runs as its own process after this exits (the
tunnel tolerates one attached process).

Usage: python tools/chip_session_r3b.py   (tunnel env already in shell)
"""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import chip_session as cs  # noqa: E402  (journal + watchdog scaffolding)


def main():
    # Probe the backend in a disposable child first: a downed tunnel hangs
    # backend init in uninterruptible C code (xla_env notes).
    detail = ""
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=180)
        platform = (probe.stdout or "").strip().splitlines()[-1] \
            if probe.returncode == 0 and probe.stdout.strip() else None
        if platform is None:
            tail = (probe.stderr or "").strip().splitlines()[-3:]
            detail = f" rc={probe.returncode}: " + " | ".join(tail)
    except subprocess.TimeoutExpired:
        platform = None
        detail = " (probe timed out after 180s)"
    if platform is None or platform == "cpu":
        cs.emit({"experiment": "probe", "ok": False,
                 "error": f"no TPU backend (probe got {platform!r}; "
                          f"tunnel down or hung){detail}"[:500]})
        return 1

    import jax

    dev = jax.devices()[0]
    cs.emit({"experiment": "probe", "ok": dev.platform != "cpu",
             "result": {"platform": dev.platform, "kind": dev.device_kind,
                        "session": "r3b"}})
    if dev.platform == "cpu":
        return 1

    import bench
    import paddle_tpu as pt
    from paddle_tpu import layers, models

    cs._PT = pt
    peak = bench._peak_flops(dev.device_kind)
    pt.set_amp(True)

    # 1. The three bf16 saved-model inference rows (BASELINE.md "Infer
    #    Speed"; first session hit the '|V2' np.save bug, now fixed).
    for name in bench.INFER_BASELINES:
        cs.experiment(f"infer_{name}",
                      lambda n=name: bench.bench_inference(
                          jax, pt, layers, models, n),
                      seconds=420)

    # 2. Transformer MFU candidates, fused backward off (won the bs8 A/B).
    def lm(bs, d=1024, H=8, L=8):
        pt.flags.FLAGS.fused_linear_grad = False
        tok_s, flops_s = bench.bench_transformer_step(
            jax, pt, layers, models, bs=bs, d=d, H=H, L=L)
        return {"tokens_per_sec": round(tok_s),
                "mfu": round(flops_s / peak, 4) if peak else None,
                "d_model": d, "d_head": d // H, "bs": bs}

    r16 = cs.experiment("lm_h8_bs16", lambda: lm(16), seconds=600)
    if r16 is None:
        cs.experiment("lm_h8_bs12", lambda: lm(12), seconds=600)

    r2048 = cs.experiment("lm_d2048_bs8",
                          lambda: lm(8, d=2048, H=16), seconds=700)
    if r2048 is None:
        cs.experiment("lm_d2048_bs4", lambda: lm(4, d=2048, H=16),
                      seconds=700)

    # 3. Per-op profile of the winning (unfused) ResNet config.
    def profile_resnet():
        import numpy as np

        from paddle_tpu import profiler

        pt.flags.FLAGS.fused_linear_grad = False
        main_prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(main_prog, startup):
            images = layers.data("images", shape=[224, 224, 3])
            label = layers.data("label", shape=[1], dtype="int64")
            logits = models.resnet_imagenet(images, num_classes=1000,
                                            depth=50)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, label))
            pt.optimizer.MomentumOptimizer(
                learning_rate=0.1, momentum=0.9).minimize(
                loss, startup_program=startup)
        scope = pt.Scope()
        exe = pt.Executor(pt.TPUPlace())
        exe.run(startup, scope=scope)
        rng = np.random.RandomState(0)
        feed = {"images": rng.rand(256, 224, 224, 3).astype("float32"),
                "label": rng.randint(0, 1000, (256, 1)).astype("int64")}
        for _ in range(3):
            exe.run(main_prog, feed=feed, fetch_list=[loss], scope=scope)
        logdir = "/tmp/chip_session_trace_r3b"
        with profiler.xprof_trace(logdir):
            for _ in range(5):
                o, = exe.run(main_prog, feed=feed, fetch_list=[loss],
                             scope=scope, return_numpy=False)
            np.asarray(o)
        return profiler.framework_op_stats(logdir, top=12)

    cs.experiment("profile_resnet_unfused", profile_resnet, seconds=1500)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Aggregate a paddle_tpu trace file into a per-name table.

Accepts either export format (Chrome trace-event JSON from
``trace.export_chrome_trace`` or the JSONL journal from
``trace.export_jsonl``) and prints calls/total/min/max/avg ms per span
name, sorted by total — the offline analogue of
``profiler.print_all_status`` for traces:

    python tools/trace_summary.py /tmp/trace.json
    python tools/trace_summary.py spans.jsonl --top 20 --prefix serving/
    python tools/trace_summary.py run.jsonl --runlog   # RunLog journals

``--runlog`` summarizes a trace.RunLog training journal instead:
per-pass cost, examples/sec, and the pass-end StatSet highlights.
``--goodput`` renders the training-observatory waterfall from the same
journal — per-bucket attributed seconds (device-compute vs badput), the
MFU trend, and (with ``--master-metrics FILE``, a saved master
Prometheus exposition) the per-trainer step-time skew table.
``--pipeline`` shows the async-trainer host-gap view; ``--resilience``
shows checkpoint stall (ckpt/save vs ckpt/write), retry pressure
(retry/attempt spans per policy), and the elastic-training lease plane:
leases expired/fenced per trainer, zombie acks the master rejected by
token, vetoed (fenced-writer) checkpoint saves, and trainer rejoin
counts with rollback wall time — plus the serving-recovery plane:
recovered requests (``fleet/recover`` resumes with emitted tokens
re-admitted via prefill) and disagg decode-leg failovers.

``--distributed`` stitches N JSONL journals from DIFFERENT processes
(the fleet router's + each replica's, written via
``trace.export_jsonl`` or the servers' ``/admin/trace_export``) by
trace id — the 128-bit ids are globally unique and every journal header
carries its process's wall-clock epoch, so spans align on one absolute
timeline — and prints the chosen request's cross-process tree plus its
critical-path budget (where did the request spend its time: queue,
hedge wait, prefill, decode?):

    python tools/trace_summary.py --distributed router.jsonl r0.jsonl \\
        r1.jsonl [--trace-id <32-hex>]
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def summarize(events, prefix=""):
    """Per-name rows (name, calls, total_ms, min_ms, max_ms, avg_ms)
    from trace events (``load_trace_events`` output), sorted by total
    descending."""
    agg = {}
    for e in events:
        name = e.get("name", "?")
        if not name.startswith(prefix):
            continue
        dur = float(e.get("dur", 0.0)) / 1e3  # us -> ms
        row = agg.setdefault(name, [0, 0.0, float("inf"), float("-inf")])
        row[0] += 1
        row[1] += dur
        row[2] = min(row[2], dur)
        row[3] = max(row[3], dur)
    rows = [(name, c, tot, mn, mx, tot / c)
            for name, (c, tot, mn, mx) in agg.items()]
    rows.sort(key=lambda r: -r[2])
    return rows


def format_rows(rows):
    head = (f"{'name':<40}{'calls':>8}{'total ms':>12}{'min ms':>10}"
            f"{'max ms':>10}{'avg ms':>10}")
    lines = [head, "-" * len(head)]
    for name, calls, total, mn, mx, avg in rows:
        lines.append(f"{name:<40}{calls:>8}{total:>12.3f}{mn:>10.3f}"
                     f"{mx:>10.3f}{avg:>10.3f}")
    return "\n".join(lines) if rows else "(no spans)"


def summarize_runlog(path):
    """Condense a RunLog JSONL journal: per-pass cost / examples/sec and
    iteration counts."""
    passes = {}
    for line in open(path):
        line = line.strip()
        if not line:
            continue
        row = json.loads(line)
        t = row.get("type")
        if t == "iteration":
            p = passes.setdefault(row["pass"], {"iters": 0, "cost": None})
            p["iters"] += 1
            p["cost"] = row["cost"]
        elif t == "pass_end":
            p = passes.setdefault(row["pass"], {"iters": 0, "cost": None})
            p["metrics"] = row.get("metrics")
            p["examples_per_sec"] = row.get("examples_per_sec")
    lines = []
    for pid in sorted(passes):
        p = passes[pid]
        m = p.get("metrics") or {}
        eps = p.get("examples_per_sec")
        lines.append(
            f"pass {pid}: {p['iters']} iters, last cost="
            f"{p['cost'] if p['cost'] is not None else '?'}, "
            f"mean cost={m.get('cost', '?')}"
            + (f", {eps} examples/s" if eps else ""))
    return "\n".join(lines) if lines else "(no passes)"


#: goodput taxonomy display order (paddle_tpu.trace.goodput.BUCKETS) —
#: hardcoded so the tool summarizes journals without importing jax
_GOODPUT_BUCKETS = ("device_compute", "host_dispatch", "data_wait",
                    "fresh_compile", "checkpoint_stall", "master_wait",
                    "recovery_rollback")


def _parse_trainer_series(text):
    """``trainer_<metric>{trainer="id"} value`` rows from a master
    Prometheus exposition -> {trainer: {metric: value}}."""
    import re

    out = {}
    pat = re.compile(r'^trainer_(\w+)\{trainer="([^"]+)"\}\s+(\S+)$')
    for line in text.splitlines():
        m = pat.match(line.strip())
        if m:
            metric, tid, val = m.group(1), m.group(2), float(m.group(3))
            out.setdefault(tid, {})[metric] = val
    return out


def summarize_goodput(path, master_metrics=None):
    """Goodput waterfall from a RunLog journal: where every attributed
    second went (per-bucket seconds and share), the MFU trend from the
    per-iteration gauges, and — given ``--master-metrics`` (a saved
    master Prometheus exposition) — the per-trainer step-time skew
    table the straggler detector works from."""
    iters = []
    buckets = {}
    for line in open(path):
        line = line.strip()
        if not line:
            continue
        row = json.loads(line)
        t = row.get("type")
        if t == "iteration":
            iters.append(row)
        elif t == "pass_end":
            # publish_stats mirrors cumulative bucket seconds into the
            # StatSet, so the LAST pass_end carries the run totals
            for name, s in (row.get("stat_set") or {}).items():
                if name.startswith("goodput/"):
                    buckets[name[len("goodput/"):]] = \
                        float(s.get("total_ms", 0.0)) / 1e3
    lines = []
    total = sum(buckets.values())
    wall = sum(r.get("wall_ms", 0.0) for r in iters) / 1e3
    if buckets:
        lines.append(f"{'bucket':<20}{'seconds':>12}{'share':>9}")
        lines.append("-" * 41)
        ordered = [b for b in _GOODPUT_BUCKETS if b in buckets] + \
            sorted(set(buckets) - set(_GOODPUT_BUCKETS))
        for b in ordered:
            s = buckets[b]
            pct = 100.0 * s / total if total > 0 else 0.0
            lines.append(f"{b:<20}{s:>12.3f}{pct:>8.1f}%")
        lines.append(f"{'total attributed':<20}{total:>12.3f}")
        if wall > 0:
            lines.append(f"{'measured step wall':<20}{wall:>12.3f}"
                         f"{100.0 * total / wall:>8.1f}% attributed")
        good = buckets.get("device_compute", 0.0)
        lines.append(f"goodput: {100.0 * good / total:.1f}% "
                     "(device-compute share of attributed time)"
                     if total > 0 else "goodput: n/a")
    else:
        lines.append("(no goodput/* stats in any pass_end — run with "
                     "SGD.train(goodput=...) enabled)")
    mfus = [r["mfu"] for r in iters if r.get("mfu") is not None]
    if mfus:
        emas = [r["mfu_ema"] for r in iters if r.get("mfu_ema") is not None]
        lines.append("")
        lines.append(f"MFU: first={mfus[0]:.4f} last={mfus[-1]:.4f} "
                     f"mean={sum(mfus) / len(mfus):.4f}"
                     + (f" ema={emas[-1]:.4f}" if emas else "")
                     + f"  ({len(mfus)} steps)")
    if master_metrics:
        series = _parse_trainer_series(open(master_metrics).read())
        if series:
            steps = [d.get("step_seconds") for d in series.values()
                     if d.get("step_seconds")]
            p50 = sorted(steps)[len(steps) // 2] if steps else 0.0
            lines.append("")
            head = (f"{'trainer':<16}{'step s':>10}{'skew':>7}"
                    f"{'goodput':>9}{'mfu':>8}{'flag':>6}")
            lines.append(head)
            lines.append("-" * len(head))
            for tid in sorted(series):
                d = series[tid]
                ss = d.get("step_seconds")
                skew = (ss / p50) if ss and p50 > 0 else None
                gp = d.get("goodput_fraction")
                mfu = d.get("mfu")
                lines.append(
                    f"{tid:<16}"
                    f"{(f'{ss:.4f}' if ss is not None else '-'):>10}"
                    f"{(f'{skew:.2f}x' if skew else '-'):>7}"
                    f"{(f'{gp:.3f}' if gp is not None else '-'):>9}"
                    f"{(f'{mfu:.3f}' if mfu is not None else '-'):>8}"
                    f"{('STRAG' if d.get('straggler') else ''):>6}")
    return "\n".join(lines)


def summarize_pipeline(events):
    """Host-gap view of an async training trace: aggregates the
    ``trainer/dispatch`` / ``trainer/resolve`` phase spans the
    ``SGD.train(async_depth=N)`` loop emits (trainer/iteration for the
    sync loop), plus dispatch-to-dispatch cadence and queue depth — how
    much of each step the host spends NOT overlapped with the device."""
    dispatch = sorted((e for e in events if e["name"] == "trainer/dispatch"),
                      key=lambda e: e["ts"])
    resolve = [e for e in events if e["name"] == "trainer/resolve"]
    sync_iters = [e for e in events if e["name"] == "trainer/iteration"]
    if not dispatch:
        return ("(no trainer/dispatch spans — sync loop?"
                + (f" {len(sync_iters)} trainer/iteration spans,"
                   f" avg {sum(e['dur'] for e in sync_iters) / len(sync_iters) / 1e3:.3f} ms"
                   if sync_iters else "")
                + ")")

    def avg_ms(evs):
        return sum(e["dur"] for e in evs) / len(evs) / 1e3 if evs else 0.0

    gaps = [b["ts"] - a["ts"] for a, b in zip(dispatch, dispatch[1:])]
    depths = [e["args"].get("queue_depth") for e in dispatch
              if e.get("args", {}).get("queue_depth") is not None]
    lines = [
        f"steps dispatched:        {len(dispatch)}",
        f"avg dispatch ms:         {avg_ms(dispatch):.3f}"
        "   (host work on the critical path)",
        f"avg resolve ms:          {avg_ms(resolve):.3f}"
        "   (blocking fetch; large = device-bound, overlapped)",
    ]
    if gaps:
        lines.append(f"avg dispatch-to-dispatch:"
                     f" {sum(gaps) / len(gaps) / 1e3:.3f} ms")
    if depths:
        lines.append(f"avg queue depth:         "
                     f"{sum(depths) / len(depths):.2f}")
    return "\n".join(lines)


def summarize_resilience(events):
    """Checkpoint-stall and retry-pressure view of a trace: how long the
    step loop blocked in ``ckpt/save`` (vs the background ``ckpt/write``
    cost), restore/fallback activity, and ``retry/attempt`` spans grouped
    by policy with their error samples."""

    def by_name(name):
        return [e for e in events if e.get("name") == name]

    def tot_ms(evs):
        return sum(float(e.get("dur", 0.0)) for e in evs) / 1e3

    lines = []
    saves = by_name("ckpt/save")
    writes = by_name("ckpt/write")
    restores = by_name("ckpt/restore")
    if saves:
        bg = [e for e in saves
              if e.get("args", {}).get("mode") == "background"]
        lines.append(
            f"ckpt saves:              {len(saves)} "
            f"({len(bg)} background), step-loop stall "
            f"{tot_ms(saves):.3f} ms total "
            f"({tot_ms(saves) / len(saves):.3f} avg)")
    if writes:
        bytes_ = [e.get("args", {}).get("bytes") for e in writes
                  if e.get("args", {}).get("bytes") is not None]
        lines.append(
            f"ckpt writes:             {len(writes)}, "
            f"{tot_ms(writes):.3f} ms total off-path"
            + (f", {max(bytes_)} bytes/ckpt" if bytes_ else ""))
    if restores:
        fb = [e for e in restores if e.get("args", {}).get("fallback")]
        lines.append(f"ckpt restores:           {len(restores)}"
                     + (f" ({len(fb)} FELL BACK past a torn checkpoint)"
                        if fb else ""))
    vetoed = by_name("ckpt/save_vetoed")
    if vetoed:
        lines.append(f"ckpt saves VETOED:       {len(vetoed)} "
                     "(fenced writer — zombie generation blocked)")
    retries = by_name("retry/attempt")
    if retries:
        pols = {}
        for e in retries:
            a = e.get("args", {})
            p = pols.setdefault(a.get("policy", "?"), [0, None])
            p[0] += 1
            p[1] = a.get("error") or p[1]
        for pol, (n, err) in sorted(pols.items(), key=lambda kv: -kv[1][0]):
            lines.append(f"retry pressure [{pol}]:   {n} failed attempts"
                         + (f"  last: {err}" if err else ""))
    # elastic plane: lease churn, fenced zombies, rejoin cost
    leases = by_name("master/lease_expired")
    if leases:
        trainers = sorted({e.get("args", {}).get("trainer", "?")
                           for e in leases})
        lines.append(f"leases expired/fenced:   {len(leases)} "
                     f"(trainers: {', '.join(trainers)})")
    zombies = by_name("master/zombie_ack_rejected")
    if zombies:
        ops = {}
        for e in zombies:
            op = e.get("args", {}).get("op", "?")
            ops[op] = ops.get(op, 0) + 1
        detail = ", ".join(f"{k} x{v}" for k, v in sorted(ops.items()))
        lines.append(f"zombie acks rejected:    {len(zombies)} ({detail})")
    rejoins = by_name("trainer/rejoin")
    if rejoins:
        lines.append(f"trainer rejoins:         {len(rejoins)}, "
                     f"rollback {tot_ms(rejoins):.3f} ms total "
                     f"({tot_ms(rejoins) / len(rejoins):.3f} avg)")
    # serving recovery plane: lineage resumes + decode-leg failovers
    recovers = by_name("fleet/recover")
    if recovers:
        reqs = sum(1 for e in recovers
                   if int(e.get("args", {}).get("recoveries", 1)) == 1)
        reused = sum(int(e.get("args", {}).get("tokens_reused", 0))
                     for e in recovers)
        lines.append(f"recovered requests:      {reqs} "
                     f"({len(recovers)} resumes), {reused} emitted "
                     f"tokens re-admitted via prefill (never re-decoded)")
    failovers = by_name("disagg/decode_leg_failover")
    if failovers:
        legs = sorted({str(e.get("args", {}).get("leg", "?"))
                       for e in failovers})
        reused = sum(int(e.get("args", {}).get("tokens_reused", 0))
                     for e in failovers)
        lines.append(f"decode-leg failovers:    {len(failovers)} "
                     f"(legs: {', '.join(legs)}), {reused} tokens "
                     f"re-prefilled on another leg")
    return "\n".join(lines) if lines else \
        "(no ckpt/* or retry/* spans — resilience idle)"


def load_journal(path):
    """One JSONL span journal -> rows with ABSOLUTE wall-clock times
    (header epoch + relative span seconds), tagged with the source
    file — the unit ``--distributed`` stitches."""
    from paddle_tpu.trace import load_jsonl_spans

    return load_jsonl_spans(path)


#: critical-path categories: first matching (prefix, label) claims the
#: span's self-time in the budget table
_BUDGET_BINS = (
    ("serving/queue", "queue"),
    ("fleet/hedge", "hedge fired"),
    ("serving/execute", "prefill"),
    ("serving/prefill", "prefill"),
    ("serving/decode", "decode"),
    ("fleet/attempt", "attempt (transport + replica)"),
    ("fleet/request", "router"),
    ("serving/request", "replica overhead"),
)


def _pick_trace(by_trace, want=None):
    if want is not None:
        tid = int(want, 16) if isinstance(want, str) else int(want)
        if tid not in by_trace:
            raise SystemExit(f"trace {want} not found; have "
                             f"{[f'{t:032x}' for t in by_trace]}")
        return tid
    # default: the longest-running REQUEST trace (the one a P99
    # investigation is after) — compile/background traces only win when
    # no request trace exists; ties break toward more spans
    def score(tid):
        spans = by_trace[tid]
        is_request = any(s["name"] in ("fleet/request", "serving/request")
                         for s in spans)
        roots = [s for s in spans if s["parent_id"] is None]
        dur = max((s["end"] - s["start"] for s in roots), default=0.0)
        return (is_request, dur, len(spans))
    return max(by_trace, key=score)


def summarize_distributed(paths, trace_id=None):
    """Stitch journals, pick one trace, print the cross-process span
    tree + the critical-path budget."""
    rows = [r for p in paths for r in load_journal(p)]
    if not rows:
        return "(no spans in any journal)"
    by_trace = {}
    for r in rows:
        by_trace.setdefault(r["trace_id"], []).append(r)
    tid = _pick_trace(by_trace, trace_id)
    spans = sorted(by_trace[tid], key=lambda r: (r["start"], -r["end"]))
    t0 = min(s["start"] for s in spans)
    t_end = max(s["end"] for s in spans)
    total_ms = (t_end - t0) * 1e3
    by_id = {s["span_id"]: s for s in spans}
    children = {}
    roots = []
    for s in spans:
        if s["parent_id"] in by_id and s["parent_id"] != s["span_id"]:
            children.setdefault(s["parent_id"], []).append(s)
        else:
            roots.append(s)  # true root OR parent still open/unsampled

    lines = [f"trace {tid:032x}: {len(spans)} spans from "
             f"{len(set(s['source'] for s in spans))} journal(s) "
             f"({', '.join(sorted(set(s['source'] for s in spans)))}), "
             f"{total_ms:.3f} ms end to end"]

    def key_attrs(s):
        a = s["attrs"]
        keep = [(k, a[k]) for k in ("replica", "status", "phase", "slot",
                                    "hedge", "tokens", "queue_wait_s",
                                    "prompt_len") if k in a]
        return (" {" + ", ".join(f"{k}={v}" for k, v in keep) + "}"
                if keep else "")

    def walk(s, depth):
        off = (s["start"] - t0) * 1e3
        dur = (s["end"] - s["start"]) * 1e3
        lines.append(f"  {'  ' * depth}{s['name']:<{max(1, 38 - 2 * depth)}}"
                     f" +{off:9.3f}ms {dur:9.3f}ms  [{s['source']}]"
                     f"{key_attrs(s)}")
        for c in sorted(children.get(s["span_id"], []),
                        key=lambda r: r["start"]):
            walk(c, depth + 1)

    for root in roots:
        walk(root, 0)

    # critical path: every instant of the trace is attributed to the
    # DEEPEST span covering it (flame-graph attribution, but across
    # processes), then binned — so queue/hedge/prefill/decode
    # percentages PARTITION the request's wall time instead of
    # double-counting overlapping parent/sibling spans
    depth = {}

    def _depth(s):
        sid = s["span_id"]
        if sid in depth:
            return depth[sid]
        d = 0
        seen = set()
        cur = s
        while cur["parent_id"] in by_id and cur["span_id"] not in seen:
            seen.add(cur["span_id"])
            cur = by_id[cur["parent_id"]]
            d += 1
        depth[sid] = d
        return d

    for s in spans:
        _depth(s)
    bounds = sorted({s["start"] for s in spans}
                    | {s["end"] for s in spans})
    budget = {}
    covered_ms = 0.0
    for a, b in zip(bounds, bounds[1:]):
        cover = [s for s in spans if s["start"] <= a and s["end"] >= b]
        if not cover:
            continue
        s = max(cover, key=lambda s: (depth[s["span_id"]], s["start"]))
        label = next((lab for prefix, lab in _BUDGET_BINS
                      if s["name"].startswith(prefix)), s["name"])
        ms = (b - a) * 1e3
        budget[label] = budget.get(label, 0.0) + ms
        covered_ms += ms
    lines.append("")
    lines.append("critical path (exclusive time per category):")
    for label, ms in sorted(budget.items(), key=lambda kv: -kv[1]):
        pct = 100.0 * ms / covered_ms if covered_ms > 0 else 0.0
        lines.append(f"  {label:<36}{ms:10.3f} ms  {pct:5.1f}%")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="+",
                    help="trace file(s): chrome JSON or JSONL (multiple "
                         "JSONL journals with --distributed)")
    ap.add_argument("--top", type=int, default=None,
                    help="show only the top-N rows by total time")
    ap.add_argument("--prefix", default="",
                    help="only span names with this prefix")
    ap.add_argument("--runlog", action="store_true",
                    help="input is a trace.RunLog training journal")
    ap.add_argument("--goodput", action="store_true",
                    help="goodput/badput waterfall + MFU trend from a "
                         "RunLog journal (SGD.train(goodput=...) runs)")
    ap.add_argument("--master-metrics", default=None,
                    help="with --goodput: a saved master Prometheus "
                         "exposition; adds the per-trainer skew table")
    ap.add_argument("--pipeline", action="store_true",
                    help="host-gap view of trainer dispatch/resolve spans")
    ap.add_argument("--resilience", action="store_true",
                    help="checkpoint-stall + retry-pressure + elastic "
                         "lease/rejoin view")
    ap.add_argument("--distributed", action="store_true",
                    help="stitch N process journals by trace id; print "
                         "the cross-process tree + critical path")
    ap.add_argument("--trace-id", default=None,
                    help="with --distributed: the 32-hex trace id to "
                         "show (default: the longest-running trace)")
    args = ap.parse_args(argv)
    if args.distributed:
        print(summarize_distributed(args.trace, trace_id=args.trace_id))
        return 0
    if len(args.trace) != 1:
        ap.error("multiple trace files need --distributed")
    if args.goodput:
        print(summarize_goodput(args.trace[0],
                                master_metrics=args.master_metrics))
        return 0
    if args.runlog:
        print(summarize_runlog(args.trace[0]))
        return 0
    from paddle_tpu.trace import load_trace_events

    events = load_trace_events(args.trace[0])
    if args.pipeline:
        print(summarize_pipeline(events))
        return 0
    if args.resilience:
        print(summarize_resilience(events))
        return 0
    rows = summarize(events, prefix=args.prefix)
    if args.top:
        rows = rows[:args.top]
    print(format_rows(rows))
    print(f"\n{len(events)} spans, {len(rows)} distinct names")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Online learning end to end: train -> publish -> serve -> AUC improves LIVE.

The loop real CTR systems run, on the CPU mesh: a Wide&Deep model with
``is_sparse=True`` high-dimensional embeddings trains FOREVER on a
synthetic click-stream served by the fault-tolerant master's task queue
(``paddle_tpu.online.StreamingTrainer`` — endless passes, periodic
checkpoints, preemption-safe), while an ``online.Publisher`` watches the
checkpoint directory and rolls every fresh weight generation into a
live 2-replica serving fleet with zero downtime and zero recompiles
(``Fleet.update_weights``). A held-out CTR batch is scored against the
FLEET between generations: the served AUC climbs as the trainer learns
— the weights the fleet answers with are getting better while it
serves.

The freshness SLO (seconds-behind-trainer) and the weight-version /
staleness gauges ride ``/fleet/status`` — the same payload
``tools/fleetctl.py status --table`` renders.

Run:  python demos/online_ctr.py   (PADDLE_TPU_DEMO_FAST=1 to smoke)
"""
import os
import tempfile

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, io
from paddle_tpu.dataset import ctr
from paddle_tpu.master import MasterServer
from paddle_tpu.online import Publisher, StreamingTrainer
from paddle_tpu.resilience import CheckpointConfig
from paddle_tpu.serving import Fleet, InferenceEngine
from paddle_tpu.trace.slo import SLO

FAST = bool(os.environ.get("PADDLE_TPU_DEMO_FAST"))
VOCAB = 2000 if FAST else 50_000
GENERATIONS = 2 if FAST else 4
SHARDS = 4 if FAST else 12
RECORDS = 320 if FAST else 512
EVAL_N = 256 if FAST else 1024


def build():
    """The train program + its pruned serving twin (same param names)."""
    main, startup = pt.Program(), pt.Program()
    startup.random_seed = 11
    with pt.program_guard(main, startup):
        ids = layers.data("ids", shape=[ctr.SLOTS], dtype="int64")
        dense = layers.data("dense", shape=[ctr.DENSE_DIM])
        label = layers.data("label", shape=[1])
        logit = pt.models.wide_deep(ids, dense, vocab_size=VOCAB,
                                    embed_dim=8, hidden_sizes=(32, 16))
        loss, prob = pt.models.wide_deep_loss(logit, label)
        sgd = pt.trainer.SGD(
            loss, pt.optimizer.AdagradOptimizer(learning_rate=0.05),
            [ids, dense, label], scope=pt.Scope())
    serve = io.prune_program(main, ["ids", "dense"], [prob.name])
    return sgd, startup, serve, prob.name


def auc(probs, labels):
    """Plain rank AUC over a held-out batch."""
    order = np.argsort(probs)
    ranks = np.empty(len(probs))
    ranks[order] = np.arange(1, len(probs) + 1)
    pos = labels.ravel() > 0.5
    n_pos, n_neg = pos.sum(), (~pos).sum()
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def main():
    sgd, startup, serve_prog, prob_name = build()

    def engine(seed):
        scope = pt.Scope()
        startup.random_seed = seed
        pt.Executor(pt.TPUPlace()).run(startup, scope=scope)
        return InferenceEngine(program=serve_prog,
                               feed_names=["ids", "dense"],
                               fetch_names=[prob_name], scope=scope,
                               batch_buckets=(64, EVAL_N),
                               place=pt.CPUPlace())

    srv = MasterServer(timeout_s=30, port=0)
    addr = srv.start()
    ckdir = tempfile.mkdtemp(prefix="online-ctr-ck")
    descs = ctr.task_descs(SHARDS, records_per_shard=RECORDS,
                           vocab=VOCAB)

    fleet = Fleet([engine(3), engine(4)], hedge=False,
                  slo=SLO(freshness_s=120.0, availability=0.99))
    publisher = Publisher(fleet, ckdir)
    fleet.start()

    # held-out eval batch, scored against the LIVE fleet each generation
    rng = ctr.common.synthetic_rng("ctr-heldout")
    eval_ids, eval_dense, eval_label = ctr._impressions(rng, EVAL_N,
                                                        VOCAB)

    def served_auc():
        futs = [fleet.submit({"ids": eval_ids[i],
                              "dense": eval_dense[i]})
                for i in range(EVAL_N)]
        probs = np.array([np.asarray(f.result(timeout=60)[0]).ravel()[0]
                          for f in futs])
        return auc(probs, eval_label)

    print(f"online CTR: vocab={VOCAB}, {SHARDS} shards x {RECORDS} "
          f"records, {GENERATIONS} generations -> 2-replica fleet")
    baseline = served_auc()
    print(f"  AUC served (random init): {baseline:.4f}")
    history = []
    for gen in range(GENERATIONS):
        trainer = StreamingTrainer(
            sgd, addr, ctr.task_reader, task_descs=descs, batch_size=64,
            checkpoint=CheckpointConfig(ckdir, every_n_steps=16,
                                        background=False),
            max_passes=1)
        stats = trainer.run()
        step = publisher.poll_once()
        a = served_auc()
        history.append(a)
        w = publisher.status()
        print(f"  gen {gen + 1}: trained {stats['steps']} steps "
              f"({stats['tasks_finished']} tasks), published step "
              f"{step}, served AUC {a:.4f}, staleness "
              f"{w['staleness_s']}s")
    status = fleet.status()
    fresh = status["slo"]["objectives"]["freshness"]
    print(f"  freshness SLO: attainment={fresh['attainment']} "
          f"(threshold {fresh['threshold_s']}s), generations="
          f"{status['weights']['generations']}")
    assert history[-1] > baseline, (
        "served AUC must improve over the random-init fleet as "
        "generations publish")
    assert status["weights"]["generations"] == GENERATIONS
    print("AUC improved live: "
          + f"{baseline:.4f} (init) -> "
          + " -> ".join(f"{a:.4f}" for a in history))
    fleet.stop()
    srv.stop()


if __name__ == "__main__":
    main()

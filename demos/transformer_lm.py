"""Train a small causal transformer LM (fluid-style API) and sample from
it — the long-context flagship path (flash attention, PERF.md). Beyond the
reference's capability set (it predates Transformers); shown here as the
idiomatic way to train one with this framework.

Run:  python demos/transformer_lm.py  (PADDLE_TPU_DEMO_FAST=1 to smoke)
"""
import os

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, models

FAST = bool(os.environ.get("PADDLE_TPU_DEMO_FAST"))


def synthetic_corpus(rng, vocab, n, T):
    """A learnable language: token t+1 = (3*t + noise) % vocab."""
    x = np.zeros((n, T + 1), np.int64)
    x[:, 0] = rng.randint(0, vocab, size=n)
    for t in range(T):
        noise = rng.randint(0, 2, size=n)
        x[:, t + 1] = (3 * x[:, t] + noise) % vocab
    return x


def main():
    vocab, T = 97, 32 if FAST else 64
    d_model, n_layers = 64, 2
    main_prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_prog, startup):
        ids = layers.data("ids", shape=[T], dtype="int64")
        tgt = layers.data("tgt", shape=[T], dtype="int64")
        logits = models.transformer_lm(ids, vocab_size=vocab,
                                       d_model=d_model, n_layers=n_layers,
                                       num_heads=4, max_len=T)
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.reshape(logits, shape=[-1, vocab]),
            layers.reshape(tgt, shape=[-1, 1])))
        pt.optimizer.AdamOptimizer(learning_rate=3e-3).minimize(
            loss, startup_program=startup)

    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)
    steps = 10 if FAST else 120
    for step in range(steps):
        seq = synthetic_corpus(rng, vocab, n=32, T=T)
        lo, = exe.run(main_prog,
                      feed={"ids": seq[:, :-1], "tgt": seq[:, 1:]},
                      fetch_list=[loss], scope=scope)
        if step % 20 == 0 or step == steps - 1:
            print(f"step {step}: loss {float(lo):.4f}")

    # greedy sampling: feed back argmax next-token predictions
    ctx = synthetic_corpus(rng, vocab, n=1, T=T)[:, :-1]
    out, = exe.run(main_prog, feed={"ids": ctx, "tgt": ctx},
                   fetch_list=[logits], scope=scope)
    pred = np.argmax(np.asarray(out)[0, -8:], axis=-1)
    truth = [(3 * t) % vocab for t in ctx[0, -8:]]
    print("model next-token:", pred.tolist())
    print("rule  next-token:", truth, "(modulo the +1 noise)")


if __name__ == "__main__":
    main()

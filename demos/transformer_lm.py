"""Train a small causal transformer LM (fluid-style API) and sample from
it — the long-context flagship path (flash attention, PERF.md). Beyond the
reference's capability set (it predates Transformers); shown here as the
idiomatic way to train one with this framework.

Run:  python demos/transformer_lm.py  (PADDLE_TPU_DEMO_FAST=1 to smoke)
"""
import os

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, models

FAST = bool(os.environ.get("PADDLE_TPU_DEMO_FAST"))


def synthetic_corpus(rng, vocab, n, T):
    """A learnable language: token t+1 = (3*t + noise) % vocab."""
    x = np.zeros((n, T + 1), np.int64)
    x[:, 0] = rng.randint(0, vocab, size=n)
    for t in range(T):
        noise = rng.randint(0, 2, size=n)
        x[:, t + 1] = (3 * x[:, t] + noise) % vocab
    return x


def main():
    vocab, T = 97, 32 if FAST else 64
    d_model, n_layers = 64, 2
    main_prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_prog, startup):
        ids = layers.data("ids", shape=[T], dtype="int64")
        tgt = layers.data("tgt", shape=[T], dtype="int64")
        # pipeline_stack: stacked [L, ...] weights (scan over layers; the
        # same tensors pipeline over a 'pp' mesh) — also what the KV-cache
        # generation program rejoins by name below
        logits = models.transformer_lm(ids, vocab_size=vocab,
                                       d_model=d_model, n_layers=n_layers,
                                       num_heads=4, max_len=2 * T,
                                       pipeline_stack=True)
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.reshape(logits, shape=[-1, vocab]),
            layers.reshape(tgt, shape=[-1, 1])))
        pt.optimizer.AdamOptimizer(learning_rate=3e-3).minimize(
            loss, startup_program=startup)

    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)
    steps = 10 if FAST else 120
    for step in range(steps):
        seq = synthetic_corpus(rng, vocab, n=32, T=T)
        lo, = exe.run(main_prog,
                      feed={"ids": seq[:, :-1], "tgt": seq[:, 1:]},
                      fetch_list=[loss], scope=scope)
        if step % 20 == 0 or step == steps - 1:
            print(f"step {step}: loss {float(lo):.4f}")

    # greedy generation through the KV-cache decode path: a sibling
    # program that rejoins the trained weights by name (startup never run)
    n_new = 8
    gen_prog, gen_startup = pt.Program(), pt.Program()
    with pt.program_guard(gen_prog, gen_startup):
        prompt = layers.data("prompt", shape=[T], dtype="int64")
        out_ids = models.transformer_lm_generate(
            prompt, vocab_size=vocab, d_model=d_model, n_layers=n_layers,
            num_heads=4, max_len=2 * T, max_new_tokens=n_new)
    ctx = synthetic_corpus(rng, vocab, n=1, T=T)[:, :-1]
    gen, = exe.run(gen_prog, feed={"prompt": ctx}, fetch_list=[out_ids],
                   scope=scope)
    gen = np.asarray(gen)[0]
    tail = gen[-(n_new + 1):]
    # the language allows next in {3t, 3t+1} mod vocab: judge each
    # generated step against the rule applied to ITS OWN predecessor
    # (an independent chain would diverge at the first +1 branch)
    ok = [int(tail[i + 1]) in {(3 * int(tail[i])) % vocab,
                               (3 * int(tail[i]) + 1) % vocab}
          for i in range(n_new)]
    print("generated continuation:", gen[-n_new:].tolist())
    print(f"rule-consistent steps: {sum(ok)}/{n_new}")

    # the other decoders over the same trained weights: beam search
    # (best-first with scores) and self-speculative decoding (exactly the
    # greedy output, fewer full-stack passes)
    alt_prog, alt_startup = pt.Program(), pt.Program()
    with pt.program_guard(alt_prog, alt_startup):
        prompt2 = layers.data("prompt2", shape=[T], dtype="int64")
        beams, scores = models.transformer_lm_beam_search(
            prompt2, vocab_size=vocab, d_model=d_model,
            n_layers=n_layers, num_heads=4, max_len=2 * T,
            max_new_tokens=n_new, beam_size=3)
        spec, rounds = models.transformer_lm_speculative_generate(
            prompt2, vocab_size=vocab, d_model=d_model,
            n_layers=n_layers, num_heads=4, max_len=2 * T,
            max_new_tokens=n_new, draft_layers=1, gamma=3)
    # the only params this program ADDS are the draft head's three
    # tensors; set them directly (here: copy the target head — a real
    # deployment would distill a cheaper one) and never run alt_startup,
    # which would re-initialize the trained weights
    scope.set("draft_head.w", np.asarray(scope.get("lm_head.w")))
    scope.set("draft_ln.scale", np.asarray(scope.get("final_ln.scale")))
    scope.set("draft_ln.bias", np.asarray(scope.get("final_ln.bias")))
    bm, sc_, sp, rd = exe.run(
        alt_prog, feed={"prompt2": ctx},
        fetch_list=[beams, scores, spec, rounds], scope=scope)
    bm, sc_, sp = np.asarray(bm), np.asarray(sc_), np.asarray(sp)
    print("beam best :", bm[0, 0, -n_new:].tolist(),
          f"(score {sc_[0, 0]:.2f})")
    print("beam 2nd  :", bm[0, 1, -n_new:].tolist(),
          f"(score {sc_[0, 1]:.2f})")
    print("speculative:", sp[0, -n_new:].tolist(),
          f"({int(np.asarray(rd)[0])} verify rounds vs {n_new} plain; "
          f"greedy-exact: {bool((sp[0, -n_new:] == gen[-n_new:]).all())})")


if __name__ == "__main__":
    main()

"""Linear-chain-CRF sequence tagging — the reference's
v1_api_demo/sequence_tagging (linear_crf) in fluid style: embedding +
bi-directional context + CRF loss, viterbi decode, chunk-F1 evaluation.

Run:  python demos/sequence_tagging_crf.py  (PADDLE_TPU_DEMO_FAST=1 to smoke)
"""
import os

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers

FAST = bool(os.environ.get("PADDLE_TPU_DEMO_FAST"))


def synthetic_tagging(rng, n, T, vocab, n_tags):
    """Tags follow the word class (word % n_tags) with BIO-ish structure."""
    words = rng.randint(0, vocab, size=(n, T)).astype(np.int64)
    tags = (words % n_tags).astype(np.int64)
    lens = rng.randint(max(2, T // 2), T + 1, size=n).astype(np.int32)
    return words, tags, lens


def main():
    vocab, n_tags, T = 200, 5, 12
    main_prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_prog, startup):
        words = layers.data("words", shape=[1], dtype="int64", lod_level=1)
        tags = layers.data("tags", shape=[1], dtype="int64", lod_level=1)
        emb = layers.embedding(words, size=[vocab, 32])
        emb.seq_len = words.seq_len
        feat = layers.fc(emb, size=n_tags, num_flatten_dims=2)
        feat.seq_len = words.seq_len
        crf = layers.linear_chain_crf(feat, tags)
        loss = layers.mean(crf)
        decoded = layers.crf_decoding(feat, transition=crf.transition)
        pt.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(
            loss, startup_program=startup)

    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)
    steps = 8 if FAST else 80
    for step in range(steps):
        w, t, lens = synthetic_tagging(rng, 32, T, vocab, n_tags)
        lo, = exe.run(main_prog,
                      feed={"words": w[..., None], "words@len": lens,
                            "tags": t[..., None], "tags@len": lens},
                      fetch_list=[loss], scope=scope)
        if step % 20 == 0 or step == steps - 1:
            print(f"step {step}: -log-likelihood {float(lo):.4f}")

    w, t, lens = synthetic_tagging(rng, 16, T, vocab, n_tags)
    dec, = exe.run(main_prog,
                   feed={"words": w[..., None], "words@len": lens,
                         "tags": t[..., None], "tags@len": lens},
                   fetch_list=[decoded], scope=scope)
    dec = np.asarray(dec).reshape(16, T)
    mask = np.arange(T)[None, :] < lens[:, None]
    acc = (dec == t)[mask].mean()
    print(f"viterbi tag accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()

"""A 3-replica serving fleet surviving chaos + a rolling weight update.

The robustness walkthrough on top of demos/serving_lm.py's single
server: three in-process replicas of a small classifier behind a
``Fleet`` (router + per-replica circuit breakers + hedging + load
shedding), then

1. a deterministic FaultPlan hard-crashes replica 1 and slow-injects
   replica 2 mid-storm — every client request still succeeds (retries
   re-route around the crash until the breaker opens; hedging outruns
   the slow replica), the breaker/hedge counters are the proof;
2. a zero-downtime rolling weight update: ``Fleet.update_weights``
   drains each replica (healthz 503), hot-swaps its params from a
   trainer checkpoint (same shapes -> zero recompiles), and rejoins it
   while traffic keeps flowing through the rest;
3. the fleet's HTTP control plane — the same endpoints
   ``tools/fleetctl.py`` drives.

Run:  python demos/serving_fleet.py  (PADDLE_TPU_DEMO_FAST=1 to smoke)
"""
import json
import os
import tempfile
import threading
import time
import urllib.request

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.resilience import FaultPlan
from paddle_tpu.serving import Fleet, InferenceEngine

FAST = bool(os.environ.get("PADDLE_TPU_DEMO_FAST"))
N_REPLICAS = 3
N_REQUESTS = 48 if FAST else 200
DIM, CLASSES = 16, 4


def build_model():
    main_prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_prog, startup):
        x = layers.data("x", shape=[DIM])
        h = layers.fc(x, size=32, act="relu")
        out = layers.fc(h, size=CLASSES, act="softmax")
    return main_prog, startup, out


def main():
    main_prog, startup, out = build_model()
    exe = pt.Executor(pt.CPUPlace())

    def fresh_scope(seed):
        scope = pt.Scope()
        startup.random_seed = seed
        exe.run(startup, scope=scope)
        return scope

    def replica_engine(seed):
        return InferenceEngine(
            program=main_prog, feed_names=["x"], fetch_names=[out.name],
            scope=fresh_scope(seed), batch_buckets=(2, 4, 8),
            place=pt.CPUPlace())

    # "v2" weights the trainer published as a checkpoint
    ckpt_dir = tempfile.mkdtemp(prefix="fleet_ckpt_")
    pt.checkpoint.save_checkpoint(ckpt_dir, scope=fresh_scope(99), step=100)

    engines = [replica_engine(seed=7) for _ in range(N_REPLICAS)]
    plan = (FaultPlan()
            .at(step=1, kind="replica_crash")
            .at(step=2, kind="slow_replica", delay_s=0.08))
    fleet = Fleet(engines, hedge=True, hedge_delay_ms=25,
                  breaker={"failure_threshold": 2, "recovery_s": 0.3})

    rng = np.random.RandomState(0)
    ok, failed = [], []

    def storm(n):
        for _ in range(n):
            try:
                fut = fleet.submit(
                    {"x": rng.rand(DIM).astype(np.float32)},
                    timeout_ms=15_000)
                np.asarray(fut.result(timeout=20)[0])
                ok.append(1)
            except Exception as exc:  # noqa: BLE001 - counted, reported
                failed.append(repr(exc))

    with plan.active(), fleet:
        # warm every replica before chaos bites
        storm(2 * N_REPLICAS)
        print(f"warm: {len(ok)} ok")
        threads = [threading.Thread(target=storm, args=(N_REQUESTS // 4,))
                   for _ in range(4)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        chaos_s = time.monotonic() - t0
        counters = fleet.metrics.snapshot()["counters"]
        print(f"chaos storm: {len(ok)} ok / {len(failed)} failed "
              f"in {chaos_s:.2f}s (crash@r1 + slow@r2 injected)")
        print("  breakers:", fleet.router.breaker_states())
        print("  counters:", {k: counters[k] for k in sorted(counters)
                              if k in ("attempts", "retries", "hedges",
                                       "hedge_wins", "breaker_opens",
                                       "sheds")})
        assert not failed, failed[:3]

        # rolling weight update while a light storm keeps running
        bg = threading.Thread(target=storm, args=(N_REQUESTS // 2,))
        bg.start()
        upd = fleet.update_weights(ckpt_dir)
        bg.join()
        print("rolling update:", [(r["replica"], r["swap"]["swapped"],
                                   f"{r['seconds']:.2f}s")
                                  for r in upd["replicas"]])
        assert not failed, failed[:3]

        # the control plane fleetctl drives
        port = fleet.serve_http()
        status = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fleet/status", timeout=10).read())
        print("fleetctl status:",
              [(r["name"], r["health"]["state"], r["breaker"])
               for r in status["replicas"]])
        prom = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics?format=prom",
            timeout=10).read().decode()
        print("prometheus:", [ln for ln in prom.splitlines()
                              if ln.startswith("paddle_tpu_fleet_"
                                               "breaker_state")])
    print(f"fleet demo OK: {len(ok)} requests, 0 failed, "
          "1 crashed + 1 slow replica absorbed, rolling update "
          "completed with zero downtime")


if __name__ == "__main__":
    main()

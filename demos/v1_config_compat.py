"""v1 .conf compatibility: run a REFERENCE config file unmodified.

The reference's first user surface is a Python config evaluated by
``paddle_trainer --config=...`` (/root/reference/paddle/trainer/
TrainerMain.cpp:32 -> python/paddle/trainer/config_parser.py:4345).
This demo writes the classic config shapes — a CNN text classifier and
a recurrent_group tagger, in the exact trainer_config_helpers dialect —
to disk, then drives them through the same three entry points the
reference offers:

  1. ``paddle_tpu.v1.parse_config``      (parse + inspect)
  2. ``paddle_tpu.v1.train_from_config`` (the paddle_trainer one-shot)
  3. ``python -m paddle_tpu.v1.trainer --job=time``  (the CLI)

When the reference tree is mounted, the suite goes further and runs its
own v1_api_demo configs AS-IS (tests/test_v1_config.py: the 16-config
sweep); this demo is the self-contained version of the same story.

Run:  python demos/v1_config_compat.py
"""
import os
import subprocess
import sys
import tempfile
import textwrap

import numpy as np

FAST = bool(os.environ.get("PADDLE_TPU_DEMO_FAST"))

PROVIDER = textwrap.dedent("""
    import numpy as np
    from paddle.trainer.PyDataProvider2 import *

    def init(settings, file_list, **kw):
        settings.input_types = {'word': integer_value_sequence(64),
                                'label': integer_value(2)}

    @provider(init_hook=init, cache=CacheType.CACHE_PASS_IN_MEM)
    def process(settings, filename):
        rng = np.random.RandomState(hash(filename) % 1000)
        for _ in range(24):
            lbl = int(rng.randint(2))
            T = int(rng.randint(4, 9))
            lo, hi = (2, 32) if lbl else (32, 62)
            yield {'word': [int(rng.randint(lo, hi)) for _ in range(T)],
                   'label': lbl}
""")

CNN_CONF = textwrap.dedent("""
    from paddle.trainer_config_helpers import *

    define_py_data_sources2(train_list='data/train.list', test_list=None,
                            module='provider_demo', obj='process')
    settings(batch_size=8, learning_rate=5e-3,
             learning_method=AdamOptimizer(),
             regularization=L2Regularization(1e-4))

    word = data_layer(name='word', size=64)
    label = data_layer(name='label', size=2)
    emb = embedding_layer(input=word, size=16)
    conv = sequence_conv_pool(input=emb, context_len=3, hidden_size=24)
    prob = fc_layer(input=conv, size=2, act=SoftmaxActivation())
    outputs(classification_cost(input=prob, label=label))
""")

RNN_CONF = textwrap.dedent("""
    from paddle.trainer_config_helpers import *

    define_py_data_sources2(train_list='data/train.list', test_list=None,
                            module='provider_demo', obj='process')
    settings(batch_size=8, learning_rate=5e-3,
             learning_method=AdamOptimizer())

    word = data_layer(name='word', size=64)
    label = data_layer(name='label', size=2)
    emb = embedding_layer(input=word, size=16)

    def step(y_t):
        mem = memory(name='h', size=16)
        return fc_layer(input=[y_t, mem], size=16,
                        act=TanhActivation(), name='h')

    rnn = recurrent_group(step=step, input=emb)
    prob = fc_layer(input=last_seq(input=rnn), size=2,
                    act=SoftmaxActivation())
    outputs(classification_cost(input=prob, label=label))
""")


def main():
    from paddle_tpu import v1

    workdir = tempfile.mkdtemp(prefix="v1_compat_")
    os.makedirs(os.path.join(workdir, "data"))
    with open(os.path.join(workdir, "provider_demo.py"), "w") as f:
        f.write(PROVIDER)
    with open(os.path.join(workdir, "data", "train.list"), "w") as f:
        f.write("data/part-0\n")
    open(os.path.join(workdir, "data", "part-0"), "w").close()
    for name, conf in (("cnn_conf.py", CNN_CONF), ("rnn_conf.py",
                                                   RNN_CONF)):
        with open(os.path.join(workdir, name), "w") as f:
            f.write(conf)
    os.chdir(workdir)

    passes = 1 if FAST else 4
    for name in ("cnn_conf.py", "rnn_conf.py"):
        parsed = v1.parse_config(name)
        print(f"{name}: {len(parsed.main_program.global_block.ops)} ops, "
              f"inputs {[v.name for v in parsed.input_vars]}")
        parsed, scope, costs = v1.train_from_config(name,
                                                    num_passes=passes)
        assert np.isfinite(costs).all()
        print(f"  trained {passes} pass(es): cost "
              f"{costs[0]:.4f} -> {costs[-1]:.4f}")

    # the paddle_trainer CLI, as a user would invoke it
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.v1.trainer",
         "--config", "cnn_conf.py", "--job", "time"],
        capture_output=True, text=True, env=os.environ)
    assert proc.returncode == 0, proc.stderr[-500:]
    print("--job=time:",
          [ln for ln in proc.stdout.splitlines() if "ms/batch" in ln][0])
    print("v1 config compatibility demo done")


if __name__ == "__main__":
    main()

"""Serve a transformer LM with continuous batching (paddle_tpu.serving).

The full deployment path: train a small stacked LM, freeze it with
save_inference_model, load it into a GenerationEngine (slot-table KV
cache), pre-warm every compile bucket, then push a wave of concurrent
generate requests through the Server's dynamic batcher — requests join
and leave decode slots mid-flight, and after warmup the whole workload
runs without a single fresh XLA compile (the executor's compile-cache
counters are printed as proof). A JSON HTTP endpoint serves the same
engine over stdlib http.server.

Run:  python demos/serving_lm.py  (PADDLE_TPU_DEMO_FAST=1 to smoke)
"""
import json
import os
import tempfile
import threading
import time
import urllib.request

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, models, trace
from paddle_tpu.serving import GenerationEngine, Server

FAST = bool(os.environ.get("PADDLE_TPU_DEMO_FAST"))

VOCAB, D_MODEL, N_LAYERS, HEADS = 97, 32 if FAST else 64, 2, 4
MAX_LEN = 64
N_REQUESTS = 64 if FAST else 96
SLOTS = 8


def train_and_save(model_dir):
    """Train next = (3*cur + noise) % VOCAB and save the GENERATION
    program (KV-cache decode op + shared weights) as the frozen serving
    artifact."""
    T = 16
    main_prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_prog, startup):
        ids = layers.data("ids", shape=[T], dtype="int64")
        tgt = layers.data("tgt", shape=[T], dtype="int64")
        logits = models.transformer_lm(ids, vocab_size=VOCAB,
                                       d_model=D_MODEL, n_layers=N_LAYERS,
                                       num_heads=HEADS, max_len=MAX_LEN,
                                       pipeline_stack=True)
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.reshape(logits, shape=[-1, VOCAB]),
            layers.reshape(tgt, shape=[-1, 1])))
        pt.optimizer.AdamOptimizer(learning_rate=3e-3).minimize(
            loss, startup_program=startup)
    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    steps = 8 if FAST else 80
    for step in range(steps):
        seq = np.zeros((32, T + 1), np.int64)
        seq[:, 0] = rng.randint(0, VOCAB, size=32)
        for t in range(T):
            seq[:, t + 1] = (3 * seq[:, t]
                             + rng.randint(0, 2, size=32)) % VOCAB
        lo, = exe.run(main_prog,
                      feed={"ids": seq[:, :-1], "tgt": seq[:, 1:]},
                      fetch_list=[loss], scope=scope)
        if step % 20 == 0 or step == steps - 1:
            print(f"train step {step}: loss {float(lo):.4f}")

    gen_prog, gen_startup = pt.Program(), pt.Program()
    with pt.program_guard(gen_prog, gen_startup):
        prompt = layers.data("prompt", shape=[8], dtype="int64")
        out_ids = models.transformer_lm_generate(
            prompt, vocab_size=VOCAB, d_model=D_MODEL, n_layers=N_LAYERS,
            num_heads=HEADS, max_len=MAX_LEN, max_new_tokens=8)
    pt.io.save_inference_model(model_dir, ["prompt"], [out_ids], exe,
                               main_program=gen_prog, scope=scope)
    print(f"saved inference model -> {model_dir}")


def main():
    model_dir = os.path.join(tempfile.mkdtemp(prefix="pdtpu_serving_"),
                             "lm")
    train_and_save(model_dir)

    engine = GenerationEngine.from_saved(
        model_dir, slots=SLOTS, prompt_buckets=(8, 16),
        prefill_batch_buckets=(1, 2, 4, 8),
        default_max_new_tokens=8)
    t0 = time.perf_counter()
    n_shapes = engine.warmup()
    print(f"warmup: {n_shapes} bucket shapes compiled in "
          f"{time.perf_counter() - t0:.1f}s -> {engine.cache_stats()}")
    misses_after_warmup = engine.cache_stats()["misses"]

    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, VOCAB, size=rng.randint(3, 13))
               for _ in range(N_REQUESTS)]

    # span tracing across the wave: every request records admission ->
    # queue wait -> prefill -> completion; exported below as a Chrome
    # trace (chrome://tracing / Perfetto)
    trace.enable(level=1)

    with Server(engine, max_wait_ms=2, max_queue=2 * N_REQUESTS) as srv:
        # ---- concurrent wave through the continuous batcher ----------
        t0 = time.perf_counter()
        futs, lock = [], threading.Lock()

        def client(chunk):
            for p in chunk:
                f = srv.submit({"prompt": p},
                               max_new_tokens=int(4 + p[0] % 5))
                with lock:
                    futs.append((p, f))

        threads = [threading.Thread(target=client,
                                    args=(prompts[i::4],))
                   for i in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        results = [(p, f.result(timeout=300)) for p, f in futs]
        wall = time.perf_counter() - t0
        for p, ids in results:
            assert ids.shape[0] > p.shape[0]
            np.testing.assert_array_equal(ids[:p.shape[0]], p)
        print(f"served {len(results)} concurrent generate requests in "
              f"{wall:.2f}s through {SLOTS} decode slots")
        stats = engine.cache_stats()
        print(f"paged KV cache: {stats.get('kv_pages_in_use', 0)} of "
              f"{stats.get('kv_pages_n_pages', 0)} pages in use, "
              f"{engine.metrics.counter('prefix_hit_tokens')} prompt "
              "tokens served from the prefix cache")

        stats = engine.cache_stats()
        fresh = stats["misses"] - misses_after_warmup
        print(f"compile cache: {stats} -> {fresh} recompiles after "
              "warmup" + (" (WARM STEADY STATE)" if fresh == 0 else ""))
        assert fresh == 0, "serving path recompiled after warmup!"

        # a learned-rule spot check: the model was trained on
        # next = 3*cur (+noise), so generated tokens should mostly track
        p, ids = results[0]
        gen = ids[p.shape[0]:]
        print(f"sample: prompt={p.tolist()} -> generated={gen.tolist()}")

        # ---- the same engine over HTTP -------------------------------
        port = srv.serve_http(port=0)
        body = json.dumps({"prompt": prompts[0].tolist(),
                           "max_new_tokens": 5}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            print("HTTP /v1/generate ->", json.loads(resp.read()))
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
            snap = json.loads(resp.read())
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics?format=prom",
                timeout=30) as resp:
            prom = resp.read().decode()
        print("Prometheus exposition (first lines):")
        for line in prom.splitlines()[:6]:
            print("  " + line)

    trace_path = os.path.join(tempfile.gettempdir(),
                              "paddle_tpu_serving_trace.json")
    n_events = trace.export_chrome_trace(trace_path)
    trace.disable()
    print(f"chrome trace: {n_events} spans -> {trace_path} "
          "(load in chrome://tracing or Perfetto)")

    lat = snap["latency"].get("request_ms", {})
    print("metrics snapshot:")
    print(f"  qps(10s window)   {snap['qps']:.1f}")
    print(f"  completed         {snap['counters'].get('completed')}")
    print(f"  decode steps      {snap['counters'].get('decode_steps')}")
    print(f"  prefills          {snap['counters'].get('prefills')}")
    print(f"  latency ms        p50={lat.get('p50', 0):.1f} "
          f"p95={lat.get('p95', 0):.1f} p99={lat.get('p99', 0):.1f}")
    print(f"  batch occupancy   "
          f"{snap['gauges'].get('batch_occupancy', 0):.2f}")
    print(f"  compile cache     {snap.get('compile_cache/engine0')}")
    print("serving demo OK")


if __name__ == "__main__":
    main()

"""Neural machine translation: GRU encoder-decoder with attention + beam
search — the reference's seq2seq demo shape
(/root/reference/python/paddle/v2/fluid/tests/book/
test_machine_translation.py; demo/seqToseq in the v1 tree) on the
synthetic WMT14 reader.

Training is teacher-forced: the decoder consumes <s> + target and predicts
target + </s>, with Luong-style dot-product attention over the encoder
states; the loss is per-sequence length-normalised so ragged batches are
weighted evenly (the LoD contract). Generation runs the fused beam-search
decoder op over the trained weights, shared with the training program by
parameter NAME through one scope.

Run:  python demos/nmt_seq2seq.py   (PADDLE_TPU_DEMO_FAST=1 for a smoke run)
"""
import os

import numpy as np

import paddle_tpu as pt
from paddle_tpu import dataset, layers
from paddle_tpu.reader import batch as batch_reader
from paddle_tpu.reader import decorator

FAST = bool(os.environ.get("PADDLE_TPU_DEMO_FAST"))

DICT = 256
EMB = 32
HID = 64
BOS, EOS = 0, 1


def build_train():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        src = layers.data("src", shape=[1], dtype="int64", lod_level=1)
        trg_in = layers.data("trg_in", shape=[1], dtype="int64",
                             lod_level=1)
        trg_next = layers.data("trg_next", shape=[1], dtype="int64",
                               lod_level=1)
        s_emb = layers.embedding(src, size=[DICT, EMB],
                                 param_attr=pt.ParamAttr(name="src_emb"))
        s_emb.seq_len = src.seq_len
        s_proj = layers.fc(s_emb, size=3 * HID, num_flatten_dims=2,
                           param_attr=pt.ParamAttr(name="src_proj_w"),
                           bias_attr=False)
        enc = layers.dynamic_gru(s_proj, size=HID,
                                 param_attr=pt.ParamAttr(name="enc_wh"),
                                 bias_attr=False)
        enc_last = layers.sequence_last_step(enc)

        t_emb = layers.embedding(trg_in, size=[DICT, EMB],
                                 param_attr=pt.ParamAttr(name="trg_emb"))
        t_emb.seq_len = trg_in.seq_len
        t_proj = layers.fc(t_emb, size=3 * HID, num_flatten_dims=2,
                           param_attr=pt.ParamAttr(name="dec_wx"),
                           bias_attr=pt.ParamAttr(name="dec_bx"))
        dec = layers.dynamic_gru(t_proj, size=HID, h0=enc_last,
                                 param_attr=pt.ParamAttr(name="dec_wh"),
                                 bias_attr=False)
        # attention over encoder states (padded rows are zero -> no
        # contribution), concatenated with the decoder state for the head
        scores = layers.matmul(dec, enc, transpose_y=True)
        ctx = layers.matmul(layers.softmax(scores), enc)
        both = layers.concat([dec, ctx], axis=2)
        both.seq_len = trg_in.seq_len
        logits = layers.fc(both, size=DICT, num_flatten_dims=2,
                           param_attr=pt.ParamAttr(name="dec_wout"),
                           bias_attr=False)
        tok_loss = layers.softmax_with_cross_entropy(logits, trg_next)
        tok_loss.seq_len = trg_next.seq_len
        loss = layers.mean(layers.sequence_pool(tok_loss, "average"))
        pt.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(
            loss, startup_program=startup)
    return main, startup, loss


def build_infer():
    """Beam decode over the TRAINED weights (declared by name; values come
    from the shared scope)."""
    infer, istart = pt.Program(), pt.Program()
    with pt.program_guard(infer, istart):
        src = layers.data("src", shape=[1], dtype="int64", lod_level=1)
        s_emb = layers.embedding(src, size=[DICT, EMB],
                                 param_attr=pt.ParamAttr(name="src_emb"))
        s_emb.seq_len = src.seq_len
        s_proj = layers.fc(s_emb, size=3 * HID, num_flatten_dims=2,
                           param_attr=pt.ParamAttr(name="src_proj_w"),
                           bias_attr=False)
        enc = layers.dynamic_gru(s_proj, size=HID,
                                 param_attr=pt.ParamAttr(name="enc_wh"),
                                 bias_attr=False)
        enc_last = layers.sequence_last_step(enc)
        gb = infer.global_block

        def declare(name, shape):
            return gb.create_var(name=name, shape=shape, dtype="float32",
                                 persistable=True)

        trg_emb = declare("trg_emb", [DICT, EMB])
        dec_wx = declare("dec_wx", [EMB, 3 * HID])
        dec_bx = declare("dec_bx", [3 * HID])
        dec_wh = declare("dec_wh", [HID, 3 * HID])
        dec_wout = declare("dec_wout", [2 * HID, DICT])
        # the trained head covers [dec_state, attention_ctx]; the fused
        # decoder is attention-free, so decode on the dec-state half
        w_half, _ = layers.split(dec_wout, [HID, HID], dim=0)
        ids, scores, lens = layers.beam_search_decoder(
            enc_last, trg_emb, (dec_wx, dec_wh, dec_bx), (w_half, None),
            beam_size=4, max_len=12, bos_id=BOS, eos_id=EOS, cell="gru")
    return infer, istart, ids, scores, lens


def main():
    bs = 32
    epochs = 2 if FAST else 10
    n_batches = 6 if FAST else 24

    main_prog, startup, loss = build_train()
    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    startup.random_seed = 5
    exe.run(startup, scope=scope)

    from paddle_tpu.data_feeder import DataFeeder

    feed_vars = [main_prog.global_block.var(n)
                 for n in ("src", "trg_in", "trg_next")]
    feeder = DataFeeder(feed_vars)

    # wmt14 rows are already (src, <s>+trg, trg+</s>)
    rows = decorator.firstn(dataset.wmt14.train(DICT), bs * n_batches)

    hist = []
    for epoch in range(epochs):
        for b_id, rws in enumerate(batch_reader(rows, bs)()):
            lo, = exe.run(main_prog, feed=feeder.feed(rws),
                          fetch_list=[loss], scope=scope)
            hist.append(float(lo))
        print(f"epoch {epoch} loss {hist[-1]:.3f}")
    assert np.isfinite(hist).all()
    if not FAST:
        assert hist[-1] < 0.8 * hist[0], (hist[0], hist[-1])

    # generation
    infer, istart, ids, scores, lens = build_infer()
    sample = next(iter(batch_reader(rows, 4)()))
    feed = feeder.feed(sample)
    out_ids, out_scores, out_lens = exe.run(
        infer, feed={"src": feed["src"], "src@len": feed["src@len"]},
        fetch_list=[ids, scores, lens], scope=scope)
    for i in range(len(sample)):
        best = np.asarray(out_ids)[i, 0, : int(np.asarray(out_lens)[i, 0])]
        print(f"src={sample[i][0][:8]}... -> beam0={best.tolist()} "
              f"score={float(np.asarray(out_scores)[i, 0]):.2f}")
    assert np.asarray(out_ids).shape[1] == 4  # beam width
    print("OK")


if __name__ == "__main__":
    main()

"""Traffic prediction: multi-task forecasting with shared weights.

The reference demo (/root/reference/v1_api_demo/traffic_prediction/
trainer_config.py) predicts road congestion at 24 future horizons from the
last 24 five-minute readings. Every horizon is its own 4-class
classification head, but all 24 share one link-embedding weight by naming
it (`ParamAttr(name='_link_vec.w')`) — multi-task training over a shared
representation. The 24 per-horizon costs train jointly as a sum.

Synthetic data (no egress): congestion follows a daily sinusoid + noise,
quantized into the reference's 4 levels, so the shared embedding genuinely
helps every horizon.

Run:  python demos/traffic_prediction.py
      (add PADDLE_TPU_DEMO_FAST=1 for a smoke run)
"""
import os

import numpy as np

import paddle_tpu.v2 as paddle

TERM_NUM = 24          # input horizon: last 24 readings
FORECASTING_NUM = 24   # predict 24 future 5-minute slots
LEVELS = 4             # congestion levels
EMB_SIZE = 16
FAST = bool(os.environ.get("PADDLE_TPU_DEMO_FAST"))


def make_series(n_days=30, seed=0):
    """Daily-sinusoid congestion in [0, 1], one reading per 5 minutes."""
    rng = np.random.RandomState(seed)
    t = np.arange(n_days * 288)
    base = 0.5 + 0.35 * np.sin(2 * np.pi * t / 288.0 - 1.2)
    rush = 0.15 * np.exp(-0.5 * ((t % 288 - 102) / 12.0) ** 2)
    return np.clip(base + rush + 0.05 * rng.randn(t.size), 0, 1)


def quantize(x):
    return np.minimum((x * LEVELS).astype(np.int64), LEVELS - 1)


def windows(series, n, seed=1):
    """(past TERM_NUM readings, next FORECASTING_NUM quantized levels)."""
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            i = rng.randint(0, series.size - TERM_NUM - FORECASTING_NUM)
            past = series[i:i + TERM_NUM].astype(np.float32)
            future = quantize(series[i + TERM_NUM:
                                     i + TERM_NUM + FORECASTING_NUM])
            yield (past, *[np.array([lvl]) for lvl in future])
    return reader


def build():
    link_encode = paddle.layer.data(
        "link_encode", paddle.data_type.dense_vector(TERM_NUM))
    shared = paddle.attr.Param(name="_link_vec.w")
    total_cost, scores = None, []
    for i in range(FORECASTING_NUM):
        # tanh trunk: the v1 fc_layer's default activation
        link_vec = paddle.layer.fc(input=link_encode, size=EMB_SIZE,
                                   act=paddle.activation.Tanh(),
                                   param_attr=shared)
        score = paddle.layer.fc(input=link_vec, size=LEVELS,
                                act=paddle.activation.Softmax())
        label = paddle.layer.data(f"label_{(i + 1) * 5}min",
                                  paddle.data_type.integer_value(LEVELS))
        cls = paddle.layer.classification_cost(input=score, label=label)
        total_cost = cls if total_cost is None else total_cost + cls
        scores.append(score)
    return total_cost, scores


def main():
    paddle.init(trainer_count=1, seed=11)
    series = make_series()
    cost, scores = build()
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2))

    def event_handler(event):
        if isinstance(event, paddle.event.EndIteration) \
                and event.batch_id % 8 == 0:
            print(f"pass {event.pass_id} batch {event.batch_id} "
                  f"summed cost {event.cost:.3f}")

    n_train = 256 if FAST else 8192
    trainer.train(paddle.batch(windows(series, n_train), 128),
                  num_passes=1 if FAST else 8,
                  event_handler=event_handler)

    # Predict all 24 horizons for one window, reference predict.sh-style.
    i = series.size - TERM_NUM - FORECASTING_NUM - 1
    past = series[i:i + TERM_NUM].astype(np.float32)
    truth = quantize(series[i + TERM_NUM:i + TERM_NUM + FORECASTING_NUM])
    probs = paddle.infer(output_layer=scores, parameters=parameters,
                         input=[(past,)])
    pred = [int(np.argmax(p, axis=1)[0]) for p in probs]
    agree = float(np.mean(np.array(pred) == truth))
    print("predicted levels:", pred)
    print("true levels:     ", truth.tolist())
    print(f"horizon agreement: {agree:.2f}")


if __name__ == "__main__":
    main()

"""Closing the loop: serve -> log -> join outcomes -> train -> publish.

The previous demo (``online_ctr.py``) trains on a SYNTHETIC
click-stream. This one trains on the fleet's OWN traffic — the loop
production CTR systems actually run:

1. a 2-replica fleet serves CTR requests with a ``feedback.FeedbackHook``
   attached: every completed request writes one impression (features +
   served score + weights version) to a crash-safe segmented log, and
   every reply carries a ``request_id``;
2. "users" click on some impressions — outcomes post back keyed by that
   request id (``POST /v1/outcome`` on the HTTP plane; the direct
   ``OutcomeJoiner.post_outcome`` here). The joiner emits EXACTLY ONE
   labeled example per impression: joined positives inside the window,
   negatives on expiry (click/no-click);
3. the ``feedback.Compactor`` feeds sealed joined segments to the
   master's task queue — the ``StreamingTrainer`` trains on precisely
   the traffic the fleet served, nothing else;
4. the ``online.Publisher`` rolls each new checkpoint generation back
   into the SAME fleet — the next impression records the new weights
   version, and the served AUC on a held-out batch climbs.

``tools/loopctl.py --log-dir ... --joined-dir ...`` prints the same
per-stage lag summary this demo reports.

Run:  python demos/feedback_loop.py   (PADDLE_TPU_DEMO_FAST=1 to smoke)
"""
import os
import tempfile
import time

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, io
from paddle_tpu.dataset import ctr
from paddle_tpu.feedback import (Compactor, FeedbackHook, ImpressionLog,
                                 OutcomeJoiner, loop_status, task_reader)
from paddle_tpu.master import MasterClient, MasterServer
from paddle_tpu.online import Publisher, StreamingTrainer
from paddle_tpu.resilience import CheckpointConfig
from paddle_tpu.serving import Fleet, InferenceEngine
from paddle_tpu.trace.slo import SLO

FAST = bool(os.environ.get("PADDLE_TPU_DEMO_FAST"))
VOCAB = 1000 if FAST else 20_000
ROUNDS = 2 if FAST else 4
REQUESTS = 128 if FAST else 512
EVAL_N = 128 if FAST else 512
BATCH = 16 if FAST else 64


def build():
    main, startup = pt.Program(), pt.Program()
    startup.random_seed = 11
    with pt.program_guard(main, startup):
        ids = layers.data("ids", shape=[ctr.SLOTS], dtype="int64")
        dense = layers.data("dense", shape=[ctr.DENSE_DIM])
        label = layers.data("label", shape=[1])
        logit = pt.models.wide_deep(ids, dense, vocab_size=VOCAB,
                                    embed_dim=8, hidden_sizes=(32, 16))
        loss, prob = pt.models.wide_deep_loss(logit, label)
        sgd = pt.trainer.SGD(
            loss, pt.optimizer.AdagradOptimizer(learning_rate=0.05),
            [ids, dense, label], scope=pt.Scope())
    serve = io.prune_program(main, ["ids", "dense"], [prob.name])
    return sgd, startup, serve, prob.name


def auc(probs, labels):
    order = np.argsort(probs)
    ranks = np.empty(len(probs))
    ranks[order] = np.arange(1, len(probs) + 1)
    pos = labels.ravel() > 0.5
    n_pos, n_neg = pos.sum(), (~pos).sum()
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def main():
    sgd, startup, serve_prog, prob_name = build()

    def engine(seed):
        scope = pt.Scope()
        startup.random_seed = seed
        pt.Executor(pt.TPUPlace()).run(startup, scope=scope)
        return InferenceEngine(program=serve_prog,
                               feed_names=["ids", "dense"],
                               fetch_names=[prob_name], scope=scope,
                               batch_buckets=(64, EVAL_N),
                               place=pt.CPUPlace())

    srv = MasterServer(timeout_s=30, port=0)
    addr = srv.start()
    workdir = tempfile.mkdtemp(prefix="feedback-loop")
    log_dir = os.path.join(workdir, "impressions")
    joined_dir = os.path.join(workdir, "joined")
    ckdir = os.path.join(workdir, "ck")

    fleet = Fleet([engine(3), engine(4)], hedge=False,
                  slo=SLO(freshness_s=120.0, availability=0.99))
    publisher = Publisher(fleet, ckdir)
    log = ImpressionLog(log_dir, segment_records=64, flush_s=0.005)
    joiner = OutcomeJoiner(log_dir, joined_dir, window_s=0.05,
                           park_ttl_s=30.0, segment_records=64)
    fleet.attach_feedback(FeedbackHook(log, joiner=joiner))
    compactor = Compactor(joined_dir)
    fleet.start()

    rng = ctr.common.synthetic_rng("feedback-heldout")
    eval_ids, eval_dense, eval_label = ctr._impressions(rng, EVAL_N,
                                                        VOCAB)

    def served_auc():
        # scoring traffic is not user traffic: detach the hook so the
        # held-out batch never leaks into the training log
        hook, fleet.feedback = fleet.feedback, None
        try:
            futs = [fleet.submit({"ids": eval_ids[i],
                                  "dense": eval_dense[i]})
                    for i in range(EVAL_N)]
            probs = np.array(
                [np.asarray(f.result(timeout=60)[0]).ravel()[0]
                 for f in futs])
        finally:
            fleet.feedback = hook
        return auc(probs, eval_label)

    traffic = ctr.common.synthetic_rng("feedback-traffic")
    print(f"feedback loop: vocab={VOCAB}, {ROUNDS} rounds x {REQUESTS} "
          f"served requests -> the trainer sees ONLY logged traffic")
    baseline = served_auc()
    print(f"  AUC served (random init): {baseline:.4f}")
    client = MasterClient(addr)
    history = []
    for rnd in range(ROUNDS):
        # -- serve: real traffic, real replies, every one logged ------
        ids, dense, label = ctr._impressions(traffic, REQUESTS, VOCAB)
        futs = [fleet.submit({"ids": ids[i], "dense": dense[i]})
                for i in range(REQUESTS)]
        rids = []
        for i, f in enumerate(futs):
            f.result(timeout=60)
            rids.append((f.request_id, float(label[i, 0])))
        log.seal()
        # -- outcomes post back; no-clicks expire as negatives --------
        clicks = 0
        for rid, lab in rids:
            if lab > 0.5:
                joiner.post_outcome(rid, 1.0)
                clicks += 1
        joiner.poll_once()
        time.sleep(0.1)                      # the join window lapses
        joiner.poll_once()
        joiner.seal()
        # -- feed the queue, train, publish ---------------------------
        # the trainer's max_passes=1 recycles the consumed pass back to
        # todo when its stream ends, so from round 2 on the fresh
        # segments REPLACE an already-trained (recycled) pass — that is
        # what the drained gate exists to make an explicit decision
        descs = compactor.enqueue(client, require_drained=(rnd == 0))
        trainer = StreamingTrainer(
            sgd, addr, task_reader, task_descs=None, batch_size=BATCH,
            checkpoint=CheckpointConfig(ckdir, every_n_steps=8,
                                        background=False),
            max_passes=1)
        stats = trainer.run()
        step = publisher.poll_once()
        a = served_auc()
        history.append(a)
        print(f"  round {rnd + 1}: served {REQUESTS} "
              f"({clicks} clicks), fed {len(descs)} segments, "
              f"trained {stats['steps']} steps, published step {step}, "
              f"served AUC {a:.4f}")

    js = joiner.stats()
    print(f"  joiner: {js['joined']} joined / "
          f"{js['expired_negatives']} expired negatives / "
          f"{js['duplicate_outcomes']} duplicates")
    status = loop_status(log_dir, joined_dir, ckpt_dir=ckdir)
    print(f"  loopctl view: log_lag={status['log_lag_s']}s "
          f"join_lag={status['join_lag_s']}s "
          f"backlog={status['backlog_segments']} "
          f"fed_examples={status['examples_enqueued']} "
          f"trained_step={status['trained_step']}")
    total = js["joined"] + js["expired_negatives"]
    assert total == ROUNDS * REQUESTS, (total, ROUNDS * REQUESTS)
    assert status["examples_enqueued"] == ROUNDS * REQUESTS
    assert history[-1] > baseline, (
        "served AUC must improve once the fleet trains on its own "
        "logged traffic")
    print("the loop closed: "
          + f"{baseline:.4f} (init) -> "
          + " -> ".join(f"{a:.4f}" for a in history))
    client.close()
    log.close()
    fleet.stop()
    srv.stop()


if __name__ == "__main__":
    main()

"""Data-parallel + tensor-parallel training over a device mesh — the
in-graph replacement for the reference's pserver/NCCL cluster recipes
(/root/reference/doc/design/cluster_train/README.md). One process, one
program: the ShardingPlan annotates params and batches, GSPMD inserts the
collectives.

Run on any host:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python demos/distributed_data_parallel.py
On a real pod slice it uses the chips as-is; across hosts call
pt.parallel.initialize_multihost() first (see parallel/multihost.py).
"""
import os

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.parallel import make_mesh, megatron_plan

FAST = bool(os.environ.get("PADDLE_TPU_DEMO_FAST"))


def main():
    import jax

    n = len(jax.devices())
    mp = 2 if n % 2 == 0 else 1
    mesh = make_mesh({"dp": n // mp, "mp": mp})
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}")

    main_prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_prog, startup):
        x = layers.data("x", shape=[64])
        y = layers.data("y", shape=[1], dtype="int64")
        h = layers.fc(x, size=256, act="relu")
        h = layers.fc(h, size=256, act="relu")
        logits = layers.fc(h, size=10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        pt.optimizer.MomentumOptimizer(learning_rate=0.05,
                                       momentum=0.9).minimize(
            loss, startup_program=startup)

    # One sharding plane: the ShardProgram pass annotates every var with
    # its plan-resolved PartitionSpec; the executor (plan carries the
    # mesh) lowers the whole block with in/out_shardings + donation and
    # the analysis plane prices the result PER DEVICE.
    from paddle_tpu import analysis
    from paddle_tpu.transpiler import shard_program

    plan = megatron_plan(mesh)
    shard_program(main_prog, plan, ["x", "y"], [loss.name])
    mem = analysis.analyze_memory(main_prog, ["x", "y"], [loss.name],
                                  batch_size=8 * n)
    print(f"per-device static peak: {mem.peak_bytes / 1e6:.2f} MB; "
          f"collectives {mem.collective_bytes / 1e6:.2f} MB/step")

    scope = pt.Scope()
    exe = pt.Executor(plan=plan)
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)
    W = rng.randn(64, 10)
    steps = 5 if FAST else 60
    batch = 8 * n
    for step in range(steps):
        xb = rng.randn(batch, 64).astype(np.float32)
        yb = np.argmax(xb @ W, axis=1)[:, None].astype(np.int64)
        lo, = exe.run(main_prog, feed={"x": xb, "y": yb},
                      fetch_list=[loss], scope=scope)
        if step % 10 == 0 or step == steps - 1:
            print(f"step {step}: loss {float(lo):.4f}")


if __name__ == "__main__":
    main()

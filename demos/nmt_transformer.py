"""Transformer NMT through the decode platform: train a tiny
encoder-decoder with teacher forcing, then serve it with
Seq2SeqGenerationEngine — the encoder runs once at admission (cross-KV
parked next to the page pool), greedy decode streams through the paged
continuous batcher, and beam search runs as refcounted paged forks
sharing the source's cross-KV row.

The task is synthetic "translation": the target is the source sequence
reversed and shifted into the target vocab, terminated by EOS — enough
structure for the model to learn in seconds and for beam search to
reliably out-score greedy on log-likelihood.

Run:  python demos/nmt_transformer.py   (PADDLE_TPU_DEMO_FAST=1 smoke)
"""
import os

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, models
from paddle_tpu.decoding import Seq2SeqGenerationEngine, Seq2SeqSpec

FAST = bool(os.environ.get("PADDLE_TPU_DEMO_FAST"))

SRC_V, TGT_V = 24, 24
D, L, H = 32, 2, 2
TS, TT = 12, 16
BOS, EOS = 0, 1
SHIFT = 2  # source id s "translates" to target id (s + SHIFT) % TGT_V


def make_batch(rng, bs, ts):
    n = rng.randint(3, ts + 1, size=bs)
    src = np.zeros((bs, TS), np.int64)
    slen = np.zeros(bs, np.int32)
    tgt_in = np.full((bs, TT), EOS, np.int64)
    tgt_next = np.full((bs, TT), EOS, np.int64)
    for i in range(bs):
        s = rng.randint(2, SRC_V, size=n[i])  # ids 0/1 are reserved
        t = ((s[::-1] + SHIFT) % (TGT_V - 2)) + 2
        src[i, :n[i]] = s
        slen[i] = n[i]
        tgt_in[i, 0] = BOS
        tgt_in[i, 1:n[i] + 1] = t
        tgt_next[i, :n[i]] = t
        tgt_next[i, n[i]] = EOS
    return src, slen, tgt_in, tgt_next


def build_train():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        src = layers.data("src", shape=[TS], dtype="int64")
        slen = layers.data("slen", shape=[], dtype="int32")
        tgt_in = layers.data("tgt_in", shape=[TT], dtype="int64")
        tgt_next = layers.data("tgt_next", shape=[TT], dtype="int64")
        logits = models.transformer_nmt_teacher(
            src, slen, tgt_in, src_vocab_size=SRC_V, tgt_vocab_size=TGT_V,
            d_model=D, n_layers=L, num_heads=H,
            max_src_len=TS, max_tgt_len=TT)
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.reshape(logits, shape=[-1, TGT_V]),
            layers.reshape(tgt_next, shape=[-1, 1])))
        pt.optimizer.AdamOptimizer(learning_rate=4e-3).minimize(
            loss, startup_program=startup)
    return main, startup, loss


def main():
    bs = 32
    steps = 12 if FAST else 700

    main_prog, startup, loss = build_train()
    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    startup.random_seed = 9
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)
    hist = []
    for step in range(steps):
        src, slen, tgt_in, tgt_next = make_batch(rng, bs, TS)
        lo, = exe.run(main_prog,
                      feed={"src": src, "slen": slen, "tgt_in": tgt_in,
                            "tgt_next": tgt_next},
                      fetch_list=[loss], scope=scope)
        hist.append(float(lo))
        if step % 50 == 0 or step == steps - 1:
            print(f"step {step} loss {hist[-1]:.3f}")
    assert np.isfinite(hist).all()
    if not FAST:
        assert hist[-1] < 0.5 * hist[0], (hist[0], hist[-1])

    # -- serve the trained scope through the decode platform ------------
    spec = Seq2SeqSpec(src_vocab_size=SRC_V, tgt_vocab_size=TGT_V,
                       d_model=D, n_layers=L, num_heads=H,
                       max_src_len=TS, max_tgt_len=TT)
    eng = Seq2SeqGenerationEngine(spec, scope, slots=4, page_size=4,
                                  bos_id=BOS, beam_width=4,
                                  default_max_new_tokens=TT - 1)
    srcs = [rng.randint(2, SRC_V, size=rng.randint(3, 9)).astype("int64")
            for _ in range(4)]
    greedy = eng.translate(srcs, eos_id=EOS)
    for s, g in zip(srcs, greedy):
        print(f"src={s.tolist()} -> greedy={g[1:].tolist()}")
    ids, scores = eng.translate_beam(srcs[0], beam_size=4, eos_id=EOS,
                                     length_penalty=0.6)
    print(f"beam0={ids[0, 1:].tolist()} score={scores[0]:.3f} "
          f"(beam forks: {eng.metrics.counter('beam_forks')}, "
          f"encodes: {eng.metrics.counter('encodes')})")
    assert ids.shape[0] == 4
    if not FAST:
        # a trained model round-trips the synthetic translation
        want = ((srcs[0][::-1] + SHIFT) % (TGT_V - 2)) + 2
        got = greedy[0][1:1 + want.size]
        acc = float(np.mean(got == want))
        print(f"greedy round-trip accuracy: {acc:.2f}")
        assert acc > 0.6, (got, want)
    print("OK")


if __name__ == "__main__":
    main()

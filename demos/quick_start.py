"""Quick start: sentiment classification three ways, with the v2 API.

The reference's flagship first-contact demo
(/root/reference/v1_api_demo/quick_start/): the same text-classification
pipeline configured as logistic regression over a sparse bag of words
(trainer_config.lr.py), a sequence-conv-pool CNN (trainer_config.cnn.py),
or a max-pooled LSTM (trainer_config.lstm.py), trained through
api_train.py's trainer loop and served through api_predict.py's infer.

The LR config exercises the sparse feed contract: each example is a
``sparse_binary_vector`` row (a list of active word ids) that travels to
the device as an O(nnz) id-list into an embedding-sum, never a dense
multi-hot row.

Run:  python demos/quick_start.py [lr|cnn|lstm]
      (add PADDLE_TPU_DEMO_FAST=1 for a smoke run)
"""
import os
import sys

import numpy as np

import paddle_tpu.v2 as paddle
from paddle_tpu import dataset
from paddle_tpu.reader import decorator

FAST = bool(os.environ.get("PADDLE_TPU_DEMO_FAST"))


def bow_reader(reader, dim):
    """ids-sequence -> (sorted unique ids, label): the bag-of-words view
    the reference's dataprovider_bow.py produces."""
    def wrapped():
        for ids, label in reader():
            yield sorted(set(i for i in ids if i < dim)), label
    return wrapped


def build(config, word_dim):
    """The three trainer_config.*.py topologies over one data plane."""
    if config == "lr":
        words = paddle.layer.data(
            "words", paddle.data_type.sparse_binary_vector(word_dim))
        output = paddle.layer.fc(input=words, size=2,
                                 act=paddle.activation.Softmax())
    else:
        words = paddle.layer.data(
            "words", paddle.data_type.integer_value_sequence(word_dim))
        emb = paddle.layer.embedding(input=words, size=128)
        if config == "cnn":
            hidden = paddle.networks.sequence_conv_pool(
                input=emb, context_len=3, hidden_size=128)
        else:  # lstm
            lstm = paddle.networks.simple_lstm(input=emb, size=128)
            hidden = paddle.layer.pooling(
                input=lstm, pooling_type=paddle.pooling.Max())
        output = paddle.layer.fc(input=hidden, size=2,
                                 act=paddle.activation.Softmax())
    label = paddle.layer.data("label", paddle.data_type.integer_value(2))
    cost = paddle.layer.classification_cost(input=output, label=label)
    return cost, output


def main():
    config = sys.argv[1] if len(sys.argv) > 1 else "lstm"
    assert config in ("lr", "cnn", "lstm"), config
    paddle.init(trainer_count=1, seed=7)

    word_idx = dataset.imdb.word_dict()
    dim = len(word_idx)
    cost, output = build(config, dim)

    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=2e-3))

    train = dataset.imdb.train(word_idx)
    test = dataset.imdb.test(word_idx)
    if config == "lr":
        train, test = bow_reader(train, dim), bow_reader(test, dim)
    if FAST:
        train = decorator.firstn(train, 256)
        test = decorator.firstn(test, 64)

    def event_handler(event):
        if isinstance(event, paddle.event.EndIteration) \
                and event.batch_id % 16 == 0:
            print(f"pass {event.pass_id} batch {event.batch_id} "
                  f"cost {event.cost:.4f}")

    trainer.train(paddle.batch(decorator.shuffle(train, 512), 64),
                  num_passes=1 if FAST else 4,
                  event_handler=event_handler)

    result = trainer.test(paddle.batch(test, 64))
    print(f"[{config}] test cost: {result.cost:.4f}")

    rows = [(x,) for x, _ in decorator.firstn(test, 8)()]
    probs = paddle.infer(output_layer=output, parameters=parameters,
                         input=rows)
    print(f"[{config}] predicted labels:",
          np.argmax(probs, axis=1).tolist())

    # Deployment view: run the transpiler's inference pipeline over the
    # pruned serving program and show the per-pass stats table (wall time
    # + op-count deltas — the same numbers the serving engines publish
    # into their MetricsRegistry).
    from paddle_tpu import Scope, transpiler

    prog = parameters.test_program_for([output])
    feeds = [v.name for v in parameters.data_vars(program=prog)]
    pm = transpiler.inference_pipeline()
    pm.run(prog, feeds, [output.name],
           scope=Scope(parent=parameters.scope))
    print(f"[{config}] transpiler pass stats:")
    print(pm.format_stats())
    return result.cost


if __name__ == "__main__":
    main()

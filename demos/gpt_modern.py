"""The modern LM training recipe on the per-layer transformer path:
RMSNorm pre-norms, rotary positions, grouped-query attention, AdamW
(decoupled weight decay), and the chunked fused head+loss that never
materializes the [tokens, vocab] logits — every piece beyond the
reference's capability set (it predates Transformers), all through the
same program/executor idiom as the classic demos.

Run:  python demos/gpt_modern.py  (PADDLE_TPU_DEMO_FAST=1 to smoke)
"""
import os

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, models

FAST = bool(os.environ.get("PADDLE_TPU_DEMO_FAST"))


def synthetic_corpus(rng, vocab, n, T):
    """A learnable language: token t+1 = (5*t + noise) % vocab."""
    x = np.zeros((n, T + 1), np.int64)
    x[:, 0] = rng.randint(0, vocab, size=n)
    for t in range(T):
        noise = rng.randint(0, 2, size=n)
        x[:, t + 1] = (5 * x[:, t] + noise) % vocab
    return x


def main():
    vocab, T = 211, 24 if FAST else 64  # odd vocab: the fused head pads
    d_model, n_layers, heads = 64, 2, 4
    main_prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_prog, startup):
        ids = layers.data("ids", shape=[T], dtype="int64")
        tgt = layers.data("tgt", shape=[T, 1], dtype="int64")
        h = models.transformer_lm(
            ids, vocab_size=vocab, d_model=d_model, n_layers=n_layers,
            num_heads=heads, num_kv_heads=2,       # GQA: 2 KV head groups
            use_rope=True,                          # rotary positions
            norm_type="rms_norm",                   # single-reduction norm
            max_len=2 * T,
            include_head=False)                     # head lives in the loss
        loss = layers.mean(layers.fused_head_cross_entropy(
            h, tgt, num_classes=vocab, chunk=128,
            label_smoothing=0.05,                   # smoothed targets
            param_attr=pt.ParamAttr(name="head.w")))
        # eval clone BEFORE minimize (the reference contract)
        eval_prog = main_prog.clone(for_test=True)
        from paddle_tpu.learning_rate_decay import (cosine_decay,
                                                    linear_lr_warmup)

        lr = linear_lr_warmup(cosine_decay(3e-3, decay_steps=150),
                              warmup_steps=10, start_lr=3e-4, end_lr=3e-3)
        pt.optimizer.AdamWOptimizer(
            learning_rate=lr, weight_decay=0.01).minimize(
            loss, startup_program=startup)

    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)
    steps = 10 if FAST else 150
    first = last = None
    for step in range(steps):
        seq = synthetic_corpus(rng, vocab, n=32, T=T)
        lo, = exe.run(main_prog,
                      feed={"ids": seq[:, :-1], "tgt": seq[:, 1:, None]},
                      fetch_list=[loss], scope=scope)
        lo = float(np.asarray(lo))
        first = lo if first is None else first
        last = lo
        if step % 25 == 0 or step == steps - 1:
            print(f"step {step}: loss {lo:.4f}")
    print(f"loss {first:.3f} -> {last:.3f} "
          f"(rms_norm + rope + gqa + adamw + warmup-cosine + "
          f"smoothed fused head)")

    # next-token accuracy: run the eval clone up to the hidden states and
    # project against the trained fused head weight on the host
    seq = synthetic_corpus(rng, vocab, n=16, T=T)
    hv, = exe.run(eval_prog,
                  feed={"ids": seq[:, :-1], "tgt": seq[:, 1:, None]},
                  fetch_list=[h.name], scope=scope)
    w_np = np.asarray(scope.get("head.w"), dtype=np.float32)
    pred = (np.asarray(hv, dtype=np.float32) @ w_np).argmax(-1)
    acc = float((pred[:, :-1] == seq[:, 1:-1]).mean())
    print(f"next-token accuracy: {acc:.2f}")
    if not FAST:
        assert acc > 0.4, acc


if __name__ == "__main__":
    main()

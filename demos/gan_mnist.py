"""GAN on MNIST — two-optimizer adversarial training.

The capability ported from the reference's GAN demo
(/root/reference/v1_api_demo/gan/gan_trainer.py): a generator and a
discriminator defined as SEPARATE programs that SHARE parameters by name
through one scope, trained by alternating minimize steps — discriminator on
real+fake batches, generator through the (frozen) discriminator. Exercises
program cloning/parameter sharing across programs and per-program optimizer
state in a way nothing else in demos/ does.

TPU notes: both steps compile to single XLA computations; the generator's
step traces through the discriminator but ``parameter_list`` restricts the
update (and therefore the optimizer state) to the generator's weights, so
the unused discriminator gradients are dead code XLA eliminates.

Run:  python demos/gan_mnist.py   (PADDLE_TPU_DEMO_FAST=1 for a smoke run)
"""
import os

import numpy as np

import paddle_tpu as pt
from paddle_tpu import dataset, layers
from paddle_tpu.param_attr import ParamAttr
from paddle_tpu.reader import batch as batch_reader
from paddle_tpu.reader import decorator

FAST = bool(os.environ.get("PADDLE_TPU_DEMO_FAST"))

Z_DIM = 64
HIDDEN = 256
X_DIM = 784

G_PARAMS = ["g_fc1_w", "g_fc1_b", "g_fc2_w", "g_fc2_b"]
D_PARAMS = ["d_fc1_w", "d_fc1_b", "d_fc2_w", "d_fc2_b"]


def generator(z):
    """z [b, Z_DIM] -> tanh image [b, 784]; parameters shared by name."""
    h = layers.fc(z, size=HIDDEN, act="relu",
                  param_attr=ParamAttr(name="g_fc1_w"),
                  bias_attr=ParamAttr(name="g_fc1_b"))
    return layers.fc(h, size=X_DIM, act="tanh",
                     param_attr=ParamAttr(name="g_fc2_w"),
                     bias_attr=ParamAttr(name="g_fc2_b"))


def discriminator(x):
    """x [b, 784] -> real/fake logit [b, 1]; parameters shared by name."""
    h = layers.fc(x, size=HIDDEN,
                  param_attr=ParamAttr(name="d_fc1_w"),
                  bias_attr=ParamAttr(name="d_fc1_b"))
    h = layers.leaky_relu(h, alpha=0.2)
    return layers.fc(h, size=1,
                     param_attr=ParamAttr(name="d_fc2_w"),
                     bias_attr=ParamAttr(name="d_fc2_b"))


def _bce_mean(logit, target_value):
    target = layers.fill_constant_batch_size_like(
        logit, shape=[-1, 1], value=target_value, dtype="float32")
    return layers.mean(
        layers.sigmoid_cross_entropy_with_logits(logit, target))


def build_programs():
    """Returns (d_prog, g_prog, startup, d_loss, g_loss)."""
    startup = pt.Program()

    # Discriminator step: real batch up, generated batch down. The
    # generator runs inside this program too, but only D_PARAMS are updated.
    d_prog = pt.Program()
    with pt.program_guard(d_prog, startup):
        x_real = layers.data("x_real", shape=[X_DIM])
        z = layers.data("z", shape=[Z_DIM])
        fake = generator(z)
        d_loss = layers.elementwise_add(
            _bce_mean(discriminator(x_real), 0.9),  # one-sided smoothing
            _bce_mean(discriminator(fake), 0.0))
        pt.optimizer.AdamOptimizer(learning_rate=2e-4, beta1=0.5).minimize(
            d_loss, parameter_list=D_PARAMS, startup_program=startup)

    # Generator step: fool the (frozen) discriminator.
    g_prog = pt.Program()
    with pt.program_guard(g_prog, startup):
        z = layers.data("z", shape=[Z_DIM])
        fake = generator(z)
        g_loss = _bce_mean(discriminator(fake), 1.0)
        pt.optimizer.AdamOptimizer(learning_rate=2e-4, beta1=0.5).minimize(
            g_loss, parameter_list=G_PARAMS, startup_program=startup)

    return d_prog, g_prog, startup, d_loss, g_loss


def main():
    batch = 64
    passes = 1 if FAST else 5
    n_batches = 8 if FAST else 200

    d_prog, g_prog, startup, d_loss, g_loss = build_programs()
    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    startup.random_seed = 7
    exe.run(startup, scope=scope)

    reader = batch_reader(
        decorator.shuffle(dataset.mnist.train(), buf_size=2048), batch)
    rng = np.random.RandomState(0)

    d_hist, g_hist = [], []
    for pass_id in range(passes):
        for batch_id, rows in enumerate(reader()):
            if batch_id >= n_batches:
                break
            # dataset.mnist rows are already in tanh range [-1, 1]
            x = np.stack([np.asarray(r[0], np.float32) for r in rows])
            x = x.reshape(len(rows), X_DIM)
            z = rng.randn(len(rows), Z_DIM).astype(np.float32)
            dl, = exe.run(d_prog, feed={"x_real": x, "z": z},
                          fetch_list=[d_loss], scope=scope)
            # two generator steps per discriminator step (reference
            # gan_trainer.py trains G more to keep the game balanced)
            for _ in range(2):
                z = rng.randn(len(rows), Z_DIM).astype(np.float32)
                gl, = exe.run(g_prog, feed={"z": z},
                              fetch_list=[g_loss], scope=scope)
            d_hist.append(float(dl))
            g_hist.append(float(gl))
            if batch_id % 20 == 0:
                print(f"pass {pass_id} batch {batch_id} "
                      f"d_loss {float(dl):.3f} g_loss {float(gl):.3f}")

    print(f"final d_loss {d_hist[-1]:.3f} g_loss {g_hist[-1]:.3f}")
    assert np.isfinite(d_hist).all() and np.isfinite(g_hist).all()
    # healthy adversarial band: neither side has collapsed to 0 or blown up
    assert 0.05 < d_hist[-1] < 3.5, d_hist[-5:]
    assert 0.02 < g_hist[-1] < 6.0, g_hist[-5:]
    print("OK")


if __name__ == "__main__":
    main()

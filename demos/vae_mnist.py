"""Variational autoencoder on MNIST.

The capability ported from the reference's VAE demo
(/root/reference/v1_api_demo/vae/vae_train.py): an encoder producing a
(mu, log-variance) posterior, the reparameterization trick, and a decoder
trained end to end on reconstruction + KL. Exercises the RNG plane inside
a training graph — ``gaussian_random_batch_size_like`` noise is a
non-differentiated leaf, so gradients flow through mu/sigma exactly as the
reparameterization trick requires — plus in-graph KL assembled from
elementwise ops.

TPU notes: the whole step (encoder, sampling, decoder, both loss terms,
Adam) compiles to one XLA computation; the PRNG is the threaded counter
state every compiled program carries (core/executor.py RNG threading), so
runs are deterministic per seed.

Run:  python demos/vae_mnist.py   (PADDLE_TPU_DEMO_FAST=1 for a smoke run)
"""
import os

import numpy as np

import paddle_tpu as pt
from paddle_tpu import dataset, layers
from paddle_tpu.reader import batch as batch_reader
from paddle_tpu.reader import decorator

FAST = bool(os.environ.get("PADDLE_TPU_DEMO_FAST"))

X_DIM = 784
HIDDEN = 256
Z_DIM = 16


def build():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[X_DIM])
        # encoder
        h = layers.fc(x, size=HIDDEN, act="relu")
        mu = layers.fc(h, size=Z_DIM)
        logvar = layers.fc(h, size=Z_DIM)
        # reparameterization: z = mu + exp(logvar/2) * eps
        eps = layers.gaussian_random_batch_size_like(
            mu, shape=[-1, Z_DIM], mean=0.0, std=1.0)
        sigma = layers.exp(layers.scale(logvar, 0.5))
        z = layers.elementwise_add(mu, layers.elementwise_mul(sigma, eps))
        # decoder
        d = layers.fc(z, size=HIDDEN, act="relu")
        x_logits = layers.fc(d, size=X_DIM)
        # losses: Bernoulli reconstruction + analytic KL(q || N(0, I))
        rec = layers.reduce_sum(
            layers.sigmoid_cross_entropy_with_logits(x_logits, x), dim=[1])
        kl_terms = layers.elementwise_sub(
            layers.elementwise_add(layers.exp(logvar),
                                   layers.square(mu)),
            layers.scale(logvar, 1.0, bias=1.0))
        kl = layers.scale(layers.reduce_sum(kl_terms, dim=[1]), 0.5)
        loss = layers.mean(layers.elementwise_add(rec, kl))
        recon = layers.sigmoid(x_logits)
        # inference clone BEFORE the optimizer ops: fetching recon from it
        # must not take a hidden training step
        infer_prog = main.clone(for_test=True)
        pt.optimizer.AdamOptimizer(learning_rate=1e-3).minimize(
            loss, startup_program=startup)
    return main, startup, infer_prog, loss, recon


def main():
    bs = 128
    passes = 1 if FAST else 5
    n_batches = 8 if FAST else 200

    main_prog, startup, infer_prog, loss, recon = build()
    scope = pt.Scope()
    exe = pt.Executor(pt.TPUPlace())
    startup.random_seed = 11
    exe.run(startup, scope=scope)

    reader = batch_reader(
        decorator.shuffle(dataset.mnist.train(), buf_size=2048), bs)
    hist = []
    for pass_id in range(passes):
        for batch_id, rows in enumerate(reader()):
            if batch_id >= n_batches:
                break
            # dataset rows are in [-1, 1]; Bernoulli targets live in [0, 1]
            x = (np.stack([np.asarray(r[0], np.float32) for r in rows])
                 .reshape(len(rows), X_DIM) + 1.0) / 2.0
            lo, = exe.run(main_prog, feed={"x": x}, fetch_list=[loss],
                          scope=scope)
            hist.append(float(lo))
            if batch_id % 20 == 0:
                print(f"pass {pass_id} batch {batch_id} elbo-loss "
                      f"{hist[-1]:.2f}")

    print(f"loss {hist[0]:.2f} -> {hist[-1]:.2f}")
    assert np.isfinite(hist).all()
    assert hist[-1] < hist[0], (hist[0], hist[-1])
    # reconstructions stay probabilities
    x0 = (np.stack([np.asarray(r[0], np.float32)
                    for r in next(iter(reader()))[:4]])
          .reshape(-1, X_DIM) + 1.0) / 2.0
    rec_np, = exe.run(infer_prog, feed={"x": x0}, fetch_list=[recon],
                      scope=scope)
    assert 0.0 <= np.min(rec_np) and np.max(rec_np) <= 1.0
    print("OK")


if __name__ == "__main__":
    main()

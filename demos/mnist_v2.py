"""MNIST with the v2 API — the reference's first demo
(/root/reference/v1_api_demo/mnist/api_train.py), unchanged in shape:
init -> layers -> parameters.create -> trainer.SGD -> train with an event
handler -> infer.

Run:  python demos/mnist_v2.py  (add PADDLE_TPU_DEMO_FAST=1 for a smoke run)
"""
import os

import numpy as np

import paddle_tpu.v2 as paddle
from paddle_tpu import dataset
from paddle_tpu.reader import decorator

FAST = bool(os.environ.get("PADDLE_TPU_DEMO_FAST"))


def main():
    paddle.init(trainer_count=1, seed=42)

    images = paddle.layer.data("pixel", paddle.data_type.dense_vector(784))
    label = paddle.layer.data("label", paddle.data_type.integer_value(10))
    h1 = paddle.layer.fc(input=images, size=128,
                         act=paddle.activation.Relu())
    h2 = paddle.layer.fc(input=h1, size=64, act=paddle.activation.Relu())
    logits = paddle.layer.fc(input=h2, size=10)
    cost = paddle.layer.classification_cost(input=logits, label=label)

    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-3))

    def event_handler(event):
        if isinstance(event, paddle.event.EndIteration) \
                and event.batch_id % 50 == 0:
            print(f"pass {event.pass_id} batch {event.batch_id} "
                  f"cost {event.cost:.4f}")
        elif isinstance(event, paddle.event.EndPass):
            print(f"pass {event.pass_id} done: {event.metrics}")

    train_reader = dataset.mnist.train()
    if FAST:
        train_reader = decorator.firstn(train_reader, 512)
    trainer.train(paddle.batch(train_reader, 64),
                  num_passes=1 if FAST else 5,
                  event_handler=event_handler)

    # evaluate
    result = trainer.test(paddle.batch(
        decorator.firstn(dataset.mnist.test(), 256), 64))
    print(f"test cost: {result.cost:.4f}")

    rows = [(img,) for img, _ in list(dataset.mnist.test()())[:8]]
    probs = paddle.infer(output_layer=logits, parameters=parameters,
                         input=rows)
    print("predicted digits:", np.argmax(probs, axis=1).tolist())


if __name__ == "__main__":
    main()

"""Evaluators: metric accumulation across batches, built into the program.

TPU-native parity with both evaluator stacks of the reference:
- fluid evaluators (/root/reference/python/paddle/v2/fluid/evaluator.py):
  state variables live in the program's scope, update ops run with every
  batch, ``eval()`` computes the aggregate, ``reset()`` zeroes state.
- legacy gserver evaluators
  (/root/reference/paddle/gserver/evaluators/Evaluator.cpp:172-1357:
  classification_error, precision_recall, rankauc/auc, chunk, ctc_error).

States are persistable scope variables updated in-graph (the same
state-threading the optimizer and batch_norm running stats use), so metric
accumulation is fused into the training step — no extra host round-trips.
"""
from __future__ import annotations

import numpy as np

from .core.program import default_main_program, default_startup_program
from .initializer import ConstantInitializer
from .layers.layer_helper import LayerHelper
from .layers.sequence import get_seq_len


class Evaluator:
    """Base: manages state vars (created in both programs) + reset/eval.

    Mirrors fluid evaluator.Evaluator (evaluator.py): ``states`` are
    persistable variables zero-initialised by the startup program; update
    ops appended to the main program accumulate into them; ``eval(exe,
    scope)`` fetches and combines; ``reset(exe, scope)`` re-zeroes.
    """

    def __init__(self, name, main_program=None, startup_program=None):
        self.helper = LayerHelper(name, main_program=main_program,
                                  startup_program=startup_program)
        self.states = []

    # Device-side count accumulators are int32 by policy: without
    # jax_enable_x64, jnp silently narrows int64 to int32 anyway, so the
    # declaration is made explicit. 2^31 events per eval pass is beyond any
    # realistic pass; eval() widens on the host (float64) for the aggregate.
    def _create_state(self, suffix, shape, dtype="int32"):
        main = self.helper.main_program
        name = main.unique_name(f"{self.helper.layer_type}.{suffix}")
        v = main.global_block.create_var(
            name=name, shape=shape, dtype=dtype, persistable=True,
            stop_gradient=True)
        sb = self.helper.startup_program.global_block
        sv = sb.create_var(name=name, shape=shape, dtype=dtype,
                           persistable=True)
        ConstantInitializer(0)(sv, sb)
        self.states.append(v)
        return v

    def reset(self, executor, scope=None):
        from .core.scope import global_scope

        scope = scope or global_scope()
        for v in self.states:
            scope.set(v.name, np.zeros(
                tuple(d for d in v.shape if d != -1) or (),
                dtype=v.dtype.name if hasattr(v.dtype, "name") else v.dtype))

    def eval(self, executor, scope=None):
        raise NotImplementedError

    def _fetch_states(self, scope):
        from .core.scope import global_scope

        scope = scope or global_scope()
        return [np.asarray(scope.get(v.name)) for v in self.states]

    def _accumulate(self, state_var, increment):
        """state += increment, written back to the same scope name."""
        self.helper.append_op(
            "elementwise_add", {"X": [state_var], "Y": [increment]},
            {"Out": [state_var]}, {})


class Accuracy(Evaluator):
    """Streaming top-k accuracy (fluid evaluator.Accuracy; legacy
    classification_error_evaluator)."""

    def __init__(self, input, label, k=1, **kwargs):
        super().__init__("accuracy_eval", **kwargs)
        self.total = self._create_state("total", [], "int32")
        self.correct = self._create_state("correct", [], "int32")
        from . import layers

        main = self.helper.main_program
        startup = self.helper.startup_program
        topk_out, topk_idx = layers.topk(input, k=k, main_program=main,
                                         startup_program=startup)
        outs, _ = self.helper.append_op(
            "accuracy",
            {"Out": [topk_out], "Indices": [topk_idx], "Label": [label]},
            ["Accuracy", "Correct", "Total"])
        self.batch_acc = outs["Accuracy"][0]
        corr = self.helper.simple_op(
            "cast", {"X": [outs["Correct"][0]]}, {"dtype": "int32"})
        tot = self.helper.simple_op(
            "cast", {"X": [outs["Total"][0]]}, {"dtype": "int32"})
        self._accumulate(self.correct, corr)
        self._accumulate(self.total, tot)

    def eval(self, executor, scope=None):
        total, correct = self._fetch_states(scope)
        return float(correct) / max(float(total), 1.0)


class ChunkEvaluator(Evaluator):
    """Streaming chunk F1 (fluid ChunkEvaluator / legacy chunk evaluator).
    eval() returns (precision, recall, f1)."""

    def __init__(self, input, label, chunk_scheme="IOB", num_chunk_types=1,
                 **kwargs):
        super().__init__("chunk_eval_streaming", **kwargs)
        self.n_infer = self._create_state("num_infer", [1], "int32")
        self.n_label = self._create_state("num_label", [1], "int32")
        self.n_correct = self._create_state("num_correct", [1], "int32")
        from . import layers

        main = self.helper.main_program
        startup = self.helper.startup_program
        _, _, _, ni, nl, nc = layers.chunk_eval(
            input, label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types, main_program=main,
            startup_program=startup)
        self._accumulate(self.n_infer, ni)
        self._accumulate(self.n_label, nl)
        self._accumulate(self.n_correct, nc)

    def eval(self, executor, scope=None):
        ni, nl, nc = self._fetch_states(scope)
        ni, nl, nc = float(ni[0]), float(nl[0]), float(nc[0])
        precision = nc / ni if ni else 0.0
        recall = nc / nl if nl else 0.0
        f1 = (2 * precision * recall / (precision + recall)
              if precision + recall else 0.0)
        return precision, recall, f1


class PrecisionRecall(Evaluator):
    """Multi-class streaming precision/recall/F1 from confusion counts
    (legacy precision_recall_evaluator, Evaluator.cpp). eval() returns
    (macro_p, macro_r, macro_f1) plus per-class arrays."""

    def __init__(self, input, label, num_classes, **kwargs):
        super().__init__("precision_recall", **kwargs)
        self.num_classes = num_classes
        self.tp = self._create_state("tp", [num_classes], "int32")
        self.fp = self._create_state("fp", [num_classes], "int32")
        self.fn = self._create_state("fn", [num_classes], "int32")
        outs, _ = self.helper.append_op(
            "confusion_counts", {"Pred": [input], "Label": [label]},
            ["TP", "FP", "FN"], {"num_classes": num_classes})
        self._accumulate(self.tp, outs["TP"][0])
        self._accumulate(self.fp, outs["FP"][0])
        self._accumulate(self.fn, outs["FN"][0])

    def eval(self, executor, scope=None):
        tp, fp, fn = [a.astype(np.float64) for a in
                      self._fetch_states(scope)]
        p = tp / np.maximum(tp + fp, 1)
        r = tp / np.maximum(tp + fn, 1)
        f1 = 2 * p * r / np.maximum(p + r, 1e-10)
        return float(p.mean()), float(r.mean()), float(f1.mean())


class Auc(Evaluator):
    """Streaming AUC via score histograms (legacy rankauc / AucEvaluator,
    Evaluator.cpp; fluid auc_op.cc). Positive-class scores bucketed into
    ``num_thresholds`` bins; AUC computed by trapezoidal rule on eval()."""

    def __init__(self, input, label, num_thresholds=200, **kwargs):
        super().__init__("auc", **kwargs)
        self.num_thresholds = num_thresholds
        self.pos = self._create_state("pos_hist", [num_thresholds], "int32")
        self.neg = self._create_state("neg_hist", [num_thresholds], "int32")
        outs, _ = self.helper.append_op(
            "auc_histogram", {"Score": [input], "Label": [label]},
            ["Pos", "Neg"], {"num_thresholds": num_thresholds})
        self._accumulate(self.pos, outs["Pos"][0])
        self._accumulate(self.neg, outs["Neg"][0])

    def eval(self, executor, scope=None):
        pos, neg = self._fetch_states(scope)
        pos, neg = pos.astype(np.float64), neg.astype(np.float64)
        # cum from highest threshold down: TPR/FPR curve
        tp = np.cumsum(pos[::-1])
        fp = np.cumsum(neg[::-1])
        tot_pos, tot_neg = tp[-1], fp[-1]
        if tot_pos == 0 or tot_neg == 0:
            return 0.5
        tpr = np.concatenate([[0.0], tp / tot_pos])
        fpr = np.concatenate([[0.0], fp / tot_neg])
        return float(np.trapezoid(tpr, fpr))


class CTCError(Evaluator):
    """Streaming CTC error (legacy ctc_error_evaluator,
    /root/reference/paddle/gserver/evaluators/CTCErrorEvaluator.cpp:162-192):
    per sequence, the edit distance between the greedy-decoded best path
    and the label, normalized by max(len(decoded), len(label)); ``eval()``
    returns the average over sequences. ``seq_error`` additionally tracks
    the fraction of sequences with any error (seqClassficationError_)."""

    def __init__(self, input, label, blank=0, **kwargs):
        super().__init__("ctc_error", **kwargs)
        self.total_norm_dist = self._create_state("norm_dist", [], "float32")
        self.total_seqs = self._create_state("seqs", [], "float32")
        self.total_wrong = self._create_state("wrong", [], "float32")
        from . import layers

        main = self.helper.main_program
        startup = self.helper.startup_program
        dec, dec_len = layers.ctc_greedy_decoder(
            input, blank=blank, main_program=main, startup_program=startup)
        ins = {"Hyps": [dec], "Refs": [label], "HypsLength": [dec_len]}
        tl = get_seq_len(label)
        if tl is not None:
            ins["RefsLength"] = [tl]
        outs, _ = self.helper.append_op(
            "edit_distance", ins, ["Out", "SequenceNum"], {})
        dist = outs["Out"][0]  # [b, 1]
        # normalize by max(len(hyp), len(ref)) per sequence
        ref_len = (tl if tl is not None else
                   self.helper.simple_op(
                       "fill_constant_batch_size_like",
                       {"Input": [dist]},
                       {"shape": [-1, 1], "dtype": "float32",
                        "value": float(label.shape[-1])}))
        hyp_f = self.helper.simple_op("cast", {"X": [dec_len]},
                                      {"dtype": "float32"})
        ref_f = self.helper.simple_op("cast", {"X": [ref_len]},
                                      {"dtype": "float32"})
        # lengths from lod data layers are [b]; align to dist's [b, 1] so
        # the elementwise ops below never cross-broadcast to [b, b]
        ref_f = self.helper.simple_op("reshape", {"X": [ref_f]},
                                      {"shape": [-1, 1]})
        hyp_f = self.helper.simple_op("reshape", {"X": [hyp_f]},
                                      {"shape": [-1, 1]})
        max_len = self.helper.simple_op(
            "elementwise_max", {"X": [hyp_f], "Y": [ref_f]}, {})
        one = self.helper.simple_op(
            "fill_constant_batch_size_like", {"Input": [dist]},
            {"shape": [-1, 1], "dtype": "float32", "value": 1.0})
        denom = self.helper.simple_op(
            "elementwise_max", {"X": [max_len], "Y": [one]}, {})
        norm = self.helper.simple_op(
            "elementwise_div", {"X": [dist], "Y": [denom]}, {})
        nsum = self.helper.simple_op("reduce_sum", {"X": [norm]},
                                     {"keep_dim": False})
        # dist > 0 <=> the sequence has at least one error
        zero = self.helper.simple_op("scale", {"X": [one]}, {"scale": 0.0})
        wrong = self.helper.simple_op(
            "greater_than", {"X": [dist], "Y": [zero]}, {})
        wrong_f = self.helper.simple_op("cast", {"X": [wrong]},
                                       {"dtype": "float32"})
        wsum = self.helper.simple_op("reduce_sum", {"X": [wrong_f]},
                                     {"keep_dim": False})
        n = self.helper.simple_op("cast", {"X": [outs["SequenceNum"][0]]},
                                  {"dtype": "float32"})
        self._accumulate(self.total_norm_dist, nsum)
        self._accumulate(self.total_seqs, n)
        self._accumulate(self.total_wrong, wsum)

    def eval(self, executor, scope=None):
        nd, n, _ = self._fetch_states(scope)
        return float(nd) / max(float(n), 1.0)

    def seq_error(self, scope=None):
        _, n, w = self._fetch_states(scope)
        return float(w) / max(float(n), 1.0)


class EditDistance(Evaluator):
    """Streaming average edit distance (legacy ctc_error_evaluator;
    fluid edit_distance_op.cc)."""

    def __init__(self, input, label, normalized=False, **kwargs):
        super().__init__("edit_distance", **kwargs)
        self.total_dist = self._create_state("total_dist", [], "float32")
        self.total_seqs = self._create_state("total_seqs", [], "float32")
        ins = {"Hyps": [input], "Refs": [label]}
        hl, rl = get_seq_len(input), get_seq_len(label)
        if hl is not None:
            ins["HypsLength"] = [hl]
        if rl is not None:
            ins["RefsLength"] = [rl]
        outs, _ = self.helper.append_op(
            "edit_distance", ins, ["Out", "SequenceNum"],
            {"normalized": normalized})
        self.batch_dist = outs["Out"][0]
        dist_sum = self.helper.simple_op(
            "reduce_sum", {"X": [self.batch_dist]}, {"keep_dim": False})
        n = self.helper.simple_op(
            "cast", {"X": [outs["SequenceNum"][0]]}, {"dtype": "float32"})
        self._accumulate(self.total_dist, dist_sum)
        self._accumulate(self.total_seqs, n)

    def eval(self, executor, scope=None):
        dist, n = self._fetch_states(scope)
        return float(dist) / max(float(n), 1.0)

"""Evaluators: metric accumulation across batches, built into the program.

TPU-native parity with both evaluator stacks of the reference:
- fluid evaluators (/root/reference/python/paddle/v2/fluid/evaluator.py):
  state variables live in the program's scope, update ops run with every
  batch, ``eval()`` computes the aggregate, ``reset()`` zeroes state.
- legacy gserver evaluators
  (/root/reference/paddle/gserver/evaluators/Evaluator.cpp:172-1357:
  classification_error, precision_recall, rankauc/auc, chunk, ctc_error).

States are persistable scope variables updated in-graph (the same
state-threading the optimizer and batch_norm running stats use), so metric
accumulation is fused into the training step — no extra host round-trips.
"""
from __future__ import annotations

import numpy as np

from .core.program import default_main_program, default_startup_program
from .initializer import ConstantInitializer
from .layers.layer_helper import LayerHelper
from .layers.sequence import get_seq_len


class Evaluator:
    """Base: manages state vars (created in both programs) + reset/eval.

    Mirrors fluid evaluator.Evaluator (evaluator.py): ``states`` are
    persistable variables zero-initialised by the startup program; update
    ops appended to the main program accumulate into them; ``eval(exe,
    scope)`` fetches and combines; ``reset(exe, scope)`` re-zeroes.
    """

    def __init__(self, name, main_program=None, startup_program=None):
        self.helper = LayerHelper(name, main_program=main_program,
                                  startup_program=startup_program)
        self.states = []

    # Device-side count accumulators are int32 by policy: without
    # jax_enable_x64, jnp silently narrows int64 to int32 anyway, so the
    # declaration is made explicit. 2^31 events per eval pass is beyond any
    # realistic pass; eval() widens on the host (float64) for the aggregate.
    def _create_state(self, suffix, shape, dtype="int32"):
        main = self.helper.main_program
        name = main.unique_name(f"{self.helper.layer_type}.{suffix}")
        v = main.global_block.create_var(
            name=name, shape=shape, dtype=dtype, persistable=True,
            stop_gradient=True)
        sb = self.helper.startup_program.global_block
        sv = sb.create_var(name=name, shape=shape, dtype=dtype,
                           persistable=True)
        ConstantInitializer(0)(sv, sb)
        self.states.append(v)
        return v

    def reset(self, executor, scope=None):
        from .core.scope import global_scope

        scope = scope or global_scope()
        for v in self.states:
            scope.set(v.name, np.zeros(
                tuple(d for d in v.shape if d != -1) or (),
                dtype=v.dtype.name if hasattr(v.dtype, "name") else v.dtype))

    def eval(self, executor, scope=None):
        raise NotImplementedError

    def _fetch_states(self, scope):
        from .core.scope import global_scope

        scope = scope or global_scope()
        return [np.asarray(scope.get(v.name)) for v in self.states]

    def _accumulate(self, state_var, increment):
        """state += increment, written back to the same scope name."""
        self.helper.append_op(
            "elementwise_add", {"X": [state_var], "Y": [increment]},
            {"Out": [state_var]}, {})


class Accuracy(Evaluator):
    """Streaming top-k accuracy (fluid evaluator.Accuracy; legacy
    classification_error_evaluator)."""

    def __init__(self, input, label, k=1, **kwargs):
        super().__init__("accuracy_eval", **kwargs)
        self.total = self._create_state("total", [], "int32")
        self.correct = self._create_state("correct", [], "int32")
        from . import layers

        main = self.helper.main_program
        startup = self.helper.startup_program
        topk_out, topk_idx = layers.topk(input, k=k, main_program=main,
                                         startup_program=startup)
        outs, _ = self.helper.append_op(
            "accuracy",
            {"Out": [topk_out], "Indices": [topk_idx], "Label": [label]},
            ["Accuracy", "Correct", "Total"])
        self.batch_acc = outs["Accuracy"][0]
        corr = self.helper.simple_op(
            "cast", {"X": [outs["Correct"][0]]}, {"dtype": "int32"})
        tot = self.helper.simple_op(
            "cast", {"X": [outs["Total"][0]]}, {"dtype": "int32"})
        self._accumulate(self.correct, corr)
        self._accumulate(self.total, tot)

    def eval(self, executor, scope=None):
        total, correct = self._fetch_states(scope)
        return float(correct) / max(float(total), 1.0)


class ChunkEvaluator(Evaluator):
    """Streaming chunk F1 (fluid ChunkEvaluator / legacy chunk evaluator).
    eval() returns (precision, recall, f1)."""

    def __init__(self, input, label, chunk_scheme="IOB", num_chunk_types=1,
                 **kwargs):
        super().__init__("chunk_eval_streaming", **kwargs)
        self.n_infer = self._create_state("num_infer", [1], "int32")
        self.n_label = self._create_state("num_label", [1], "int32")
        self.n_correct = self._create_state("num_correct", [1], "int32")
        from . import layers

        main = self.helper.main_program
        startup = self.helper.startup_program
        _, _, _, ni, nl, nc = layers.chunk_eval(
            input, label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types, main_program=main,
            startup_program=startup)
        self._accumulate(self.n_infer, ni)
        self._accumulate(self.n_label, nl)
        self._accumulate(self.n_correct, nc)

    def eval(self, executor, scope=None):
        ni, nl, nc = self._fetch_states(scope)
        ni, nl, nc = float(ni[0]), float(nl[0]), float(nc[0])
        precision = nc / ni if ni else 0.0
        recall = nc / nl if nl else 0.0
        f1 = (2 * precision * recall / (precision + recall)
              if precision + recall else 0.0)
        return precision, recall, f1


class PrecisionRecall(Evaluator):
    """Multi-class streaming precision/recall/F1 from confusion counts
    (legacy precision_recall_evaluator, Evaluator.cpp). eval() returns
    (macro_p, macro_r, macro_f1) plus per-class arrays."""

    def __init__(self, input, label, num_classes, **kwargs):
        super().__init__("precision_recall", **kwargs)
        self.num_classes = num_classes
        self.tp = self._create_state("tp", [num_classes], "int32")
        self.fp = self._create_state("fp", [num_classes], "int32")
        self.fn = self._create_state("fn", [num_classes], "int32")
        outs, _ = self.helper.append_op(
            "confusion_counts", {"Pred": [input], "Label": [label]},
            ["TP", "FP", "FN"], {"num_classes": num_classes})
        self._accumulate(self.tp, outs["TP"][0])
        self._accumulate(self.fp, outs["FP"][0])
        self._accumulate(self.fn, outs["FN"][0])

    def eval(self, executor, scope=None):
        tp, fp, fn = [a.astype(np.float64) for a in
                      self._fetch_states(scope)]
        p = tp / np.maximum(tp + fp, 1)
        r = tp / np.maximum(tp + fn, 1)
        f1 = 2 * p * r / np.maximum(p + r, 1e-10)
        return float(p.mean()), float(r.mean()), float(f1.mean())


class Auc(Evaluator):
    """Streaming AUC via score histograms (legacy rankauc / AucEvaluator,
    Evaluator.cpp; fluid auc_op.cc). Positive-class scores bucketed into
    ``num_thresholds`` bins; AUC computed by trapezoidal rule on eval()."""

    def __init__(self, input, label, num_thresholds=200, **kwargs):
        super().__init__("auc", **kwargs)
        self.num_thresholds = num_thresholds
        self.pos = self._create_state("pos_hist", [num_thresholds], "int32")
        self.neg = self._create_state("neg_hist", [num_thresholds], "int32")
        outs, _ = self.helper.append_op(
            "auc_histogram", {"Score": [input], "Label": [label]},
            ["Pos", "Neg"], {"num_thresholds": num_thresholds})
        self._accumulate(self.pos, outs["Pos"][0])
        self._accumulate(self.neg, outs["Neg"][0])

    def eval(self, executor, scope=None):
        pos, neg = self._fetch_states(scope)
        pos, neg = pos.astype(np.float64), neg.astype(np.float64)
        # cum from highest threshold down: TPR/FPR curve
        tp = np.cumsum(pos[::-1])
        fp = np.cumsum(neg[::-1])
        tot_pos, tot_neg = tp[-1], fp[-1]
        if tot_pos == 0 or tot_neg == 0:
            return 0.5
        tpr = np.concatenate([[0.0], tp / tot_pos])
        fpr = np.concatenate([[0.0], fp / tot_neg])
        return float(np.trapezoid(tpr, fpr))


class CTCError(Evaluator):
    """Streaming CTC error (legacy ctc_error_evaluator,
    /root/reference/paddle/gserver/evaluators/CTCErrorEvaluator.cpp:162-192):
    per sequence, the edit distance between the greedy-decoded best path
    and the label, normalized by max(len(decoded), len(label)); ``eval()``
    returns the average over sequences. ``seq_error`` additionally tracks
    the fraction of sequences with any error (seqClassficationError_)."""

    def __init__(self, input, label, blank=0, **kwargs):
        super().__init__("ctc_error", **kwargs)
        self.total_norm_dist = self._create_state("norm_dist", [], "float32")
        self.total_seqs = self._create_state("seqs", [], "float32")
        self.total_wrong = self._create_state("wrong", [], "float32")
        from . import layers

        main = self.helper.main_program
        startup = self.helper.startup_program
        dec, dec_len = layers.ctc_greedy_decoder(
            input, blank=blank, main_program=main, startup_program=startup)
        ins = {"Hyps": [dec], "Refs": [label], "HypsLength": [dec_len]}
        tl = get_seq_len(label)
        if tl is not None:
            ins["RefsLength"] = [tl]
        outs, _ = self.helper.append_op(
            "edit_distance", ins, ["Out", "SequenceNum"], {})
        dist = outs["Out"][0]  # [b, 1]
        # normalize by max(len(hyp), len(ref)) per sequence
        ref_len = (tl if tl is not None else
                   self.helper.simple_op(
                       "fill_constant_batch_size_like",
                       {"Input": [dist]},
                       {"shape": [-1, 1], "dtype": "float32",
                        "value": float(label.shape[-1])}))
        hyp_f = self.helper.simple_op("cast", {"X": [dec_len]},
                                      {"dtype": "float32"})
        ref_f = self.helper.simple_op("cast", {"X": [ref_len]},
                                      {"dtype": "float32"})
        # lengths from lod data layers are [b]; align to dist's [b, 1] so
        # the elementwise ops below never cross-broadcast to [b, b]
        ref_f = self.helper.simple_op("reshape", {"X": [ref_f]},
                                      {"shape": [-1, 1]})
        hyp_f = self.helper.simple_op("reshape", {"X": [hyp_f]},
                                      {"shape": [-1, 1]})
        max_len = self.helper.simple_op(
            "elementwise_max", {"X": [hyp_f], "Y": [ref_f]}, {})
        one = self.helper.simple_op(
            "fill_constant_batch_size_like", {"Input": [dist]},
            {"shape": [-1, 1], "dtype": "float32", "value": 1.0})
        denom = self.helper.simple_op(
            "elementwise_max", {"X": [max_len], "Y": [one]}, {})
        norm = self.helper.simple_op(
            "elementwise_div", {"X": [dist], "Y": [denom]}, {})
        nsum = self.helper.simple_op("reduce_sum", {"X": [norm]},
                                     {"keep_dim": False})
        # dist > 0 <=> the sequence has at least one error
        zero = self.helper.simple_op("scale", {"X": [one]}, {"scale": 0.0})
        wrong = self.helper.simple_op(
            "greater_than", {"X": [dist], "Y": [zero]}, {})
        wrong_f = self.helper.simple_op("cast", {"X": [wrong]},
                                       {"dtype": "float32"})
        wsum = self.helper.simple_op("reduce_sum", {"X": [wrong_f]},
                                     {"keep_dim": False})
        n = self.helper.simple_op("cast", {"X": [outs["SequenceNum"][0]]},
                                  {"dtype": "float32"})
        self._accumulate(self.total_norm_dist, nsum)
        self._accumulate(self.total_seqs, n)
        self._accumulate(self.total_wrong, wsum)

    def eval(self, executor, scope=None):
        nd, n, _ = self._fetch_states(scope)
        return float(nd) / max(float(n), 1.0)

    def seq_error(self, scope=None):
        _, n, w = self._fetch_states(scope)
        return float(w) / max(float(n), 1.0)


class RankAuc(Evaluator):
    """Streaming per-query ranking AUC (legacy rankauc evaluator,
    /root/reference/paddle/gserver/evaluators/Evaluator.cpp:514-592).

    ``score``/``click``/``pv`` are dense padded [b, L] per-query rows with
    optional ``length`` [b] (the TPU layout for the reference's
    sequence-start-position segments). eval() returns the mean per-query
    AUC, exactly the reference's batchAuc / numSamples_.
    """

    def __init__(self, score, click, pv=None, length=None, **kwargs):
        super().__init__("rank_auc_eval", **kwargs)
        self.auc_sum = self._create_state("auc_sum", [], "float32")
        self.queries = self._create_state("queries", [], "float32")
        ins = {"Score": [score], "Click": [click]}
        if pv is not None:
            ins["Pv"] = [pv]
        if length is not None:
            ins["Length"] = [length]
        outs, _ = self.helper.append_op(
            "rank_auc", ins, ["AucSum", "QueryCount"], {})
        self._accumulate(self.auc_sum, outs["AucSum"][0])
        self._accumulate(self.queries, outs["QueryCount"][0])

    def eval(self, executor, scope=None):
        s, n = self._fetch_states(scope)
        return float(s) / max(float(n), 1.0)


class Pnpair(Evaluator):
    """Streaming positive/negative pair counts for ranking
    (legacy pnpair evaluator, /root/reference/paddle/gserver/evaluators/
    Evaluator.cpp:873-1000). eval() returns pos/neg ratio; ``counts()``
    gives (pos, neg, special)."""

    def __init__(self, score, label, weight=None, length=None, **kwargs):
        super().__init__("pnpair_eval", **kwargs)
        self.pos = self._create_state("pos", [], "float32")
        self.neg = self._create_state("neg", [], "float32")
        self.spe = self._create_state("spe", [], "float32")
        ins = {"Score": [score], "Label": [label]}
        if weight is not None:
            ins["Weight"] = [weight]
        if length is not None:
            ins["Length"] = [length]
        outs, _ = self.helper.append_op(
            "pnpair_counts", ins, ["Pos", "Neg", "Spe"], {})
        self._accumulate(self.pos, outs["Pos"][0])
        self._accumulate(self.neg, outs["Neg"][0])
        self._accumulate(self.spe, outs["Spe"][0])

    def counts(self, scope=None):
        p, n, s = self._fetch_states(scope)
        return float(p), float(n), float(s)

    def eval(self, executor, scope=None):
        p, n, _ = self._fetch_states(scope)
        return float(p) / max(float(n), 1e-10)


class DetectionMAP(Evaluator):
    """Streaming detection mean-average-precision (legacy detection_map
    evaluator, /root/reference/paddle/gserver/evaluators/
    DetectionMAPEvaluator.cpp).

    Detections and ground truth are dense padded per-image rows (boxes
    [b, M, 4] xyxy, scores [b, M], int classes [b, M]; gt [b, G, 4]/[b, G])
    with valid counts ``det_length``/``gt_length``. The in-graph update op
    greedily matches score-sorted detections to unmatched same-class gt at
    ``overlap_threshold`` and buckets TP/FP by score into a fixed [C, K]
    histogram state; eval() recovers the PR curve per class from bin
    cumsums and integrates AP (``ap_version``: '11point' like the
    reference's default, or 'integral'), averaging over classes with gt.
    """

    def __init__(self, det_boxes, det_scores, det_classes, gt_boxes,
                 gt_classes, num_classes, det_length=None, gt_length=None,
                 overlap_threshold=0.5, num_buckets=200,
                 ap_version="11point", **kwargs):
        super().__init__("detection_map_eval", **kwargs)
        self.num_classes, self.num_buckets = num_classes, num_buckets
        self.ap_version = ap_version
        self.tp = self._create_state("tp", [num_classes, num_buckets],
                                     "int32")
        self.fp = self._create_state("fp", [num_classes, num_buckets],
                                     "int32")
        self.gt = self._create_state("gt", [num_classes], "int32")
        ins = {"DetBoxes": [det_boxes], "DetScores": [det_scores],
               "DetClasses": [det_classes], "GtBoxes": [gt_boxes],
               "GtClasses": [gt_classes]}
        if det_length is not None:
            ins["DetLength"] = [det_length]
        if gt_length is not None:
            ins["GtLength"] = [gt_length]
        outs, _ = self.helper.append_op(
            "detection_map_counts", ins, ["TP", "FP", "GtCount"],
            {"num_classes": num_classes, "num_buckets": num_buckets,
             "overlap_threshold": overlap_threshold})
        self._accumulate(self.tp, outs["TP"][0])
        self._accumulate(self.fp, outs["FP"][0])
        self._accumulate(self.gt, outs["GtCount"][0])

    def eval(self, executor, scope=None):
        tp, fp, gt = self._fetch_states(scope)
        tp = tp.astype(np.float64)[:, ::-1]  # high-score bins first
        fp = fp.astype(np.float64)[:, ::-1]
        ctp, cfp = np.cumsum(tp, axis=1), np.cumsum(fp, axis=1)
        gt = gt.astype(np.float64)
        aps = []
        for c in range(self.num_classes):
            if gt[c] <= 0:
                continue
            recall = ctp[c] / gt[c]
            precision = ctp[c] / np.maximum(ctp[c] + cfp[c], 1e-10)
            if self.ap_version == "11point":
                ap = np.mean([precision[recall >= t].max()
                              if (recall >= t).any() else 0.0
                              for t in np.linspace(0, 1, 11)])
            else:  # integral over recall increments
                d_recall = np.diff(np.concatenate([[0.0], recall]))
                ap = float((precision * d_recall).sum())
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0


class Sum(Evaluator):
    """Streaming sum of a variable (legacy sum / ColumnSumEvaluator,
    /root/reference/paddle/gserver/evaluators/Evaluator.cpp:1007-1011).
    ``column`` selects one column (-1 = last, 'last-column-sum');
    None sums everything. eval() returns (sum, mean-per-instance)."""

    def __init__(self, input, column=None, **kwargs):
        super().__init__("sum_eval", **kwargs)
        self.total = self._create_state("total", [], "float32")
        self.insts = self._create_state("insts", [], "float32")
        x = input
        if column is not None and len(input.shape) < 2:
            raise ValueError(
                f"Sum(column={column}) needs a rank>=2 input, got shape "
                f"{tuple(input.shape)}")
        if column is not None:
            x = self.helper.simple_op(
                "slice", {"X": [input]},
                {"axes": [len(input.shape) - 1],
                 "starts": [column if column >= 0
                            else input.shape[-1] + column],
                 "ends": [(column if column >= 0
                           else input.shape[-1] + column) + 1]})
        xs = self.helper.simple_op("reduce_sum", {"X": [x]},
                                   {"keep_dim": False})
        xs = self.helper.simple_op("cast", {"X": [xs]}, {"dtype": "float32"})
        n = self.helper.simple_op(
            "fill_constant_batch_size_like", {"Input": [input]},
            {"shape": [-1, 1], "dtype": "float32", "value": 1.0})
        n = self.helper.simple_op("reduce_sum", {"X": [n]},
                                  {"keep_dim": False})
        self._accumulate(self.total, xs)
        self._accumulate(self.insts, n)

    def eval(self, executor, scope=None):
        t, n = self._fetch_states(scope)
        return float(t), float(t) / max(float(n), 1.0)


# --------------------------------------------------------------------------
# Printer evaluators (legacy value_printer / gradient_printer /
# max_id_printer / seq_text_printer / classification_error_printer,
# /root/reference/paddle/gserver/evaluators/Evaluator.cpp:1033-1357).
#
# TPU-native stance: printers are host-side observers. They register the
# variables to observe; ``fetches()`` exposes them for the caller's
# fetch_list and ``update(values)`` (called with the fetched arrays each
# batch) formats them to ``stream`` (stdout by default). Unlike states,
# printing never syncs the device unless the caller actually fetches.
# --------------------------------------------------------------------------
class Printer:
    """Base printer: observe ``vars``, print each batch on update()."""

    def __init__(self, vars, name="printer", stream=None, formatter=None):
        import sys

        self.vars = list(vars)
        self.name = name
        self.stream = stream or sys.stdout
        self._formatter = formatter

    def fetches(self):
        return list(self.vars)

    def _format(self, var, value):
        v = np.asarray(value)
        body = np.array2string(v, threshold=64, precision=6)
        return f"[{self.name}] {var.name} shape={tuple(v.shape)} {body}"

    def update(self, values):
        for var, value in zip(self.vars, values):
            fmt = self._formatter or self._format
            print(fmt(var, value), file=self.stream)


class ValuePrinter(Printer):
    """Print variable values per batch (value_printer)."""

    def __init__(self, *vars, **kw):
        super().__init__(vars, name=kw.pop("name", "value_printer"), **kw)


class GradientPrinter(Printer):
    """Print parameter gradients per batch (gradient_printer): observes
    the ``<var>@GRAD`` companions of the given vars (requires
    append_backward to have run)."""

    def __init__(self, *vars, **kw):
        from .core.program import grad_var_name

        gvars = []
        for v in vars:
            gname = grad_var_name(v.name)
            if not v.block.has_var(gname):
                raise ValueError(
                    f"no gradient variable {gname!r} for {v.name!r}: run "
                    "append_backward (or Optimizer.minimize) first")
            gvars.append(v.block.var(gname))
        super().__init__(gvars, name=kw.pop("name", "gradient_printer"),
                         **kw)


class MaxIdPrinter(Printer):
    """Print the argmax id per row of a score matrix (max_id_printer)."""

    def __init__(self, input, **kw):
        super().__init__([input], name=kw.pop("name", "max_id_printer"),
                         **kw)

    def _format(self, var, value):
        ids = np.argmax(np.asarray(value), axis=-1).reshape(-1)
        return f"[{self.name}] {var.name} max_id=" + \
            np.array2string(ids, threshold=64)


class SeqTextPrinter(Printer):
    """Print int id sequences, optionally mapped through a vocab
    (seq_text_printer)."""

    def __init__(self, input, id_to_word=None, **kw):
        super().__init__([input], name=kw.pop("name", "seq_text_printer"),
                         **kw)
        self.id_to_word = id_to_word

    def _format(self, var, value):
        rows = np.asarray(value).astype(np.int64)
        if rows.ndim == 1:
            rows = rows[None, :]
        rows = rows.reshape(rows.shape[0], -1)
        lines = []
        for r in rows:
            if self.id_to_word:
                lines.append(" ".join(self.id_to_word.get(int(i), "<unk>")
                                      for i in r))
            else:
                lines.append(" ".join(str(int(i)) for i in r))
        return f"[{self.name}] {var.name}\n  " + "\n  ".join(lines)


class ClassificationErrorPrinter(Printer):
    """Print per-batch classification error (classification_error_printer):
    observes (scores, label) and prints the error rate."""

    def __init__(self, input, label, **kw):
        super().__init__([input, label],
                         name=kw.pop("name", "classification_error_printer"),
                         **kw)

    def update(self, values):
        scores, label = (np.asarray(v) for v in values)
        pred = (np.argmax(scores, -1) if scores.ndim > 1 and
                scores.shape[-1] > 1 else (scores.reshape(-1) > 0.5))
        err = float((pred.reshape(-1) != label.reshape(-1)).mean())
        print(f"[{self.name}] error={err:.6f}", file=self.stream)


class EditDistance(Evaluator):
    """Streaming average edit distance (legacy ctc_error_evaluator;
    fluid edit_distance_op.cc)."""

    def __init__(self, input, label, normalized=False, **kwargs):
        super().__init__("edit_distance", **kwargs)
        self.total_dist = self._create_state("total_dist", [], "float32")
        self.total_seqs = self._create_state("total_seqs", [], "float32")
        ins = {"Hyps": [input], "Refs": [label]}
        hl, rl = get_seq_len(input), get_seq_len(label)
        if hl is not None:
            ins["HypsLength"] = [hl]
        if rl is not None:
            ins["RefsLength"] = [rl]
        outs, _ = self.helper.append_op(
            "edit_distance", ins, ["Out", "SequenceNum"],
            {"normalized": normalized})
        self.batch_dist = outs["Out"][0]
        dist_sum = self.helper.simple_op(
            "reduce_sum", {"X": [self.batch_dist]}, {"keep_dim": False})
        n = self.helper.simple_op(
            "cast", {"X": [outs["SequenceNum"][0]]}, {"dtype": "float32"})
        self._accumulate(self.total_dist, dist_sum)
        self._accumulate(self.total_seqs, n)

    def eval(self, executor, scope=None):
        dist, n = self._fetch_states(scope)
        return float(dist) / max(float(n), 1.0)

"""Gradient clipping, built into the training program as ops.

Parity with /root/reference/python/paddle/v2/fluid/clip.py:23
(GradientClipByValue, append_gradient_clip_ops) plus the legacy engine's
global-norm clipping knob (gradient_clipping_threshold in
/root/reference/proto/ParameterConfig.proto, applied by the trainer's
updaters) — expressed TPU-natively: per-grad clips append ``clip`` ops and
the global-norm clip is ONE fused ``clip_by_global_norm`` op over every
gradient at once, so the norm reduction and all the rescales compile into
the same XLA computation as the backward pass (no per-parameter host loop).

SelectedRows gradients (sparse embeddings) clip on their row values —
by-value clips elementwise, norm clips on the deduplicated rows — so
clipping never densifies a sparse gradient.
"""
from __future__ import annotations

import functools

from .layers.layer_helper import LayerHelper

__all__ = [
    "BaseGradientClipAttr", "NullGradientClipAttr", "GradientClipByValue",
    "GradientClipByNorm", "GradientClipByGlobalNorm", "ClipByValue",
    "append_gradient_clip_ops", "set_gradient_clip",
]


class BaseGradientClipAttr:
    def process_context(self, context, p_g):
        raise NotImplementedError()

    def create_operators(self, param, grad):
        raise NotImplementedError()


class NullGradientClipAttr(BaseGradientClipAttr):
    def process_context(self, context, p_g):
        pass

    def create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    """Elementwise clip to [min, max] (fluid clip.py:23 GradientClipByValue)."""

    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = -self.max if min is None else float(min)

    def process_context(self, context, p_g):
        pass

    def create_operators(self, param, grad):
        helper = LayerHelper("gradient_clip",
                             main_program=param.block.program)
        new_grad = helper.simple_op(
            "clip", {"X": [grad]}, {"min": self.min, "max": self.max})
        return param, new_grad


class GradientClipByNorm(BaseGradientClipAttr):
    """Rescale a single gradient to L2 norm <= clip_norm."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def process_context(self, context, p_g):
        pass

    def create_operators(self, param, grad):
        helper = LayerHelper("gradient_clip",
                             main_program=param.block.program)
        new_grad = helper.simple_op(
            "clip_by_norm", {"X": [grad]}, {"max_norm": self.clip_norm})
        return param, new_grad


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """Jointly rescale ALL participating gradients so the global L2 norm of
    the set is <= clip_norm. All (param, grad) pairs sharing one instance
    are clipped together by a single fused op."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)
        self._group = []
        self._clipped = None

    def process_context(self, context, p_g):
        # collect this instance's group once per append_gradient_clip_ops run
        self._group = [(p, g) for p, g in p_g
                       if getattr(p, "gradient_clip", None) is self]
        self._clipped = None

    def create_operators(self, param, grad):
        if self._clipped is None:
            helper = LayerHelper("gradient_clip",
                                 main_program=param.block.program)
            block = param.block
            grads = [g for _, g in self._group]
            out_vars = [
                block.create_var(
                    name=block.program.unique_name(g.name + "@CLIP"),
                    shape=g.shape, dtype=g.dtype, stop_gradient=True)
                for g in grads
            ]
            helper.append_op("clip_by_global_norm", {"X": grads},
                             {"Out": out_vars}, {"max_norm": self.clip_norm})
            self._clipped = {g.name: v for g, v in zip(grads, out_vars)}
        return param, self._clipped[grad.name]


ClipByValue = GradientClipByValue


def set_gradient_clip(clip, param_list=None, program=None):
    """Attach ``clip`` to every parameter in ``param_list`` (default: all
    parameters of ``program``)."""
    from .core.program import default_main_program

    if not isinstance(clip, BaseGradientClipAttr):
        raise TypeError(
            "clip should be an instance of BaseGradientClipAttr")
    program = program or default_main_program()
    if param_list is None:
        params = program.global_block.all_parameters()
    else:
        params = [program.global_block.var(p) if isinstance(p, str) else p
                  for p in param_list]
    for p in params:
        p.gradient_clip = clip


def append_gradient_clip_ops(param_grad):
    """Append clip ops per the parameters' ``gradient_clip`` attrs; returns
    the new [(param, grad)] list (fluid clip.py append_gradient_clip_ops)."""
    context = {}
    callbacks = []
    seen = set()
    for p, g in param_grad:
        clip_attr = getattr(p, "gradient_clip", None) or NullGradientClipAttr()
        if not isinstance(clip_attr, BaseGradientClipAttr):
            raise TypeError(
                "gradient_clip should be an instance of BaseGradientClipAttr")
        if id(clip_attr) not in seen:
            seen.add(id(clip_attr))
            clip_attr.process_context(context=context, p_g=param_grad)
        callbacks.append(functools.partial(
            clip_attr.create_operators, param=p, grad=g))
    return [cb() for cb in callbacks]

"""Fault-tolerant data-sharding master: C++ engine + TCP service + client.

The control plane replacing the reference's Go master
(/root/reference/go/master/service.go + client
/root/reference/python/paddle/v2/master/client.py, which loads the Go C
library via ctypes — the exact loading pattern used here for our C++
engine, paddle_tpu/native/master.cc).

Roles:
- ``Master``       — in-process engine handle (ctypes over libptmaster).
- ``MasterServer`` — one-process TCP front-end (JSON lines) so trainers on
                     other hosts share the queue; etcd discovery is replaced
                     by passing the (host, port) — on TPU pods the trainer
                     set is static (JAX coordinator), so dynamic discovery
                     buys nothing.
- ``MasterClient`` — trainer-side API: ``set_dataset``, ``get_task``,
                     ``task_finished``/``task_failed``, and
                     ``task_reader(make_reader)`` which turns the task queue
                     into an ordinary record iterator
                     (client.py:244 next_record flow).

Fault tolerance semantics match the reference: tasks time out and re-queue,
K-strikes discard (service.go:313-366), finished passes recycle, snapshots
go to a file with atomic replace and can be recovered after a master
restart (service.go:166-230).
"""
from __future__ import annotations

import ctypes
import json
import os
import socket
import socketserver
import threading
from typing import Callable, Iterable, List, Optional, Sequence

from ..native import load_library

PASS_DONE = -2
NO_TASK = -1
_DESC_BUF = 65536


class Master:
    """In-process task-queue engine (C++; thread-safe)."""

    def __init__(self, timeout_s: int = 60, max_failures: int = 3):
        self._lib = load_library("master")
        if self._lib is None:
            raise RuntimeError("no C++ toolchain; cannot build master engine")
        lib = self._lib
        lib.ptmaster_create.restype = ctypes.c_void_p
        lib.ptmaster_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.ptmaster_destroy.argtypes = [ctypes.c_void_p]
        lib.ptmaster_set_dataset.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p), ctypes.c_int]
        lib.ptmaster_get_task.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_int,
                                          ctypes.POINTER(ctypes.c_int)]
        for fn in ("task_finished", "task_failed"):
            getattr(lib, f"ptmaster_{fn}").argtypes = [ctypes.c_void_p,
                                                       ctypes.c_int,
                                                       ctypes.c_int]
        lib.ptmaster_pass.argtypes = [ctypes.c_void_p]
        lib.ptmaster_new_pass.argtypes = [ctypes.c_void_p]
        lib.ptmaster_snapshot.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ptmaster_recover.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ptmaster_counts.argtypes = [ctypes.c_void_p] + [
            ctypes.POINTER(ctypes.c_int)] * 4
        self._h = lib.ptmaster_create(timeout_s, max_failures)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.ptmaster_destroy(h)
            self._h = None

    def set_dataset(self, task_descs: Sequence[str]):
        encoded = [d.encode() for d in task_descs]
        for i, e in enumerate(encoded):
            if len(e) >= _DESC_BUF:
                # an oversized desc at the queue head would wedge get_task
                raise ValueError(
                    f"task desc {i} is {len(e)} bytes; limit is "
                    f"{_DESC_BUF - 1}")
        arr = (ctypes.c_char_p * len(encoded))(*encoded)
        self._lib.ptmaster_set_dataset(self._h, arr, len(encoded))

    def get_task(self):
        """-> (task_id, desc, epoch) | NO_TASK | PASS_DONE. The epoch must
        be echoed back to task_finished/task_failed — stale reports from a
        timed-out claim are rejected."""
        buf = ctypes.create_string_buffer(_DESC_BUF)
        epoch = ctypes.c_int()
        tid = self._lib.ptmaster_get_task(self._h, buf, _DESC_BUF,
                                          ctypes.byref(epoch))
        if tid == -3:
            raise ValueError(f"task desc exceeds {_DESC_BUF} bytes")
        if tid < 0:
            return tid
        return tid, buf.value.decode(), epoch.value

    def task_finished(self, task_id: int, epoch: int) -> bool:
        return self._lib.ptmaster_task_finished(self._h, task_id,
                                                epoch) == 0

    def task_failed(self, task_id: int, epoch: int) -> bool:
        return self._lib.ptmaster_task_failed(self._h, task_id, epoch) == 0

    def new_pass(self) -> int:
        """Recycle done tasks for the next epoch; -1 while tasks pending."""
        return self._lib.ptmaster_new_pass(self._h)

    def snapshot(self, path: str) -> bool:
        return self._lib.ptmaster_snapshot(self._h, path.encode()) == 0

    def recover(self, path: str) -> bool:
        """False on missing/corrupt/truncated snapshot (state left empty
        rather than partially loaded)."""
        return self._lib.ptmaster_recover(self._h, path.encode()) == 0

    @property
    def pass_id(self) -> int:
        return self._lib.ptmaster_pass(self._h)

    def counts(self):
        vals = [ctypes.c_int() for _ in range(4)]
        self._lib.ptmaster_counts(self._h, *[ctypes.byref(v) for v in vals])
        return {"todo": vals[0].value, "pending": vals[1].value,
                "done": vals[2].value, "discarded": vals[3].value}


# ---------------------------------------------------------------------------
# TCP service: JSON-lines request/response over the engine.
# ---------------------------------------------------------------------------
class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        # register so stop() can sever live connections: a stopped master
        # must actually be DEAD to its clients (daemon handler threads
        # would otherwise keep serving the old engine after "restart")
        with self.server.conn_lock:  # type: ignore[attr-defined]
            self.server.active_conns.add(self.connection)  # type: ignore
        try:
            self._serve()
        finally:
            with self.server.conn_lock:  # type: ignore[attr-defined]
                self.server.active_conns.discard(  # type: ignore
                    self.connection)

    def _serve(self):
        master: Master = self.server.master  # type: ignore[attr-defined]
        snapshot_path = self.server.snapshot_path  # type: ignore
        for line in self.rfile:
            try:
                req = json.loads(line)
                op = req["op"]
                mutated = False
                if op == "set_dataset":
                    master.set_dataset(req["tasks"])
                    resp = {"ok": True}
                    mutated = True
                elif op == "get_task":
                    r = master.get_task()
                    if isinstance(r, tuple):
                        resp = {"ok": True, "task_id": r[0], "desc": r[1],
                                "epoch": r[2]}
                    else:
                        resp = {"ok": True, "task_id": r}
                elif op == "task_finished":
                    resp = {"ok": master.task_finished(req["task_id"],
                                                       req.get("epoch", 0))}
                    mutated = True
                elif op == "task_failed":
                    resp = {"ok": master.task_failed(req["task_id"],
                                                     req.get("epoch", 0))}
                    mutated = True
                elif op == "new_pass":
                    resp = {"ok": True, "pass": master.new_pass()}
                    mutated = True
                elif op == "counts":
                    resp = {"ok": True, **master.counts(),
                            "pass": master.pass_id}
                else:
                    resp = {"ok": False, "error": f"unknown op {op!r}"}
            except Exception as e:  # noqa: BLE001 — service must not die
                resp = {"ok": False, "error": str(e)}
                mutated = False
            if mutated and snapshot_path:
                # Throttle: set_dataset/new_pass snapshot immediately (rare,
                # high-value); per-task mutations batch every
                # snapshot_every ops — a crash replays at most that many
                # task completions, vs O(n^2) file writes per pass.
                # (stop() flushes a final snapshot for graceful shutdown.)
                srv = self.server
                with srv.snapshot_lock:
                    if op in ("set_dataset", "new_pass"):
                        master.snapshot(snapshot_path)
                        srv.mutations_since_snapshot = 0
                    else:
                        srv.mutations_since_snapshot += 1
                        if (srv.mutations_since_snapshot
                                >= srv.snapshot_every):
                            master.snapshot(snapshot_path)
                            srv.mutations_since_snapshot = 0
            self.wfile.write((json.dumps(resp) + "\n").encode())
            self.wfile.flush()


class _ReusableTCPServer(socketserver.ThreadingTCPServer):
    # A restarted master must be able to rebind its old port immediately
    # (TIME_WAIT sockets from the dead instance's clients linger) so
    # reconnecting trainers find it at the same address.
    allow_reuse_address = True


class MasterServer:
    """Threaded TCP front-end. ``with MasterServer(...) as addr:`` or
    ``.start()``/``.stop()``."""

    def __init__(self, timeout_s=60, max_failures=3, host="127.0.0.1",
                 port=0, snapshot_path: Optional[str] = None,
                 snapshot_every: int = 32):
        self.master = Master(timeout_s, max_failures)
        if snapshot_path and os.path.exists(snapshot_path):
            self.master.recover(snapshot_path)  # master fault tolerance
        self._srv = _ReusableTCPServer((host, port), _Handler)
        self._srv.daemon_threads = True
        self._srv.master = self.master  # type: ignore[attr-defined]
        self._srv.snapshot_path = snapshot_path  # type: ignore
        self._srv.snapshot_every = snapshot_every  # type: ignore
        self._srv.mutations_since_snapshot = 0  # type: ignore
        self._srv.snapshot_lock = threading.Lock()  # type: ignore
        self._srv.active_conns = set()  # type: ignore
        self._srv.conn_lock = threading.Lock()  # type: ignore
        self._snapshot_path = snapshot_path
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self):
        return self._srv.server_address

    def start(self):
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.address

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
        # sever live client connections: their next RPC fails like a real
        # master death, and a reconnect-retrying client finds the
        # replacement instead of a ghost handler thread on the old engine
        with self._srv.conn_lock:  # type: ignore[attr-defined]
            for conn in list(self._srv.active_conns):  # type: ignore
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
            self._srv.active_conns.clear()  # type: ignore[attr-defined]
        if self._snapshot_path:
            # daemon handler threads may still be mid-request: take the same
            # lock they use so the final flush cannot interleave with theirs
            with self._srv.snapshot_lock:  # type: ignore[attr-defined]
                self.master.snapshot(self._snapshot_path)

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()


class MasterClient:
    """Trainer-side client (reference client.py API shape), with the Go
    client's reconnect-and-retry transport semantics: a dropped socket, a
    refused connect (master restarting), or a torn response triggers an
    exponential-backoff reconnect through a
    :class:`paddle_tpu.resilience.Retry` policy instead of killing the
    trainer. Safe because the protocol is effectively idempotent: a
    re-sent ``task_finished``/``task_failed`` with its epoch is rejected
    as stale, and a ``get_task`` whose response was lost just leaves a
    claim to expire back into the queue (service.go timeout semantics).
    Pass ``retry=False`` for the old fail-fast behavior, or your own
    policy via ``retry=Retry(...)``.
    """

    def __init__(self, addr, retry=None):
        self.addr = tuple(addr)
        if retry is None:
            from ..resilience import Retry

            retry = Retry(max_attempts=8, backoff=0.05, multiplier=2.0,
                          max_backoff=1.0, name="master/rpc")
        self._retry = retry or None  # retry=False disables
        self._sock = None
        self._f = None
        self._ncalls = 0
        if self._retry is not None:
            self._retry.call(self._connect)
        else:
            self._connect()

    def _connect(self):
        self._teardown()
        self._sock = socket.create_connection(self.addr)
        self._f = self._sock.makefile("rwb")

    def _teardown(self):
        for obj in (self._f, self._sock):
            if obj is not None:
                try:
                    obj.close()
                except OSError:
                    pass
        self._f = self._sock = None

    def _call_once(self, req, call_idx):
        from ..resilience import faults

        plan = faults.active_plan()
        if plan is not None \
                and plan.fire("master_drop", call_idx) is not None:
            # injected connection drop: this attempt fails like a real
            # mid-RPC disconnect; the retry policy (or the caller's next
            # call) reconnects
            self._teardown()
            raise ConnectionError("master connection dropped (injected)")
        if self._sock is None:
            self._connect()
        try:
            self._f.write((json.dumps(req) + "\n").encode())
            self._f.flush()
            line = self._f.readline()
        except OSError as exc:
            self._teardown()
            raise ConnectionError(f"master connection lost: {exc}") from exc
        if not line:
            self._teardown()
            raise ConnectionError("master closed the connection")
        try:
            resp = json.loads(line)
        except ValueError as exc:  # torn mid-line response
            self._teardown()
            raise ConnectionError(
                f"torn response from master: {exc}") from exc
        if not resp.get("ok", False) and "error" in resp:
            # an application-level error is NOT retryable: the request
            # reached the engine and was rejected
            raise RuntimeError(f"master error: {resp['error']}")
        return resp

    def _call(self, **req):
        self._ncalls += 1
        call_idx = self._ncalls
        if self._retry is not None:
            return self._retry.call(self._call_once, req, call_idx)
        return self._call_once(req, call_idx)

    def set_dataset(self, tasks: Sequence[str]):
        self._call(op="set_dataset", tasks=list(tasks))

    def get_task(self):
        resp = self._call(op="get_task")
        tid = resp["task_id"]
        if tid < 0:
            return tid
        return tid, resp["desc"], resp.get("epoch", 0)

    def task_finished(self, task_id: int, epoch: int = 0) -> bool:
        return bool(self._call(op="task_finished", task_id=task_id,
                               epoch=epoch)["ok"])

    def task_failed(self, task_id: int, epoch: int = 0) -> bool:
        return bool(self._call(op="task_failed", task_id=task_id,
                               epoch=epoch)["ok"])

    def new_pass(self) -> int:
        return self._call(op="new_pass")["pass"]

    def counts(self):
        return self._call(op="counts")

    def close(self):
        self._teardown()

    def task_reader(self, make_reader: Callable[[str], Iterable],
                    stop_after_pass: bool = True):
        """Records iterator over master-assigned tasks: pull a task, stream
        its records (``make_reader(desc)``), report finished; report failed
        and continue if the reader raises. Ends when the pass completes."""

        def reader():
            while True:
                t = self.get_task()
                if t == PASS_DONE:
                    return  # epoch complete; caller may new_pass() + re-iter
                if t == NO_TASK:
                    # other trainers still hold pending tasks
                    import time as _t

                    _t.sleep(0.05)
                    continue
                tid, desc, epoch = t
                try:
                    for rec in make_reader(desc):
                        yield rec
                except Exception:  # noqa: BLE001 — task retry semantics
                    self.task_failed(tid, epoch)
                    continue
                self.task_finished(tid, epoch)

        # resume contract (trainer.SGD checkpoint auto-resume): the
        # master already tracks consumed tasks, so a resumed trainer must
        # NOT also skip batches from this stream
        reader.master_backed = True
        return reader

"""Fault-tolerant data-sharding master: C++ engine + TCP service + client.

The control plane replacing the reference's Go master
(/root/reference/go/master/service.go + client
/root/reference/python/paddle/v2/master/client.py, which loads the Go C
library via ctypes — the exact loading pattern used here for our C++
engine, paddle_tpu/native/master.cc).

Roles:
- ``Master``       — in-process engine handle (ctypes over libptmaster).
- ``MasterServer`` — one-process TCP front-end (JSON lines) so trainers on
                     other hosts share the queue; etcd discovery is replaced
                     by passing the (host, port) — on TPU pods the trainer
                     set is static (JAX coordinator), so dynamic discovery
                     buys nothing.
- ``MasterClient`` — trainer-side API: ``set_dataset``, ``get_task``,
                     ``task_finished``/``task_failed``, and
                     ``task_reader(make_reader)`` which turns the task queue
                     into an ordinary record iterator
                     (client.py:244 next_record flow).

Fault tolerance semantics match the reference: tasks time out and re-queue,
K-strikes discard (service.go:313-366), finished passes recycle, snapshots
go to a file with atomic replace and can be recovered after a master
restart (service.go:166-230).

Elastic multi-trainer training adds a *lease plane* on top of the task
queue (the analogue of the reference's etcd-leased task ownership):
trainers ``register_trainer(trainer_id)`` for a monotonically increasing
**fencing token** and a lease they renew implicitly on every call (or
explicitly via ``heartbeat``). A lease that expires — or a re-registration
of the same trainer id (the preempted host's reincarnation) — *fences*
the old token: the fenced trainer's claims are requeued at the FRONT of
the queue (no failure strike — losing a lease is not the task's fault,
and front placement keeps the effective task order stable for
checkpoint-lineage-consistent resume), and every later report carrying
the stale token is rejected and counted (``zombie_acks_rejected``) — a
zombie that wakes up after a long GC pause can neither ack a task it no
longer owns nor double-count a batch. Token monotonicity survives master
restarts via a tokens sidecar next to the snapshot.
"""
from __future__ import annotations

import ctypes
import json
import os
import socket
import socketserver
import threading
import time
from collections import deque
from typing import Callable, Iterable, List, Optional, Sequence

from ..native import load_library

PASS_DONE = -2
NO_TASK = -1
_DESC_BUF = 65536

#: ``task_status`` engine codes -> names
TASK_STATES = {0: "todo", 1: "pending", 2: "done", 3: "discarded"}


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile of an ascending list (empty -> 0.0)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class FencedTokenError(RuntimeError):
    """The caller's fencing token is stale: its lease expired (or its
    trainer id re-registered) and the master requeued its claims. The
    trainer must re-register — a fresh token — and roll its state back
    to the newest durable checkpoint generation before continuing.
    Deliberately NOT retryable: retrying the same RPC with the same
    token can never succeed."""


def snapshot_durable(master: "Master", path: str) -> bool:
    """Atomic + durable snapshot rotation: the engine writes ``path.new``
    (itself tmp+rename), the file is fsync'd, the previous snapshot is
    rotated to ``path.prev``, and ``path.new`` renames into place — so a
    crash at ANY point leaves either the new or the previous snapshot
    intact on disk, never only a torn file that ``recover()`` silently
    drops."""
    new = path + ".new"
    if not master.snapshot(new):
        return False
    try:
        with open(new, "rb") as f:
            os.fsync(f.fileno())
        if os.path.exists(path):
            os.replace(path, path + ".prev")
        os.replace(new, path)
        dfd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                      os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        return False
    return True


def recover_durable(master: "Master", path: str) -> Optional[str]:
    """Recover from ``path``, walking back to ``path.prev`` when the
    latest snapshot is missing/truncated/corrupt (the crash-mid-rotation
    case). Returns the file that recovered, or None."""
    for cand in (path, path + ".prev"):
        if os.path.exists(cand) and master.recover(cand):
            if cand != path:
                from .. import profiler

                profiler.global_stat.add_count(
                    "master/snapshot_fallbacks", 1)
            return cand
    return None


class Master:
    """In-process task-queue engine (C++; thread-safe) plus the Python
    lease/fencing plane (monotonic trainer tokens, lease-expiry requeue,
    zombie-report rejection) layered over it."""

    #: straggler verdict: a trainer whose recent-mean step wall exceeds
    #: ``straggler_skew`` x the cross-trainer median (with at least
    #: ``straggler_min_trainers`` trainers reporting telemetry).  The
    #: quorum is 3: with only two samples the nearest-rank median IS the
    #: faster trainer, so any natural 2x spread between two healthy
    #: trainers would read as skew
    STRAGGLER_SKEW = 2.0
    STRAGGLER_MIN_TRAINERS = 3

    def __init__(self, timeout_s: int = 60, max_failures: int = 3,
                 token_path: Optional[str] = None, now_fn=None,
                 straggler_skew: Optional[float] = None):
        self._lib = load_library("master")
        if self._lib is None:
            raise RuntimeError("no C++ toolchain; cannot build master engine")
        lib = self._lib
        lib.ptmaster_create.restype = ctypes.c_void_p
        lib.ptmaster_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.ptmaster_destroy.argtypes = [ctypes.c_void_p]
        lib.ptmaster_set_dataset.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p), ctypes.c_int]
        lib.ptmaster_get_task.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_int,
                                          ctypes.POINTER(ctypes.c_int)]
        for fn in ("task_finished", "task_failed"):
            getattr(lib, f"ptmaster_{fn}").argtypes = [ctypes.c_void_p,
                                                       ctypes.c_int,
                                                       ctypes.c_int]
        lib.ptmaster_requeue.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                         ctypes.c_int, ctypes.c_int]
        lib.ptmaster_touch.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_int]
        lib.ptmaster_task_status.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptmaster_pass.argtypes = [ctypes.c_void_p]
        lib.ptmaster_new_pass.argtypes = [ctypes.c_void_p]
        lib.ptmaster_snapshot.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ptmaster_recover.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ptmaster_counts.argtypes = [ctypes.c_void_p] + [
            ctypes.POINTER(ctypes.c_int)] * 4
        self._h = lib.ptmaster_create(timeout_s, max_failures)
        # ---- lease plane (Python-side; engine stays policy-free) ----
        self._now = now_fn or time.monotonic
        self._lease_lock = threading.Lock()
        self._leases: dict = {}   # trainer_id -> {token, deadline, lease_s}
        self._token_owner: dict = {}   # token -> trainer_id (ever issued)
        self._fenced: set = set()      # tokens no longer valid
        self._claims: dict = {}   # task_id -> (token, epoch, claim_seq)
        self._claim_seq = 0
        self._next_token = 1
        self.lease_expired_total = 0
        self.zombie_acks_rejected = 0
        # ---- straggler plane: per-trainer step-time digests fed by ----
        # ---- heartbeat telemetry, skew-checked on every beat        ----
        self.straggler_skew = float(straggler_skew
                                    if straggler_skew is not None
                                    else self.STRAGGLER_SKEW)
        self._telemetry: dict = {}   # trainer_id -> digest dict
        self._stragglers: set = set()    # currently-flagged trainer ids
        self.stragglers_detected_total = 0
        self.token_path = token_path
        if token_path and os.path.exists(token_path):
            try:
                with open(token_path) as f:
                    self._next_token = max(
                        self._next_token, int(json.load(f)["next_token"]))
            except (OSError, ValueError, KeyError):
                pass  # corrupt sidecar: tokens restart (documented risk)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.ptmaster_destroy(h)
            self._h = None

    def set_dataset(self, task_descs: Sequence[str]):
        encoded = [d.encode() for d in task_descs]
        for i, e in enumerate(encoded):
            if len(e) >= _DESC_BUF:
                # an oversized desc at the queue head would wedge get_task
                raise ValueError(
                    f"task desc {i} is {len(e)} bytes; limit is "
                    f"{_DESC_BUF - 1}")
        arr = (ctypes.c_char_p * len(encoded))(*encoded)
        self._lib.ptmaster_set_dataset(self._h, arr, len(encoded))

    # -- lease plane ----------------------------------------------------
    def _persist_tokens_locked(self) -> None:
        if not self.token_path:
            return
        tmp = self.token_path + f".tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump({"next_token": self._next_token}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.token_path)
        except OSError:
            pass  # best effort: in-memory monotonicity still holds

    def _fence_locked(self, trainer_id: str, reason: str) -> None:
        lease = self._leases.pop(trainer_id, None)
        if lease is None:
            return
        token = lease["token"]
        self._fenced.add(token)
        if reason == "expired":
            self.lease_expired_total += 1
        from .. import profiler, trace

        profiler.global_stat.add_count("master/lease_expired", 1)
        t = time.perf_counter()
        trace.record("master/lease_expired", t, t, trainer=trainer_id,
                     token=token, reason=reason)
        # requeue the fenced token's claims at the queue FRONT, earliest
        # claim first (reverse-seq front pushes), with no failure strike
        owned = sorted((c for c in self._claims.items()
                        if c[1][0] == token),
                       key=lambda c: c[1][2], reverse=True)
        for task_id, (_, epoch, _seq) in owned:
            self._lib.ptmaster_requeue(self._h, task_id, epoch, 1)
            del self._claims[task_id]

    def _check_leases_locked(self) -> None:
        now = self._now()
        for tid in [t for t, l in self._leases.items()
                    if l["deadline"] <= now]:
            self._fence_locked(tid, "expired")

    def _renew_locked(self, token: int) -> str:
        """Validate + renew the lease owning ``token``; raises
        FencedTokenError on a stale/unknown token."""
        trainer_id = self._token_owner.get(token)
        lease = self._leases.get(trainer_id) if trainer_id else None
        if lease is None or lease["token"] != token:
            raise FencedTokenError(
                f"fencing token {token} is stale (lease expired or "
                f"trainer re-registered); re-register for a fresh token")
        lease["deadline"] = self._now() + lease["lease_s"]
        return trainer_id

    def register_trainer(self, trainer_id: str,
                         lease_s: float = 30.0) -> int:
        """Grant ``trainer_id`` a lease and a fresh monotonic fencing
        token. Re-registering a live trainer id fences its previous
        token first (the preempted host's reincarnation must never race
        its own zombie)."""
        with self._lease_lock:
            self._check_leases_locked()
            if trainer_id in self._leases:
                self._fence_locked(trainer_id, "re-registered")
            token = self._next_token
            self._next_token += 1
            self._persist_tokens_locked()
            self._leases[trainer_id] = {
                "token": token, "lease_s": float(lease_s),
                "deadline": self._now() + float(lease_s)}
            self._token_owner[token] = trainer_id
            from .. import profiler

            profiler.global_stat.add_count("master/trainer_registered", 1)
            return token

    def heartbeat(self, token: int, telemetry: Optional[dict] = None) -> bool:
        """Renew ``token``'s lease and the engine deadlines of its
        claims; False when the token is fenced (the caller must
        re-register). ``telemetry`` (optional: ``step_wall_s``,
        ``steps``, ``goodput``, ``mfu``) feeds the per-trainer
        step-time digest the straggler plane skew-checks on every
        beat."""
        with self._lease_lock:
            self._check_leases_locked()
            try:
                trainer_id = self._renew_locked(token)
            except FencedTokenError:
                return False
            for task_id, (tok, epoch, _seq) in list(self._claims.items()):
                if tok == token:
                    self._lib.ptmaster_touch(self._h, task_id, epoch)
            if telemetry:
                self._note_telemetry_locked(trainer_id, telemetry)
            return True

    # -- straggler plane ------------------------------------------------
    def _note_telemetry_locked(self, trainer_id: str,
                               telemetry: dict) -> None:
        d = self._telemetry.setdefault(
            trainer_id, {"walls": deque(maxlen=32), "steps": 0,
                         "goodput": None, "mfu": None, "beats": 0})
        d["beats"] += 1
        wall = telemetry.get("step_wall_s")
        if wall is not None and float(wall) > 0:
            d["walls"].append(float(wall))
        for key in ("steps", "goodput", "mfu"):
            if telemetry.get(key) is not None:
                d[key] = telemetry[key]
        self._check_stragglers_locked()

    def _check_stragglers_locked(self) -> None:
        """Skew check over the per-trainer recent-mean step walls: a
        trainer running ``straggler_skew`` x slower than the
        cross-trainer median is flagged (trace record + counter at
        onset, cleared when it catches back up)."""
        means = {tid: sum(d["walls"]) / len(d["walls"])
                 for tid, d in self._telemetry.items()
                 if d["walls"] and tid in self._leases}
        if len(means) < self.STRAGGLER_MIN_TRAINERS:
            return
        vals = sorted(means.values())
        p50 = _percentile(vals, 0.50)
        if p50 <= 0:
            return
        flagged = {tid for tid, mean in means.items()
                   if mean > self.straggler_skew * p50}
        for tid in flagged - self._stragglers:
            self.stragglers_detected_total += 1
            from .. import profiler, trace

            profiler.global_stat.add_count("master/straggler_detected", 1)
            t = time.perf_counter()
            trace.record("master/straggler_detected", t, t, trainer=tid,
                         mean_step_s=round(means[tid], 6),
                         p50_step_s=round(p50, 6),
                         skew=round(means[tid] / p50, 3))
        self._stragglers = flagged

    def train_status(self) -> dict:
        """The training-fleet aggregate the straggler plane exports:
        per-trainer digests (recent-mean step wall, steps, goodput,
        MFU), the cross-trainer p50/p99 step-time skew, and the
        currently-flagged stragglers."""
        with self._lease_lock:
            self._check_leases_locked()
            trainers = {}
            means = []
            for tid, d in self._telemetry.items():
                mean = (sum(d["walls"]) / len(d["walls"])
                        if d["walls"] else None)
                active = tid in self._leases
                if mean is not None and active:
                    means.append(mean)
                trainers[tid] = {
                    "step_seconds": (round(mean, 6)
                                     if mean is not None else None),
                    "steps": d["steps"], "goodput": d["goodput"],
                    "mfu": d["mfu"], "active": active,
                    "straggler": tid in self._stragglers,
                }
            means.sort()
            p50 = _percentile(means, 0.50) if means else None
            p99 = _percentile(means, 0.99) if means else None
            goodputs = [t["goodput"] for t in trainers.values()
                        if t["active"] and t["goodput"] is not None]
            mfus = [t["mfu"] for t in trainers.values()
                    if t["active"] and t["mfu"] is not None]
            return {
                "trainers": trainers,
                "step_seconds_p50": (round(p50, 6)
                                     if p50 is not None else None),
                "step_seconds_p99": (round(p99, 6)
                                     if p99 is not None else None),
                "skew": (round(p99 / p50, 3)
                         if p50 and p99 is not None else None),
                "goodput": (round(sum(goodputs) / len(goodputs), 4)
                            if goodputs else None),
                "mfu": (round(sum(mfus) / len(mfus), 6)
                        if mfus else None),
                "stragglers": sorted(self._stragglers),
                "stragglers_detected_total":
                    self.stragglers_detected_total,
            }

    def token_active(self, token: int) -> bool:
        with self._lease_lock:
            self._check_leases_locked()
            trainer_id = self._token_owner.get(token)
            lease = self._leases.get(trainer_id) if trainer_id else None
            return lease is not None and lease["token"] == token

    def expire_trainer(self, trainer_id: str) -> bool:
        """Administratively revoke a trainer's lease NOW (operator evict;
        also how chaos tests simulate a network partition outliving the
        lease without wall-clock sleeps)."""
        with self._lease_lock:
            if trainer_id not in self._leases:
                return False
            self._fence_locked(trainer_id, "expired")
            return True

    def lease_state(self) -> dict:
        """Operator view of the lease plane."""
        with self._lease_lock:
            self._check_leases_locked()
            now = self._now()
            return {
                "trainers": {
                    tid: {"token": l["token"],
                          "expires_in_s": round(l["deadline"] - now, 3)}
                    for tid, l in self._leases.items()},
                "next_token": self._next_token,
                "lease_expired_total": self.lease_expired_total,
                "zombie_acks_rejected": self.zombie_acks_rejected,
            }

    def _reject_zombie(self, op: str, task_id: int, token: int) -> None:
        self.zombie_acks_rejected += 1
        from .. import profiler, trace

        profiler.global_stat.add_count("master/zombie_acks_rejected", 1)
        t = time.perf_counter()
        trace.record("master/zombie_ack_rejected", t, t, op=op,
                     task_id=task_id, token=token)

    # -- task queue (token-aware) --------------------------------------
    def get_task(self, token: Optional[int] = None):
        """-> (task_id, desc, epoch) | NO_TASK | PASS_DONE. The epoch must
        be echoed back to task_finished/task_failed — stale reports from a
        timed-out claim are rejected. With ``token`` the claim is
        lease-owned: expiry requeues it (front) and fences later reports;
        a stale token raises :class:`FencedTokenError`."""
        if token is not None:
            with self._lease_lock:
                self._check_leases_locked()
                self._renew_locked(token)
        buf = ctypes.create_string_buffer(_DESC_BUF)
        epoch = ctypes.c_int()
        tid = self._lib.ptmaster_get_task(self._h, buf, _DESC_BUF,
                                          ctypes.byref(epoch))
        if tid == -3:
            raise ValueError(f"task desc exceeds {_DESC_BUF} bytes")
        if tid < 0:
            return tid
        if token is not None:
            with self._lease_lock:
                self._claim_seq += 1
                self._claims[tid] = (token, epoch.value, self._claim_seq)
        return tid, buf.value.decode(), epoch.value

    def _report(self, op: str, engine_fn, task_id: int, epoch: int,
                token: Optional[int]) -> bool:
        """Shared fencing guard + engine call for task_finished/
        task_failed. The tokened path holds the lease lock across check
        AND engine call, so a fence can never interleave between the
        two (lock order is always lease lock -> engine mutex)."""
        if token is None:
            return engine_fn(self._h, task_id, epoch) == 0
        with self._lease_lock:
            self._check_leases_locked()
            try:
                self._renew_locked(token)
            except FencedTokenError:
                self._reject_zombie(op, task_id, token)
                return False
            claim = self._claims.get(task_id)
            if claim is not None and claim[0] != token:
                # the task was requeued and is now owned by a NEWER
                # claim: this caller's lease is alive but its claim is
                # gone — a zombie report all the same
                self._reject_zombie(op, task_id, token)
                return False
            ok = engine_fn(self._h, task_id, epoch) == 0
            if ok:
                self._claims.pop(task_id, None)
            return ok

    def task_finished(self, task_id: int, epoch: int,
                      token: Optional[int] = None) -> bool:
        return self._report("task_finished",
                            self._lib.ptmaster_task_finished,
                            task_id, epoch, token)

    def task_failed(self, task_id: int, epoch: int,
                    token: Optional[int] = None) -> bool:
        return self._report("task_failed", self._lib.ptmaster_task_failed,
                            task_id, epoch, token)

    def task_status(self, task_id: int) -> Optional[str]:
        """'todo' | 'pending' | 'done' | 'discarded' | None — the
        queue-state probe lineage-consistency checks use."""
        return TASK_STATES.get(
            self._lib.ptmaster_task_status(self._h, task_id))

    def new_pass(self) -> int:
        """Recycle done tasks for the next epoch; -1 while tasks pending."""
        return self._lib.ptmaster_new_pass(self._h)

    def snapshot(self, path: str) -> bool:
        return self._lib.ptmaster_snapshot(self._h, path.encode()) == 0

    def recover(self, path: str) -> bool:
        """False on missing/corrupt/truncated snapshot (state left empty
        rather than partially loaded)."""
        return self._lib.ptmaster_recover(self._h, path.encode()) == 0

    @property
    def pass_id(self) -> int:
        return self._lib.ptmaster_pass(self._h)

    def counts(self):
        with self._lease_lock:
            self._check_leases_locked()
            trainers_active = len(self._leases)
            lease_expired = self.lease_expired_total
            zombies = self.zombie_acks_rejected
        vals = [ctypes.c_int() for _ in range(4)]
        self._lib.ptmaster_counts(self._h, *[ctypes.byref(v) for v in vals])
        return {"todo": vals[0].value, "pending": vals[1].value,
                "done": vals[2].value, "discarded": vals[3].value,
                "trainers_active": trainers_active,
                "lease_expired_total": lease_expired,
                "zombie_acks_rejected": zombies}

    def prometheus_text(self) -> str:
        """The master's queue + lease plane as Prometheus gauges (served
        by ``MasterServer`` op ``metrics``; scrape-ready text), plus the
        straggler plane's labeled per-trainer series
        (``trainer_step_seconds{trainer=...}``, goodput fraction, MFU)
        and the ``master_straggler`` gauge."""
        c = self.counts()
        ts = self.train_status()
        names = {
            "master_tasks_todo": c["todo"],
            "master_tasks_pending": c["pending"],
            "master_tasks_done": c["done"],
            "master_tasks_discarded": c["discarded"],
            "master_pass": self.pass_id,
            "master_trainers_active": c["trainers_active"],
            "master_lease_expired_total": c["lease_expired_total"],
            "master_zombie_acks_rejected": c["zombie_acks_rejected"],
            "master_straggler": len(ts["stragglers"]),
            "master_stragglers_detected_total":
                ts["stragglers_detected_total"],
        }
        lines = []
        for name, value in names.items():
            kind = "counter" if name.endswith(("_total", "_rejected")) \
                else "gauge"
            lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name} {value}")
        labeled = {"trainer_step_seconds": "step_seconds",
                   "trainer_goodput_fraction": "goodput",
                   "trainer_mfu": "mfu",
                   "trainer_straggler": "straggler"}
        for metric, key in labeled.items():
            rows = []
            for tid, t in sorted(ts["trainers"].items()):
                val = t.get(key)
                if key == "straggler":
                    val = 1 if val else 0
                if val is None:
                    continue
                rows.append(f'{metric}{{trainer="{tid}"}} {val}')
            if rows:
                lines.append(f"# TYPE {metric} gauge")
                lines.extend(rows)
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# TCP service: JSON-lines request/response over the engine.
# ---------------------------------------------------------------------------
class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        # register so stop() can sever live connections: a stopped master
        # must actually be DEAD to its clients (daemon handler threads
        # would otherwise keep serving the old engine after "restart")
        with self.server.conn_lock:  # type: ignore[attr-defined]
            self.server.active_conns.add(self.connection)  # type: ignore
        try:
            self._serve()
        finally:
            with self.server.conn_lock:  # type: ignore[attr-defined]
                self.server.active_conns.discard(  # type: ignore
                    self.connection)

    def _serve(self):
        master: Master = self.server.master  # type: ignore[attr-defined]
        snapshot_path = self.server.snapshot_path  # type: ignore
        for line in self.rfile:
            try:
                req = json.loads(line)
                op = req["op"]
                token = req.get("token")
                mutated = False
                if op == "set_dataset":
                    master.set_dataset(req["tasks"])
                    resp = {"ok": True}
                    mutated = True
                elif op == "get_task":
                    r = master.get_task(token=token)
                    if isinstance(r, tuple):
                        resp = {"ok": True, "task_id": r[0], "desc": r[1],
                                "epoch": r[2]}
                    else:
                        resp = {"ok": True, "task_id": r}
                elif op == "task_finished":
                    resp = {"ok": master.task_finished(
                        req["task_id"], req.get("epoch", 0), token=token)}
                    mutated = True
                elif op == "task_failed":
                    resp = {"ok": master.task_failed(
                        req["task_id"], req.get("epoch", 0), token=token)}
                    mutated = True
                elif op == "new_pass":
                    resp = {"ok": True, "pass": master.new_pass()}
                    mutated = True
                elif op == "counts":
                    resp = {"ok": True, **master.counts(),
                            "pass": master.pass_id}
                elif op == "register_trainer":
                    resp = {"ok": True, "token": master.register_trainer(
                        req["trainer_id"],
                        lease_s=float(req.get("lease_s") or 30.0))}
                    mutated = True
                elif op == "heartbeat":
                    resp = {"ok": True, "alive": master.heartbeat(
                        token, telemetry=req.get("telemetry"))}
                elif op == "expire_trainer":
                    resp = {"ok": True, "expired": master.expire_trainer(
                        req["trainer_id"])}
                    mutated = True
                elif op == "lease_state":
                    resp = {"ok": True, "leases": master.lease_state()}
                elif op == "task_status":
                    resp = {"ok": True,
                            "status": master.task_status(req["task_id"])}
                elif op == "metrics":
                    resp = {"ok": True, "text": master.prometheus_text()}
                elif op == "train_status":
                    resp = {"ok": True, "train": master.train_status()}
                else:
                    resp = {"ok": False, "error": f"unknown op {op!r}"}
            except FencedTokenError as e:
                # typed for the client: NOT retryable, the trainer must
                # re-register and roll back
                resp = {"ok": False, "fenced": True, "error": str(e)}
                mutated = False
            except Exception as e:  # noqa: BLE001 — service must not die
                resp = {"ok": False, "error": str(e)}
                mutated = False
            if mutated and snapshot_path:
                # Throttle: set_dataset/new_pass snapshot immediately (rare,
                # high-value); per-task mutations batch every
                # snapshot_every ops — a crash replays at most that many
                # task completions, vs O(n^2) file writes per pass.
                # (stop() flushes a final snapshot for graceful shutdown.)
                srv = self.server
                with srv.snapshot_lock:
                    if op in ("set_dataset", "new_pass",
                              "register_trainer", "expire_trainer"):
                        snapshot_durable(master, snapshot_path)
                        srv.mutations_since_snapshot = 0
                    else:
                        srv.mutations_since_snapshot += 1
                        if (srv.mutations_since_snapshot
                                >= srv.snapshot_every):
                            snapshot_durable(master, snapshot_path)
                            srv.mutations_since_snapshot = 0
            self.wfile.write((json.dumps(resp) + "\n").encode())
            self.wfile.flush()


class _ReusableTCPServer(socketserver.ThreadingTCPServer):
    # A restarted master must be able to rebind its old port immediately
    # (TIME_WAIT sockets from the dead instance's clients linger) so
    # reconnecting trainers find it at the same address.
    allow_reuse_address = True


class MasterServer:
    """Threaded TCP front-end. ``with MasterServer(...) as addr:`` or
    ``.start()``/``.stop()``."""

    def __init__(self, timeout_s=60, max_failures=3, host="127.0.0.1",
                 port=0, snapshot_path: Optional[str] = None,
                 snapshot_every: int = 32):
        # the tokens sidecar keeps fencing monotonic across master
        # restarts: a zombie from before the restart must still rank
        # below every token the reborn master grants
        self.master = Master(
            timeout_s, max_failures,
            token_path=snapshot_path + ".tokens" if snapshot_path else None)
        if snapshot_path:
            # recover the latest intact snapshot, walking back to .prev
            # when the latest is truncated/corrupt (crash mid-rotation)
            recover_durable(self.master, snapshot_path)
        self._srv = _ReusableTCPServer((host, port), _Handler)
        self._srv.daemon_threads = True
        self._srv.master = self.master  # type: ignore[attr-defined]
        self._srv.snapshot_path = snapshot_path  # type: ignore
        self._srv.snapshot_every = snapshot_every  # type: ignore
        self._srv.mutations_since_snapshot = 0  # type: ignore
        self._srv.snapshot_lock = threading.Lock()  # type: ignore
        self._srv.active_conns = set()  # type: ignore
        self._srv.conn_lock = threading.Lock()  # type: ignore
        self._snapshot_path = snapshot_path
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self):
        return self._srv.server_address

    def start(self):
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.address

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
        # sever live client connections: their next RPC fails like a real
        # master death, and a reconnect-retrying client finds the
        # replacement instead of a ghost handler thread on the old engine
        with self._srv.conn_lock:  # type: ignore[attr-defined]
            for conn in list(self._srv.active_conns):  # type: ignore
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
            self._srv.active_conns.clear()  # type: ignore[attr-defined]
        if self._snapshot_path:
            # daemon handler threads may still be mid-request: take the same
            # lock they use so the final flush cannot interleave with theirs
            with self._srv.snapshot_lock:  # type: ignore[attr-defined]
                snapshot_durable(self.master, self._snapshot_path)

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()


class MasterClient:
    """Trainer-side client (reference client.py API shape), with the Go
    client's reconnect-and-retry transport semantics: a dropped socket, a
    refused connect (master restarting), or a torn response triggers an
    exponential-backoff reconnect through a
    :class:`paddle_tpu.resilience.Retry` policy instead of killing the
    trainer. Safe because the protocol is effectively idempotent: a
    re-sent ``task_finished``/``task_failed`` with its epoch is rejected
    as stale, and a ``get_task`` whose response was lost just leaves a
    claim to expire back into the queue (service.go timeout semantics).
    Pass ``retry=False`` for the old fail-fast behavior, or your own
    policy via ``retry=Retry(...)``.
    """

    def __init__(self, addr, retry=None):
        self.addr = tuple(addr)
        if retry is None:
            from ..resilience import Retry

            retry = Retry(max_attempts=8, backoff=0.05, multiplier=2.0,
                          max_backoff=1.0, name="master/rpc")
        self._retry = retry or None  # retry=False disables
        self._sock = None
        self._f = None
        self._ncalls = 0
        self.token: Optional[int] = None       # set by register()
        self.trainer_id: Optional[str] = None
        self.lease_s: Optional[float] = None
        if self._retry is not None:
            self._retry.call(self._connect)
        else:
            self._connect()

    def _connect(self):
        self._teardown()
        self._sock = socket.create_connection(self.addr)
        self._f = self._sock.makefile("rwb")

    def _teardown(self):
        for obj in (self._f, self._sock):
            if obj is not None:
                try:
                    obj.close()
                except OSError:
                    pass
        self._f = self._sock = None

    def _call_once(self, req, call_idx):
        from ..resilience import faults

        plan = faults.active_plan()
        if plan is not None \
                and plan.fire("master_drop", call_idx) is not None:
            # injected connection drop: this attempt fails like a real
            # mid-RPC disconnect; the retry policy (or the caller's next
            # call) reconnects
            self._teardown()
            raise ConnectionError("master connection dropped (injected)")
        if plan is not None and self.trainer_id is not None \
                and plan.fire("master_partition", call_idx) is not None:
            # injected partition outliving the lease: the master fences
            # us while we are "away" (simulated via an admin expire on a
            # side connection), then this attempt dies like a network
            # drop — the reconnecting client's next tokened call finds
            # its token stale and raises FencedTokenError
            self._expire_self()
            self._teardown()
            raise ConnectionError(
                "master partition (injected): lease expired while away")
        if self._sock is None:
            self._connect()
        try:
            self._f.write((json.dumps(req) + "\n").encode())
            self._f.flush()
            line = self._f.readline()
        except OSError as exc:
            self._teardown()
            raise ConnectionError(f"master connection lost: {exc}") from exc
        if not line:
            self._teardown()
            raise ConnectionError("master closed the connection")
        try:
            resp = json.loads(line)
        except ValueError as exc:  # torn mid-line response
            self._teardown()
            raise ConnectionError(
                f"torn response from master: {exc}") from exc
        if resp.get("fenced"):
            # typed so callers can rejoin (re-register + roll back to the
            # newest durable generation) instead of dying on RuntimeError
            raise FencedTokenError(resp.get("error",
                                            "fencing token is stale"))
        if not resp.get("ok", False) and "error" in resp:
            # an application-level error is NOT retryable: the request
            # reached the engine and was rejected
            raise RuntimeError(f"master error: {resp['error']}")
        return resp

    def _expire_self(self):
        """Fault-injection helper: expire our own lease server-side over
        a throwaway connection (the master-side effect of a partition
        that outlives the lease)."""
        if self.trainer_id is None:
            return
        try:
            with socket.create_connection(self.addr, timeout=5.0) as s:
                f = s.makefile("rwb")
                f.write((json.dumps({"op": "expire_trainer",
                                     "trainer_id": self.trainer_id})
                         + "\n").encode())
                f.flush()
                f.readline()
        except OSError:
            pass

    def _call(self, **req):
        self._ncalls += 1
        call_idx = self._ncalls
        if self._retry is not None:
            return self._retry.call(self._call_once, req, call_idx)
        return self._call_once(req, call_idx)

    def set_dataset(self, tasks: Sequence[str]):
        self._call(op="set_dataset", tasks=list(tasks))

    # -- lease plane ----------------------------------------------------
    def register(self, trainer_id: str,
                 lease_s: Optional[float] = None) -> int:
        """Register for a lease + fencing token; every subsequent
        ``get_task``/``task_finished``/``task_failed`` carries the token
        automatically. Re-registering (or :meth:`rejoin`) fences the
        previous token server-side."""
        self.trainer_id = trainer_id
        self.lease_s = lease_s
        self.token = int(self._call(op="register_trainer",
                                    trainer_id=trainer_id,
                                    lease_s=lease_s)["token"])
        return self.token

    def rejoin(self) -> int:
        """Fresh token for the same trainer id — the preempted host's
        reincarnation path. The caller must roll its training state back
        to the newest durable checkpoint generation first."""
        if self.trainer_id is None:
            raise RuntimeError("rejoin() requires a prior register()")
        return self.register(self.trainer_id, lease_s=self.lease_s)

    def heartbeat(self, telemetry: Optional[dict] = None) -> bool:
        """Renew the lease (and the engine deadlines of our claims);
        False when our token is fenced — the rejoin signal. Optional
        ``telemetry`` ({step_wall_s, steps, goodput, mfu}) rides the
        beat into the master's straggler plane."""
        if self.token is None:
            return True
        req = {"op": "heartbeat", "token": self.token}
        if telemetry:
            req["telemetry"] = telemetry
        return bool(self._call(**req)["alive"])

    def train_status(self) -> dict:
        """The master's straggler-plane aggregate (per-trainer step
        digests, p50/p99 skew, flagged stragglers)."""
        return self._call(op="train_status")["train"]

    def task_status(self, task_id: int) -> Optional[str]:
        return self._call(op="task_status", task_id=task_id)["status"]

    def lease_state(self) -> dict:
        return self._call(op="lease_state")["leases"]

    def metrics_text(self) -> str:
        """The master's Prometheus gauge text (queue + lease plane)."""
        return self._call(op="metrics")["text"]

    # -- task queue -----------------------------------------------------
    def get_task(self):
        resp = self._call(op="get_task", token=self.token)
        tid = resp["task_id"]
        if tid < 0:
            return tid
        return tid, resp["desc"], resp.get("epoch", 0)

    def task_finished(self, task_id: int, epoch: int = 0) -> bool:
        return bool(self._call(op="task_finished", task_id=task_id,
                               epoch=epoch, token=self.token)["ok"])

    def task_failed(self, task_id: int, epoch: int = 0) -> bool:
        return bool(self._call(op="task_failed", task_id=task_id,
                               epoch=epoch, token=self.token)["ok"])

    def new_pass(self) -> int:
        return self._call(op="new_pass")["pass"]

    def counts(self):
        return self._call(op="counts")

    def close(self):
        self._teardown()

    def task_reader(self, make_reader: Callable[[str], Iterable],
                    stop_after_pass: bool = True):
        """Records iterator over master-assigned tasks: pull a task, stream
        its records (``make_reader(desc)``), report finished; report failed
        and continue if the reader raises. Ends when the pass completes."""

        def reader():
            while True:
                t = self.get_task()
                if t == PASS_DONE:
                    return  # epoch complete; caller may new_pass() + re-iter
                if t == NO_TASK:
                    # other trainers still hold pending tasks
                    import time as _t

                    _t.sleep(0.05)
                    continue
                tid, desc, epoch = t
                try:
                    for rec in make_reader(desc):
                        yield rec
                except Exception:  # noqa: BLE001 — task retry semantics
                    self.task_failed(tid, epoch)
                    continue
                self.task_finished(tid, epoch)

        # resume contract (trainer.SGD checkpoint auto-resume): the
        # master already tracks consumed tasks, so a resumed trainer must
        # NOT also skip batches from this stream
        reader.master_backed = True
        return reader

"""Typed serving errors — the admission-control contract.

Callers (and the HTTP front end) distinguish overload from timeout from
bad input by type, the way the reference's pserver distinguishes its RPC
status codes; a bare exception string is not a backpressure protocol.
"""
from __future__ import annotations


class ServingError(RuntimeError):
    """Base class for every serving-subsystem failure."""


class QueueFullError(ServingError):
    """Admission rejected: the request queue is at capacity.

    The backpressure signal — clients should retry with backoff (the
    HTTP front end maps it to 429).
    """


class RequestTimeoutError(ServingError):
    """The request's deadline expired before a result was produced
    (while queued, or because a fault-injected batch was delayed or
    dropped past the deadline). Maps to HTTP 504."""


class BadRequestError(ServingError):
    """Malformed request payload (wrong feed names/shapes, prompt longer
    than the model's context, non-positive max_new_tokens). Maps to
    HTTP 400."""


class EngineClosedError(ServingError):
    """Submit after the server/engine was stopped."""


class ModelNotFoundError(ServingError):
    """The request named a ``model``/``tenant`` id that no resident
    model serves. Maps to HTTP 404 on ``/v1/*`` (and ``HttpReplica``
    maps 404 back to this type) — an unknown id is a routing error, not
    an overload, so it must never silently fall through to a default
    engine or be retried against another replica."""


class CacheExhaustedError(ServingError):
    """The paged KV cache cannot hold this request: the pages its prompt
    + max_new_tokens need exceed what the pool can EVER free for it.

    Carries ``pages_needed`` and ``pages_free`` so callers can size
    retries or shrink the request. Transient pressure (pages held by
    in-flight requests) is NOT this error — the engine defers admission
    and the queue exerts backpressure instead; this fires only when the
    request can never fit. Maps to HTTP 503 with Retry-After.
    """

    def __init__(self, message: str, pages_needed: int = 0,
                 pages_free: int = 0, retry_after_s: float = 1.0):
        super().__init__(message)
        self.pages_needed = int(pages_needed)
        self.pages_free = int(pages_free)
        self.retry_after_s = float(retry_after_s)


class ConnectionDroppedError(ServingError, ConnectionError):
    """The replica connection died MID-RESPONSE (reset, truncated body,
    socket torn after the status line). Distinct from a refused connect:
    the request may have been partially served, so the fleet treats it as
    retryable — with request lineage, the retry resumes from the tokens
    already emitted instead of starting over. Subclasses
    ``ConnectionError`` so every existing retry-on-ConnectionError policy
    already covers it."""


class ReplicaUnavailableError(ServingError):
    """No replica could be routed to for an attempt: every candidate is
    draining, crashed, or behind an open circuit breaker. Retryable —
    the fleet's retry loop backs off and re-routes."""


class FleetOverloadedError(ServingError):
    """Fleet admission rejected: the fleet-wide pending queue is at
    capacity, or every replica's breaker is open (shed-before-queue).

    Carries ``retry_after_s`` — the backoff hint clients should honor;
    the HTTP front end maps it to 503 with a ``Retry-After`` header.
    """

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)

"""Prefill/decode disaggregation: split pools, KV handoff, one router.

Prefill and decode want different machines: prefill is compute-bound
(long chunked matmuls, few slots), decode is latency-bound (one token
per tick across many slots). Batching them in one engine makes every
decode tick wait behind whatever prefill chunk is in flight — the
classic TTFT-vs-TPOT interference. This module splits them:

- :class:`PrefillPool` / :class:`DecodePool` — engine groups with
  independent replica counts and admission policies. Same-process
  pools are built over ONE shared page pool
  (``share_cache_with=``), so migration is free.
- :class:`DisaggEngine` — the composite the :class:`~.server.Server`
  drives like any engine: admissions place onto a prefill engine (the
  :class:`~.router.Router` is the placement layer — least-loaded with
  per-leg breakers), prefill ticks run there, and the moment a
  request's prompt K/V is fully cached it MIGRATES to a decode leg.
- **KV handoff** — the migration is refcounted pages + the int32 block
  table, never a recompute. Same-process: ``export_slot`` /
  ``adopt_slot`` transfer by refcount through the shared pool.
  Cross-process: :func:`serialize_handoff` moves the page byte ranges
  over the existing HTTP leg (``POST /v1/adopt``), and
  :func:`install_serialized_handoff` writes them into the remote
  pool and resumes decode — byte-identical tokens, zero prefill
  recompute (the decode pool's ``prefills`` counter stays 0).

Judged on goodput: the A/B that matters is SLO-good fraction vs a
unified pool at equal engine count (the ``disagg`` row in bench.py),
not aggregate QPS.
"""
from __future__ import annotations

import base64
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from .. import trace
from .errors import BadRequestError, EngineClosedError, QueueFullError
from .metrics import MetricsRegistry
from .router import LeastLoadedPolicy, Router

#: serialized-handoff schema version (reject anything else, typed)
HANDOFF_V = 1


# ---------------------------------------------------------------------------
# cross-process KV handoff: serialize / install
# ---------------------------------------------------------------------------
def _b64(arr: np.ndarray) -> str:
    return base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode(
        "ascii")


def serialize_handoff(engine, handoff: dict, release: bool = True) -> dict:
    """Turn an :meth:`~.generation.PagedGenerationEngine.export_slot`
    handoff into a JSON-safe migration payload: the slot's page byte
    ranges (gathered from the paged K/V tensors by block-table order),
    the decode cursor, and the request's decode policy. ``release=True``
    drops the exporter's page references afterwards (the bytes are the
    handoff now); pass False to keep them so a failed install can roll
    back via ``adopt_slot``."""
    st = handoff["st"]
    pids = list(st.pages)
    from .generation import PAGED_CACHE_K, PAGED_CACHE_V

    k = np.asarray(engine.scope.get(PAGED_CACHE_K))[:, pids]
    v = np.asarray(engine.scope.get(PAGED_CACHE_V))[:, pids]
    sp = st.sampling
    blob = {
        "v": HANDOFF_V,
        "prompt": np.asarray(st.prompt, np.int64).tolist(),
        "generated": [int(t) for t in st.generated],
        # a resumed slot's prompt CONTAINS its first ``resumed`` emitted
        # tokens (the re-prefilled context); _finish strips the overlap,
        # so the cursor must migrate with the slot
        "resumed": int(getattr(st, "resumed", 0) or 0),
        "max_new": int(st.max_new),
        "eos_id": None if st.eos_id is None else int(st.eos_id),
        "tok": int(handoff["tok"]),
        "pos": int(handoff["pos"]),
        "page_size": int(engine.page_size),
        "sampling": {
            "temperature": float(sp.temperature),
            "top_k": int(sp.top_k), "top_p": float(sp.top_p),
            "seed": sp.seed if sp.seed is None else int(sp.seed),
            "max_tokens": (None if sp.max_tokens is None
                           else int(sp.max_tokens)),
            "stop": [list(map(int, s)) for s in sp.stop],
        },
        "dtype": str(k.dtype), "shape": list(k.shape),
        "k": _b64(k), "v_": _b64(v),
    }
    if release:
        release_handoff(engine, handoff)
    return blob


def release_handoff(engine, handoff: dict) -> None:
    """Drop the exporter's claim on a serialized-away handoff: decref
    every page (shared prefix pages just lose one holder) and release
    the copy-on-write reservation."""
    st = handoff["st"]
    for pid in st.pages:
        engine.pool.decref(pid)
    st.pages = []
    if st.cow_reserve:
        engine.pool.release_reservation(st.cow_reserve)
        st.cow_reserve = 0


def install_handoff(engine, blob: dict, request) -> bool:
    """Install a serialized handoff into ``engine`` and resume decode
    for ``request``. Returns False — with the engine untouched — when
    there is no free slot or not enough pages (transient pressure: the
    caller retries or rolls back); raises :class:`BadRequestError` when
    the payload can never fit this engine (schema/page-size/context
    mismatch). Every migrated-in page is exclusively owned, so the
    prefix-sharing copy-on-write machinery never fires for it."""
    if blob.get("v") != HANDOFF_V:
        raise BadRequestError(
            f"handoff schema v{blob.get('v')!r} != v{HANDOFF_V}")
    if int(blob["page_size"]) != engine.page_size:
        raise BadRequestError(
            f"handoff page_size {blob['page_size']} != engine page_size "
            f"{engine.page_size} — pools must agree on the page shape")
    prompt = np.asarray(blob["prompt"], np.int64)
    if prompt.size + int(blob["max_new"]) > engine.tmax:
        raise BadRequestError(
            f"handoff needs context {prompt.size + int(blob['max_new'])}"
            f" > engine serving context ({engine.tmax})")
    n = int(blob["shape"][1])
    if engine.free_slots == 0:
        return False
    try:
        pids = engine.pool.alloc_many(n)
    except RuntimeError:
        if engine.prefix_index is not None:
            engine.prefix_index.evict_until(n)
        try:
            pids = engine.pool.alloc_many(n)
        except RuntimeError:
            return False
    from .generation import (PAGED_CACHE_K, PAGED_CACHE_V, _PagedSlot)
    from ..decoding import SamplingParams

    shape = tuple(blob["shape"])
    dtype = np.dtype(blob["dtype"])
    for name, key in ((PAGED_CACHE_K, "k"), (PAGED_CACHE_V, "v_")):
        pages = np.frombuffer(base64.b64decode(blob[key]),
                              dtype).reshape(shape)
        full = np.array(np.asarray(engine.scope.get(name)))
        full[:, pids] = pages
        engine.scope.set(name, full)
    s = blob["sampling"]
    sampling = SamplingParams(
        temperature=s["temperature"], top_k=s["top_k"],
        top_p=s["top_p"], seed=s["seed"], max_tokens=s["max_tokens"],
        stop=tuple(tuple(x) for x in s["stop"]))
    st = _PagedSlot(request, prompt, int(blob["max_new"]),
                    blob["eos_id"], sampling)
    st.pages = pids
    st.prefill_done = prompt.size
    st.state = "decode"
    st.generated = [int(t) for t in blob["generated"]]
    st.resumed = int(blob.get("resumed", 0) or 0)
    # tokens already emitted at the source: advance the timeline so the
    # next emit records TPOT (the migration gap, honestly), not a fake
    # TTFT on this pool
    import time as _time

    for _ in st.generated:
        st.timeline.mark_token(_time.monotonic())
    slot = engine._slots.index(None)
    engine._slots[slot] = st
    engine._tok[slot] = int(blob["tok"])
    engine._pos[slot] = int(blob["pos"])
    engine.metrics.inc("kv_handoffs_in")
    engine.metrics.inc("kv_handoff_pages", n)
    return True


def install_serialized_handoff(engine, req) -> bool:
    """The admission-path entry (``admit`` intercepts payloads carrying
    ``handoff``): install and resume, or complete the request's future
    typed — BadRequestError for payloads that can never fit,
    QueueFullError (429, retryable) under transient slot/page
    pressure."""
    try:
        ok = install_handoff(engine, req.payload["handoff"], req)
    except BadRequestError as exc:
        engine.metrics.inc("bad_requests")
        req.end_trace(status="bad_request")
        req.future.set_exception(exc)
        return False
    if not ok:
        engine.metrics.inc("handoff_rejected")
        req.end_trace(status="handoff_rejected")
        req.future.set_exception(QueueFullError(
            "no free slot/pages to adopt the KV handoff; retry"))
    return ok


# ---------------------------------------------------------------------------
# pools and placement legs
# ---------------------------------------------------------------------------
class EnginePool:
    """N engines of one role. Same-process pools share ONE page pool
    (build the extra engines with ``share_cache_with=``), which is what
    makes migration a refcount transfer."""

    role = "pool"

    def __init__(self, engines):
        self.engines = list(engines) if isinstance(
            engines, (list, tuple)) else [engines]

    @property
    def free_slots(self) -> int:
        return sum(e.free_slots for e in self.engines)

    @property
    def active(self) -> int:
        return sum(e.active for e in self.engines)

    def __len__(self) -> int:
        return len(self.engines)


class PrefillPool(EnginePool):
    role = "prefill"


class DecodePool(EnginePool):
    role = "decode"


class _EngineLeg:
    """One local engine as a routable placement target — the Replica
    surface (:attr:`routable`/:attr:`inflight`/:meth:`healthz`) the
    :class:`Router` picks over."""

    def __init__(self, engine, name: str, index: int, fleet_size: int):
        self.engine = engine
        self.name = name
        self.index = index
        self.fleet_size = fleet_size
        self.remote = False

    @property
    def routable(self) -> bool:
        return self.engine.free_slots > 0

    @property
    def inflight(self) -> int:
        return self.engine.active

    def healthz(self) -> dict:
        return {"state": "ready", "ok": True,
                "free_slots": self.engine.free_slots}


class RemoteDecodeLeg:
    """A decode pool in ANOTHER process as a placement target. The
    migration rides the existing HTTP replica leg: serialized page
    ranges POST to ``/v1/adopt``, the response carries the finished
    ids, and the SOURCE request's future resolves with them — the
    client never sees the pool boundary."""

    def __init__(self, base_url: str, name: Optional[str] = None,
                 model: Optional[str] = None, max_inflight: int = 8,
                 timeout_s: float = 120.0):
        from .fleet import HttpReplica

        self.name = name or f"remote:{base_url}"
        self.index = 0
        self.fleet_size = 1
        self.model = model
        self.remote = True
        self.max_inflight = int(max_inflight)
        self.timeout_s = float(timeout_s)
        self._rep = HttpReplica(base_url, name=self.name)
        self._lock = threading.Lock()
        self._inflight = 0
        # DisaggEngine installs its failover hook here: a leg that dies
        # AFTER the pages were serialized away (the no-rollback window)
        # hands (blob, request) back instead of failing the future
        self.on_failure = None

    @property
    def routable(self) -> bool:
        with self._lock:
            return self._inflight < self.max_inflight

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def healthz(self) -> dict:
        return self._rep.healthz()

    def _fail(self, blob: dict, request, exc: BaseException) -> None:
        """A dead/overloaded leg hands the work BACK (the failover
        hook re-prefills it elsewhere); non-retryable errors still fail
        the future typed."""
        cb = self.on_failure
        if cb is not None and isinstance(
                exc, (ConnectionError, TimeoutError, EngineClosedError,
                      QueueFullError)):
            cb(self, blob, request, exc)
        else:
            request.future.set_exception(exc)

    def adopt(self, blob: dict, request) -> bool:
        """Ship the serialized handoff; resolve the source request's
        future from the remote decode. The pages were already released
        to the bytes, so there is no rollback past this point — a leg
        that dies here goes through :meth:`_fail`, and the failover
        hook re-prefills the blob's context on another leg. Returns
        False when the leg died before dispatch (the fault-plan
        ``decode_leg_crash`` window) so the caller records a failure,
        not a success."""
        body: Dict[str, object] = {"handoff": blob}
        if self.model is not None:
            body["model"] = self.model
        from ..resilience import faults

        plan = faults.active_plan()
        if plan is not None \
                and plan.fire("decode_leg_crash") is not None:
            self._fail(blob, request, ConnectionError(
                f"{self.name} died after KV handoff (fault-plan "
                "decode_leg_crash) — pages already serialized away"))
            return False
        with self._lock:
            self._inflight += 1

        def run():
            try:
                out = self._rep._http("POST", "/v1/adopt", body,
                                      timeout_s=self.timeout_s)
                request.future.set_result(np.asarray(out["ids"]))
            except BaseException as exc:  # noqa: BLE001 - typed upstream
                self._fail(blob, request, exc)
            finally:
                with self._lock:
                    self._inflight -= 1

        threading.Thread(target=run, name=f"kv-handoff-{self.name}",
                         daemon=True).start()
        return True


# ---------------------------------------------------------------------------
# the composite engine
# ---------------------------------------------------------------------------
class DisaggEngine:
    """Prefill pool + decode pool behind one engine surface.

    Drives like any engine from a :class:`~.server.Server` (or
    directly): ``serve_step`` admits onto the least-loaded prefill leg,
    runs prefill ticks there, migrates every handoff-ready slot to a
    decode leg (local adopt through the shared page pool; remote legs
    get serialized page ranges), and runs decode ticks on the decode
    pool only — so a prefill burst can never stall a decode tick, and
    the prefill pool's ``decode_steps`` / the decode pool's
    ``prefills`` both pin at 0 (beam requests, whose job state is
    engine-local, live their whole life on a decode leg instead).

    Backpressure is structural: a slot whose decode legs are all full
    simply stays on its prefill engine — holding its pages, admitting
    no successor — until a decode slot frees.
    """

    def __init__(self, prefill, decode, *, policy=None,
                 remote_decode=(), metrics: Optional[MetricsRegistry] = None):
        self.prefill = (prefill if isinstance(prefill, EnginePool)
                        else PrefillPool(prefill))
        self.decode = (decode if isinstance(decode, EnginePool)
                       else DecodePool(decode))
        if not self.prefill.engines:
            raise ValueError("PrefillPool needs >= 1 local engine")
        if not self.decode.engines and not remote_decode:
            raise ValueError("need >= 1 decode leg (local or remote)")
        self.metrics = metrics or self.prefill.engines[0].metrics
        legs = [_EngineLeg(e, f"prefill{i}", i, len(self.prefill))
                for i, e in enumerate(self.prefill.engines)]
        self._prefill_router = Router(legs, policy=policy
                                      or LeastLoadedPolicy())
        dlegs: List[object] = [
            _EngineLeg(e, f"decode{i}", i,
                       len(self.decode) + len(remote_decode))
            for i, e in enumerate(self.decode.engines)]
        for j, leg in enumerate(remote_decode):
            if not isinstance(leg, RemoteDecodeLeg):
                leg = RemoteDecodeLeg(str(leg))
            leg.index = len(self.decode.engines) + j
            leg.fleet_size = len(self.decode) + len(remote_decode)
            dlegs.append(leg)
        self.router = Router(dlegs, policy=policy or LeastLoadedPolicy())
        self._remote = [leg for leg in dlegs
                        if isinstance(leg, RemoteDecodeLeg)]
        # decode-leg failover: a remote leg that dies after the KV
        # handoff parks (blob, request) here; _failover_tick re-prefills
        # the context on another leg — work-preserving, never a failure
        self._failover: deque = deque()
        self._failover_lock = threading.Lock()
        for leg in self._remote:
            leg.on_failure = self._decode_leg_failed
        self.engines = self.prefill.engines + self.decode.engines
        self.spec = self.engines[0].spec

    @classmethod
    def build(cls, spec, *, prefill_replicas: int = 1,
              decode_replicas: int = 1, scope=None, **engine_kw):
        """Construct split pools over ONE scope (shared weights) and ONE
        page pool (``share_cache_with`` chain) — the same-process
        deployment where migration is a pure refcount transfer."""
        from .generation import GenerationEngine

        engine_kw.pop("kv_cache", None)
        first = GenerationEngine(spec, scope=scope, kv_cache="paged",
                                 **engine_kw)
        engines = [first]
        for _ in range(prefill_replicas + decode_replicas - 1):
            engines.append(GenerationEngine(
                spec, scope=first.scope, kv_cache="paged",
                share_cache_with=first, **engine_kw))
        return cls(PrefillPool(engines[:prefill_replicas]),
                   DecodePool(engines[prefill_replicas:]))

    # -- engine surface (what Server drives) -------------------------------
    @property
    def active(self) -> int:
        return (self.prefill.active + self.decode.active
                + sum(leg.inflight for leg in self._remote))

    @property
    def free_slots(self) -> int:
        return self.prefill.free_slots

    def _is_beam(self, req) -> bool:
        k = (req.meta or {}).get("beam_size")
        return bool(k) and int(k) > 1

    def _place(self, reqs) -> Dict[object, list]:
        """Admission placement: the Router picks a prefill leg per
        request (least loaded); beam requests go straight to a decode
        leg — their BeamJob holds engine-local state that cannot
        migrate, so they live their whole lifecycle decode-side."""
        groups: Dict[object, list] = {}
        for req in reqs:
            if self._is_beam(req) and not self.decode.engines:
                # a BeamJob's state is engine-local and cannot ride the
                # serialized handoff — remote-only decode can't host it
                req.future.set_exception(BadRequestError(
                    "beam requests need a local decode engine"))
                continue
            router = (self.router if self._is_beam(req)
                      else self._prefill_router)
            leg = router.route(req.meta)
            if leg is None or getattr(leg, "remote", False):
                # no local capacity: fall back to any local engine — its
                # own deferral queue is the backpressure, typed
                eng = (self.decode.engines[0] if self._is_beam(req)
                       else self.prefill.engines[0])
            else:
                eng = leg.engine
            groups.setdefault(eng, []).append(req)
        return groups

    def _migrate(self) -> int:
        """Move every handoff-ready slot from the prefill pool to a
        decode leg. Local legs adopt by refcount through the shared
        pool; remote legs get the serialized page ranges. A slot with
        no routable decode leg stays put (backpressure, retried next
        step)."""
        moved = 0
        for src in self.prefill.engines:
            for slot in src.handoff_ready():
                leg = self.router.route()
                if leg is None:
                    self.metrics.inc("kv_migration_stalls")
                    return moved
                if isinstance(leg, RemoteDecodeLeg):
                    hand = src.export_slot(slot)
                    req = hand["st"].request
                    blob = serialize_handoff(src, hand, release=True)
                    if leg.adopt(blob, req):
                        self.router.record(leg, ok=True)
                elif leg.engine.pool is src.pool:
                    hand = src.export_slot(slot)
                    leg.engine.adopt_slot(hand)
                else:  # local leg, separate pool: move the bytes
                    hand = src.export_slot(slot)
                    blob = serialize_handoff(src, hand, release=False)
                    if install_handoff(leg.engine, blob,
                                       hand["st"].request):
                        release_handoff(src, hand)
                    else:  # transient: roll back, retry next step
                        src.adopt_slot(hand)
                        self.metrics.inc("kv_migration_stalls")
                        return moved
                moved += 1
                self.metrics.inc("kv_migrations")
        return moved

    def _decode_leg_failed(self, leg, blob: dict, request,
                           exc: BaseException) -> None:
        """RemoteDecodeLeg failure hook (handoff-thread-safe: only
        enqueues). The leg is quarantined immediately — a mid-handoff
        death is a strong signal — and the blob re-enters via
        :meth:`_failover_tick` on the drive loop."""
        self.router.record(leg, ok=False, reason=type(exc).__name__)
        self.router.quarantine(leg, reason="decode leg crash")
        with self._failover_lock:
            self._failover.append((blob, request))
        self.metrics.inc("decode_leg_failovers")
        now = time.perf_counter()
        trace.record("disagg/decode_leg_failover", now, now,
                     leg=leg.name, error=repr(exc)[:200],
                     tokens_reused=len(blob.get("generated", [])))

    def _failover_tick(self) -> bool:
        """Re-admit every parked failover: the blob's already-emitted
        tokens become ``resume_tokens`` (chunk-prefilled, never
        re-decoded) and ``recovery=True`` buys priority admission on
        the prefill pool — pressure defers NEW work, not recoveries."""
        did = False
        while True:
            with self._failover_lock:
                if not self._failover:
                    return did
                blob, req = self._failover.popleft()
            meta = dict(req.meta or {})
            meta["resume_tokens"] = [int(t) for t in blob["generated"]]
            meta["recovery"] = True
            req.meta = meta
            leg = self._prefill_router.route(meta)
            eng = (leg.engine
                   if leg is not None and not getattr(leg, "remote",
                                                      False)
                   else self.prefill.engines[0])
            eng.admit([req])
            did = True

    def serve_step(self, batcher,
                   idle_wait_s: Optional[float] = None) -> bool:
        did = self._failover_tick()
        did = self._migrate() > 0 or did
        free = self.prefill.free_slots
        deferred = any(e._deferred for e in self.engines)
        if free and not deferred:
            wait = 0 if (self.active or did) else idle_wait_s
            reqs = batcher.next_batch(max_n=free, wait_s=wait)
            for eng, group in self._place(reqs or []).items():
                did = eng.admit(group) > 0 or did
        for eng in self.prefill.engines:
            did = eng._admit_deferred() > 0 or did
            did = eng.prefill_tick() or did
        for eng in self.decode.engines:
            did = eng._beam_maintenance() or did
            did = eng._admit_deferred() > 0 or did
            did = eng.prefill_tick() or did  # beam lifecycles only
            did = eng.decode_tick() or did
        return did

    def _drive(self, reqs) -> None:
        """Run the split-pool loop until every request completes — the
        in-process test/bench harness, like the engine's own."""
        pending = list(reqs)
        while pending or self.active or self._failover \
                or any(e._deferred for e in self.engines) \
                or any(e._beam_jobs for e in self.engines):
            if pending and self.prefill.free_slots:
                k = min(len(pending), self.prefill.free_slots)
                for eng, group in self._place(pending[:k]).items():
                    eng.admit(group)
                pending = pending[k:]
            self._failover_tick()
            self._migrate()
            for eng in self.prefill.engines:
                eng._admit_deferred()
                eng.prefill_tick()
            for eng in self.decode.engines:
                eng._beam_maintenance()
                eng._admit_deferred()
                eng.prefill_tick()
                eng.decode_tick()

    # -- maintenance pass-throughs -----------------------------------------
    def warm_start(self) -> None:
        for eng in self.engines:
            warm = (getattr(eng, "warm_start", None)
                    or getattr(eng, "warmup", None))
            if warm is not None:
                warm()

    def warm_from_manifest(self, dirname: Optional[str] = None):
        warmed = None
        for eng in self.engines:
            warm = getattr(eng, "warm_from_manifest", None)
            if warm is None:
                continue
            n = warm(dirname) if dirname is not None else warm()
            if n is not None:
                warmed = (warmed or 0) + n
        return warmed

    def swap_params(self, source, *, strict: bool = True) -> dict:
        """One swap covers both pools — they share the scope in the
        ``build()`` shape, but per-engine swaps also invalidate each
        engine's prefix index, which must happen pool-wide."""
        stats: Dict[str, int] = {}
        for eng in self.engines:
            for k, v in eng.swap_params(source, strict=strict).items():
                stats[k] = stats.get(k, 0) + v
        return stats

    def cache_stats(self) -> dict:
        out: Dict[str, float] = {}
        for eng in self.engines:
            for k, v in eng.cache_stats().items():
                if isinstance(v, (int, float)):
                    out[k] = out.get(k, 0) + v
        return out

    def flight_state(self) -> dict:
        return {
            "prefill": [e.flight_state() for e in self.prefill.engines],
            "decode": [e.flight_state() for e in self.decode.engines],
            "remote_inflight": sum(leg.inflight for leg in self._remote),
        }

    def metrics_snapshot(self) -> dict:
        return MetricsRegistry.merge(
            {f"{'p' if i < len(self.prefill.engines) else 'd'}{i}":
             e.metrics.snapshot() for i, e in enumerate(self.engines)})

    def close(self, drain: bool = False) -> None:
        for eng in self.engines:
            if hasattr(eng, "close"):
                try:
                    eng.close(drain=drain)
                except TypeError:
                    eng.close()

"""Request lineage — the work-preserving serving-recovery plane.

The reference system's control plane re-queues work from dead trainers
so a crash never loses the job; serving needs the same story. PR 14
made every token a pure function of ``(request, seed)`` — sampling
folds ``(seed, step)`` per emitted position — which means a generation
interrupted mid-stream is *replayable*: re-prefill ``prompt + emitted``
on any healthy replica and keep decoding at the right step counter, and
the resumed stream is bitwise-identical to an uninterrupted one.

This module keeps the router-side state that makes that possible:

- :class:`LineageRecord` — one admitted generation's recovery identity:
  the prompt ids, the request meta snapshot (with the fleet-pinned seed
  — :meth:`Fleet._pin_seed` runs BEFORE any attempt, so retries and
  hedges share one policy), the tokens emitted so far (streamed back by
  the engine through the ``on_token`` progress callback), tenant/model,
  and the deadline.
- :class:`LineageStore` — a bounded (LRU-evicting) thread-safe map from
  request key to record, registered as a flight-recorder source so a
  crash dump shows exactly which streams were in flight and how far
  each had gotten.

The fleet's retry loop consults the store between attempts: a record
with emitted tokens turns the retry into a RESUME (``resume_tokens`` in
the attempt meta) — the engine chunk-prefills the resumed context into
fresh pages and never re-decodes a token the client already has.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

__all__ = ["LineageRecord", "LineageStore"]


class LineageRecord:
    """Everything needed to re-admit one interrupted generation."""

    __slots__ = ("key", "prompt", "meta", "emitted", "deadline",
                 "recoveries")

    def __init__(self, key: str, prompt: Sequence[int], meta: dict,
                 deadline: Optional[float] = None):
        self.key = key
        self.prompt: List[int] = [int(t) for t in prompt]
        self.meta = dict(meta)            # seed already fleet-pinned
        self.emitted: List[int] = []      # tokens the client already has
        self.deadline = deadline          # absolute monotonic, or None
        self.recoveries = 0               # resumes performed so far

    def progress(self, step: int, token: int) -> None:
        """Record that position ``step`` decoded ``token``.

        Positional (not append-only) on purpose: hedged attempts may
        both stream progress, and (request, seed) determinism guarantees
        they emit IDENTICAL tokens per position — last write wins and
        writes the same value. A resumed attempt re-reports positions
        the record already holds; those are idempotent too.
        """
        step = int(step)
        if step < len(self.emitted):
            self.emitted[step] = int(token)
            return
        if step != len(self.emitted):
            # a gap means a progress callback went missing (an attempt
            # died between emits); truncate is impossible — positions
            # only ever extend — so pad conservatively never happens:
            # the engine reports every emit in order per attempt, and a
            # resumed attempt starts at len(resume_tokens).
            raise ValueError(
                f"non-contiguous progress for {self.key!r}: step {step} "
                f"with {len(self.emitted)} emitted")
        self.emitted.append(int(token))

    def resume_tokens(self) -> List[int]:
        return list(self.emitted)

    def to_dict(self) -> dict:
        return {
            "key": self.key,
            "prompt_len": len(self.prompt),
            "emitted": len(self.emitted),
            "recoveries": self.recoveries,
            "model": self.meta.get("model"),
            "seed": self.meta.get("seed"),
        }


class LineageStore:
    """Bounded, thread-safe lineage map (router-side).

    ``limit`` bounds memory: the store is an LRU over *registration* —
    when full, the oldest record is evicted (and counted). Records are
    discarded eagerly on completion/terminal failure, so eviction only
    bites under pathological churn.
    """

    def __init__(self, limit: int = 512, *, register_flight: bool = True):
        if limit < 1:
            raise ValueError("limit must be >= 1")
        self.limit = int(limit)
        self._records: "OrderedDict[str, LineageRecord]" = OrderedDict()
        self._lock = threading.Lock()
        self.registered = 0
        self.discarded = 0
        self.evicted = 0
        self.recovered = 0
        if register_flight:
            from ..trace import flight as trace_flight

            trace_flight.get_recorder().add_source("lineage",
                                                   self.flight_state)

    def register(self, key: str, prompt: Sequence[int], meta: dict,
                 deadline: Optional[float] = None) -> LineageRecord:
        rec = LineageRecord(key, prompt, meta, deadline)
        with self._lock:
            self._records[key] = rec
            self._records.move_to_end(key)
            self.registered += 1
            while len(self._records) > self.limit:
                self._records.popitem(last=False)
                self.evicted += 1
        return rec

    def progress(self, key: str, step: int, token: int) -> None:
        with self._lock:
            rec = self._records.get(key)
        if rec is not None:
            rec.progress(step, token)

    def get(self, key: str) -> Optional[LineageRecord]:
        with self._lock:
            return self._records.get(key)

    def mark_recovery(self, key: str) -> Optional[LineageRecord]:
        """Fetch the record for a resume attempt and count the recovery."""
        with self._lock:
            rec = self._records.get(key)
            if rec is not None:
                rec.recoveries += 1
                self.recovered += 1
        return rec

    def discard(self, key: str) -> None:
        with self._lock:
            if self._records.pop(key, None) is not None:
                self.discarded += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"live": len(self._records),
                    "registered": self.registered,
                    "discarded": self.discarded,
                    "evicted": self.evicted,
                    "recovered": self.recovered}

    def flight_state(self) -> dict:
        """Flight-recorder source: which streams are in flight and how
        far each has gotten — the crash dump IS the recovery worklist."""
        with self._lock:
            records = [rec.to_dict() for rec in self._records.values()]
        return dict(self.stats(), records=records[-32:])

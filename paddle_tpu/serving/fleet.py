"""Multi-replica serving fleet: retries, hedging, breakers, rolling updates.

One :class:`Server` is one replica; this module is the layer above — the
reference's pserver fleet behind etcd leases, rebuilt for the request
path. A :class:`Fleet` owns N replicas behind one :class:`Replica`
interface (:class:`LocalReplica` wraps an in-process Server/engine,
:class:`HttpReplica` a remote ``Server.serve_http`` endpoint) and routes
every request through the robustness stack:

- **deadline propagation** — the request's remaining budget travels
  router -> replica batcher -> engine, so no layer waits past the
  caller's deadline;
- **retries** — a failed attempt resubmits to a *different* replica with
  :class:`paddle_tpu.resilience.Retry` backoff/jitter (idempotent
  requests only; the absolute deadline is never overshot);
- **hedging** — a request still unanswered after the P99-derived hedge
  delay fires a second attempt on another replica; first answer wins,
  the loser is abandoned and counted;
- **circuit breakers** — per-replica closed/open/half-open driven by
  outcome stats + ``/healthz`` probes (:mod:`.router`);
- **load shedding** — bounded fleet-wide admission; over capacity (or
  every breaker open) rejects with a typed
  :class:`FleetOverloadedError` carrying Retry-After, *before* queueing;
- **rolling weight updates** — :meth:`Fleet.update_weights` walks
  replicas one at a time through drain (healthz 503) -> param hot-swap
  (``swap_params``: same shapes/dtypes, no recompile) -> warm-start
  verify (manifest replay) -> rejoin, so the fleet serves throughout.

Chaos-testable end to end: the ``replica_crash`` / ``slow_replica``
fault kinds (:mod:`paddle_tpu.resilience.faults`) fire per replica
index, deterministically.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import trace
from ..resilience.faults import TransientFault, active_plan
from ..resilience.retry import Retry
from .batcher import Future
from .errors import (BadRequestError, ConnectionDroppedError,
                     EngineClosedError, FleetOverloadedError,
                     ModelNotFoundError, QueueFullError,
                     ReplicaUnavailableError, RequestTimeoutError,
                     ServingError)
from .metrics import MetricsRegistry
from .recovery import LineageStore
from .router import Router

#: attempt errors worth resubmitting to a different replica
FLEET_RETRYABLE = (ConnectionError, TimeoutError, TransientFault,
                   QueueFullError, EngineClosedError,
                   ReplicaUnavailableError)
#: errors that must escape immediately (bad input, expired deadline,
#: unknown model/tenant id — every replica serves the same registry, so
#: retrying a 404 elsewhere only burns attempts)
FLEET_GIVE_UP = (BadRequestError, RequestTimeoutError,
                 ModelNotFoundError)

#: fleet-control meta keys never forwarded to the replica's batcher
_FLEET_META = ("session", "idempotent")

_POLL_S = 0.001  # attempt-completion poll (local futures have no waitset)


class _Attempt:
    """One in-flight try of a request on one replica. ``not_before``
    implements the ``slow_replica`` fault: the result exists but is not
    VISIBLE until the injected delay elapses — exactly how a slow remote
    looks to the router."""

    __slots__ = ("future", "replica", "hedge", "not_before", "t0")

    def __init__(self, future: Future, replica: "Replica",
                 hedge: bool = False, not_before: Optional[float] = None):
        self.future = future
        self.replica = replica
        self.hedge = hedge
        self.not_before = not_before
        self.t0 = time.perf_counter()

    def done(self) -> bool:
        if self.not_before is not None \
                and time.monotonic() < self.not_before:
            return False
        return self.future.done()


class Replica:
    """The one interface the router sees. Subclasses provide transport."""

    name: str = "?"
    index: int = 0
    fleet_size: int = 1

    @property
    def routable(self) -> bool:
        raise NotImplementedError

    @property
    def inflight(self) -> int:
        return 0

    def begin(self, payload, meta: dict,
              timeout_ms: Optional[float]) -> _Attempt:
        raise NotImplementedError

    def healthz(self) -> dict:
        raise NotImplementedError

    def drain(self, wait: bool = True, timeout: float = 30.0) -> None:
        raise NotImplementedError

    def rejoin(self) -> None:
        raise NotImplementedError

    def swap_params(self, source, tenant: Optional[str] = None) -> dict:
        raise NotImplementedError

    def warm_verify(self) -> Optional[int]:
        return None

    def metrics_snapshot(self) -> dict:
        return {}

    def close(self, drain: bool = False) -> None:
        pass

    def __repr__(self):
        return f"{type(self).__name__}({self.name!r})"


class LocalReplica(Replica):
    """An in-process engine (or prebuilt Server) as a fleet replica.

    The ``replica_crash`` / ``slow_replica`` fault kinds fire against the
    replica *index* (``plan.at(step=1, kind="replica_crash")`` kills
    replica 1); a crashed replica raises ConnectionError on every attempt
    until :meth:`revive`.
    """

    def __init__(self, target, name: Optional[str] = None, **server_kwargs):
        from .server import Server

        if isinstance(target, Server):
            self.server = target
            self._owns_server = False
        else:
            engines = target if isinstance(target, (list, tuple)) \
                else [target]
            self.server = Server(list(engines), **server_kwargs)
            self._owns_server = True
        if name is not None:
            self.name = name
        self._crashed = False
        self._slow_s: Optional[float] = None

    # -- chaos ----------------------------------------------------------
    def _fault_gate(self) -> None:
        plan = active_plan()
        if plan is not None:
            if not self._crashed \
                    and plan.fire("replica_crash", self.index) is not None:
                self._crashed = True
            if self._slow_s is None:
                p = plan.fire("slow_replica", self.index)
                if p is not None:
                    self._slow_s = float(p.get("delay_s", 0.05))
        if self._crashed:
            raise ConnectionError(
                f"replica {self.name}: injected crash (fault plan)")

    def revive(self) -> None:
        """Clear injected crash/slowness — the 'operator replaced the
        pod' step of a chaos run."""
        self._crashed = False
        self._slow_s = None

    # -- Replica interface ----------------------------------------------
    @property
    def routable(self) -> bool:
        # deliberately blind to the injected crash: a dead replica looks
        # routable until its failures trip the breaker — exactly like a
        # remote whose process died. Drain state IS control-plane
        # knowledge (we initiated it), so it short-circuits here.
        return self.server.state == "ready"

    @property
    def inflight(self) -> int:
        eng_active = sum(getattr(e, "active", 0)
                         + getattr(e, "_inflight", 0)
                         for e in self.server.engines)
        return self.server.batcher.depth + eng_active

    def begin(self, payload, meta: dict,
              timeout_ms: Optional[float]) -> _Attempt:
        self._fault_gate()
        fwd = {k: v for k, v in meta.items() if k not in _FLEET_META}
        fut = self.server.submit(payload, timeout_ms=timeout_ms, **fwd)
        not_before = (time.monotonic() + self._slow_s
                      if self._slow_s else None)
        return _Attempt(fut, self, not_before=not_before)

    def healthz(self) -> dict:
        if self._crashed:
            return {"state": "dead", "ok": False}
        return {"state": self.server.state,
                "ok": self.server.state == "ready",
                "queue": self.server.batcher.depth}

    def drain(self, wait: bool = True, timeout: float = 30.0) -> None:
        self.server.pause(wait=wait, timeout=timeout)

    def rejoin(self) -> None:
        self.server.resume()

    def swap_params(self, source, tenant: Optional[str] = None) -> dict:
        # the server owns the swap: a MultiTenantServer scopes it to one
        # tenant (draining only that tenant's queue/engines); a plain
        # Server answers tenant-scoped swaps with a typed 404
        return self.server.swap_params(source, tenant=tenant)

    def warm_verify(self) -> Optional[int]:
        warmed = None
        for eng in self.server.engines:
            warm = getattr(eng, "warm_from_manifest", None)
            if warm is None:
                continue
            try:
                n = warm()
            except Exception:  # noqa: BLE001 - verify is best-effort
                n = None
            if n is not None:
                warmed = (warmed or 0) + n
        return warmed

    def metrics_snapshot(self) -> dict:
        return self.server.metrics.snapshot()

    def cache_stats(self) -> dict:
        out: Dict[str, int] = {}
        for eng in self.server.engines:
            if hasattr(eng, "cache_stats"):
                for k, v in eng.cache_stats().items():
                    if isinstance(v, (int, float)):
                        out[k] = out.get(k, 0) + v
        return out

    def close(self, drain: bool = False) -> None:
        self.server.stop(drain=drain)


class HttpReplica(Replica):
    """A remote ``Server.serve_http`` endpoint as a fleet replica.

    Data plane: POST /v1/generate | /v1/infer (picked by payload shape).
    Control plane: GET /healthz, POST /admin/drain | /admin/resume |
    /admin/swap — the endpoints ``tools/fleetctl.py`` also drives.
    HTTP statuses map back onto the typed serving errors, so the router
    treats a remote exactly like a local replica.
    """

    def __init__(self, base_url: str, name: Optional[str] = None,
                 connect_timeout_s: float = 10.0,
                 read_timeout_s: Optional[float] = None):
        self.base_url = base_url.rstrip("/")
        if name is not None:
            self.name = name
        # connect and read are SEPARATE failure modes: a refused/hung
        # connect means a dead peer (fail fast, retry elsewhere); a slow
        # response means a busy one (wait out read_timeout_s — or the
        # per-request deadline when one is set). read_timeout_s=None
        # falls back to the request timeout, then connect_timeout_s.
        self.connect_timeout_s = float(connect_timeout_s)
        self.read_timeout_s = (None if read_timeout_s is None
                               else float(read_timeout_s))
        self._draining = False

    # -- transport -------------------------------------------------------
    def _http(self, method: str, path: str, body: Optional[dict] = None,
              timeout_s: Optional[float] = None,
              headers: Optional[dict] = None) -> dict:
        import http.client
        import socket
        import urllib.parse

        data = json.dumps(body).encode() if body is not None else None
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update({k: v for k, v in headers.items()
                         if v is not None})
        if "traceparent" not in hdrs:
            # control-plane calls (drain/swap/warm) ride the caller's
            # open span (e.g. fleet/rolling_update) when there is one
            tp = trace.inject()
            if tp is not None:
                hdrs["traceparent"] = tp
        url = urllib.parse.urlsplit(self.base_url + path)
        conn_cls = (http.client.HTTPSConnection
                    if url.scheme == "https" else
                    http.client.HTTPConnection)
        conn = conn_cls(url.hostname, url.port,
                        timeout=self.connect_timeout_s)
        read_timeout = (timeout_s if timeout_s is not None
                        else self.read_timeout_s
                        if self.read_timeout_s is not None
                        else self.connect_timeout_s)
        try:
            try:
                conn.connect()
            except socket.timeout:
                raise ConnectionError(
                    f"{self.name} connect timed out after "
                    f"{self.connect_timeout_s}s") from None
            except OSError as exc:
                raise ConnectionError(
                    f"{self.name} unreachable: {exc}") from None
            if conn.sock is not None:
                conn.sock.settimeout(read_timeout)
            target = url.path or "/"
            if url.query:
                target += f"?{url.query}"
            try:
                conn.request(method, target, body=data, headers=hdrs)
                resp = conn.getresponse()
                status = resp.status
                raw = resp.read()
            except socket.timeout:
                raise RequestTimeoutError(
                    f"{self.name} {path} timed out") from None
            except (http.client.HTTPException, OSError) as exc:
                # the peer died MID-EXCHANGE (reset, truncated body,
                # torn status line): typed retryable, distinct from a
                # bad request — with lineage the retry RESUMES from the
                # tokens already emitted
                raise ConnectionDroppedError(
                    f"{self.name} {path} connection dropped "
                    f"mid-response: {exc!r}") from None
        finally:
            conn.close()
        if status < 400:
            try:
                return json.loads(raw or b"{}")
            except ValueError as exc:
                raise ConnectionDroppedError(
                    f"{self.name} {path} returned a torn body: "
                    f"{exc}") from None
        try:
            detail = json.loads(raw or b"{}").get("error", "")
        except ValueError:
            detail = ""
        msg = f"{self.name} {path} -> {status}: {detail}"
        if status == 429:
            raise QueueFullError(msg)
        if status in (503, 502):
            raise EngineClosedError(msg)
        if status in (504, 408):
            raise RequestTimeoutError(msg)
        if status == 400:
            raise BadRequestError(msg)
        if status == 404:
            raise ModelNotFoundError(msg)
        raise ServingError(msg)

    # -- Replica interface ----------------------------------------------
    @property
    def routable(self) -> bool:
        return not self._draining

    def begin(self, payload, meta: dict,
              timeout_ms: Optional[float]) -> _Attempt:
        fut = Future()
        if isinstance(payload, dict) and ("prompt" in payload
                                          or "src" in payload):
            from .server import GENERATE_META

            path = "/v1/generate"
            body = {}
            for key in ("prompt", "src"):
                if payload.get(key) is not None:
                    body[key] = np.asarray(payload[key]).tolist()
            for k in GENERATE_META:
                if meta.get(k) is not None:
                    body[k] = meta[k]
            if meta.get("model") is not None:
                body["model"] = meta["model"]
        else:
            path = "/v1/infer"
            body = {"inputs": {k: np.asarray(v).tolist()
                               for k, v in payload.items()}}
        if timeout_ms is not None:
            body["timeout_ms"] = timeout_ms
            body["timeout_s"] = timeout_ms / 1e3
        timeout_s = (timeout_ms / 1e3 + 1.0) if timeout_ms is not None \
            else None
        headers = {"traceparent": meta.get("traceparent")}

        def run():
            try:
                out = self._http("POST", path, body, timeout_s=timeout_s,
                                 headers=headers)
                fut.set_result(np.asarray(out["ids"])
                               if "ids" in out
                               else [np.asarray(o)
                                     for o in out["outputs"]])
            except BaseException as exc:  # noqa: BLE001 - typed above
                fut.set_exception(exc)

        threading.Thread(target=run, name=f"fleet-http-{self.name}",
                         daemon=True).start()
        return _Attempt(fut, self)

    def healthz(self) -> dict:
        import urllib.error

        try:
            return self._http("GET", "/healthz")
        except EngineClosedError:
            # 503 carries the state body; re-read it as health, not error
            try:
                import urllib.request

                with urllib.request.urlopen(
                        self.base_url + "/healthz",
                        timeout=self.connect_timeout_s):
                    pass
            except urllib.error.HTTPError as exc:
                try:
                    return json.loads(exc.read() or b"{}")
                except ValueError:
                    pass
            except Exception:  # noqa: BLE001
                pass
            return {"state": "draining", "ok": False}
        except Exception:  # noqa: BLE001 - unreachable == dead
            return {"state": "unreachable", "ok": False}

    def drain(self, wait: bool = True, timeout: float = 30.0) -> None:
        self._http("POST", "/admin/drain",
                   {"wait": wait, "timeout": timeout},
                   timeout_s=timeout + 5.0)
        self._draining = True

    def rejoin(self) -> None:
        self._http("POST", "/admin/resume", {})
        self._draining = False

    def swap_params(self, source, tenant: Optional[str] = None) -> dict:
        body = {"checkpoint_dir": str(source)}
        if tenant is not None:
            body["tenant"] = tenant
        return self._http("POST", "/admin/swap", body, timeout_s=120.0)

    def warm_verify(self) -> Optional[int]:
        out = self._http("POST", "/admin/warm", {}, timeout_s=300.0)
        return out.get("warmed")

    def metrics_snapshot(self) -> dict:
        try:
            return self._http("GET", "/metrics")
        except Exception:  # noqa: BLE001 - metrics are best-effort
            return {}


# ---------------------------------------------------------------------------
# the fleet
# ---------------------------------------------------------------------------
class Fleet:
    """N replicas + a Router, behind one ``submit()``.

    replicas:        Replica instances, engines, or Servers (the latter
                     two are wrapped in LocalReplica).
    policy:          router pick policy (default LeastLoadedPolicy).
    retry:           a ``resilience.Retry`` carrying the backoff/jitter/
                     max_attempts knobs for per-request resubmission
                     (its ``deadline`` is ignored — each request's own
                     deadline governs).
    hedge:           fire a second attempt on another replica when the
                     first is still unanswered after the hedge delay.
    hedge_delay_ms:  fixed hedge delay; None derives it from the P99 of
                     observed attempt latency (>= ``hedge_min_ms``).
    max_pending:     fleet-wide admission bound — beyond it submits shed
                     with FleetOverloadedError (Retry-After attached).
    breaker:         kwargs for each replica's CircuitBreaker.
    """

    def __init__(self, replicas: Sequence, *, policy=None,
                 retry: Optional[Retry] = None, hedge: bool = True,
                 hedge_delay_ms: Optional[float] = None,
                 hedge_min_ms: float = 20.0, max_pending: int = 256,
                 default_timeout_ms: Optional[float] = 30_000.0,
                 breaker: Optional[dict] = None, workers: Optional[int] = None,
                 metrics: Optional[MetricsRegistry] = None, slo=None,
                 lineage_limit: int = 512):
        from ..trace.slo import SLOTracker

        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.metrics = metrics or MetricsRegistry()
        # fleet-level SLO: evaluated over the MERGED replica histograms
        # (bucket sums), so attainment/burn are correct fleet-wide
        self.slo_tracker = SLOTracker(slo) if slo is not None else None
        # a paddle_tpu.online.Publisher attaches itself here; /fleet/
        # status then grows the weights/freshness block. Tenant-scoped
        # publishers (Publisher(tenant=...)) register per tenant name
        # instead, each rolling its tenant independently.
        self.publisher = None
        self.tenant_publishers: Dict[str, object] = {}
        # paddle_tpu.feedback hook: attach_feedback() logs every
        # completed request as an impression and opens /v1/outcome
        self.feedback = None
        self.flight = trace.get_recorder()
        self.replicas: List[Replica] = []
        for i, rep in enumerate(replicas):
            if not isinstance(rep, Replica):
                rep = LocalReplica(rep)
            if rep.name == "?":
                rep.name = f"r{i}"
            rep.index = i
            rep.fleet_size = len(replicas)
            self.replicas.append(rep)
        self.router = Router(self.replicas, policy=policy,
                             breaker_kwargs=breaker, metrics=self.metrics)
        self.retry = retry or Retry(max_attempts=3, backoff=0.01,
                                    multiplier=2.0, jitter=0.25,
                                    name="fleet")
        self.hedge = bool(hedge)
        self.hedge_delay_ms = hedge_delay_ms
        self.hedge_min_ms = float(hedge_min_ms)
        self.max_pending = int(max_pending)
        self.default_timeout_ms = default_timeout_ms
        # materialize the headline counters at 0 so dashboards (and the
        # Prometheus text) show them before the first shed/hedge happens
        for counter in ("requests", "completed", "failed", "attempts",
                        "retries", "hedges", "hedge_wins", "sheds",
                        "breaker_opens", "requests_recovered"):
            self.metrics.inc(counter, 0)
        # work-preserving recovery: every admitted generation registers a
        # lineage record; a retry after mid-stream progress RESUMES from
        # the tokens the client already has instead of starting over
        self.lineage = LineageStore(limit=lineage_limit)
        self._lineage_seq = 0
        self._attempt_lat: deque = deque(maxlen=512)  # hedge-delay source
        self._lock = threading.Lock()
        self._pending = 0
        self._closed = False
        self._pool: Optional[ThreadPoolExecutor] = None
        self._workers = workers or max(8, 4 * len(self.replicas))
        self._httpd = None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "Fleet":
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self._workers,
                thread_name_prefix="paddle-tpu-fleet")
            for rep in self.replicas:
                if isinstance(rep, LocalReplica) \
                        and rep.server._thread is None:
                    rep.server.start()
        return self

    def stop(self, drain: bool = False) -> None:
        self._closed = True
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        for rep in self.replicas:
            rep.close(drain=drain)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None

    def __enter__(self) -> "Fleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- admission -------------------------------------------------------
    def submit(self, payload, timeout_ms: Optional[float] = None,
               **meta) -> Future:
        """Route one request through the fleet; returns a Future.

        Sheds (typed FleetOverloadedError, Retry-After attached) when the
        fleet queue is at capacity or no replica can take traffic —
        *before* queueing, so overload degrades into fast typed failures.
        ``meta['session']`` keys session affinity;
        ``meta['idempotent']=False`` disables retries/hedging for
        requests that must execute at most once.
        ``meta['traceparent']`` (a W3C header from an upstream caller)
        resumes that trace; every attempt then re-injects the fleet
        span's own context, so router attempts, hedges, and the winning
        replica's spans all share ONE trace id.
        """
        if self._closed:
            raise EngineClosedError("fleet is stopped")
        self.start()
        if not self.router.any_routable():
            self.metrics.inc("sheds")
            raise FleetOverloadedError(
                "every replica is down or breaker-open; shedding before "
                "queueing", retry_after_s=max(
                    0.05, self.router.min_recovery_s()))
        with self._lock:
            if self._pending >= self.max_pending:
                self.metrics.inc("sheds")
                raise FleetOverloadedError(
                    f"fleet queue at capacity ({self.max_pending})",
                    retry_after_s=0.5)
            self._pending += 1
        self.metrics.inc("requests")
        if timeout_ms is None:
            timeout_ms = self.default_timeout_ms
        deadline = (time.monotonic() + timeout_ms / 1e3
                    if timeout_ms is not None else None)
        fut = Future()
        meta = dict(meta)
        self._pin_seed(meta)
        self._register_lineage(payload, meta, deadline)
        span = trace.start_span(
            "fleet/request", detached=True, timeout_ms=timeout_ms,
            parent=trace.extract(meta.pop("traceparent", None)))
        rid = None
        if self.feedback is not None:
            rid = self.feedback.new_request_id()
            fut.request_id = rid
        self._pool.submit(self._run, fut, payload, meta, deadline,
                          span, rid)
        return fut

    # -- feedback plane --------------------------------------------------
    def attach_feedback(self, hook):
        """Start the impression log on this fleet: every COMPLETED
        request (whichever replica won) logs one record through
        ``hook`` (:class:`paddle_tpu.feedback.FeedbackHook`), submits
        gain a ``request_id``, and the HTTP plane serves
        ``POST /v1/outcome`` into the hook's joiner. The hook's
        ``weights_version`` defaults to the attached Publisher's
        published generation — impressions record which weights served
        them."""
        self.feedback = hook
        if hook.version_source is None:
            hook.version_source = (
                lambda: self.publisher.published_step
                if self.publisher is not None else None)
        return hook

    def _register_lineage(self, payload, meta: dict,
                          deadline: Optional[float]) -> None:
        """Give this generation a recovery identity BEFORE any attempt.

        The record carries the prompt + pinned meta; the ``on_token``
        progress callback streams every emitted token back into it, so
        if the serving attempt dies mid-stream the retry loop can turn
        the next attempt into a resume. Beam jobs are skipped: beams are
        engine-local search state, not a resumable token stream."""
        if not isinstance(payload, dict):
            return
        prompt = payload.get("prompt")
        if prompt is None or meta.get("beam_size"):
            return
        with self._lock:
            self._lineage_seq += 1
            key = f"req-{self._lineage_seq}"
        store = self.lineage
        store.register(key, np.asarray(prompt).reshape(-1).tolist(),
                       meta, deadline)
        meta["_lineage_key"] = key
        meta["on_token"] = (
            lambda step, tok: store.progress(key, step, int(tok)))

    def _maybe_resume(self, meta: dict, span) -> None:
        """Between attempts: if the dead attempt emitted tokens, turn
        this retry into a RESUME — the engine chunk-prefills
        ``prompt + emitted`` and continues at the right step counter,
        never re-decoding a token the client already has."""
        key = meta.get("_lineage_key")
        if key is None:
            return
        rec = self.lineage.get(key)
        if rec is None or not rec.emitted:
            return  # no progress yet — a plain retry from scratch
        rec = self.lineage.mark_recovery(key)
        emitted = rec.resume_tokens()
        meta["resume_tokens"] = emitted
        meta["recovery"] = True
        if rec.recoveries == 1:
            self.metrics.inc("requests_recovered")
        self.metrics.inc("recovered_tokens", len(emitted))
        now = time.perf_counter()
        trace.record("fleet/recover", now, now, parent=span,
                     tokens_reused=len(emitted),
                     recoveries=rec.recoveries)

    def _had_progress(self, meta: dict) -> bool:
        key = meta.get("_lineage_key")
        if key is None:
            return False
        rec = self.lineage.get(key)
        return bool(rec is not None and rec.emitted)

    @staticmethod
    def _pin_seed(meta: dict) -> None:
        """Pin ONE per-request seed BEFORE any attempt dispatches: a
        sampled request served by hedged/retried attempts on different
        replicas must produce identical tokens whichever attempt wins —
        the (request, seed) determinism contract extended fleet-wide."""
        import os

        sp = meta.get("sampling_params")
        sampled = (meta.get("temperature") or 0) > 0 or (
            sp is not None and getattr(sp, "sampled", False))
        if not sampled:
            return
        if sp is not None:
            if sp.seed is None:
                meta["sampling_params"] = sp.with_seed(
                    int.from_bytes(os.urandom(4), "big") & 0x7FFFFFFF)
        elif meta.get("seed") is None:
            meta["seed"] = int.from_bytes(os.urandom(4),
                                          "big") & 0x7FFFFFFF

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 timeout_s: Optional[float] = 60.0, **meta) -> np.ndarray:
        """Blocking convenience wrapper for LM fleets."""
        fut = self.submit({"prompt": prompt},
                          timeout_ms=None if timeout_s is None
                          else timeout_s * 1e3,
                          max_new_tokens=max_new_tokens, eos_id=eos_id,
                          **meta)
        return fut.result(timeout=None if timeout_s is None
                          else timeout_s + 5.0)

    # -- request execution ----------------------------------------------
    def _remaining_ms(self, deadline: Optional[float]) -> Optional[float]:
        if deadline is None:
            return None
        return max(1.0, (deadline - time.monotonic()) * 1e3)

    def _hedge_delay_s(self) -> float:
        if self.hedge_delay_ms is not None:
            return self.hedge_delay_ms / 1e3
        lat = sorted(self._attempt_lat)
        if len(lat) < 16:
            return self.hedge_min_ms / 1e3
        p99 = lat[min(len(lat) - 1, int(round(0.99 * (len(lat) - 1))))]
        return max(self.hedge_min_ms / 1e3, p99)

    def _run(self, fut: Future, payload, meta: dict,
             deadline: Optional[float], span, rid=None) -> None:
        t0 = time.monotonic()
        try:
            result = self._execute(payload, meta, deadline, span)
        except BaseException as exc:  # noqa: BLE001 - typed, re-raised
            self.metrics.inc("failed")
            if span is not None:
                span.finish(status="error", error=repr(exc)[:200])
            fut.set_exception(exc)
        else:
            self.metrics.inc("completed")
            self.metrics.observe_latency(time.monotonic() - t0)
            if span is not None:
                span.finish(status="ok")
            fut.set_result(result)
            if self.feedback is not None and rid is not None:
                # impression AFTER the caller unblocks: one bounded
                # non-blocking append, failures never touch the request
                try:
                    self.feedback.on_served(
                        rid, payload, result, model=meta.get("model"),
                        trace_id=getattr(span, "trace_id", None))
                except Exception:  # noqa: BLE001
                    pass
        finally:
            key = meta.get("_lineage_key")
            if key is not None:
                self.lineage.discard(key)
            with self._lock:
                self._pending -= 1

    def _execute(self, payload, meta: dict, deadline: Optional[float],
                 span):
        """The retry loop: each attempt routes to a replica not yet
        tried (falling back to re-tries when the fleet is smaller than
        max_attempts), with resilience.Retry supplying backoff/jitter
        and the deadline-clamp semantics."""
        tried: List[str] = []
        idempotent = meta.get("idempotent", True)
        remaining = (None if deadline is None
                     else deadline - time.monotonic())
        policy = Retry(
            max_attempts=self.retry.max_attempts if idempotent else 1,
            backoff=self.retry.backoff, multiplier=self.retry.multiplier,
            max_backoff=self.retry.max_backoff, jitter=self.retry.jitter,
            deadline=remaining, retry_on=FLEET_RETRYABLE,
            give_up_on=FLEET_GIVE_UP, name="fleet",
            sleep=self.retry._sleep)

        def one_attempt():
            replica = self.router.route(meta, exclude=tried) \
                or self.router.route(meta)
            if replica is None:
                raise ReplicaUnavailableError(
                    "no routable replica (all draining, dead, or "
                    "breaker-open)")
            if replica.name not in tried:
                tried.append(replica.name)
            if len(tried) > 1:
                self.metrics.inc("retries")
            self._maybe_resume(meta, span)
            return self._attempt_with_hedge(replica, payload, meta,
                                            deadline, span,
                                            hedge=idempotent and self.hedge)

        return policy.call(one_attempt)

    def _begin(self, replica: Replica, payload, meta: dict,
               deadline: Optional[float], span, hedge: bool) -> _Attempt:
        self.metrics.inc("attempts")
        header = trace.inject(span)
        if header is not None:  # the replica resumes THIS trace
            meta = dict(meta, traceparent=header)
        att = replica.begin(payload, meta, self._remaining_ms(deadline))
        att.hedge = hedge
        if span is not None:
            span.set_attrs(replica=replica.name)
        return att

    def _attempt_with_hedge(self, replica: Replica, payload, meta: dict,
                            deadline: Optional[float], span,
                            hedge: bool):
        """Run one attempt; optionally fire a hedge on another replica
        after the hedge delay. First SUCCESS wins (a fast failure lets
        the surviving attempt keep going); raises when every in-flight
        attempt has failed — the caller's Retry decides what's next."""
        try:
            attempts = [self._begin(replica, payload, meta, deadline,
                                    span, hedge=False)]
        except FLEET_RETRYABLE as exc:
            # a synchronous begin() failure (dead transport, closed
            # server) is an outcome too — the breaker must see it
            self.router.record(replica, ok=False,
                               reason=type(exc).__name__)
            now = time.perf_counter()
            trace.record("fleet/attempt", now, now, parent=span,
                         replica=replica.name, hedge=False,
                         status="begin_error", error=repr(exc)[:200])
            raise
        hedge_at = (time.monotonic() + self._hedge_delay_s()
                    if hedge and len(self.replicas) > 1 else None)
        last_exc: Optional[BaseException] = None
        while True:
            for att in list(attempts):
                if not att.done():
                    continue
                t1 = time.perf_counter()
                try:
                    value = att.future.result(timeout=0)
                except BaseException as exc:  # noqa: BLE001 - outcome
                    attempts.remove(att)
                    last_exc = exc
                    self.router.record(att.replica, ok=False,
                                       reason=type(exc).__name__)
                    if isinstance(exc, ConnectionError) \
                            and self._had_progress(meta):
                        # the replica died with a stream in flight:
                        # quarantine it immediately so the resume never
                        # routes back to the corpse
                        self.router.quarantine(
                            att.replica, reason="mid-stream drop")
                    self._attempt_lat.append(t1 - att.t0)
                    self.metrics.observe_latency(t1 - att.t0,
                                                 name="attempt")
                    trace.record("fleet/attempt", att.t0, t1,
                                 parent=span, replica=att.replica.name,
                                 hedge=att.hedge, status="error",
                                 error=repr(exc)[:200])
                    continue
                # success — first answer wins
                self.router.record(att.replica, ok=True)
                self._attempt_lat.append(t1 - att.t0)
                self.metrics.observe_latency(t1 - att.t0, name="attempt")
                trace.record("fleet/attempt", att.t0, t1, parent=span,
                             replica=att.replica.name, hedge=att.hedge,
                             status="ok")
                if att.hedge:
                    self.metrics.inc("hedge_wins")
                for loser in attempts:
                    if loser is not att:
                        self.metrics.inc("hedge_cancelled")
                        self.router.release(loser.replica)
                return value
            if not attempts:
                raise last_exc or ReplicaUnavailableError(
                    "attempt vanished without an outcome")
            now = time.monotonic()
            if deadline is not None and now >= deadline:
                for att in attempts:  # abandoned without an outcome
                    self.router.release(att.replica)
                raise RequestTimeoutError(
                    "fleet deadline expired with attempts still in "
                    f"flight on {[a.replica.name for a in attempts]}")
            if hedge_at is not None and now >= hedge_at:
                hedge_at = None
                exclude = [a.replica.name for a in attempts]
                backup = self.router.route(meta, exclude=exclude)
                if backup is not None:
                    self.metrics.inc("hedges")
                    trace.record("fleet/hedge", time.perf_counter(),
                                 time.perf_counter(), parent=span,
                                 primary=replica.name,
                                 backup=backup.name)
                    try:
                        att = self._begin(backup, payload, meta,
                                          deadline, span, hedge=True)
                    except FLEET_RETRYABLE as exc:
                        self.router.record(backup, ok=False,
                                           reason=type(exc).__name__)
                    else:
                        attempts.append(att)
            time.sleep(_POLL_S)

    # -- rolling weight updates ------------------------------------------
    def update_weights(self, checkpoint_dir: str, *, verify: bool = True,
                       drain_timeout: float = 30.0,
                       tenant: Optional[str] = None) -> dict:
        """Zero-downtime rolling param swap: one replica at a time is
        drained (healthz flips to 503, the router stops sending, in-
        flight work finishes), hot-swapped from ``checkpoint_dir`` (a
        resilience checkpoint dir or a ``save_inference_model`` dir —
        same shapes/dtypes, so the warm compile caches survive),
        warm-verified (manifest replay), and rejoined before the next
        one drains. The rest of the fleet serves throughout.

        ``tenant=`` narrows the roll to ONE resident model on
        multi-tenant replicas: the replica stays ready (no whole-server
        drain) and the server drains just that tenant's queue/engines —
        the other tenants never see the update."""
        results = []
        for rep in self.replicas:
            t0 = time.monotonic()
            with trace.span("fleet/rolling_update", replica=rep.name,
                            checkpoint_dir=str(checkpoint_dir),
                            tenant=tenant or ""):
                if tenant is None:
                    rep.drain(wait=True, timeout=drain_timeout)
                try:
                    # untenanted rolls keep the pre-tenancy call shape so
                    # single-model replicas (old swap_params signature)
                    # serve unchanged
                    if tenant is None:
                        swap = rep.swap_params(checkpoint_dir)
                    else:
                        swap = rep.swap_params(checkpoint_dir, tenant=tenant)
                    warmed = rep.warm_verify() if verify else None
                finally:
                    if tenant is None:
                        rep.rejoin()
            self.metrics.inc("weight_updates")
            results.append({"replica": rep.name, "swap": swap,
                            "warm_verified": warmed,
                            "seconds": round(time.monotonic() - t0, 6)})
        self.metrics.inc("rolling_updates")
        return {"checkpoint_dir": str(checkpoint_dir),
                "replicas": results}

    # -- observability ----------------------------------------------------
    def _replica_by(self, key) -> Replica:
        for rep in self.replicas:
            if rep.name == key or rep.index == key:
                return rep
        raise KeyError(f"no replica {key!r}; have "
                       f"{[r.name for r in self.replicas]}")

    def _refresh_labels(self) -> None:
        if self.publisher is not None:
            self.publisher.refresh_gauges()
        for pub in self.tenant_publishers.values():
            pub.refresh_gauges()
        for rep in self.replicas:
            health = rep.healthz()
            self.metrics.set_labeled(
                "fleet_replica_health",
                1.0 if health.get("state") == "ready" else 0.0,
                replica=rep.name, state=health.get("state", "?"))
            self.metrics.set_labeled("fleet_replica_inflight",
                                     rep.inflight, replica=rep.name)
        from .router import BREAKER_GAUGE

        for name, state in self.router.breaker_states().items():
            self.metrics.set_labeled("fleet_breaker_state",
                                     BREAKER_GAUGE[state], replica=name)

    @staticmethod
    def _decode_latency_cols(snap: dict) -> dict:
        """Per-replica TTFT/TPOT columns for /fleet/status, read from a
        replica's snapshot histograms (None until it has decoded)."""
        hist = (snap or {}).get("hist") or {}
        out = {}
        for metric in ("ttft", "tpot"):
            h = hist.get(metric) or {}
            for q in ("p50_ms", "p99_ms"):
                val = h.get(q)
                out[f"{metric}_{q}"] = (None if not h.get("count")
                                        else round(float(val), 3))
        return out

    def status(self) -> dict:
        self._refresh_labels()
        rep_snaps = {rep.name: rep.metrics_snapshot()
                     for rep in self.replicas}
        merged = MetricsRegistry.merge(rep_snaps)
        status = {
            "replicas": [dict({
                "name": rep.name,
                "index": rep.index,
                "health": rep.healthz(),
                "inflight": rep.inflight,
                "breaker": self.router.breakers[rep.name].state,
            }, **self._decode_latency_cols(rep_snaps.get(rep.name)))
                for rep in self.replicas],
            "pending": self._pending,
            "max_pending": self.max_pending,
            "hedge": self.hedge,
            "hedge_delay_ms": round(self._hedge_delay_s() * 1e3, 3),
            "counters": self.metrics.snapshot()["counters"],
            "fleet": self._decode_latency_cols(merged),
            # always present so fleetctl renders a stable schema: null
            # when no SLO is configured / no publisher attached
            "slo": (self.slo_tracker.status(self._slo_view(merged))
                    if self.slo_tracker is not None else None),
            "weights": (self.publisher.status()
                        if self.publisher is not None else None),
            # multi-tenant replicas: per-tenant rows (queue/SLO burn/
            # weights version/pages), merged with any tenant-scoped
            # publishers — what fleetctl's TENANTS table renders
            "tenants": self._tenant_rows(),
        }
        return status

    def _tenant_rows(self) -> Optional[list]:
        rows = None
        for rep in self.replicas:
            ts = getattr(getattr(rep, "server", None),
                         "tenant_status", None)
            if ts is not None:
                rows = ts()
                break
        if rows is None and not self.tenant_publishers:
            return None
        rows = rows or [{"tenant": name}
                        for name in sorted(self.tenant_publishers)]
        for row in rows:
            pub = self.tenant_publishers.get(row.get("tenant"))
            if pub is not None:
                row["weights"] = pub.status()
                if pub.published_step is not None:
                    row["weights_version"] = float(pub.published_step)
        return rows

    def _slo_view(self, merged: dict) -> dict:
        """What the SLO evaluates: the fleet-merged decode histograms +
        the FLEET's own completed/failed counters (availability is a
        property of the fleet's answers, retries/hedges included — a
        replica-level failure the router absorbed doesn't burn
        budget) + the fleet's own gauges (the publisher's
        weights-staleness freshness signal)."""
        snap = self.metrics.snapshot()
        return {"hist": merged.get("hist") or {},
                "counters": snap["counters"],
                "gauges": snap.get("gauges") or {}}

    def metrics_snapshot(self) -> dict:
        """Fleet registry + MetricsRegistry.merge() of every replica's
        snapshot — the /metrics body."""
        self._refresh_labels()
        merged = MetricsRegistry.merge(
            {rep.name: rep.metrics_snapshot() for rep in self.replicas})
        if self.slo_tracker is not None:
            self.slo_tracker.publish_gauges(
                self.metrics,
                self.slo_tracker.status(self._slo_view(merged)))
        snap = self.metrics.snapshot()
        snap["fleet"] = merged
        if self.slo_tracker is not None:
            snap["slo"] = self.slo_tracker.status()
        return snap

    def metrics_prometheus(self) -> str:
        self._refresh_labels()
        if self.slo_tracker is not None:
            merged = MetricsRegistry.merge(
                {rep.name: rep.metrics_snapshot()
                 for rep in self.replicas})
            self.slo_tracker.publish_gauges(
                self.metrics,
                self.slo_tracker.status(self._slo_view(merged)))
        return self.metrics.prometheus_text()

    # -- HTTP front end ---------------------------------------------------
    def serve_http(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """The fleet's own JSON endpoint: /v1/* data plane routed through
        the fleet, /fleet/* control plane for ``tools/fleetctl.py``."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        fleet = self
        self.start()
        # operator poke: SIGUSR1 dumps a flight bundle (best-effort —
        # a no-op off the main thread)
        trace.install_signal_handler(recorder=self.flight)

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code: int, obj, headers=()) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path == "/healthz":
                    ok = fleet.router.any_routable() \
                        and not fleet._closed
                    self._send(200 if ok else 503, {
                        "ok": ok,
                        "state": "ready" if ok else "unavailable",
                        "replicas": {
                            r.name: r.healthz().get("state")
                            for r in fleet.replicas},
                    })
                elif path == "/metrics":
                    if "format=prom" in query:
                        body = fleet.metrics_prometheus().encode()
                        self.send_response(200)
                        self.send_header(
                            "Content-Type",
                            "text/plain; version=0.0.4; charset=utf-8")
                        self.send_header("Content-Length",
                                         str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    else:
                        self._send(200, fleet.metrics_snapshot())
                elif path == "/fleet/status":
                    self._send(200, fleet.status())
                elif path == "/fleet/flightdump":
                    self._send(200, fleet.flight.bundle("admin"))
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, TypeError) as exc:
                    self._send(400, {"error": f"bad JSON: {exc}"})
                    return
                try:
                    self._route_post(req)
                except KeyError as exc:
                    self._send(400, {"error": f"missing field {exc}"})
                except BadRequestError as exc:
                    self._send(400, {"error": str(exc)})
                except FleetOverloadedError as exc:
                    self._send(503, {"error": str(exc),
                                     "retry_after_s": exc.retry_after_s},
                               headers=[("Retry-After", str(max(
                                   1, int(round(exc.retry_after_s)))))])
                except QueueFullError as exc:
                    self._send(429, {"error": str(exc)})
                except (RequestTimeoutError, TimeoutError) as exc:
                    self._send(504, {"error": str(exc) or "timed out"})
                except ModelNotFoundError as exc:
                    self._send(404, {"error": str(exc)})
                except (EngineClosedError, ServingError) as exc:
                    self._send(503, {"error": str(exc)})
                except ConnectionError as exc:
                    # retries exhausted against dead replicas
                    self._send(502, {"error": str(exc)})
                except Exception as exc:  # noqa: BLE001 - don't drop conn
                    self._send(500, {"error": repr(exc)[:300]})

            def _route_post(self, req):
                meta = {k: req[k] for k in ("session", "idempotent")
                        if k in req}
                tp = self.headers.get("traceparent")
                if tp:
                    meta["traceparent"] = tp
                if self.path == "/v1/generate":
                    from .server import GENERATE_META

                    meta.update({k: req[k] for k in GENERATE_META
                                 if req.get(k) is not None})
                    model = (req.get("model")
                             if req.get("model") is not None
                             else req.get("tenant"))
                    if model is not None:
                        meta["model"] = model
                    payload = ({"src": req["src"],
                                "prompt": req.get("prompt")}
                               if req.get("src") is not None
                               else {"prompt": req["prompt"]})
                    fut = fleet.submit(payload,
                                       timeout_ms=req.get("timeout_ms"),
                                       **meta)
                    res = fut.result(timeout=req.get("timeout_s", 60))
                    rid = getattr(fut, "request_id", None)
                    if isinstance(res, tuple):
                        ids, scores = res
                        body = {
                            "ids": np.asarray(ids)[0].tolist(),
                            "beams": np.asarray(ids).tolist(),
                            "scores": np.asarray(scores).tolist()}
                    else:
                        body = {"ids": np.asarray(res).tolist()}
                    if rid is not None:  # feedback plane attached
                        body["request_id"] = rid
                    self._send(200, body)
                elif self.path == "/v1/infer":
                    inputs = {k: np.asarray(v)
                              for k, v in req["inputs"].items()}
                    fut = fleet.submit(inputs,
                                       timeout_ms=req.get("timeout_ms"),
                                       **meta)
                    outs = fut.result(timeout=req.get("timeout_s", 60))
                    body = {"outputs": [np.asarray(o).tolist()
                                        for o in outs]}
                    rid = getattr(fut, "request_id", None)
                    if rid is not None:  # feedback plane attached
                        body["request_id"] = rid
                    self._send(200, body)
                elif self.path == "/v1/outcome":
                    joiner = getattr(fleet.feedback, "joiner", None)
                    if joiner is None:
                        self._send(404, {
                            "error": "no outcome joiner attached to "
                                     "this fleet"})
                    else:
                        status = joiner.post_outcome(
                            req["request_id"],
                            req.get("outcome", req.get("label")))
                        self._send(200, {"status": status})
                elif self.path == "/fleet/drain":
                    rep = fleet._replica_by(req["replica"])
                    rep.drain(wait=req.get("wait", True),
                              timeout=req.get("timeout", 30.0))
                    self._send(200, {"ok": True,
                                     "state": rep.healthz()})
                elif self.path == "/fleet/resume":
                    rep = fleet._replica_by(req["replica"])
                    rep.rejoin()
                    self._send(200, {"ok": True,
                                     "state": rep.healthz()})
                elif self.path == "/fleet/update_weights":
                    out = fleet.update_weights(
                        req["checkpoint_dir"],
                        verify=req.get("verify", True),
                        tenant=req.get("tenant"))
                    self._send(200, out)
                elif self.path == "/fleet/chaos":
                    from ..resilience.faults import (FaultPlan,
                                                     install_plan)

                    plan = FaultPlan.parse(req["plan"])
                    install_plan(plan)
                    self._send(200, {"ok": True,
                                     "pending": plan.pending()})
                else:
                    self._send(404, {"error": "not found"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        threading.Thread(target=self._httpd.serve_forever,
                         name="paddle-tpu-fleet-http",
                         daemon=True).start()
        return self._httpd.server_address[1]

"""Server front end: a dispatch thread over engines + a JSON HTTP endpoint.

``Server`` owns the DynamicBatcher and a daemon dispatch loop that drives
one or more engines' ``serve_step`` — an InferenceEngine executes whole
batches, a GenerationEngine interleaves prefill admissions with decode
ticks (continuous batching). Multiple engines round-robin the shared
queue: the local-replica pattern (one engine per device via ``place``).

The HTTP endpoint is stdlib ``http.server`` (no framework dependency —
the container bakes none), JSON in/out:

    POST /v1/generate  {"prompt": [ids], "max_new_tokens": n, "eos_id": e,
                        # decode-platform fields (all optional; absent =
                        # legacy greedy, byte-identical):
                        "temperature": t, "top_k": k, "top_p": p,
                        "seed": s, "stop": [[ids], ...],
                        "beam_size": K, "length_penalty": a,
                        "return_beams": bool,
                        # seq2seq engines: "src" replaces/joins "prompt"
                        "src": [ids]}
                       -> {"ids": [...]} (+ "beams"/"scores" for
                       return_beams)
    POST /v1/infer     {"inputs": {feed: nested-list-row}}
                       -> {"outputs": [...]}
    GET  /metrics      -> MetricsRegistry snapshot + serving timers
    GET  /metrics?format=prom -> Prometheus text exposition (v0.0.4),
                       also selected by an Accept: text/plain header
    GET  /healthz      -> {"ok": true, "active": ..., "queue": ...};
                       503 while ``warming`` (boot-time manifest replay /
                       warmup) or ``draining``, so routers only send
                       traffic to ready replicas

Typed errors map onto status codes: QueueFullError -> 429,
RequestTimeoutError -> 504, BadRequestError -> 400.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

import numpy as np

from .. import profiler, trace
from ..resilience.faults import TransientFault, active_plan
from ..trace import flight as trace_flight
from ..trace.slo import SLOTracker
from .batcher import DynamicBatcher, Future
from .errors import (BadRequestError, EngineClosedError,
                     ModelNotFoundError, QueueFullError,
                     RequestTimeoutError, ServingError)
from .metrics import MetricsRegistry

_IDLE_WAIT_S = 0.02  # dispatch-loop poll when the queue is empty

#: /v1/generate request fields forwarded into the engine meta — the
#: decode-platform schema (paddle_tpu.decoding.SamplingParams/BeamParams)
GENERATE_META = ("max_new_tokens", "eos_id", "temperature", "top_k",
                 "top_p", "seed", "stop", "beam_size", "length_penalty",
                 "return_beams",
                 # work-preserving recovery: a resumed stream carries the
                 # already-emitted tokens (re-entering as prefill
                 # context) and the recovery flag (priority admission)
                 "resume_tokens", "recovery")


class Server:
    """Dispatch loop + admission queue over one or more engines."""

    def __init__(self, engine, *, batcher: Optional[DynamicBatcher] = None,
                 batch_buckets: Sequence[int] = (1, 2, 4, 8),
                 max_wait_ms: float = 5.0, max_queue: int = 256,
                 default_timeout_ms: Optional[float] = None,
                 metrics: Optional[MetricsRegistry] = None,
                 serve_retry=None, warmup=False, slo=None,
                 model_ids: Sequence[str] = ()):
        self.engines = list(engine) if isinstance(
            engine, (list, tuple)) else [engine]
        self.metrics = metrics or self.engines[0].metrics
        # ids this replica answers a "model"/"tenant" request field
        # with; anything else is a typed 404 — an unknown id must never
        # silently fall through to the default engine
        self.model_ids = tuple(model_ids)
        self.batcher = batcher or DynamicBatcher(
            buckets=batch_buckets, max_wait_ms=max_wait_ms,
            max_queue=max_queue, default_timeout_ms=default_timeout_ms,
            metrics=self.metrics)
        if self.batcher.metrics is None:
            self.batcher.metrics = self.metrics
        # Optional resilience.Retry applied around each serve_step: a
        # transient dispatch failure (ConnectionError/TimeoutError/
        # injected TransientFault) retries with backoff instead of
        # failing the whole formed batch.
        self._serve_retry = serve_retry
        # warmup=True runs each engine's warm_start()/warmup() on the
        # dispatch thread before serving; a callable runs instead of the
        # default. While it runs, /healthz reports ``warming`` (503) so a
        # router never sends traffic to a cold replica — the boot-side
        # mirror of the drain machinery.
        self._warmup = warmup
        # declarative SLO (trace.SLO): evaluated from the TTFT/TPOT/
        # request histograms on every metrics render; burn-rate gauges
        # land on /metrics?format=prom
        self.slo_tracker = (SLOTracker(slo) if slo is not None else None)
        # flight recorder: dispatch-loop errors capture a bundle
        # (throttled); /admin/flightdump serves it on demand
        self.flight = trace_flight.get_recorder()
        self._dispatch_step = 0
        self._thread: Optional[threading.Thread] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._running = False
        self._paused = False
        self._state = "ready"
        # feedback plane (paddle_tpu.feedback): attach_feedback() starts
        # impression logging + the /v1/outcome endpoint
        self.feedback = None

    @property
    def state(self) -> str:
        """``warming`` | ``ready`` | ``draining`` | ``closed`` — what
        /healthz reports (load balancers route to ``ready`` only:
        ``warming`` covers boot exactly like ``draining`` covers
        shutdown)."""
        return self._state

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "Server":
        if self._thread is not None:
            return self
        self._running = True
        self._paused = False
        self._state = "warming" if self._warmup else "ready"
        self._thread = threading.Thread(target=self._loop,
                                        name="paddle-tpu-serving",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = False, timeout: float = 30.0) -> None:
        """Stop the server. Default fails queued requests immediately;
        ``drain=True`` first stops admissions (submit raises
        EngineClosedError, /healthz flips to ``draining``/503), lets the
        dispatch loop finish the backlog (bounded by ``timeout``), and
        gracefully releases engines that support ``close``."""
        if drain:
            self._state = "draining"
            for b in self._batchers():
                b.close(drain=True)
            deadline = time.monotonic() + timeout
            while self._queue_depth() > 0 and time.monotonic() < deadline:
                time.sleep(0.01)
        self._running = False
        for b in self._batchers():
            b.close()  # fail whatever remains (no-op when drained)
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if drain:
            # graceful shutdown releases the engines too; the default
            # stop() leaves them usable (tests restart servers on them)
            for eng in self.engines:
                if hasattr(eng, "close"):
                    try:
                        eng.close(drain=True)
                    except TypeError:  # engines with a plain close()
                        eng.close()
        self._state = "closed"
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- reversible drain (rolling updates) --------------------------------
    def pause(self, wait: bool = True, timeout: float = 30.0) -> None:
        """REVERSIBLE drain — the per-replica step of a rolling weight
        update. Admissions stop (submit raises EngineClosedError,
        /healthz flips to ``draining``/503 so routers hold traffic) but
        the dispatch loop keeps running and finishes the backlog;
        ``wait=True`` blocks (bounded by ``timeout``) until the queue is
        empty and every engine is idle — the safe point for
        ``swap_params``. :meth:`resume` rejoins. Unlike :meth:`stop`,
        nothing is closed."""
        self._paused = True
        if self._state == "ready":
            self._state = "draining"
        if not wait:
            return
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            busy = self._queue_depth() > 0 or any(
                getattr(eng, "active", 0) or getattr(eng, "_inflight", 0)
                for eng in self.engines)
            if not busy:
                break
            time.sleep(0.005)

    def resume(self) -> None:
        """Rejoin after :meth:`pause`: admissions reopen and /healthz
        reports ``ready`` again."""
        self._paused = False
        if self._state == "draining" and self._running:
            self._state = "ready"

    def swap_params(self, source, *, strict: bool = True,
                    tenant: Optional[str] = None) -> dict:
        """Hot-swap every engine's params (see engine.swap_params);
        call between :meth:`pause` and :meth:`resume`. ``tenant``
        scopes the swap on a multi-tenant server; this single-model
        server answers a tenant-scoped swap with a typed 404."""
        if tenant is not None:
            raise ModelNotFoundError(
                f"unknown tenant {tenant!r}: this replica hosts one "
                "unnamed model (tenant-scoped swaps need a "
                "MultiTenantServer)")
        stats: dict = {}
        for eng in self.engines:
            for k, v in eng.swap_params(source, strict=strict).items():
                stats[k] = stats.get(k, 0) + v
        return stats

    def _do_warmup(self) -> None:
        """Manifest replay / warmup on the dispatch thread, before the
        first batch is pulled. Requests submitted meanwhile queue in the
        batcher; /healthz says ``warming`` so routers hold traffic. A
        warmup failure downgrades to lazy compiles instead of killing the
        replica."""
        t0 = time.monotonic()
        try:
            if callable(self._warmup):
                self._warmup()
            else:
                for eng in self.engines:
                    if not self._running:
                        break
                    warm = (getattr(eng, "warm_start", None)
                            or getattr(eng, "warmup", None))
                    if warm is not None:
                        warm()
        except Exception:  # noqa: BLE001 - cold replica beats dead replica
            self.metrics.inc("warmup_errors")
        self.metrics.set_gauge("warmup/boot_s",
                               round(time.monotonic() - t0, 6))
        if self._state == "warming":  # stop() during warmup wins
            self._state = "ready"

    def _batchers(self):
        """Every admission queue this server owns — one for the base
        server; one per tenant on a MultiTenantServer."""
        return [self.batcher]

    def _queue_depth(self) -> int:
        return sum(b.depth for b in self._batchers())

    def _dispatch_pairs(self):
        """(engine, batcher) pairs the dispatch loop round-robins. The
        base server shares ONE admission queue across its engines; a
        MultiTenantServer pairs each tenant's engines with that
        tenant's own queue."""
        return [(eng, self.batcher) for eng in self.engines]

    def _loop(self) -> None:
        if self._warmup:
            self._do_warmup()
        idx = 0
        while self._running:
            pairs = self._dispatch_pairs()
            engine, batcher = pairs[idx % len(pairs)]
            idx += 1
            try:
                plan = active_plan()
                if plan is not None and plan.fire(
                        "executor_error", self._dispatch_step) is not None:
                    raise TransientFault(
                        "injected executor_error (fault plan) in the "
                        "serving dispatch loop")
                if self._serve_retry is not None:
                    did = self._serve_retry.call(
                        engine.serve_step, batcher,
                        idle_wait_s=_IDLE_WAIT_S)
                else:
                    did = engine.serve_step(batcher,
                                            idle_wait_s=_IDLE_WAIT_S)
            except Exception as exc:  # noqa: BLE001 - keep dispatching
                # engine errors fail their requests individually; a crash
                # here would silently stop dispatch — keep looping, but
                # FIRST capture the flight bundle: spans, metric history
                # and engine state at the moment it fell over
                self.metrics.inc("dispatch_errors")
                self.flight.auto_dump("dispatch_error", error=exc)
                did = False
            else:
                if did:
                    self._dispatch_step += 1
            if not did and len(pairs) > 1:
                continue  # try the next replica before idling

    # -- in-process API ----------------------------------------------------
    def submit(self, payload, timeout_ms: Optional[float] = None,
               **meta) -> Future:
        """Enqueue a request; returns a Future. Raises QueueFullError on
        backpressure. For generation engines the payload is a prompt (or
        {"prompt": ids}) with max_new_tokens/eos_id in ``meta``; for
        inference engines it is a per-row feed dict."""
        if self._paused:
            raise EngineClosedError(
                "server is draining (paused for a rolling update); "
                "route to another replica")
        model = meta.pop("model", None)
        if model is not None and model not in self.model_ids:
            self.metrics.inc("model_not_found")
            raise ModelNotFoundError(
                f"unknown model/tenant {model!r}: this replica serves "
                + (f"{sorted(self.model_ids)}" if self.model_ids
                   else "one unnamed model"))
        fut = self.batcher.submit(payload, timeout_ms=timeout_ms, **meta)
        return self._feedback_tap(fut, payload, model)

    # -- feedback plane ----------------------------------------------------
    def attach_feedback(self, hook) -> "Server":
        """Start logging served impressions through ``hook``
        (:class:`paddle_tpu.feedback.FeedbackHook`): every successful
        submit gains a ``request_id`` (returned on the HTTP surface) and
        lands one impression record in the hook's log; ``POST
        /v1/outcome`` routes into the hook's joiner."""
        self.feedback = hook
        return self

    def _feedback_tap(self, fut: Future, payload, model):
        """Tag the future with a request id and log the impression at
        completion. The tap rides set_result (success only — failed
        requests are not impressions) and costs one bounded-buffer
        append on the dispatch thread; the serving thread pays
        nothing."""
        fb = self.feedback
        if fb is None:
            return fut
        rid = fb.new_request_id()
        fut.request_id = rid
        inner = fut.set_result

        def tapped(result, _inner=inner, _rid=rid, _payload=payload,
                   _model=model):
            _inner(result)
            try:
                fb.on_served(_rid, _payload, result, model=_model)
            except Exception:  # noqa: BLE001 - never fail the request
                pass

        fut.set_result = tapped
        return fut

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 timeout_s: Optional[float] = 60.0) -> np.ndarray:
        """Blocking convenience wrapper around submit() for LM engines."""
        fut = self.submit({"prompt": prompt},
                          max_new_tokens=max_new_tokens, eos_id=eos_id)
        return fut.result(timeout=timeout_s)

    def metrics_snapshot(self) -> dict:
        self.metrics.update_device_gauges()
        snap = self.metrics.merge_timer_dict(
            profiler.global_stat.as_dict(prefix="serving/"))
        for i, eng in enumerate(self.engines):
            if hasattr(eng, "cache_stats"):
                snap[f"compile_cache/engine{i}"] = eng.cache_stats()
        snap["queue_depth"] = self._queue_depth()
        if self.slo_tracker is not None:
            snap["slo"] = self.slo_tracker.publish_gauges(
                self.metrics, self.slo_tracker.status(snap))
        return snap

    def metrics_prometheus(self) -> str:
        """The /metrics?format=prom body: Prometheus text exposition of
        the registry + serving timers + compile-cache/queue gauges +
        TTFT/TPOT histograms and SLO burn-rate gauges."""
        self.metrics.update_device_gauges()
        self.metrics.set_gauge("queue_depth", self._queue_depth())
        for i, eng in enumerate(self.engines):
            if hasattr(eng, "cache_stats"):
                for k, v in eng.cache_stats().items():
                    self.metrics.set_gauge(f"compile_cache/e{i}_{k}", v)
        if self.slo_tracker is not None:
            self.slo_tracker.publish_gauges(
                self.metrics,
                self.slo_tracker.status(self.metrics.snapshot()))
        return self.metrics.prometheus_text(
            timers=profiler.global_stat.as_dict(prefix="serving/"))

    # -- HTTP front end ----------------------------------------------------
    def serve_http(self, host: str = "127.0.0.1", port: int = 0,
                   socket_timeout_s: Optional[float] = 30.0) -> int:
        """Start the JSON endpoint on a daemon thread; returns the bound
        port (pass port=0 for an ephemeral one).

        ``socket_timeout_s`` bounds how long a stalled client may hold a
        handler thread: the per-connection socket timeout covers both
        the request line and the body read — a client that stops sending
        mid-request gets 408 (when addressable) and the connection is
        closed, counted as ``http_408_timeouts`` in the
        MetricsRegistry. Without it, one dead client per thread is a
        slow-loris outage."""
        server = self
        # operator poke: SIGUSR1 dumps a flight bundle (written to
        # $PADDLE_TPU_FLIGHT_DIR when set; in-memory last_bundle
        # always). Best-effort — a no-op off the main thread.
        trace_flight.install_signal_handler(recorder=self.flight)

        class Handler(BaseHTTPRequestHandler):
            timeout = socket_timeout_s  # socketserver: settimeout per conn

            def log_message(self, *a):  # quiet: metrics carry the signal
                pass

            def log_error(self, fmt, *args):
                # stdlib handle_one_request swallows a request-line
                # timeout after logging it — the only seam to count it
                if fmt.startswith("Request timed out"):
                    server.metrics.inc("http_408_timeouts")

            def _send(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path == "/metrics":
                    want_prom = ("format=prom" in query
                                 or "text/plain" in
                                 (self.headers.get("Accept") or ""))
                    if want_prom:
                        body = server.metrics_prometheus().encode()
                        self.send_response(200)
                        self.send_header(
                            "Content-Type",
                            "text/plain; version=0.0.4; charset=utf-8")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    self._send(200, server.metrics_snapshot())
                elif path == "/admin/flightdump":
                    # GET = read-only: assemble and return the bundle
                    self._send(200, server.flight.bundle("admin"))
                elif path == "/healthz":
                    # ready -> 200; warming/draining/closed -> 503 so load
                    # balancers route neither to a cold replica still
                    # compiling nor to one finishing in-flight work
                    state = server.state
                    self._send(200 if state == "ready" else 503, {
                        "ok": state == "ready",
                        "state": state,
                        "queue": server._queue_depth(),
                        "engines": len(server.engines),
                        "engine_states": [getattr(e, "state", "ready")
                                          for e in server.engines],
                    })
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(n)
                except TimeoutError:
                    # stalled client mid-body: free the thread with 408
                    # instead of holding it for the connection's lifetime
                    server.metrics.inc("http_408_timeouts")
                    self.close_connection = True
                    try:
                        self._send(408, {"error": "client stalled; "
                                         "request body timed out"})
                    except OSError:
                        pass  # peer already gone
                    return
                except (ValueError, TypeError) as exc:
                    self._send(400, {"error": f"bad length: {exc}"})
                    return
                try:
                    req = json.loads(raw or b"{}")
                except (ValueError, TypeError) as exc:
                    self._send(400, {"error": f"bad JSON: {exc}"})
                    return
                try:
                    # resume the caller's trace across the HTTP hop: the
                    # request's queue/prefill/decode spans join the
                    # router's trace id instead of starting a fresh one
                    tmeta = {}
                    tp = self.headers.get("traceparent")
                    if tp:
                        tmeta["traceparent"] = tp
                    if self.path.startswith("/admin/"):
                        self._admin(req)
                    elif self.path == "/v1/generate":
                        # sampling / stop / beam request fields — absent
                        # fields keep the legacy greedy behavior
                        # byte-identical (GENERATE_META names the schema)
                        meta = {k: req[k] for k in GENERATE_META
                                if req.get(k) is not None}
                        # multi-tenant routing field ("tenant" is an
                        # accepted alias); unknown ids are a typed 404
                        model = (req.get("model")
                                 if req.get("model") is not None
                                 else req.get("tenant"))
                        if model is not None:
                            meta["model"] = model
                        payload = ({"src": req["src"],
                                    "prompt": req.get("prompt")}
                                   if req.get("src") is not None
                                   else {"prompt": req["prompt"]})
                        fut = server.submit(
                            payload, timeout_ms=req.get("timeout_ms"),
                            **meta, **tmeta)
                        res = fut.result(timeout=req.get("timeout_s", 60))
                        rid = getattr(fut, "request_id", None)
                        if isinstance(res, tuple):  # all beams requested
                            ids, scores = res
                            body = {
                                "ids": np.asarray(ids)[0].tolist(),
                                "beams": np.asarray(ids).tolist(),
                                "scores": np.asarray(scores).tolist()}
                        else:
                            body = {"ids": np.asarray(res).tolist()}
                        if rid is not None:  # feedback plane attached
                            body["request_id"] = rid
                        self._send(200, body)
                    elif self.path == "/v1/adopt":
                        # cross-process KV handoff: the prefill pool
                        # POSTs serialized page ranges + the block
                        # table; the engine installs them and resumes
                        # decode (never a prefill recompute). Blocks
                        # until generation completes, like /v1/generate.
                        meta = {}
                        model = (req.get("model")
                                 if req.get("model") is not None
                                 else req.get("tenant"))
                        if model is not None:
                            meta["model"] = model
                        fut = server.submit(
                            {"handoff": req["handoff"]},
                            timeout_ms=req.get("timeout_ms"),
                            **meta, **tmeta)
                        res = fut.result(timeout=req.get("timeout_s", 60))
                        self._send(200,
                                   {"ids": np.asarray(res).tolist()})
                    elif self.path == "/v1/infer":
                        inputs = {k: np.asarray(v)
                                  for k, v in req["inputs"].items()}
                        fut = server.submit(inputs,
                                            timeout_ms=req.get("timeout_ms"),
                                            **tmeta)
                        outs = fut.result(timeout=req.get("timeout_s", 60))
                        body = {"outputs": [
                            np.asarray(o).tolist() for o in outs]}
                        rid = getattr(fut, "request_id", None)
                        if rid is not None:  # feedback plane attached
                            body["request_id"] = rid
                        self._send(200, body)
                    elif self.path == "/v1/outcome":
                        # the joiner ingress: outcomes post back keyed
                        # by the request_id a /v1/* response carried
                        fb = server.feedback
                        joiner = getattr(fb, "joiner", None)
                        if joiner is None:
                            self._send(404, {
                                "error": "no outcome joiner attached "
                                         "to this replica"})
                        else:
                            status = joiner.post_outcome(
                                req["request_id"],
                                req.get("outcome", req.get("label")))
                            self._send(200, {"status": status})
                    else:
                        self._send(404, {"error": "not found"})
                except KeyError as exc:
                    self._send(400, {"error": f"missing field {exc}"})
                except ValueError as exc:  # e.g. swap shape mismatch
                    self._send(400, {"error": str(exc)})
                except BadRequestError as exc:
                    self._send(400, {"error": str(exc)})
                except QueueFullError as exc:
                    self._send(429, {"error": str(exc)})
                except (RequestTimeoutError, TimeoutError) as exc:
                    self._send(504, {"error": str(exc) or "timed out"})
                except ModelNotFoundError as exc:
                    self._send(404, {"error": str(exc)})
                except (EngineClosedError, ServingError) as exc:
                    self._send(503, {"error": str(exc)})

            def _admin(self, req):
                """Replica control plane — what HttpReplica and
                tools/fleetctl.py drive during rolling updates."""
                if self.path == "/admin/drain":
                    server.pause(wait=req.get("wait", True),
                                 timeout=req.get("timeout", 30.0))
                    self._send(200, {"ok": True, "state": server.state})
                elif self.path == "/admin/resume":
                    server.resume()
                    self._send(200, {"ok": True, "state": server.state})
                elif self.path == "/admin/swap":
                    stats = server.swap_params(
                        req["checkpoint_dir"],
                        strict=req.get("strict", True),
                        tenant=req.get("tenant"))
                    self._send(200, stats)
                elif self.path == "/admin/warm":
                    warmed = 0
                    for eng in server.engines:
                        warm = getattr(eng, "warm_from_manifest", None)
                        if warm is not None:
                            warmed += warm() or 0
                    self._send(200, {"ok": True, "warmed": warmed})
                elif self.path == "/admin/flightdump":
                    # POST {"path": ...} writes the bundle to disk on
                    # the SERVER box and returns where; without a path
                    # it returns the bundle itself (the GET twin)
                    if req.get("path"):
                        written = server.flight.dump(
                            req.get("reason", "admin"),
                            path=req["path"])
                        self._send(200, {"ok": written is not None,
                                         "path": written})
                    else:
                        self._send(200, server.flight.bundle(
                            req.get("reason", "admin")))
                elif self.path == "/admin/trace_export":
                    # write this process's span journal (JSONL) so a
                    # fleet operator can stitch replica traces with
                    # tools/trace_summary.py --distributed
                    from ..trace import export_jsonl

                    n = export_jsonl(req["path"],
                                     drain=req.get("drain", False))
                    self._send(200, {"ok": True, "spans": n,
                                     "path": req["path"]})
                else:
                    self._send(404, {"error": "not found"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        threading.Thread(target=self._httpd.serve_forever,
                         name="paddle-tpu-serving-http",
                         daemon=True).start()
        return self._httpd.server_address[1]

"""Continuous batching for autoregressive generation (Orca-style).

The one-shot ``transformer_stack_generate`` op decodes a fixed batch to a
fixed horizon: a 64-token request and a 4-token request pay the same loop,
and nobody can join until the whole batch drains. This engine replaces
that with ITERATION-LEVEL scheduling over a slot table: the KV cache is a
persistable scope tensor ``[L, slots+1, Hkv, Tmax, dh]``; each request
claims a slot, a bucketed prefill scatters its prompt K/V into it
(``transformer_stack_slot_prefill``), and ONE compiled decode step
(``transformer_stack_slot_decode``) advances every occupied slot each
tick — finished sequences vacate between ticks and queued requests join
mid-flight. The decode step's shape depends only on the slot count, so
the steady state is a single compile-cache entry; prefill compiles once
per (batch-bucket, prompt-bucket) pair, all warmed up front.

The extra slot (index ``slots``) is a scrap slot: padding rows of a
partially-filled prefill bucket scatter their K/V there, keeping every
compiled shape independent of how many requests actually arrived.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import profiler, trace
from ..core.executor import Executor, TPUPlace
from ..core.program import Program, program_guard
from ..core.scope import Scope
from ..layers import data as data_layer
from ..layers.layer_helper import LayerHelper
from .batcher import Request
from .errors import BadRequestError
from .metrics import MetricsRegistry

CACHE_K = "serving.cache_k"
CACHE_V = "serving.cache_v"

# decode-family op types whose attrs + shared weights describe a stacked LM
_DECODE_OPS = ("transformer_stack_generate", "transformer_stack_beam_search",
               "transformer_stack_speculative_generate",
               "transformer_stack_slot_prefill",
               "transformer_stack_slot_decode")


@dataclasses.dataclass
class LMSpec:
    """Hyperparameters of a stacked transformer LM — everything the slot
    programs need to rebuild the shared-by-name weights
    (``transformer_lm(pipeline_stack=True)`` contract)."""
    vocab_size: int
    d_model: int
    n_layers: int
    num_heads: int
    num_kv_heads: Optional[int] = None
    use_rope: bool = False
    max_len: int = 2048
    d_ff: Optional[int] = None

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads


def spec_from_program_dict(pd: dict,
                           max_len: Optional[int] = None) -> LMSpec:
    """Derive an LMSpec from a saved generation program's dict (the
    ``io.read_inference_model_meta``/``program_to_dict`` payload): decode
    hyperparameters come from the decode op's attrs, sizes from the
    shared parameter shapes."""
    block = pd["blocks"][0]
    op = next((o for o in block["ops"] if o["type"] in _DECODE_OPS), None)
    if op is None:
        raise ValueError(
            "no stacked-LM decode op in the saved program — save an "
            "inference model built from transformer_lm_generate (or "
            "another transformer_stack_* decode program)")
    attrs = op["attrs"]
    shapes = {v["name"]: v["shape"] for v in block["vars"]}
    if "tok_emb" not in shapes or "lm_stack.stack_qkv_w" not in shapes:
        raise ValueError("saved program lacks the shared LM parameters "
                         "(tok_emb / lm_stack.*)")
    vocab, d_model = shapes["tok_emb"]
    n_layers = shapes["lm_stack.stack_qkv_w"][0]
    d_ff = shapes["lm_stack.stack_ff_w1"][2]
    use_rope = bool(attrs.get("use_rope", False))
    if max_len is None:
        if "pos_emb" in shapes:
            max_len = shapes["pos_emb"][0]
        else:
            raise ValueError("RoPE model has no pos_emb table to bound "
                             "sequence length — pass max_len explicitly")
    return LMSpec(vocab_size=vocab, d_model=d_model, n_layers=n_layers,
                  num_heads=attrs["num_heads"],
                  num_kv_heads=attrs.get("num_kv_heads"),
                  use_rope=use_rope, max_len=max_len, d_ff=d_ff)


def _default_prompt_buckets(tmax: int) -> List[int]:
    buckets, b = [], 8
    while b < tmax:
        buckets.append(b)
        b *= 2
    buckets.append(tmax)
    return sorted(set(buckets))


class _Slot:
    __slots__ = ("request", "generated", "max_new", "eos_id", "prompt")

    def __init__(self, request: Request, prompt: np.ndarray,
                 max_new: int, eos_id: Optional[int]):
        self.request = request
        self.prompt = prompt
        self.generated: List[int] = []
        self.max_new = max_new
        self.eos_id = eos_id


class GenerationEngine:
    """Slot-table continuous batcher over the stacked-LM decode ops."""

    def __init__(self, spec: LMSpec, scope: Optional[Scope] = None, *,
                 slots: int = 8, max_seq_len: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 prefill_batch_buckets: Optional[Sequence[int]] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 default_max_new_tokens: int = 16,
                 eos_id: Optional[int] = None, pad_id: int = 0,
                 place=None, metrics: Optional[MetricsRegistry] = None,
                 mem_budget: Optional[float] = None):
        if slots < 1:
            raise ValueError("need at least one decode slot")
        self.spec = spec
        self.scope = scope or Scope()
        self.slots = int(slots)
        self.tmax = int(max_seq_len or spec.max_len)
        if spec.use_rope is False and self.tmax > spec.max_len:
            raise ValueError(f"max_seq_len {self.tmax} exceeds the "
                             f"position table ({spec.max_len})")
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.eos_id = eos_id
        self.pad_id = int(pad_id)
        self._place = place
        self.metrics = metrics or MetricsRegistry()
        self.model_dir: Optional[str] = None  # set by from_saved
        self.executor = Executor(place or TPUPlace(0))
        self.prompt_buckets = sorted(set(
            min(int(b), self.tmax) for b in
            (prompt_buckets or _default_prompt_buckets(self.tmax))))
        nb = prefill_batch_buckets
        if nb is None:
            nb, b = [], 1
            while b < self.slots:
                nb.append(b)
                b *= 2
            nb.append(self.slots)
        self.prefill_batch_buckets = sorted(set(int(b) for b in nb))
        # slot table: index `slots` is the scrap slot (prefill padding)
        self._nslots = self.slots + 1
        self._slots: List[Optional[_Slot]] = [None] * self.slots
        self._tok = np.zeros(self._nslots, np.int64)
        self._pos = np.zeros(self._nslots, np.int32)
        self._init_cache()
        self._prefill_progs: Dict[int, tuple] = {}
        self._decode_prog = self._build_decode()
        if mem_budget is not None:
            self._check_mem_budget(mem_budget)

    # -- program/scope construction ------------------------------------
    @classmethod
    def from_saved(cls, model_dir: str, max_seq_len: Optional[int] = None,
                   **kw) -> "GenerationEngine":
        """Build from a ``save_inference_model`` directory holding a
        stacked-LM generation program: hyperparameters are read from the
        saved decode op, weights are loaded into a fresh scope."""
        from ..io import load_inference_model, read_inference_model_meta

        meta = read_inference_model_meta(model_dir)
        spec = spec_from_program_dict(meta["program"], max_len=max_seq_len)
        scope = kw.pop("scope", None) or Scope()
        eng = cls(spec, scope, max_seq_len=max_seq_len, **kw)
        load_inference_model(model_dir, eng.executor, scope=scope)
        eng.model_dir = model_dir  # manifest home for warm_start
        return eng

    def _init_cache(self):
        import jax.numpy as jnp

        s = self.spec
        shape = (s.n_layers, self._nslots, s.kv_heads, self.tmax,
                 s.head_dim)
        self.scope.set(CACHE_K, jnp.zeros(shape, jnp.float32))
        self.scope.set(CACHE_V, jnp.zeros(shape, jnp.float32))

    def _cache_vars(self, helper):
        s = self.spec
        shape = [s.n_layers, self._nslots, s.kv_heads, self.tmax,
                 s.head_dim]
        ck = helper.create_global_variable(name=CACHE_K, shape=shape,
                                           dtype="float32")
        cv = helper.create_global_variable(name=CACHE_V, shape=shape,
                                           dtype="float32")
        return ck, cv

    def _lm_ins(self, helper):
        from ..models.transformer import _shared_lm_params

        s = self.spec
        return _shared_lm_params(helper, s.vocab_size, s.d_model,
                                 s.d_ff or 4 * s.d_model, s.max_len,
                                 s.n_layers, s.num_heads, s.num_kv_heads,
                                 s.use_rope)

    def _decode_attrs(self):
        return {"num_heads": self.spec.num_heads,
                "num_kv_heads": self.spec.num_kv_heads,
                "use_rope": self.spec.use_rope,
                "temperature": self.temperature, "top_k": self.top_k}

    def _build_prefill(self, tp: int):
        prog, startup = Program(), Program()
        with program_guard(prog, startup):
            prompt = data_layer("serving.prompt", shape=[tp],
                                dtype="int64")
            slot_ids = data_layer("serving.slot_ids", shape=[],
                                  dtype="int32")
            lengths = data_layer("serving.lengths", shape=[],
                                 dtype="int32")
            helper = LayerHelper("serving_prefill", main_program=prog,
                                 startup_program=startup)
            ck, cv = self._cache_vars(helper)
            # fixed name (not unique_name): the serving programs must be
            # bit-identical across boots so warmup-manifest digests match
            nxt = helper.block.create_var(
                name="serving.next_tok", shape=[-1],
                dtype="int64", stop_gradient=True)
            ins = {"Prompt": [prompt], "SlotIds": [slot_ids],
                   "Lengths": [lengths], "CacheK": [ck], "CacheV": [cv]}
            ins.update(self._lm_ins(helper))
            helper.append_op(
                "transformer_stack_slot_prefill", ins,
                {"NextTok": [nxt], "CacheK": [ck], "CacheV": [cv]},
                self._decode_attrs())
        self._transpile(prog, ["serving.prompt", "serving.slot_ids",
                               "serving.lengths"], [nxt.name],
                        f"transpile/prefill{tp}/")
        return prog, nxt

    def _build_decode(self):
        prog, startup = Program(), Program()
        with program_guard(prog, startup):
            tok = data_layer("serving.tok", shape=[self._nslots],
                             dtype="int64", append_batch_size=False)
            pos = data_layer("serving.pos", shape=[self._nslots],
                             dtype="int32", append_batch_size=False)
            helper = LayerHelper("serving_decode", main_program=prog,
                                 startup_program=startup)
            ck, cv = self._cache_vars(helper)
            nxt = helper.block.create_var(
                name="serving.next_tok",
                shape=[self._nslots], dtype="int64", stop_gradient=True)
            ins = {"Tok": [tok], "Pos": [pos], "CacheK": [ck],
                   "CacheV": [cv]}
            ins.update(self._lm_ins(helper))
            helper.append_op(
                "transformer_stack_slot_decode", ins,
                {"NextTok": [nxt], "CacheK": [ck], "CacheV": [cv]},
                self._decode_attrs())
        self._transpile(prog, ["serving.tok", "serving.pos"], [nxt.name],
                        "transpile/decode/")
        return prog, nxt

    def _transpile(self, prog, feed_names, fetch_names, metric_prefix):
        """Run the inference pipeline over a freshly-built serving program
        before it is ever compiled (the decode/prefill ops are already
        maximally fused, so this is usually a fast no-op — but custom or
        saved-program variants get the full rewrite set) and publish the
        per-pass stats into the MetricsRegistry.
        ``preserve_state_writes`` keeps the KV-cache update ops alive even
        though nothing fetches them."""
        from ..transpiler import inference_pipeline

        pm = inference_pipeline()
        pm.run(prog, feed_names, fetch_names, scope=self.scope,
               preserve_state_writes=True)
        for k, v in pm.metrics_dict(prefix=metric_prefix).items():
            self.metrics.set_gauge(k, v)

    def _prefill_prog(self, tp: int):
        if tp not in self._prefill_progs:
            self._prefill_progs[tp] = self._build_prefill(tp)
        return self._prefill_progs[tp]

    def _check_mem_budget(self, budget: float) -> None:
        """Build-time budget gate over the decode step AND the largest
        prefill bucket. The KV-cache slot table ([L, slots+1, Hkv, Tmax,
        dh] x2, scope-resident since _init_cache) is counted as resident
        state, so an over-provisioned slot/Tmax configuration raises a
        located MemoryBudgetError before warmup compiles anything."""
        from .. import analysis

        prog, nxt = self._decode_prog
        mem = analysis.check_memory_budget(
            prog, ["serving.tok", "serving.pos"], [nxt.name], budget,
            scope=self.scope, batch_size=self._nslots,
            what=f"GenerationEngine decode step (slots={self.slots}, "
                 f"tmax={self.tmax})")
        tp = self.prompt_buckets[-1]
        pprog, pnxt = self._prefill_prog(tp)
        pmem = analysis.check_memory_budget(
            pprog, ["serving.prompt", "serving.slot_ids",
                    "serving.lengths"], [pnxt.name], budget,
            scope=self.scope,
            batch_size=self.prefill_batch_buckets[-1],
            what=f"GenerationEngine prefill (bucket {tp})")
        self.metrics.set_gauge("mem/static_peak_bytes",
                               max(mem.peak_bytes, pmem.peak_bytes))
        self.metrics.set_gauge("mem/kv_cache_bytes", 2.0 * float(
            np.prod([self.spec.n_layers, self._nslots,
                     self.spec.kv_heads, self.tmax,
                     self.spec.head_dim])) * 4)

    # -- bucket helpers -------------------------------------------------
    def prompt_bucket_for(self, n: int) -> int:
        for b in self.prompt_buckets:
            if n <= b:
                return b
        raise BadRequestError(
            f"prompt length {n} exceeds the largest prompt bucket "
            f"{self.prompt_buckets[-1]}")

    def _batch_bucket_for(self, n: int) -> int:
        for b in self.prefill_batch_buckets:
            if n <= b:
                return b
        return self.prefill_batch_buckets[-1]

    # -- slot accounting ------------------------------------------------
    @property
    def active(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    @property
    def free_slots(self) -> int:
        return self.slots - self.active

    def _device_ctx(self):
        if self._place is not None:
            import jax
            return jax.default_device(self._place.device())
        import contextlib
        return contextlib.nullcontext()

    # -- serving ---------------------------------------------------------
    def warmup(self) -> int:
        """Compile every prefill (batch-bucket x prompt-bucket) pair and
        the decode step before traffic arrives. All warmup rows target
        the scrap slot, so live slots are never polluted. Returns the
        number of shapes compiled."""
        combos = 0
        if self.temperature > 0:
            # sampled serving threads the scope RNG plane: seed it BEFORE
            # warmup so the scope key set (part of the compile-cache key)
            # is identical between warmup and live traffic
            self.executor._rng_state(self._decode_prog[0], self.scope)
        for tp in self.prompt_buckets:
            prog, nxt = self._prefill_prog(tp)
            for b in self.prefill_batch_buckets:
                feed = {
                    "serving.prompt": np.full((b, tp), self.pad_id,
                                              np.int64),
                    "serving.slot_ids": np.full(b, self.slots, np.int32),
                    "serving.lengths": np.ones(b, np.int32),
                }
                with self._device_ctx():
                    self.executor.run(prog, feed=feed, fetch_list=[nxt],
                                      scope=self.scope)
                combos += 1
        with self._device_ctx():
            self._run_decode()
        combos += 1
        self.metrics.inc("warmup_compiles", combos)
        self.save_manifest()
        return combos

    # -- cold-start plane -------------------------------------------------
    def _warm_programs(self):
        """Every program this engine compiles: the decode step plus one
        prefill program per prompt bucket (built on demand — program
        construction is cheap; compilation is what the manifest saves)."""
        progs = [self._decode_prog[0]]
        progs.extend(self._prefill_prog(tp)[0] for tp in self.prompt_buckets)
        return progs

    def save_manifest(self, dirname: Optional[str] = None) -> Optional[str]:
        """Persist the compiled (prefill x batch bucket, decode)
        signature set next to the saved model for AOT replay on the next
        boot. No-op without a model directory."""
        dirname = dirname or self.model_dir
        if dirname is None or len(self.executor.manifest) == 0:
            return None
        try:
            return self.executor.manifest.save(dirname)
        except OSError:  # read-only artifact volume: serving still works
            return None

    def warm_from_manifest(self,
                           dirname: Optional[str] = None) -> Optional[int]:
        """AOT-replay the saved warmup manifest against the engine-built
        decode/prefill programs (concurrent ``.lower().compile()``, no
        execution, live slots untouched). Returns signatures warm, or
        None when no manifest exists."""
        from ..core import manifest as manifest_mod

        dirname = dirname or self.model_dir
        if dirname is None:
            return None
        manifest = manifest_mod.try_load(dirname)
        if manifest is None:
            return None
        if self.temperature > 0:
            # same contract as warmup(): seed the RNG plane first so the
            # scope key set matches live traffic
            self.executor._rng_state(self._decode_prog[0], self.scope)
        stats = manifest_mod.replay(
            self.executor, self._warm_programs(), scope=self.scope,
            manifest=manifest, device_ctx=self._device_ctx)
        self.metrics.inc("warmup_replayed", stats["compiled"])
        if stats["skipped"]:
            self.metrics.inc("warmup_manifest_skipped", stats["skipped"])
        return stats["compiled"] + stats["already"]

    def warm_start(self) -> int:
        """Boot path: manifest replay when available, else execute-based
        :meth:`warmup`; re-persists the manifest either way."""
        import warnings as warnings_mod

        from ..core.manifest import ManifestError

        warmed = None
        try:
            warmed = self.warm_from_manifest()
        except ManifestError as exc:
            warnings_mod.warn(f"ignoring warmup manifest: {exc}",
                              RuntimeWarning, stacklevel=2)
        if warmed is None:
            warmed = self.warmup()
        self.save_manifest()
        return warmed

    def _validate(self, req: Request):
        try:
            raw = (req.payload["prompt"] if isinstance(req.payload, dict)
                   else req.payload)
            prompt = np.asarray(raw, dtype=np.int64).reshape(-1)
        except (KeyError, TypeError, ValueError) as exc:
            raise BadRequestError(f"bad prompt payload: {exc}")
        if prompt.size < 1:
            raise BadRequestError("empty prompt")
        max_new = int(req.meta.get("max_new_tokens")
                      or self.default_max_new_tokens)
        if max_new < 1:
            raise BadRequestError("max_new_tokens must be >= 1")
        if prompt.size + max_new > self.tmax:
            raise BadRequestError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new}) "
                f"exceeds the serving context ({self.tmax})")
        self.prompt_bucket_for(prompt.size)  # raises when over-long
        eos = req.meta.get("eos_id")
        return prompt, max_new, self.eos_id if eos is None else eos

    def admit(self, requests: List[Request]) -> int:
        """Prefill a group of requests into free slots (one bucketed
        batch). Returns the number admitted; invalid requests fail their
        future and consume no slot."""
        todo = []
        for req in requests:
            try:
                todo.append((req, *self._validate(req)))
            except BadRequestError as exc:
                self.metrics.inc("bad_requests")
                req.end_trace(status="bad_request")
                req.future.set_exception(exc)
        if not todo:
            return 0
        free = [i for i in range(self.slots) if self._slots[i] is None]
        if len(todo) > len(free):
            raise RuntimeError(f"admit() got {len(todo)} requests for "
                               f"{len(free)} free slots")
        tp = self.prompt_bucket_for(max(p.size for _, p, _, _ in todo))
        bucket = self._batch_bucket_for(len(todo))
        prompt = np.full((bucket, tp), self.pad_id, np.int64)
        slot_ids = np.full(bucket, self.slots, np.int32)  # scrap default
        lengths = np.ones(bucket, np.int32)
        for row, (req, p, max_new, eos) in enumerate(todo):
            slot = free[row]
            prompt[row, :p.size] = p
            slot_ids[row] = slot
            lengths[row] = p.size
        prog, nxt = self._prefill_prog(tp)
        t0 = time.perf_counter()
        with self._device_ctx(), profiler.timer("serving/prefill"):
            first, = self.executor.run(
                prog, feed={"serving.prompt": prompt,
                            "serving.slot_ids": slot_ids,
                            "serving.lengths": lengths},
                fetch_list=[nxt], scope=self.scope)
        t1 = time.perf_counter()
        self.metrics.observe_latency(t1 - t0, name="prefill")
        self.metrics.inc("prefills")
        self.metrics.set_gauge("prefill_occupancy", len(todo) / bucket)
        first = np.asarray(first)
        for row, (req, p, max_new, eos) in enumerate(todo):
            slot = free[row]
            if req.span is not None:  # keep per-request sampling
                trace.record("serving/execute", t0, t1, parent=req.span,
                             phase="prefill", slot=slot,
                             prompt_len=int(p.size), prompt_bucket=tp,
                             batch_bucket=bucket)
                req.span.set_attrs(slot=slot, prompt_len=int(p.size))
            st = _Slot(req, p, max_new, eos)
            self._slots[slot] = st
            self._tok[slot] = first[row]
            self._pos[slot] = p.size
            self._emit(slot, int(first[row]))
        self._gauges()
        return len(todo)

    def _emit(self, slot: int, token: int) -> None:
        st = self._slots[slot]
        st.generated.append(token)
        if (len(st.generated) >= st.max_new
                or (st.eos_id is not None and token == st.eos_id)):
            self._finish(slot)

    def _finish(self, slot: int) -> None:
        st = self._slots[slot]
        self._slots[slot] = None
        ids = np.concatenate([st.prompt,
                              np.asarray(st.generated, np.int64)])
        latency = time.monotonic() - st.request.enqueue_t
        st.request.future.set_result(ids)
        st.request.end_trace(status="ok",
                             tokens_generated=len(st.generated),
                             latency_s=round(latency, 6))
        self.metrics.inc("completed")
        self.metrics.observe_latency(latency)

    def _run_decode(self):
        prog, nxt = self._decode_prog
        res, = self.executor.run(
            prog, feed={"serving.tok": self._tok.copy(),
                        "serving.pos": self._pos.copy()},
            fetch_list=[nxt], scope=self.scope)
        return np.asarray(res)

    def decode_tick(self) -> bool:
        """Advance every occupied slot one token (one compiled step).
        Returns True when any slot was active."""
        if self.active == 0:
            return False
        t0 = time.perf_counter()
        with self._device_ctx(), profiler.timer("serving/decode_step"), \
                trace.span("serving/decode_step", active=self.active):
            nxt = self._run_decode()
        self.metrics.observe_latency(time.perf_counter() - t0,
                                     name="decode_step")
        self.metrics.inc("decode_steps")
        self.metrics.set_gauge("batch_occupancy", self.active / self.slots)
        for slot in range(self.slots):
            if self._slots[slot] is None:
                continue
            self._pos[slot] += 1
            self._tok[slot] = nxt[slot]
            self._emit(slot, int(nxt[slot]))
        self._gauges()
        return True

    def _gauges(self):
        self.metrics.set_gauge("active_slots", self.active)

    def cache_stats(self) -> dict:
        return self.executor.cache_stats()

    def swap_params(self, source, *, strict: bool = True):
        """Zero-recompile param hot-swap for rolling weight updates:
        replace the LM weights in place from a trainer checkpoint dir /
        saved-model dir / Scope / dict. The slot KV cache and the RNG
        stream are never touched (a checkpoint taken from another
        serving scope must not clobber live decode state) — call at a
        drained point so already-admitted requests finish on consistent
        weights."""
        from .engine import swap_scope_params

        return swap_scope_params(self.scope, source,
                                 skip=(CACHE_K, CACHE_V), strict=strict,
                                 device_ctx=self._device_ctx,
                                 metrics=self.metrics)

    # -- server-driver interface -----------------------------------------
    def serve_step(self, batcher, idle_wait_s: Optional[float] = None) -> bool:
        """One engine tick: admit queued requests into free slots (a
        non-blocking grab while decoding, a coalescing wait when idle),
        then advance the decode loop one step."""
        did = False
        free = self.free_slots
        if free:
            wait = 0 if self.active else idle_wait_s
            reqs = batcher.next_batch(max_n=free, wait_s=wait)
            if reqs:
                did = self.admit(reqs) > 0
        did = self.decode_tick() or did
        return did

    # -- synchronous convenience ------------------------------------------
    def generate_all(self, prompts: Sequence[Sequence[int]],
                     max_new_tokens: Optional[int] = None,
                     eos_id: Optional[int] = None) -> List[np.ndarray]:
        """Drive the continuous batcher to completion over a request list
        (no server thread): requests stream into slots as they free up —
        the in-process analogue of a loaded server."""
        max_new = max_new_tokens or self.default_max_new_tokens
        reqs = [Request({"prompt": p},
                        {"max_new_tokens": max_new, "eos_id": eos_id},
                        None)
                for p in prompts]
        pending = list(reqs)
        while pending or self.active:
            if pending and self.free_slots:
                k = min(len(pending), self.free_slots)
                self.admit(pending[:k])
                pending = pending[k:]
            self.decode_tick()
        return [r.future.result(timeout=0.1) for r in reqs]

"""Continuous batching for autoregressive generation (Orca-style).

The one-shot ``transformer_stack_generate`` op decodes a fixed batch to a
fixed horizon: a 64-token request and a 4-token request pay the same loop,
and nobody can join until the whole batch drains. This engine replaces
that with ITERATION-LEVEL scheduling over a KV cache: each request claims
a slot, a prefill writes its prompt K/V, and ONE compiled decode step
advances every occupied slot each tick — finished sequences vacate
between ticks and queued requests join mid-flight. The decode step's
shape depends only on the slot count, so the steady state is a single
compile-cache entry; prefill compiles once per (batch-bucket,
prompt-bucket) pair, all warmed up front.

Two cache layouts share that loop, selected by ``kv_cache=``:

- ``"paged"`` (default, :class:`PagedGenerationEngine`) — a page pool
  ``[L, n_pages, Hkv, page_size, dh]`` plus per-slot block tables
  (vLLM's PagedAttention layout): a sequence holds ``ceil(len/page_size)``
  pages instead of a dense ``Tmax`` row, a shared page-aligned prompt
  prefix is stored ONCE (radix-style prefix index, copy-on-write on
  divergence), and long prompts stream in page-budgeted chunks
  interleaved with decode ticks (Sarathi-style chunked prefill) so a
  ``Tmax`` admission never stalls the decode plane.
- ``"dense"`` — the original slot table ``[L, slots+1, Hkv, Tmax, dh]``;
  every slot pays ``Tmax`` rows regardless of true length. The extra
  slot (index ``slots``) is a scrap slot: padding rows of a partially
  filled prefill bucket scatter their K/V there, keeping every compiled
  shape independent of how many requests actually arrived.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import profiler, trace
from ..trace import flight as trace_flight
from ..core.executor import Executor, TPUPlace
from ..core.program import Program, program_guard
from ..core.scope import Scope
from ..decoding.beam import BeamJob
from ..decoding.params import BeamParams, SamplingParams
from ..decoding.stops import StopMatcher
from ..layers import data as data_layer
from ..layers.layer_helper import LayerHelper
from .batcher import Request
from .errors import BadRequestError
from .metrics import MetricsRegistry

CACHE_K = "serving.cache_k"
CACHE_V = "serving.cache_v"

PAGED_CACHE_K = "serving.paged_cache_k"
PAGED_CACHE_V = "serving.paged_cache_v"

# decode-family op types whose attrs + shared weights describe a stacked LM
_DECODE_OPS = ("transformer_stack_generate", "transformer_stack_beam_search",
               "transformer_stack_speculative_generate",
               "transformer_stack_slot_prefill",
               "transformer_stack_slot_decode",
               "transformer_stack_paged_prefill",
               "transformer_stack_paged_decode")


@dataclasses.dataclass
class LMSpec:
    """Hyperparameters of a stacked transformer LM — everything the slot
    programs need to rebuild the shared-by-name weights
    (``transformer_lm(pipeline_stack=True)`` contract)."""
    vocab_size: int
    d_model: int
    n_layers: int
    num_heads: int
    num_kv_heads: Optional[int] = None
    use_rope: bool = False
    max_len: int = 2048
    d_ff: Optional[int] = None

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads


def spec_from_program_dict(pd: dict,
                           max_len: Optional[int] = None) -> LMSpec:
    """Derive an LMSpec from a saved generation program's dict (the
    ``io.read_inference_model_meta``/``program_to_dict`` payload): decode
    hyperparameters come from the decode op's attrs, sizes from the
    shared parameter shapes."""
    block = pd["blocks"][0]
    op = next((o for o in block["ops"] if o["type"] in _DECODE_OPS), None)
    if op is None:
        raise ValueError(
            "no stacked-LM decode op in the saved program — save an "
            "inference model built from transformer_lm_generate (or "
            "another transformer_stack_* decode program)")
    attrs = op["attrs"]
    shapes = {v["name"]: v["shape"] for v in block["vars"]}
    if "tok_emb" not in shapes or "lm_stack.stack_qkv_w" not in shapes:
        raise ValueError("saved program lacks the shared LM parameters "
                         "(tok_emb / lm_stack.*)")
    vocab, d_model = shapes["tok_emb"]
    n_layers = shapes["lm_stack.stack_qkv_w"][0]
    d_ff = shapes["lm_stack.stack_ff_w1"][2]
    use_rope = bool(attrs.get("use_rope", False))
    if max_len is None:
        if "pos_emb" in shapes:
            max_len = shapes["pos_emb"][0]
        else:
            raise ValueError("RoPE model has no pos_emb table to bound "
                             "sequence length — pass max_len explicitly")
    return LMSpec(vocab_size=vocab, d_model=d_model, n_layers=n_layers,
                  num_heads=attrs["num_heads"],
                  num_kv_heads=attrs.get("num_kv_heads"),
                  use_rope=use_rope, max_len=max_len, d_ff=d_ff)


def _default_prompt_buckets(tmax: int) -> List[int]:
    buckets, b = [], 8
    while b < tmax:
        buckets.append(b)
        b *= 2
    buckets.append(tmax)
    return sorted(set(buckets))


class RequestTimeline:
    """Per-request decode timeline: admission, prefill chunk spans, the
    first-token timestamp, and per-token decode deltas — the raw record
    behind the TTFT / TPOT histograms and the flight recorder's
    last-N-requests ring. Timestamps are ``time.monotonic`` seconds (the
    request deadline clock)."""

    __slots__ = ("enqueue_t", "admitted_t", "prompt_len",
                 "prefix_hit_tokens", "chunks", "first_token_t",
                 "last_token_t", "n_tokens", "deltas_s")

    def __init__(self, enqueue_t: float, prompt_len: int,
                 prefix_hit_tokens: int = 0):
        self.enqueue_t = enqueue_t
        self.admitted_t = time.monotonic()
        self.prompt_len = int(prompt_len)
        self.prefix_hit_tokens = int(prefix_hit_tokens)
        self.chunks: List[tuple] = []   # (start_t, end_t, tokens)
        self.first_token_t: Optional[float] = None
        self.last_token_t: Optional[float] = None
        self.n_tokens = 0
        self.deltas_s: List[float] = []

    @property
    def queue_wait_s(self) -> float:
        return max(0.0, self.admitted_t - self.enqueue_t)

    def chunk(self, start_t: float, end_t: float, tokens: int) -> None:
        self.chunks.append((start_t, end_t, int(tokens)))

    def mark_token(self, now: float) -> Optional[float]:
        """Record one emitted token; returns the inter-token delta
        (None for the first token — that one is the TTFT sample)."""
        self.n_tokens += 1
        if self.first_token_t is None:
            self.first_token_t = self.last_token_t = now
            return None
        delta = now - self.last_token_t
        self.last_token_t = now
        self.deltas_s.append(delta)
        return delta

    @property
    def ttft_s(self) -> Optional[float]:
        return (None if self.first_token_t is None
                else self.first_token_t - self.enqueue_t)

    @property
    def tpot_s(self) -> Optional[float]:
        return (sum(self.deltas_s) / len(self.deltas_s)
                if self.deltas_s else None)

    def to_dict(self) -> dict:
        return {
            "prompt_len": self.prompt_len,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "queue_wait_s": round(self.queue_wait_s, 6),
            "ttft_s": (None if self.ttft_s is None
                       else round(self.ttft_s, 6)),
            "tpot_s": (None if self.tpot_s is None
                       else round(self.tpot_s, 6)),
            "tokens": self.n_tokens,
            "prefill_chunks": [
                {"start_s": round(t0, 6), "dur_s": round(t1 - t0, 6),
                 "tokens": n} for t0, t1, n in self.chunks],
            "decode_deltas_ms": [round(d * 1e3, 3)
                                 for d in self.deltas_s],
        }


class _Slot:
    __slots__ = ("request", "generated", "max_new", "eos_id", "prompt",
                 "timeline", "truncate_to")

    def __init__(self, request: Request, prompt: np.ndarray,
                 max_new: int, eos_id: Optional[int]):
        self.request = request
        self.prompt = prompt
        self.generated: List[int] = []
        self.max_new = max_new
        self.eos_id = eos_id
        self.timeline = RequestTimeline(request.enqueue_t, prompt.size)
        # set by a stop-sequence match: keep only this many generated
        # tokens in the returned ids (the stop itself is dropped)
        self.truncate_to: Optional[int] = None


class GenerationEngine:
    """Continuous batcher over the stacked-LM decode ops.

    ``kv_cache="paged"`` (the default) constructs a
    :class:`PagedGenerationEngine`; ``kv_cache="dense"`` keeps the
    original contiguous slot table. Both serve the same API.
    """

    # scope tensors swap_params must never clobber (live decode state)
    _cache_names = (CACHE_K, CACHE_V)

    def __new__(cls, *args, **kw):
        if cls is GenerationEngine and \
                (kw.get("kv_cache") or "paged") == "paged":
            cls = PagedGenerationEngine
        return object.__new__(cls)

    def __init__(self, spec: LMSpec, scope: Optional[Scope] = None, *,
                 slots: int = 8, max_seq_len: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 prefill_batch_buckets: Optional[Sequence[int]] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 sampling: Optional[SamplingParams] = None,
                 default_max_new_tokens: int = 16,
                 eos_id: Optional[int] = None, pad_id: int = 0,
                 place=None, metrics: Optional[MetricsRegistry] = None,
                 mem_budget: Optional[float] = None,
                 namespace: str = "",
                 kv_cache: Optional[str] = None):
        if kv_cache not in (None, "dense", "paged"):
            raise ValueError(f"kv_cache must be 'paged' or 'dense', "
                             f"got {kv_cache!r}")
        if slots < 1:
            raise ValueError("need at least one decode slot")
        self.spec = spec
        self.scope = scope or Scope()
        self.slots = int(slots)
        self.tmax = int(max_seq_len or spec.max_len)
        if spec.use_rope is False and self.tmax > spec.max_len:
            raise ValueError(f"max_seq_len {self.tmax} exceeds the "
                             f"position table ({spec.max_len})")
        # DEPRECATED: engine-wide ``temperature=``/``top_k=`` survive as
        # the *default* SamplingParams — per-request fields win
        # (paddle_tpu.decoding.SamplingParams.from_meta). Pass
        # ``sampling=`` for the full default policy.
        self.default_sampling = sampling if sampling is not None else \
            SamplingParams(temperature=float(temperature),
                           top_k=int(top_k))
        self.default_sampling.validate(spec.vocab_size)
        self.temperature = float(self.default_sampling.temperature)
        self.top_k = int(self.default_sampling.top_k)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.eos_id = eos_id
        self.pad_id = int(pad_id)
        # compile-cache/manifest namespace: a registry hosting several
        # resident models against ONE artifact directory keeps each
        # tenant's warmup manifest under its own filename
        self.namespace = str(namespace or "")
        self._place = place
        self.metrics = metrics or MetricsRegistry()
        # flight recorder: live engine state + last-N request timelines
        # become part of every crash/SIGUSR1/admin dump (weak
        # registration — the recorder never keeps an engine alive)
        self._flight = trace_flight.get_recorder()
        self._flight.add_source(type(self).__name__, self.flight_state)
        self.model_dir: Optional[str] = None  # set by from_saved
        self.executor = Executor(place or TPUPlace(0))
        self.prompt_buckets = sorted(set(
            min(int(b), self.tmax) for b in
            (prompt_buckets or _default_prompt_buckets(self.tmax))))
        nb = prefill_batch_buckets
        if nb is None:
            nb, b = [], 1
            while b < self.slots:
                nb.append(b)
                b *= 2
            nb.append(self.slots)
        self.prefill_batch_buckets = sorted(set(int(b) for b in nb))
        # last-N completed request timelines — the flight recorder's
        # per-engine "what was in flight when it fell over" ring
        self._recent: "deque" = deque(maxlen=64)
        # mid-stream chaos: kill() flips this — in-flight futures fail
        # retryable and serve_step drains the queue the same way until
        # revive(); _emitted_total arms the replica_kill fault threshold
        self._killed = False
        self._emitted_total = 0
        # slot table: index `slots` is the scrap slot (prefill padding)
        self._nslots = self.slots + 1
        self._slots: List[Optional[_Slot]] = [None] * self.slots
        self._tok = np.zeros(self._nslots, np.int64)
        self._pos = np.zeros(self._nslots, np.int32)
        self._init_cache()
        self._prefill_progs: Dict[int, tuple] = {}
        self._decode_prog = self._build_decode()
        if mem_budget is not None:
            self._check_mem_budget(mem_budget)

    # -- program/scope construction ------------------------------------
    @classmethod
    def from_saved(cls, model_dir: str, max_seq_len: Optional[int] = None,
                   **kw) -> "GenerationEngine":
        """Build from a ``save_inference_model`` directory holding a
        stacked-LM generation program: hyperparameters are read from the
        saved decode op, weights are loaded into a fresh scope."""
        from ..io import load_inference_model, read_inference_model_meta

        meta = read_inference_model_meta(model_dir)
        spec = spec_from_program_dict(meta["program"], max_len=max_seq_len)
        scope = kw.pop("scope", None) or Scope()
        eng = cls(spec, scope, max_seq_len=max_seq_len, **kw)
        load_inference_model(model_dir, eng.executor, scope=scope)
        eng.model_dir = model_dir  # manifest home for warm_start
        return eng

    def _init_cache(self):
        import jax.numpy as jnp

        s = self.spec
        shape = (s.n_layers, self._nslots, s.kv_heads, self.tmax,
                 s.head_dim)
        self.scope.set(CACHE_K, jnp.zeros(shape, jnp.float32))
        self.scope.set(CACHE_V, jnp.zeros(shape, jnp.float32))

    def _cache_vars(self, helper):
        s = self.spec
        shape = [s.n_layers, self._nslots, s.kv_heads, self.tmax,
                 s.head_dim]
        ck = helper.create_global_variable(name=CACHE_K, shape=shape,
                                           dtype="float32")
        cv = helper.create_global_variable(name=CACHE_V, shape=shape,
                                           dtype="float32")
        return ck, cv

    def _lm_ins(self, helper):
        from ..models.transformer import _shared_lm_params

        s = self.spec
        return _shared_lm_params(helper, s.vocab_size, s.d_model,
                                 s.d_ff or 4 * s.d_model, s.max_len,
                                 s.n_layers, s.num_heads, s.num_kv_heads,
                                 s.use_rope)

    def _decode_attrs(self):
        return {"num_heads": self.spec.num_heads,
                "num_kv_heads": self.spec.num_kv_heads,
                "use_rope": self.spec.use_rope,
                "temperature": self.temperature, "top_k": self.top_k}

    def _build_prefill(self, tp: int):
        prog, startup = Program(), Program()
        with program_guard(prog, startup):
            prompt = data_layer("serving.prompt", shape=[tp],
                                dtype="int64")
            slot_ids = data_layer("serving.slot_ids", shape=[],
                                  dtype="int32")
            lengths = data_layer("serving.lengths", shape=[],
                                 dtype="int32")
            helper = LayerHelper("serving_prefill", main_program=prog,
                                 startup_program=startup)
            ck, cv = self._cache_vars(helper)
            # fixed name (not unique_name): the serving programs must be
            # bit-identical across boots so warmup-manifest digests match
            nxt = helper.block.create_var(
                name="serving.next_tok", shape=[-1],
                dtype="int64", stop_gradient=True)
            ins = {"Prompt": [prompt], "SlotIds": [slot_ids],
                   "Lengths": [lengths], "CacheK": [ck], "CacheV": [cv]}
            ins.update(self._lm_ins(helper))
            helper.append_op(
                "transformer_stack_slot_prefill", ins,
                {"NextTok": [nxt], "CacheK": [ck], "CacheV": [cv]},
                self._decode_attrs())
        self._transpile(prog, ["serving.prompt", "serving.slot_ids",
                               "serving.lengths"], [nxt.name],
                        f"transpile/prefill{tp}/")
        return prog, nxt

    def _build_decode(self):
        prog, startup = Program(), Program()
        with program_guard(prog, startup):
            tok = data_layer("serving.tok", shape=[self._nslots],
                             dtype="int64", append_batch_size=False)
            pos = data_layer("serving.pos", shape=[self._nslots],
                             dtype="int32", append_batch_size=False)
            helper = LayerHelper("serving_decode", main_program=prog,
                                 startup_program=startup)
            ck, cv = self._cache_vars(helper)
            nxt = helper.block.create_var(
                name="serving.next_tok",
                shape=[self._nslots], dtype="int64", stop_gradient=True)
            ins = {"Tok": [tok], "Pos": [pos], "CacheK": [ck],
                   "CacheV": [cv]}
            ins.update(self._lm_ins(helper))
            helper.append_op(
                "transformer_stack_slot_decode", ins,
                {"NextTok": [nxt], "CacheK": [ck], "CacheV": [cv]},
                self._decode_attrs())
        self._transpile(prog, ["serving.tok", "serving.pos"], [nxt.name],
                        "transpile/decode/")
        return prog, nxt

    def _transpile(self, prog, feed_names, fetch_names, metric_prefix):
        """Run the inference pipeline over a freshly-built serving program
        before it is ever compiled (the decode/prefill ops are already
        maximally fused, so this is usually a fast no-op — but custom or
        saved-program variants get the full rewrite set) and publish the
        per-pass stats into the MetricsRegistry.
        ``preserve_state_writes`` keeps the KV-cache update ops alive even
        though nothing fetches them."""
        from ..transpiler import inference_pipeline

        pm = inference_pipeline()
        pm.run(prog, feed_names, fetch_names, scope=self.scope,
               preserve_state_writes=True)
        for k, v in pm.metrics_dict(prefix=metric_prefix).items():
            self.metrics.set_gauge(k, v)

    def _prefill_prog(self, tp: int):
        if tp not in self._prefill_progs:
            self._prefill_progs[tp] = self._build_prefill(tp)
        return self._prefill_progs[tp]

    def _check_mem_budget(self, budget: float) -> None:
        """Build-time budget gate over the decode step AND the largest
        prefill bucket. The KV-cache slot table ([L, slots+1, Hkv, Tmax,
        dh] x2, scope-resident since _init_cache) is counted as resident
        state, so an over-provisioned slot/Tmax configuration raises a
        located MemoryBudgetError before warmup compiles anything."""
        from .. import analysis

        prog, nxt = self._decode_prog
        mem = analysis.check_memory_budget(
            prog, ["serving.tok", "serving.pos"], [nxt.name], budget,
            scope=self.scope, batch_size=self._nslots,
            what=f"GenerationEngine decode step (slots={self.slots}, "
                 f"tmax={self.tmax})")
        tp = self.prompt_buckets[-1]
        pprog, pnxt = self._prefill_prog(tp)
        pmem = analysis.check_memory_budget(
            pprog, ["serving.prompt", "serving.slot_ids",
                    "serving.lengths"], [pnxt.name], budget,
            scope=self.scope,
            batch_size=self.prefill_batch_buckets[-1],
            what=f"GenerationEngine prefill (bucket {tp})")
        self.metrics.set_gauge("mem/static_peak_bytes",
                               max(mem.peak_bytes, pmem.peak_bytes))
        self.metrics.set_gauge("mem/kv_cache_bytes", 2.0 * float(
            np.prod([self.spec.n_layers, self._nslots,
                     self.spec.kv_heads, self.tmax,
                     self.spec.head_dim])) * 4)

    # -- bucket helpers -------------------------------------------------
    def prompt_bucket_for(self, n: int) -> int:
        for b in self.prompt_buckets:
            if n <= b:
                return b
        raise BadRequestError(
            f"prompt length {n} exceeds the largest prompt bucket "
            f"{self.prompt_buckets[-1]}")

    def _batch_bucket_for(self, n: int) -> int:
        for b in self.prefill_batch_buckets:
            if n <= b:
                return b
        return self.prefill_batch_buckets[-1]

    # -- slot accounting ------------------------------------------------
    @property
    def active(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    @property
    def free_slots(self) -> int:
        return self.slots - self.active

    def _device_ctx(self):
        if self._place is not None:
            import jax
            return jax.default_device(self._place.device())
        import contextlib
        return contextlib.nullcontext()

    def _needs_scope_rng(self) -> bool:
        """Does the decode family draw from the SCOPE RNG plane? Only
        the dense engine's legacy attrs-based sampling does; the paged
        engine's per-request plane carries seeds as inputs."""
        return self.temperature > 0

    # -- serving ---------------------------------------------------------
    def warmup(self) -> int:
        """Compile every prefill (batch-bucket x prompt-bucket) pair and
        the decode step before traffic arrives. All warmup rows target
        the scrap slot, so live slots are never polluted. Returns the
        number of shapes compiled."""
        combos = 0
        if self._needs_scope_rng():
            # sampled serving threads the scope RNG plane: seed it BEFORE
            # warmup so the scope key set (part of the compile-cache key)
            # is identical between warmup and live traffic
            self.executor._rng_state(self._decode_prog[0], self.scope)
        for tp in self.prompt_buckets:
            prog, nxt = self._prefill_prog(tp)
            for b in self.prefill_batch_buckets:
                feed = {
                    "serving.prompt": np.full((b, tp), self.pad_id,
                                              np.int64),
                    "serving.slot_ids": np.full(b, self.slots, np.int32),
                    "serving.lengths": np.ones(b, np.int32),
                }
                with self._device_ctx():
                    self.executor.run(prog, feed=feed, fetch_list=[nxt],
                                      scope=self.scope)
                combos += 1
        with self._device_ctx():
            self._run_decode()
        combos += 1
        self.metrics.inc("warmup_compiles", combos)
        self.save_manifest()
        return combos

    # -- cold-start plane -------------------------------------------------
    def _warm_programs(self):
        """Every program this engine compiles: the decode step plus one
        prefill program per prompt bucket (built on demand — program
        construction is cheap; compilation is what the manifest saves)."""
        progs = [self._decode_prog[0]]
        progs.extend(self._prefill_prog(tp)[0] for tp in self.prompt_buckets)
        return progs

    @property
    def manifest_name(self) -> str:
        """Warmup-manifest filename, namespaced per tenant: several
        resident models sharing one artifact directory each persist
        their own signature set instead of clobbering a global file."""
        from ..core.manifest import MANIFEST_NAME

        if not self.namespace:
            return MANIFEST_NAME
        stem, dot, ext = MANIFEST_NAME.rpartition(".")
        if not dot:
            return f"{MANIFEST_NAME}.{self.namespace}"
        return f"{stem}.{self.namespace}.{ext}"

    def save_manifest(self, dirname: Optional[str] = None) -> Optional[str]:
        """Persist the compiled (prefill x batch bucket, decode)
        signature set next to the saved model for AOT replay on the next
        boot. No-op without a model directory."""
        dirname = dirname or self.model_dir
        if dirname is None or len(self.executor.manifest) == 0:
            return None
        try:
            return self.executor.manifest.save(dirname,
                                               name=self.manifest_name)
        except OSError:  # read-only artifact volume: serving still works
            return None

    def warm_from_manifest(self,
                           dirname: Optional[str] = None) -> Optional[int]:
        """AOT-replay the saved warmup manifest against the engine-built
        decode/prefill programs (concurrent ``.lower().compile()``, no
        execution, live slots untouched). Returns signatures warm, or
        None when no manifest exists."""
        from ..core import manifest as manifest_mod

        dirname = dirname or self.model_dir
        if dirname is None:
            return None
        manifest = manifest_mod.try_load(dirname, name=self.manifest_name)
        if manifest is None:
            return None
        if self._needs_scope_rng():
            # same contract as warmup(): seed the RNG plane first so the
            # scope key set matches live traffic
            self.executor._rng_state(self._decode_prog[0], self.scope)
        stats = manifest_mod.replay(
            self.executor, self._warm_programs(), scope=self.scope,
            manifest=manifest, device_ctx=self._device_ctx)
        self.metrics.inc("warmup_replayed", stats["compiled"])
        if stats["skipped"]:
            self.metrics.inc("warmup_manifest_skipped", stats["skipped"])
        return stats["compiled"] + stats["already"]

    def warm_start(self) -> int:
        """Boot path: manifest replay when available, else execute-based
        :meth:`warmup`; re-persists the manifest either way."""
        import warnings as warnings_mod

        from ..core.manifest import ManifestError

        warmed = None
        try:
            warmed = self.warm_from_manifest()
        except ManifestError as exc:
            warnings_mod.warn(f"ignoring warmup manifest: {exc}",
                              RuntimeWarning, stacklevel=2)
        if warmed is None:
            warmed = self.warmup()
        self.save_manifest()
        return warmed

    def _validate(self, req: Request):
        try:
            raw = (req.payload["prompt"] if isinstance(req.payload, dict)
                   else req.payload)
            prompt = np.asarray(raw, dtype=np.int64).reshape(-1)
        except (KeyError, TypeError, ValueError) as exc:
            raise BadRequestError(f"bad prompt payload: {exc}")
        if prompt.size < 1:
            raise BadRequestError("empty prompt")
        max_new = int(req.meta.get("max_new_tokens")
                      or self.default_max_new_tokens)
        if max_new < 1:
            raise BadRequestError("max_new_tokens must be >= 1")
        if prompt.size + max_new > self.tmax:
            raise BadRequestError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new}) "
                f"exceeds the serving context ({self.tmax})")
        self._check_prompt_fits(prompt)
        eos = req.meta.get("eos_id")
        return prompt, max_new, self.eos_id if eos is None else eos

    def _check_prompt_fits(self, prompt: np.ndarray) -> None:
        """Layout-specific admission bound: the dense table serves a
        prompt only if a single prefill bucket covers it; the paged
        engine overrides this (chunked prefill takes any length the
        context allows)."""
        self.prompt_bucket_for(prompt.size)  # raises when over-long

    def admit(self, requests: List[Request]) -> int:
        """Prefill a group of requests into free slots (one bucketed
        batch). Returns the number admitted; invalid requests fail their
        future and consume no slot."""
        todo = []
        for req in requests:
            try:
                todo.append((req, *self._validate(req)))
            except BadRequestError as exc:
                self.metrics.inc("bad_requests")
                req.end_trace(status="bad_request")
                req.future.set_exception(exc)
        if not todo:
            return 0
        free = [i for i in range(self.slots) if self._slots[i] is None]
        if len(todo) > len(free):
            raise RuntimeError(f"admit() got {len(todo)} requests for "
                               f"{len(free)} free slots")
        tp = self.prompt_bucket_for(max(p.size for _, p, _, _ in todo))
        bucket = self._batch_bucket_for(len(todo))
        prompt = np.full((bucket, tp), self.pad_id, np.int64)
        slot_ids = np.full(bucket, self.slots, np.int32)  # scrap default
        lengths = np.ones(bucket, np.int32)
        for row, (req, p, max_new, eos) in enumerate(todo):
            slot = free[row]
            prompt[row, :p.size] = p
            slot_ids[row] = slot
            lengths[row] = p.size
        prog, nxt = self._prefill_prog(tp)
        t0 = time.perf_counter()
        with self._device_ctx(), profiler.timer("serving/prefill"):
            first, = self.executor.run(
                prog, feed={"serving.prompt": prompt,
                            "serving.slot_ids": slot_ids,
                            "serving.lengths": lengths},
                fetch_list=[nxt], scope=self.scope)
        t1 = time.perf_counter()
        self.metrics.observe_latency(t1 - t0, name="prefill")
        self.metrics.inc("prefills")
        self.metrics.set_gauge("prefill_occupancy", len(todo) / bucket)
        first = np.asarray(first)
        for row, (req, p, max_new, eos) in enumerate(todo):
            slot = free[row]
            if req.span is not None:  # keep per-request sampling
                trace.record("serving/execute", t0, t1, parent=req.span,
                             phase="prefill", slot=slot,
                             prompt_len=int(p.size), prompt_bucket=tp,
                             batch_bucket=bucket)
                req.span.set_attrs(slot=slot, prompt_len=int(p.size))
            st = _Slot(req, p, max_new, eos)
            st.timeline.chunk(t0, t1, int(p.size))
            self.metrics.observe_hist("queue_wait",
                                      st.timeline.queue_wait_s)
            self._slots[slot] = st
            self._tok[slot] = first[row]
            self._pos[slot] = p.size
            self._emit(slot, int(first[row]))
        self._gauges()
        return len(todo)

    def _emit(self, slot: int, token: int) -> None:
        st = self._slots[slot]
        delta = st.timeline.mark_token(time.monotonic())
        if delta is None:  # first token: the TTFT sample
            self.metrics.observe_hist("ttft", st.timeline.ttft_s)
        else:              # every later token: one TPOT sample
            self.metrics.observe_hist("tpot", delta)
        st.generated.append(token)
        self._emitted_total += 1
        cb = (st.request.meta or {}).get("on_token")
        if cb is not None:
            # progress streaming for the lineage plane: position, token.
            # Never let an observer kill the decode loop.
            try:
                cb(len(st.generated) - 1, token)
            except Exception:
                self.metrics.inc("progress_callback_errors")
        stop = getattr(st, "stop_matcher", None)
        if stop:
            keep = stop.match(st.generated)
            if keep is not None:
                # the stop sequence ends here (anywhere — including
                # mid-page on the paged cache): truncate before the
                # match and finish; the already-written K/V rows past
                # the cut are released with the request's pages
                st.truncate_to = keep
                self.metrics.inc("stop_sequence_hits")
                self._finish(slot)
                return
        if (len(st.generated) >= st.max_new
                or (st.eos_id is not None and token == st.eos_id)):
            self._finish(slot)

    def _finish(self, slot: int) -> None:
        st = self._slots[slot]
        self._slots[slot] = None
        gen = (st.generated if st.truncate_to is None
               else st.generated[:st.truncate_to])
        # a RESUMED slot's prompt is original-prompt + already-emitted
        # context while ``generated`` also starts with those emitted
        # tokens — strip the overlap so the result ids match an
        # uninterrupted run exactly
        resumed = getattr(st, "resumed", 0)
        prompt = st.prompt[:-resumed] if resumed else st.prompt
        ids = np.concatenate([prompt, np.asarray(gen, np.int64)])
        latency = time.monotonic() - st.request.enqueue_t
        tl = st.timeline
        if st.request.span is not None and tl.n_tokens > 1:
            # decode residency as ONE span per request (token-level cost
            # rides the timeline, not 1 span/token)
            trace.record("serving/decode", tl.first_token_t,
                         tl.last_token_t, parent=st.request.span,
                         tokens=tl.n_tokens,
                         tpot_ms=round((tl.tpot_s or 0.0) * 1e3, 3))
        self._recent.append(dict(tl.to_dict(), status="ok",
                                 latency_s=round(latency, 6),
                                 resumed=bool(resumed)))
        st.request.future.set_result(ids)
        st.request.end_trace(status="ok",
                             tokens_generated=len(st.generated),
                             latency_s=round(latency, 6))
        self.metrics.inc("completed")
        self.metrics.observe_latency(latency)

    def _run_decode(self):
        prog, nxt = self._decode_prog
        res, = self.executor.run(
            prog, feed={"serving.tok": self._tok.copy(),
                        "serving.pos": self._pos.copy()},
            fetch_list=[nxt], scope=self.scope)
        return np.asarray(res)

    def decode_tick(self) -> bool:
        """Advance every occupied slot one token (one compiled step).
        Returns True when any slot was active."""
        if self.active == 0:
            return False
        t0 = time.perf_counter()
        with self._device_ctx(), profiler.timer("serving/decode_step"), \
                trace.span("serving/decode_step", active=self.active):
            nxt = self._run_decode()
        self.metrics.observe_latency(time.perf_counter() - t0,
                                     name="decode_step")
        self.metrics.inc("decode_steps")
        # per-TOKEN decode work (decode_steps is per tick) — the pin a
        # recovery run is judged by: resumed context re-enters via
        # prefill, so total decode_tokens stays below an uninterrupted
        # run's, never above
        self.metrics.inc("decode_tokens", self.active)
        self.metrics.set_gauge("batch_occupancy", self.active / self.slots)
        for slot in range(self.slots):
            if self._slots[slot] is None:
                continue
            self._pos[slot] += 1
            self._tok[slot] = nxt[slot]
            self._emit(slot, int(nxt[slot]))
        self._maybe_replica_kill()
        self._gauges()
        return True

    def _gauges(self):
        self.metrics.set_gauge("active_slots", self.active)
        # throttled time-series sampling: the flight bundle's metric
        # ring sees occupancy/pages/prefix counters EVOLVE, not just
        # their value at dump time
        self._flight.maybe_sample(self.metrics)

    def flight_state(self) -> dict:
        """Live engine state for the flight recorder: per-slot decode
        progress plus the last-N completed request timelines."""
        slots = []
        for i, st in enumerate(self._slots):
            if st is None:
                continue
            slots.append({
                "slot": i,
                "state": getattr(st, "state", "decode"),
                "prompt_len": int(st.prompt.size),
                "generated": len(st.generated),
                "max_new": st.max_new,
                "pos": int(self._pos[i]),
            })
        return {
            "engine": type(self).__name__,
            "slots_total": self.slots,
            "killed": self._killed,
            "slots": slots,
            "recent_requests": list(self._recent),
        }

    def cache_stats(self) -> dict:
        return self.executor.cache_stats()

    # -- mid-stream chaos: hard engine death ------------------------------
    def _abort_slot_resources(self, st) -> None:
        """Layout hook: release whatever a killed slot held (the paged
        engine returns its pages to the pool)."""

    def kill(self, reason: str = "chaos") -> int:
        """Hard-kill the engine mid-stream (the ``replica_kill`` chaos
        path): every in-flight generation fails with ``ConnectionError``
        — RETRYABLE, so a fleet's lineage plane resumes the survivors on
        a healthy replica — resources are released, and the engine
        refuses traffic (serve_step drains the queue the same way) until
        :meth:`revive`. Returns the number of futures failed."""
        exc = ConnectionError(
            f"replica killed mid-stream ({reason}); in-flight "
            "generations are resumable from their lineage")
        failed = 0
        for slot in range(self.slots):
            st = self._slots[slot]
            if st is None:
                continue
            self._slots[slot] = None
            self._abort_slot_resources(st)
            st.request.end_trace(status="killed")
            if not st.request.future.done():
                st.request.future.set_exception(exc)
                failed += 1
        self._killed = True
        self.metrics.inc("replica_kills")
        self.metrics.inc("killed_in_flight", failed)
        self._gauges()
        return failed

    def revive(self) -> None:
        """Bring a killed engine back (slots are empty; the KV pages a
        kill released are reusable immediately). The emit counter
        restarts: ``after_tokens`` thresholds are per-incarnation."""
        self._killed = False
        self._emitted_total = 0

    def _maybe_replica_kill(self) -> None:
        """Fire an armed ``replica_kill`` fault once the engine has
        emitted ``after_tokens`` tokens (default 1) across all streams —
        the deterministic stand-in for a process dying mid-decode."""
        from ..resilience import faults

        plan = faults.active_plan()
        if plan is None or self._killed:
            return
        params = plan.peek("replica_kill")
        if params is None:
            return
        if self._emitted_total < int(params.get("after_tokens", 1)):
            return
        # fire() is the atomic claim: two engines can both pass the
        # peek, but only the one that consumes the entry dies
        if plan.fire("replica_kill") is None:
            return
        self.kill(reason="fault-plan replica_kill")

    def _drain_killed(self, batcher) -> bool:
        """A killed engine's serve loop: fail everything the batcher
        hands it, retryable, so the fleet routes around the corpse."""
        reqs = batcher.next_batch(max_n=max(self.slots, 1), wait_s=0)
        if not reqs:
            return False
        exc = ConnectionError("replica is down (killed mid-stream)")
        for req in reqs:
            req.end_trace(status="killed")
            if not req.future.done():
                req.future.set_exception(exc)
        return True

    def swap_params(self, source, *, strict: bool = True):
        """Zero-recompile param hot-swap for rolling weight updates:
        replace the LM weights in place from a trainer checkpoint dir /
        saved-model dir / Scope / dict. The slot KV cache and the RNG
        stream are never touched (a checkpoint taken from another
        serving scope must not clobber live decode state) — call at a
        drained point so already-admitted requests finish on consistent
        weights."""
        from .engine import swap_scope_params

        return swap_scope_params(self.scope, source,
                                 skip=self._cache_names, strict=strict,
                                 device_ctx=self._device_ctx,
                                 metrics=self.metrics)

    # -- server-driver interface -----------------------------------------
    def serve_step(self, batcher, idle_wait_s: Optional[float] = None) -> bool:
        """One engine tick: admit queued requests into free slots (a
        non-blocking grab while decoding, a coalescing wait when idle),
        then advance the decode loop one step."""
        if self._killed:
            return self._drain_killed(batcher)
        did = False
        free = self.free_slots
        if free:
            wait = 0 if self.active else idle_wait_s
            reqs = batcher.next_batch(max_n=free, wait_s=wait)
            if reqs:
                did = self.admit(reqs) > 0
        did = self.decode_tick() or did
        return did

    # -- synchronous convenience ------------------------------------------
    def generate_all(self, prompts: Sequence[Sequence[int]],
                     max_new_tokens: Optional[int] = None,
                     eos_id: Optional[int] = None) -> List[np.ndarray]:
        """Drive the continuous batcher to completion over a request list
        (no server thread): requests stream into slots as they free up —
        the in-process analogue of a loaded server."""
        max_new = max_new_tokens or self.default_max_new_tokens
        reqs = [Request({"prompt": p},
                        {"max_new_tokens": max_new, "eos_id": eos_id},
                        None)
                for p in prompts]
        pending = list(reqs)
        while pending or self.active:
            if pending and self.free_slots:
                k = min(len(pending), self.free_slots)
                self.admit(pending[:k])
                pending = pending[k:]
            self.decode_tick()
        return [r.future.result(timeout=0.1) for r in reqs]


# ---------------------------------------------------------------------------
# Paged KV cache: block-table slots over a shared page pool
# ---------------------------------------------------------------------------
class _PagedSlot(_Slot):
    __slots__ = ("pages", "shared_tokens", "cow_reserve", "prefill_done",
                 "state", "sampling", "stop_matcher", "mask_proc",
                 "beam_job", "role", "xrow", "resumed")

    def __init__(self, request, prompt, max_new, eos_id,
                 sampling: Optional[SamplingParams] = None):
        super().__init__(request, prompt, max_new, eos_id)
        self.pages: List[int] = []       # physical page per table entry
        self.shared_tokens = 0           # prefix-cache hit length
        self.cow_reserve = 0             # pages held for copy-on-write
        self.prefill_done = 0            # prompt tokens whose K/V is cached
        self.state = "decode"            # "prefill" while chunks stream in
                                         # ("hold"/"beam_wait" for beams)
        self.sampling = sampling or SamplingParams()
        self.stop_matcher = StopMatcher(self.sampling.stop)
        self.mask_proc = self.sampling.logits_processor
        self.beam_job = None             # set for beam-owned slots
        self.role = "normal"             # beam_parent | beam | hold
        self.xrow = None                 # seq2seq: cross-KV cache row
        self.resumed = 0                 # recovery: emitted tokens that
                                         # re-entered as prefill context


class PagedGenerationEngine(GenerationEngine):
    """Continuous batcher over a PAGED KV cache with prefix sharing and
    chunked prefill.

    The cache is a page pool ``[L, n_pages, Hkv, page_size, dh]`` (scope-
    resident, donated in place like the dense table) plus a host-side
    per-slot block table: a sequence holds ``ceil(len/page_size)``
    physical pages, so HBM holds TOKENS IN FLIGHT, not slots x Tmax.
    Three levers ride on the allocator:

    - **Prefix sharing** (``prefix_sharing=True``): a radix-style index
      over page-aligned prompt prefixes maps a shared system prompt to
      refcounted pages stored once; admission of a request whose prefix
      is cached skips that prefill entirely (``prefix_hit_tokens``
      counts the skipped tokens). A shared page about to be written
      (full-prompt hit diverging into generation) is copied first —
      copy-on-write via ``kv_cache_page_copy``, one page reserved at
      admission so decode never allocates.
    - **Chunked prefill**: a prompt longer than ``prefill_chunk`` tokens
      streams in page-budgeted chunks, one chunk per engine tick,
      INTERLEAVED with decode ticks — a Tmax admission no longer stalls
      every in-flight stream (Sarathi-style stall-free batching).
    - **Typed backpressure**: a request whose prompt + max_new_tokens can
      NEVER fit the pool fails with
      :class:`~paddle_tpu.serving.errors.CacheExhaustedError`; transient
      pressure defers admission (the batcher queue backs up and sheds)
      instead of failing mid-decode.

    Everything else — warmup manifests, ``swap_params`` rolling updates,
    drain, fleet membership, metrics names — is inherited unchanged.
    """

    _cache_names = (PAGED_CACHE_K, PAGED_CACHE_V)

    def __init__(self, spec: LMSpec, scope: Optional[Scope] = None, *,
                 page_size: Optional[int] = None,
                 n_pages: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_sharing: bool = True,
                 beam_width: int = 0, mask_plane: bool = True,
                 share_cache_with: Optional["PagedGenerationEngine"] = None,
                 kv_cache: Optional[str] = None, **kw):
        if kv_cache not in (None, "paged"):
            raise ValueError(
                f"PagedGenerationEngine is kv_cache='paged' (got "
                f"{kv_cache!r}); use GenerationEngine(kv_cache='dense') "
                "for the dense slot table")
        if page_size is not None and page_size < 1:
            raise ValueError("page_size must be >= 1")
        if beam_width < 0:
            raise ValueError("beam_width must be >= 0")
        self._page_size_arg = page_size
        self._n_pages_arg = n_pages
        self._prefill_chunk_arg = prefill_chunk
        self._prefix_sharing = bool(prefix_sharing)
        # disaggregation: a decode-pool engine built on the PREFILL
        # engine's scope adopts its page pool/prefix index — a KV
        # handoff between the two is then a pure slot-table transfer
        self._share_cache_src = share_cache_with
        # beam_width > 0 compiles the TopV/TopI (emit_topk) plane into
        # the decode/prefill programs; beam requests up to this width
        # then ride the one steady-state compile
        self.beam_width = int(beam_width)
        # mask_plane=False drops the [slots, vocab] Mask feed from the
        # programs (per-tick host->device bytes scale with vocab; turn
        # it off for large-V deployments that never constrain decoding)
        self.mask_plane = bool(mask_plane)
        super().__init__(spec, scope, **kw)

    # -- cache / program construction -----------------------------------
    def _init_cache(self):
        import jax.numpy as jnp

        from .paging import PagePool, PrefixIndex

        s = self.spec
        src = self._share_cache_src
        if src is not None:
            if self.scope is not src.scope:
                raise ValueError(
                    "share_cache_with requires constructing this engine "
                    "on the source engine's scope — the page tensors "
                    "live there")
            if s != src.spec or self.tmax != src.tmax:
                raise ValueError(
                    "share_cache_with requires an identical LMSpec and "
                    "max_seq_len — the page geometry and weight contract "
                    "must match for a block table to transfer")
            self.page_size = src.page_size
        else:
            self.page_size = int(self._page_size_arg
                                 or min(64, self.tmax))
        # table width: enough entries for a full-context sequence
        self.pmax = -(-self.tmax // self.page_size)
        # beam engines default to a bigger pool: K fully-diverged
        # hypotheses can each hold a full table plus a COW spare
        beam_extra = (self.slots + 2 * self.beam_width
                      if getattr(self, "beam_width", 0) else 0)
        self.n_pages = (src.n_pages if src is not None
                        else int(self._n_pages_arg
                                 or self.slots * self.pmax + 1
                                 + beam_extra))
        if self.n_pages < 2:
            raise ValueError("need at least 2 pages (one is scrap)")
        chunk = self._prefill_chunk_arg
        if chunk is None:
            chunk = min(self.prompt_buckets[-1],
                        max(2 * self.page_size, 128))
        self.prefill_chunk = max(1, min(int(chunk), self.tmax))
        self._chunk_widths = sorted(
            {b for b in self.prompt_buckets if b <= self.prefill_chunk}
            | {self.prefill_chunk})
        if src is not None:
            self.pool = src.pool
            self.prefix_index = src.prefix_index
        else:
            self.pool = PagePool(self.n_pages, self.page_size)
            self.prefix_index = (PrefixIndex(self.pool)
                                 if self._prefix_sharing else None)
        # no scrap SLOT here — padding/vacant rows write the scrap PAGE,
        # so the decode batch is exactly the slot count
        self._nslots = self.slots
        self._tok = np.zeros(self._nslots, np.int64)
        self._pos = np.zeros(self._nslots, np.int32)
        self._deferred = deque()  # pool-blocked validated admissions
        self._pf_cursor = 0       # round-robin over prefilling slots
        self._beam_jobs: List[BeamJob] = []
        self._seed_counter = 0    # default per-request seeds (sampled
                                  # requests without an explicit seed)
        shape = (s.n_layers, self.n_pages, s.kv_heads, self.page_size,
                 s.head_dim)
        if src is None:
            self.scope.set(PAGED_CACHE_K, jnp.zeros(shape, jnp.float32))
            self.scope.set(PAGED_CACHE_V, jnp.zeros(shape, jnp.float32))
        # shared-pool engines never re-zero: the scope tensors already
        # hold the source pool's live pages
        self._page_copy_prog_cache = None
        self.metrics.set_gauge("mem/kv_cache_bytes",
                               2.0 * float(np.prod(shape)) * 4)
        self.metrics.set_gauge("mem/kv_block_table_bytes",
                               float(self.slots * self.pmax * 4))
        self._gauges()

    def _cache_vars(self, helper):
        s = self.spec
        shape = [s.n_layers, self.n_pages, s.kv_heads, self.page_size,
                 s.head_dim]
        ck = helper.create_global_variable(name=PAGED_CACHE_K, shape=shape,
                                           dtype="float32")
        cv = helper.create_global_variable(name=PAGED_CACHE_V, shape=shape,
                                           dtype="float32")
        return ck, cv

    def _decode_attrs(self):
        # per-request sampling rides the input plane, never the attrs
        # (and never the scope RNG) — attrs stay policy-free so every
        # request shape shares one compile-cache entry
        attrs = super()._decode_attrs()
        attrs["temperature"] = 0.0
        attrs["top_k"] = 0
        attrs["page_size"] = self.page_size
        if self.beam_width:
            attrs["emit_topk"] = self.beam_width
        return attrs

    def _needs_scope_rng(self) -> bool:
        return False  # seeds are inputs: the scope RNG is never drawn

    _SAMPLING_FEEDS = ("serving.temp", "serving.topk", "serving.topp",
                       "serving.seed", "serving.step")

    @property
    def _prefill_feed_names(self):
        names = ["serving.chunk", "serving.start", "serving.chunk_len",
                 "serving.block_table", *self._SAMPLING_FEEDS]
        if self.mask_plane:
            names.append("serving.mask")
        return names

    @property
    def _decode_feed_names(self):
        names = ["serving.tok", "serving.pos", "serving.block_table",
                 *self._SAMPLING_FEEDS]
        if self.mask_plane:
            names.append("serving.mask")
        return names

    def _sampling_vars(self, rows: Optional[int]):
        """Declare the per-row sampling-plane feeds. ``rows`` is None for
        batch-dim programs (prefill: the batch axis is implicit) or the
        static slot count (decode)."""
        batched = rows is None

        def vec(name, dtype):
            if batched:
                return data_layer(name, shape=[], dtype=dtype)
            return data_layer(name, shape=[rows], dtype=dtype,
                              append_batch_size=False)

        ins = {"Temperature": [vec("serving.temp", "float32")],
               "TopK": [vec("serving.topk", "int32")],
               "TopP": [vec("serving.topp", "float32")],
               "Seed": [vec("serving.seed", "int32")],
               "Step": [vec("serving.step", "int32")]}
        if self.mask_plane:
            V = self.spec.vocab_size
            mask = (data_layer("serving.mask", shape=[V], dtype="float32")
                    if batched else
                    data_layer("serving.mask", shape=[rows, V],
                               dtype="float32", append_batch_size=False))
            ins["Mask"] = [mask]
        return ins

    def _beam_out_vars(self, helper, rows: int, prefix: str):
        """TopV/TopI output vars when the beam plane is on."""
        if not self.beam_width:
            return {}
        shape = [rows, self.beam_width] if rows else [-1, self.beam_width]
        tv = helper.block.create_var(name=f"{prefix}.topv", shape=shape,
                                     dtype="float32", stop_gradient=True)
        ti = helper.block.create_var(name=f"{prefix}.topi", shape=shape,
                                     dtype="int32", stop_gradient=True)
        return {"TopV": [tv], "TopI": [ti]}

    def _build_prefill(self, tc: int):
        prog, startup = Program(), Program()
        with program_guard(prog, startup):
            chunk = data_layer("serving.chunk", shape=[tc], dtype="int64")
            start = data_layer("serving.start", shape=[], dtype="int32")
            length = data_layer("serving.chunk_len", shape=[],
                                dtype="int32")
            table = data_layer("serving.block_table", shape=[self.pmax],
                               dtype="int32")
            helper = LayerHelper("serving_paged_prefill", main_program=prog,
                                 startup_program=startup)
            ck, cv = self._cache_vars(helper)
            nxt = helper.block.create_var(
                name="serving.next_tok", shape=[-1],
                dtype="int64", stop_gradient=True)
            ins = {"Chunk": [chunk], "StartPos": [start],
                   "Lengths": [length], "BlockTable": [table],
                   "CacheK": [ck], "CacheV": [cv]}
            ins.update(self._sampling_vars(None))
            ins.update(self._lm_ins(helper))
            outs = {"NextTok": [nxt], "CacheK": [ck], "CacheV": [cv]}
            outs.update(self._beam_out_vars(helper, 0, "serving.pf"))
            helper.append_op("transformer_stack_paged_prefill", ins,
                             outs, self._decode_attrs())
        fetches = [nxt.name] + [v[0].name for k, v in sorted(outs.items())
                                if k in ("TopV", "TopI")]
        self._transpile(prog, list(self._prefill_feed_names), fetches,
                        f"transpile/prefill{tc}/")
        return prog, outs

    def _build_decode(self):
        prog, startup = Program(), Program()
        with program_guard(prog, startup):
            tok = data_layer("serving.tok", shape=[self._nslots],
                             dtype="int64", append_batch_size=False)
            pos = data_layer("serving.pos", shape=[self._nslots],
                             dtype="int32", append_batch_size=False)
            table = data_layer("serving.block_table",
                               shape=[self._nslots, self.pmax],
                               dtype="int32", append_batch_size=False)
            helper = LayerHelper("serving_paged_decode", main_program=prog,
                                 startup_program=startup)
            ck, cv = self._cache_vars(helper)
            nxt = helper.block.create_var(
                name="serving.next_tok",
                shape=[self._nslots], dtype="int64", stop_gradient=True)
            ins = {"Tok": [tok], "Pos": [pos], "BlockTable": [table],
                   "CacheK": [ck], "CacheV": [cv]}
            ins.update(self._sampling_vars(self._nslots))
            ins.update(self._lm_ins(helper))
            outs = {"NextTok": [nxt], "CacheK": [ck], "CacheV": [cv]}
            outs.update(self._beam_out_vars(helper, self._nslots,
                                            "serving.dec"))
            helper.append_op("transformer_stack_paged_decode", ins,
                             outs, self._decode_attrs())
        fetches = [nxt.name] + [v[0].name for k, v in sorted(outs.items())
                                if k in ("TopV", "TopI")]
        self._transpile(prog, list(self._decode_feed_names), fetches,
                        "transpile/decode/")
        return prog, outs

    @property
    def _page_copy_prog(self):
        if self._page_copy_prog_cache is None:
            prog, startup = Program(), Program()
            with program_guard(prog, startup):
                src = data_layer("serving.cow_src", shape=[1],
                                 dtype="int32", append_batch_size=False)
                dst = data_layer("serving.cow_dst", shape=[1],
                                 dtype="int32", append_batch_size=False)
                helper = LayerHelper("serving_page_copy",
                                     main_program=prog,
                                     startup_program=startup)
                ck, cv = self._cache_vars(helper)
                ok = helper.block.create_var(
                    name="serving.cow_ok", shape=[1], dtype="int32",
                    stop_gradient=True)
                helper.append_op(
                    "kv_cache_page_copy",
                    {"Src": [src], "Dst": [dst],
                     "CacheK": [ck], "CacheV": [cv]},
                    {"Ok": [ok], "CacheK": [ck], "CacheV": [cv]}, {})
            self._transpile(prog, ["serving.cow_src", "serving.cow_dst"],
                            [ok.name], "transpile/page_copy/")
            self._page_copy_prog_cache = (prog, ok)
        return self._page_copy_prog_cache

    # -- admission bounds ------------------------------------------------
    def _check_prompt_fits(self, prompt: np.ndarray) -> None:
        # chunked prefill serves ANY prompt the context admits — the
        # prompt + max_new_tokens <= tmax check already ran
        pass

    def _chunk_bucket_for(self, n: int) -> int:
        for b in self._chunk_widths:
            if n <= b:
                return b
        return self._chunk_widths[-1]

    def _entries_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    # -- program plumbing --------------------------------------------------
    def _fetches(self, outs) -> list:
        """Fetch vars for a paged program: NextTok plus the beam plane
        when compiled in. The fetch list is IDENTICAL for warmup and
        live ticks — fetch-set changes would fork the compiled
        signature and break the zero-recompile steady state."""
        fetches = [outs["NextTok"][0]]
        if self.beam_width:
            fetches += [outs["TopV"][0], outs["TopI"][0]]
        return fetches

    def _neutral_sampling_feed(self, rows: int) -> Dict[str, np.ndarray]:
        """The sampling plane for rows with no live policy (warmup,
        vacant slots, padding): greedy, mask wide open."""
        feed = {
            "serving.temp": np.zeros(rows, np.float32),
            "serving.topk": np.zeros(rows, np.int32),
            "serving.topp": np.ones(rows, np.float32),
            "serving.seed": np.zeros(rows, np.int32),
            "serving.step": np.zeros(rows, np.int32),
        }
        if self.mask_plane:
            feed["serving.mask"] = np.ones(
                (rows, self.spec.vocab_size), np.float32)
        return feed

    def _slot_sampling_feed(self, row: int, st, feed: dict,
                            step: int) -> None:
        """Write one slot's policy into row ``row`` of a sampling feed."""
        sp = st.sampling
        feed["serving.temp"][row] = sp.temperature
        feed["serving.topk"][row] = sp.top_k
        feed["serving.topp"][row] = sp.top_p
        feed["serving.seed"][row] = (sp.seed or 0) & 0x7FFFFFFF
        feed["serving.step"][row] = step
        if st.mask_proc is not None and self.mask_plane:
            mask = np.asarray(
                st.mask_proc.mask(step, st.generated), np.float32)
            if mask.shape != (self.spec.vocab_size,):
                raise BadRequestError(
                    f"logits processor returned shape {mask.shape}, "
                    f"want ({self.spec.vocab_size},)")
            if mask.max() <= 0:  # dead end: fail open, count it
                self.metrics.inc("mask_dead_ends")
            else:
                feed["serving.mask"][row] = mask

    # -- warmup / manifests ----------------------------------------------
    def warmup(self) -> int:
        """Compile every (chunk-width x batch-bucket) prefill shape, the
        decode step, and the copy-on-write page copy. All warmup rows
        write the scrap page, so live pages are never touched. The
        sampling plane warms with its neutral (greedy) values — policy
        is data, so sampled/masked/beam traffic hits the same
        executables."""
        combos = 0
        for tc in self._chunk_widths:
            prog, outs = self._prefill_prog(tc)
            for b in self.prefill_batch_buckets:
                feed = {
                    "serving.chunk": np.full((b, tc), self.pad_id,
                                             np.int64),
                    "serving.start": np.zeros(b, np.int32),
                    "serving.chunk_len": np.ones(b, np.int32),
                    "serving.block_table": np.zeros((b, self.pmax),
                                                    np.int32),
                }
                feed.update(self._neutral_sampling_feed(b))
                with self._device_ctx():
                    self.executor.run(prog, feed=feed,
                                      fetch_list=self._fetches(outs),
                                      scope=self.scope)
                combos += 1
        with self._device_ctx():
            self._run_decode()
        combos += 1
        self._run_page_copy(0, 0)  # scrap onto itself: harmless
        combos += 1
        self.metrics.inc("warmup_compiles", combos)
        self.save_manifest()
        return combos

    def _warm_programs(self):
        progs = [self._decode_prog[0], self._page_copy_prog[0]]
        progs.extend(self._prefill_prog(tc)[0]
                     for tc in self._chunk_widths)
        return progs

    def _check_mem_budget(self, budget: float) -> None:
        """Budget gate with the PAGE POOL (+ block tables) counted as the
        resident KV state — the pool lives in the scope, so the analyzer
        prices what is actually allocated, not the dense slots x Tmax
        formula."""
        from .. import analysis

        prog, outs = self._decode_prog
        mem = analysis.check_memory_budget(
            prog, list(self._decode_feed_names),
            [v.name for v in self._fetches(outs)], budget,
            scope=self.scope, batch_size=self._nslots,
            what=f"PagedGenerationEngine decode step (slots={self.slots}, "
                 f"pages={self.n_pages}x{self.page_size})")
        tc = self._chunk_widths[-1]
        pprog, pouts = self._prefill_prog(tc)
        pmem = analysis.check_memory_budget(
            pprog, list(self._prefill_feed_names),
            [v.name for v in self._fetches(pouts)], budget,
            scope=self.scope,
            batch_size=self.prefill_batch_buckets[-1],
            what=f"PagedGenerationEngine prefill (chunk {tc})")
        self.metrics.set_gauge("mem/static_peak_bytes",
                               max(mem.peak_bytes, pmem.peak_bytes))

    # -- page bookkeeping -------------------------------------------------
    def _run_page_copy(self, src: int, dst: int) -> None:
        prog, ok = self._page_copy_prog
        with self._device_ctx():
            self.executor.run(
                prog, feed={"serving.cow_src": np.asarray([src], np.int32),
                            "serving.cow_dst": np.asarray([dst], np.int32)},
                fetch_list=[ok], scope=self.scope)

    def _cow_guard(self, decoding) -> None:
        """Before a decode tick writes position ``pos`` for each slot,
        any target page still shared (refcount > 1 — a prefix-cache page
        this sequence is diverging from) is copied to a fresh page from
        the slot's admission-time reserve and the block table redirected.
        Runs at page-boundary granularity: at most one copy per shared
        prefix per sequence lifetime."""
        for slot in decoding:
            st = self._slots[slot]
            entry = int(self._pos[slot]) // self.page_size
            pid = st.pages[entry]
            if self.pool.refcount(pid) <= 1:
                continue
            if st.cow_reserve > 0:
                st.cow_reserve -= 1
                new = self.pool.alloc(reserved=True)
            else:  # defensive: never expected, but never corrupt a share
                if self.pool.available() < 1 and self.prefix_index:
                    self.prefix_index.evict_until(1)
                new = self.pool.alloc()
            self._run_page_copy(pid, new)
            self.pool.decref(pid)
            st.pages[entry] = new
            self.metrics.inc("kv_cow_copies")

    def _register_prefix(self, st: _PagedSlot,
                         include_tail: bool = False) -> None:
        """Publish the slot's fully-written prompt pages into the prefix
        index (idempotent: existing keys no-op). Full pages register once
        their content is prefilled; the partial tail page only at finish
        (an index reference on a page the request still writes would
        force a pointless self-copy-on-write)."""
        if self.prefix_index is None or st.prefill_done < st.prompt.size:
            return
        ps = self.page_size
        prompt = st.prompt
        n_full = prompt.size // ps
        key = b""
        for i in range(n_full):
            key = self.prefix_index.insert(
                key, prompt[i * ps:(i + 1) * ps], st.pages[i])
        tail = prompt[n_full * ps:]
        if include_tail and tail.size:
            self.prefix_index.insert(key, tail, st.pages[n_full])

    def _release_pages(self, st: _PagedSlot) -> None:
        if self._prefix_sharing:
            self._register_prefix(st, include_tail=True)
        for pid in st.pages:
            self.pool.decref(pid)
        st.pages = []
        if st.cow_reserve:
            self.pool.release_reservation(st.cow_reserve)
            st.cow_reserve = 0

    def _finish(self, slot: int) -> None:
        self._release_pages(self._slots[slot])
        super()._finish(slot)

    # -- admission ---------------------------------------------------------
    def _validate(self, req: Request):
        """Base validation plus the per-request decode policy: a
        SamplingParams merged request-over-engine-default (request wins
        field by field — the compat contract for the deprecated
        engine-wide ``temperature=``/``top_k=``), and BeamParams when
        the request asks for beam search."""
        prompt, max_new, eos = super()._validate(req)
        meta = req.meta or {}
        sp = meta.get("sampling_params")
        try:
            sampling = (sp if isinstance(sp, SamplingParams)
                        else SamplingParams.from_meta(
                            meta, self.default_sampling))
            sampling.validate(self.spec.vocab_size)
            beam = BeamParams.from_meta(meta)
            if beam is not None:
                if beam.eos_id is None and eos is not None:
                    beam = dataclasses.replace(beam, eos_id=eos)
                beam.validate(self.spec.vocab_size)
        except (ValueError, TypeError) as exc:
            raise BadRequestError(str(exc))
        if beam is not None:
            if not self.beam_width:
                raise BadRequestError(
                    "beam request on an engine built without the beam "
                    "plane — construct with beam_width >= beam_size")
            if beam.beam_size > self.beam_width:
                raise BadRequestError(
                    f"beam_size {beam.beam_size} exceeds the engine's "
                    f"beam_width ({self.beam_width})")
            if beam.beam_size > self.slots:
                raise BadRequestError(
                    f"beam_size {beam.beam_size} exceeds the slot count "
                    f"({self.slots}) — a hypothesis occupies one slot")
        if sampling.sampled and sampling.seed is None:
            # engine-assigned default: reproducible against THIS engine
            # only — pass a seed (the fleet pins one before hedging) for
            # cross-replica reproducibility
            sampling = sampling.with_seed(self._seed_counter)
            self._seed_counter = (self._seed_counter + 1) & 0x7FFFFFFF
        if sampling.max_tokens is not None \
                and meta.get("max_new_tokens") is None:
            max_new = int(sampling.max_tokens)
            if prompt.size + max_new > self.tmax:
                raise BadRequestError(
                    f"prompt ({prompt.size}) + max_tokens ({max_new}) "
                    f"exceeds the serving context ({self.tmax})")
        return prompt, max_new, eos, sampling, beam

    def admit(self, requests: List[Request]) -> int:
        """Admit a group of requests: prefix-cache lookup + page
        allocation per request, then ONE bucketed prefill over everyone
        whose (unshared) prompt remainder fits ``prefill_chunk``; longer
        prompts claim their slot and stream in via :meth:`prefill_tick`.
        Requests the pool cannot hold right now are DEFERRED (retried
        each tick as pages free) — only a request that can never fit
        fails, typed. A beam request claims ``beam_size`` slots (parent
        plus holds its hypotheses fork into). Returns the number
        admitted to a slot."""
        hand = [r for r in requests
                if isinstance(r.payload, dict)
                and r.payload.get("handoff") is not None]
        adopted = 0
        if hand:
            # cross-process KV migration: the payload carries serialized
            # page ranges + the block table; installation writes the
            # bytes and resumes decode — never a prefill recompute
            from .disagg import install_serialized_handoff

            for req in hand:
                if install_serialized_handoff(self, req):
                    adopted += 1
            requests = [r for r in requests if r not in hand]
            if not requests:
                self._gauges()
                return adopted
        todo = []
        for req in requests:
            try:
                todo.append((req, *self._validate(req)))
            except BadRequestError as exc:
                self.metrics.inc("bad_requests")
                req.end_trace(status="bad_request")
                req.future.set_exception(exc)
        if not todo:
            return adopted
        group: list = []
        admitted = adopted
        for item in todo:
            if self._is_recovery(item[0]):
                # PRIORITY admission: a recovery re-admission never
                # queues behind deferred NEW work — under pool pressure
                # new requests defer first, and a blocked recovery goes
                # to the FRONT of the deferred queue
                r = self._admit_one(*item, group=group)
                if r == "ok":
                    admitted += 1
                elif r == "defer":
                    self._deferred.appendleft(item)
                continue
            if self._deferred:  # keep FIFO order behind blocked work
                self._deferred.append(item)
                continue
            r = self._admit_one(*item, group=group)
            if r == "ok":
                admitted += 1
            elif r == "defer":
                self._deferred.append(item)
        if group:
            self._run_prefill_group(group)
        self._gauges()
        return admitted

    @staticmethod
    def _is_recovery(req: Request) -> bool:
        meta = req.meta or {}
        return bool(meta.get("recovery") or meta.get("resume_tokens"))

    def _admit_one(self, req, prompt, max_new, eos, sampling, beam,
                   group) -> str:
        """Claim a slot + pages for one validated request. Returns "ok"
        (slot taken; short prefills appended to ``group``), "defer"
        (transient pool/slot pressure), or "failed" (future completed
        with CacheExhaustedError — the request can NEVER fit)."""
        from .errors import CacheExhaustedError

        slots_needed = beam.beam_size if beam is not None else 1
        if self.free_slots < slots_needed:
            self.metrics.inc("admission_deferred")
            return "defer"
        resume = ((req.meta or {}).get("resume_tokens")
                  if beam is None else None)
        if resume:
            # resume-from-token re-admission: the tokens the client
            # already holds re-enter as CONTEXT — chunk-prefilled into
            # fresh pages, never re-decoded. Decode then continues at
            # step len(emitted), and sampling's (seed, step) fold keeps
            # the stream token-exact vs an uninterrupted run. A resume
            # carrying the whole generation re-decodes only its final
            # token (the completed attempt's result was lost in flight).
            resume = [int(t) for t in resume][:max(max_new - 1, 0)]
            if resume:
                prompt = np.concatenate(
                    [prompt, np.asarray(resume, np.int64)])
        resumed_k = len(resume) if resume else 0
        plen = int(prompt.size)
        # total tokens this slot will ever hold: context (original
        # prompt + resumed) plus only the NEW tokens left to decode —
        # identical to the uninterrupted request's bound
        entries_total = self._entries_for(plen + max_new - resumed_k)
        # worst-case pages: entries_total when unshared; a shared prefix
        # trades >=1 allocated page for <=1 copy-on-write spare, so the
        # bound never grows — entries_total > capacity can NEVER fit
        if entries_total > self.pool.capacity:
            exc = CacheExhaustedError(
                f"prompt ({plen}) + max_new_tokens ({max_new}) needs "
                f"{entries_total} pages but the pool holds only "
                f"{self.pool.capacity} allocatable pages of "
                f"{self.page_size} tokens — shrink the request or grow "
                f"n_pages",
                pages_needed=entries_total,
                pages_free=self.pool.capacity)
            self.metrics.inc("cache_exhausted")
            req.end_trace(status="cache_exhausted")
            req.future.set_exception(exc)
            return "failed"
        shared, spages = 0, []
        if self.prefix_index is not None:
            shared, spages, _ = self.prefix_index.lookup(prompt)
        own = entries_total - len(spages)
        cow = 1 if shared == plen else 0  # generation writes a shared page
        need = own + cow
        for pid in spages:  # hold the prefix before any eviction runs
            self.pool.incref(pid)
        if self.pool.available() < need:
            if self.prefix_index is not None:
                self.prefix_index.evict_until(need)
            if self.pool.available() < need:
                for pid in spages:
                    self.pool.decref(pid)
                self.metrics.inc("admission_deferred")
                return "defer"
        owned = [self.pool.alloc() for _ in range(own)]
        if cow:
            self.pool.reserve(cow)
        slot = self._slots.index(None)
        st = _PagedSlot(req, prompt, max_new, eos, sampling)
        st.pages = list(spages) + owned
        st.shared_tokens = shared
        st.cow_reserve = cow
        st.prefill_done = shared
        st.timeline.prefix_hit_tokens = shared
        if resumed_k:
            self._install_resume(st, resume)
        self.metrics.observe_hist("queue_wait", st.timeline.queue_wait_s)
        self._slots[slot] = st
        if beam is not None:
            # parent + (K-1) parked hold slots the hypotheses fork into;
            # holds occupy the slot table now so later admissions can't
            # starve the expansion
            holds = []
            for _ in range(beam.beam_size - 1):
                h = self._slots.index(None)
                hs = _PagedSlot(req, prompt, max_new, eos, sampling)
                hs.state = "hold"
                hs.role = "hold"
                self._slots[h] = hs
                holds.append(h)
            job = BeamJob(self, req, prompt, max_new, beam,
                          parent_slot=slot, hold_slots=holds)
            st.beam_job = job
            st.role = "beam_parent"
            for h in holds:
                self._slots[h].beam_job = job
            self._beam_jobs.append(job)
            self.metrics.inc("beam_jobs")
        if shared:
            self.metrics.inc("prefix_hits")
            self.metrics.inc("prefix_hit_tokens", shared)
        if req.span is not None:
            req.span.set_attrs(slot=slot, prompt_len=plen,
                               prefix_hit_tokens=shared)
        remaining = plen - shared
        if resumed_k:
            # the bounded cost of recovery: context tokens re-entering
            # via (chunked) prefill — decode work is never repeated
            self.metrics.inc("recovery_prefill_tokens", remaining)
            if req.span is not None:
                req.span.set_attrs(resumed_tokens=resumed_k)
        if remaining == 0:
            # full prefix hit: skip prefill entirely and enter the decode
            # loop one step behind — re-feeding the last prompt token at
            # its own position re-derives (bit-identically) the K/V the
            # shared page already holds and yields the first generated
            # token on the first tick. The rewrite goes through the
            # copy-on-write guard, so the shared page itself stays intact.
            st.state = "decode"
            self._tok[slot] = prompt[-1]
            self._pos[slot] = plen - 1
        elif remaining <= self.prefill_chunk:
            st.state = "prefill"
            group.append((req, st, slot))
        else:
            st.state = "prefill"  # streams via prefill_tick
        return "ok"

    def _install_resume(self, st: _PagedSlot, resume: List[int]) -> None:
        """Seed a re-admitted slot with the tokens its interrupted
        predecessor already emitted: they live in ``generated`` (so the
        decode step counter, stop matching, and max_new accounting all
        continue where the dead replica stopped) AND in the prompt tail
        (so prefill writes their K/V). ``_finish`` strips the overlap."""
        st.resumed = len(resume)
        st.generated = list(resume)
        now = time.monotonic()
        for _ in resume:
            # replay timeline marks (the install_handoff idiom): TTFT
            # stays the original admission's concern; TPOT samples for
            # replayed tokens are ~0 and the recovered stream's real
            # added latency shows up as the resume prefill
            st.timeline.mark_token(now)
        self.metrics.inc("requests_resumed")

    def _run_prefill_group(self, group) -> None:
        """One bucketed prefill call over freshly-admitted requests whose
        unshared remainder fits a single chunk (mixed prefix offsets ride
        the per-row StartPos plane). A group beyond the largest warm
        batch bucket splits into bucket-sized calls."""
        cap = self.prefill_batch_buckets[-1]
        if len(group) > cap:
            for i in range(0, len(group), cap):
                self._run_prefill_group(group[i:i + cap])
            return
        rem = [st.prompt.size - st.prefill_done for _, st, _ in group]
        tc = self._chunk_bucket_for(max(rem))
        bucket = self._batch_bucket_for(len(group))
        chunk = np.full((bucket, tc), self.pad_id, np.int64)
        start = np.zeros(bucket, np.int32)
        length = np.zeros(bucket, np.int32)
        table = np.zeros((bucket, self.pmax), np.int32)
        feed = self._neutral_sampling_feed(bucket)
        for row, (req, st, slot) in enumerate(group):
            r = rem[row]
            chunk[row, :r] = st.prompt[st.prefill_done:]
            start[row] = st.prefill_done
            length[row] = r
            table[row, :len(st.pages)] = st.pages
            # step = tokens already sampled: 0 for a fresh request; a
            # RESUMED one samples its next token at step len(emitted),
            # keeping (seed, step) aligned with the uninterrupted stream
            self._slot_sampling_feed(row, st, feed,
                                     step=len(st.generated))
        feed.update({"serving.chunk": chunk, "serving.start": start,
                     "serving.chunk_len": length,
                     "serving.block_table": table})
        prog, outs = self._prefill_prog(tc)
        t0 = time.perf_counter()
        with self._device_ctx(), profiler.timer("serving/prefill"):
            res = self.executor.run(prog, feed=feed,
                                    fetch_list=self._fetches(outs),
                                    scope=self.scope)
        t1 = time.perf_counter()
        first = np.asarray(res[0])
        topv, topi = ((np.asarray(res[1]), np.asarray(res[2]))
                      if self.beam_width else (None, None))
        self.metrics.observe_latency(t1 - t0, name="prefill")
        self.metrics.inc("prefills")
        self.metrics.set_gauge("prefill_occupancy", len(group) / bucket)
        for row, (req, st, slot) in enumerate(group):
            if req.span is not None:
                trace.record("serving/execute", t0, t1, parent=req.span,
                             phase="prefill", slot=slot,
                             prompt_len=int(st.prompt.size),
                             prompt_bucket=tc, batch_bucket=bucket)
            st.timeline.chunk(t0, t1, rem[row])
            st.prefill_done = st.prompt.size
            self._register_prefix(st)
            if st.role == "beam_parent":
                # the parent's top-K row expands the hypothesis set; the
                # job takes over the slot bookkeeping from here
                st.state = "decode"
                st.role = "beam"
                st.beam_job.on_parent_row(topv[row], topi[row])
                continue
            st.state = "decode"
            self._tok[slot] = first[row]
            self._pos[slot] = st.prompt.size
            self._emit(slot, int(first[row]))

    def _admit_deferred(self) -> int:
        """Retry pool-blocked admissions in arrival order. Expired ones
        time out; when the engine is COMPLETELY idle and the head still
        cannot fit, nothing will ever free the pages it needs — fail it
        typed rather than park it forever."""
        from .errors import CacheExhaustedError
        from .errors import RequestTimeoutError as _Timeout

        admitted = 0
        while self._deferred:
            req, prompt, max_new, eos, sampling, beam = self._deferred[0]
            if req.expired():
                self._deferred.popleft()
                self.metrics.inc("timeouts")
                req.end_trace(status="timeout")
                req.future.set_exception(_Timeout(
                    "request deadline expired while deferred on the KV "
                    "page pool"))
                continue
            if self.free_slots == 0:
                break
            group: list = []
            r = self._admit_one(req, prompt, max_new, eos, sampling,
                                beam, group=group)
            if r == "defer":
                if self.active == 0 and admitted == 0 \
                        and not self._is_recovery(req):
                    # (a RECOVERY head is never pop-failed here: its
                    # page bound equals the original admission's, so if
                    # it can never fit the original would have failed
                    # typed already — pool pressure only defers it, and
                    # the deadline still expires it above)
                    self._deferred.popleft()
                    need = self._entries_for(prompt.size + max_new)
                    self.metrics.inc("cache_exhausted")
                    req.end_trace(status="cache_exhausted")
                    req.future.set_exception(CacheExhaustedError(
                        f"KV page pool cannot free the {need} pages this "
                        f"request needs ({self.pool.available()} "
                        "available and no requests in flight)",
                        pages_needed=need,
                        pages_free=self.pool.available()))
                    continue
                break
            self._deferred.popleft()
            if group:
                self._run_prefill_group(group)
            if r == "ok":
                admitted += 1
        if admitted:
            self._gauges()
        return admitted

    # -- the tick loop ----------------------------------------------------
    @property
    def prefilling(self) -> int:
        return sum(1 for s in self._slots
                   if s is not None and s.state == "prefill")

    def prefill_tick(self) -> bool:
        """Advance ONE prefilling slot by one chunk (<= prefill_chunk
        tokens): the tokens-per-tick budget that keeps decode latency
        flat while a long prompt streams in. Round-robin across
        prefilling slots; returns True when a chunk ran."""
        order = [(self._pf_cursor + i) % self.slots
                 for i in range(self.slots)]
        slot = next((i for i in order if self._slots[i] is not None
                     and self._slots[i].state == "prefill"), None)
        if slot is None:
            return False
        self._pf_cursor = (slot + 1) % self.slots
        st = self._slots[slot]
        plen = int(st.prompt.size)
        start0 = st.prefill_done
        k = min(self.prefill_chunk, plen - start0)
        tc = self._chunk_bucket_for(k)
        bucket = self._batch_bucket_for(1)
        chunk = np.full((bucket, tc), self.pad_id, np.int64)
        start = np.zeros(bucket, np.int32)
        length = np.zeros(bucket, np.int32)
        table = np.zeros((bucket, self.pmax), np.int32)
        chunk[0, :k] = st.prompt[start0:start0 + k]
        start[0] = start0
        length[0] = k
        table[0, :len(st.pages)] = st.pages
        feed = self._neutral_sampling_feed(bucket)
        # same step contract as the group path: 0 unless resumed
        self._slot_sampling_feed(0, st, feed, step=len(st.generated))
        feed.update({"serving.chunk": chunk, "serving.start": start,
                     "serving.chunk_len": length,
                     "serving.block_table": table})
        prog, outs = self._prefill_prog(tc)
        t0 = time.perf_counter()
        with self._device_ctx(), profiler.timer("serving/prefill"), \
                trace.span("serving/prefill_chunk", slot=slot,
                           start=start0, tokens=k):
            res = self.executor.run(prog, feed=feed,
                                    fetch_list=self._fetches(outs),
                                    scope=self.scope)
        t1 = time.perf_counter()
        self.metrics.observe_latency(t1 - t0, name="prefill_chunk")
        self.metrics.inc("prefill_chunks")
        st.timeline.chunk(t0, t1, k)
        if st.request.span is not None:
            trace.record("serving/execute", t0, t1,
                         parent=st.request.span, phase="prefill_chunk",
                         slot=slot, start=start0, tokens=k)
        st.prefill_done = start0 + k
        if st.prefill_done >= plen:
            self.metrics.inc("prefills")
            first = np.asarray(res[0])
            self._register_prefix(st)
            if st.role == "beam_parent":
                st.state = "decode"
                st.role = "beam"
                st.beam_job.on_parent_row(np.asarray(res[1])[0],
                                          np.asarray(res[2])[0])
            else:
                st.state = "decode"
                self._tok[slot] = first[0]
                self._pos[slot] = plen
                self._emit(slot, int(first[0]))
            self._gauges()
        return True

    def _run_decode(self):
        table = np.zeros((self._nslots, self.pmax), np.int32)
        tok = np.zeros(self._nslots, np.int64)
        pos = np.zeros(self._nslots, np.int32)
        feed = self._neutral_sampling_feed(self._nslots)
        for s in range(self.slots):
            st = self._slots[s]
            if st is not None and st.state == "decode":
                tok[s] = self._tok[s]
                pos[s] = self._pos[s]
                table[s, :len(st.pages)] = st.pages
                # step = tokens this request has sampled so far — a pure
                # function of the request, never of the batch around it
                self._slot_sampling_feed(s, st, feed,
                                         step=len(st.generated))
        feed.update({"serving.tok": tok, "serving.pos": pos,
                     "serving.block_table": table})
        prog, outs = self._decode_prog
        res = self.executor.run(prog, feed=feed,
                                fetch_list=self._fetches(outs),
                                scope=self.scope)
        if self.beam_width:
            return (np.asarray(res[0]), np.asarray(res[1]),
                    np.asarray(res[2]))
        return np.asarray(res[0]), None, None

    def decode_tick(self) -> bool:
        """Advance every DECODING slot one token (prefilling slots sit
        out — their block tables are mid-write; a pool-parked beam job's
        slots wait in ``beam_wait``). One compiled step, same shape
        regardless of occupancy or policy mix."""
        decoding = [s for s in range(self.slots)
                    if self._slots[s] is not None
                    and self._slots[s].state == "decode"]
        if not decoding:
            return False
        self._cow_guard(decoding)
        t0 = time.perf_counter()
        with self._device_ctx(), profiler.timer("serving/decode_step"), \
                trace.span("serving/decode_step", active=len(decoding)):
            nxt, topv, topi = self._run_decode()
        self.metrics.observe_latency(time.perf_counter() - t0,
                                     name="decode_step")
        self.metrics.inc("decode_steps")
        self.metrics.inc("decode_tokens", len(decoding))
        self.metrics.set_gauge("batch_occupancy",
                               len(decoding) / self.slots)
        beam_rows: Dict[BeamJob, dict] = {}
        parent_rows = []  # (job, slot) — full-prefix-hit first rows
        for slot in decoding:
            st = self._slots[slot]
            if st is None:
                continue
            if st.beam_job is not None:
                if st.role == "beam_parent":
                    st.role = "beam"
                    parent_rows.append((st.beam_job, slot))
                else:
                    beam_rows.setdefault(st.beam_job, {})[slot] = (
                        topv[slot], topi[slot])
                continue
            self._pos[slot] += 1
            self._tok[slot] = nxt[slot]
            self._emit(slot, int(nxt[slot]))
        for job, slot in parent_rows:
            job.on_parent_row(topv[slot], topi[slot])
        for job, rows in beam_rows.items():
            job.on_decode_rows(rows)
        self._maybe_replica_kill()
        self._gauges()
        return True

    # -- beam search as paged forks ----------------------------------------
    def _fork_layout(self, pages: List[int], n_written: int):
        """How a fork views the source's table after ``n_written``
        positions: (pages shared as-is, fresh pages to allocate, does
        the boundary fall inside a page). Fully-written pages are shared
        by refcount; the partially-written boundary page is shared with
        one copy-on-write spare; entries not yet written get FRESH pages
        (no point sharing what diverges immediately)."""
        ps = self.page_size
        n_share = min(len(pages),
                      n_written // ps + (1 if n_written % ps else 0))
        return n_share, len(pages) - n_share, bool(n_written % ps)

    def _beam_can_fork(self, job, n_forks: int, n_written: int) -> bool:
        """Pool feasibility for ``n_forks`` forks of ``job``'s cache view
        (checked BEFORE any state mutates, so a rerank either applies
        whole or parks whole)."""
        if n_forks <= 0:
            return True
        slot = (job.parent_slot if not job.expanded
                else job.live_slots()[0])
        st = self._slots[slot]
        _, own_n, partial = self._fork_layout(st.pages, n_written)
        per = own_n + (2 if partial else 0)  # fork COW + source top-up
        need = n_forks * per
        if need and self.pool.available() < need \
                and self.prefix_index is not None:
            self.prefix_index.evict_until(need)
        return self.pool.available() >= need

    def _beam_fork(self, src_slot: int, hold_slot: int,
                   n_written: int) -> int:
        """Fork ``src_slot``'s hypothesis into a parked hold slot: the
        written prefix is SHARED (refcount bumps on an int32 table copy
        — no cache bytes move), the boundary page gets a copy-on-write
        spare, and future entries allocate fresh. Feasibility was
        checked by _beam_can_fork."""
        st_src = self._slots[src_slot]
        n_share, own_n, partial = self._fork_layout(st_src.pages,
                                                    n_written)
        shared = st_src.pages[:n_share]
        for pid in shared:
            self.pool.incref(pid)
        owned = [self.pool.alloc() for _ in range(own_n)]
        st = self._slots[hold_slot]
        st.pages = list(shared) + owned
        if partial:
            self.pool.reserve(1)
            st.cow_reserve = 1
            if st_src.cow_reserve == 0:
                # the source's boundary page just became shared too —
                # whichever sibling writes first copies, so both hold a
                # spare
                self.pool.reserve(1)
                st_src.cow_reserve = 1
        st.state = "decode"
        st.role = "beam"
        st.prefill_done = int(st.prompt.size)
        self.metrics.inc("beam_forks")
        self.metrics.inc("beam_shared_pages", n_share)
        return hold_slot

    def _beam_release(self, slot: int, job) -> None:
        """A hypothesis died (or froze): its pages go back to the pool,
        the slot parks as a hold for future forks of this job."""
        st = self._slots[slot]
        self._release_pages(st)
        st.state = "hold"
        st.role = "hold"
        job.holds.append(slot)

    def _beam_park(self, job) -> None:
        """Pool-parked: the job's slots sit out decode ticks until a
        retry (serve_step) finds pages."""
        for h in job.hyps:
            if h.slot is not None:
                self._slots[h.slot].state = "beam_wait"
        if not job.expanded:
            self._slots[job.parent_slot].state = "beam_wait"
        self.metrics.inc("beam_parked")

    def _beam_unpark(self, job) -> None:
        for h in job.hyps:
            if h.slot is not None:
                self._slots[h.slot].state = "decode"
        if not job.expanded:
            self._slots[job.parent_slot].state = "decode"

    def _beam_free_slots(self, job) -> None:
        slots = list(job.holds)
        slots.extend(h.slot for h in job.hyps if h.slot is not None)
        if not job.expanded:
            slots.append(job.parent_slot)
        for slot in set(slots):
            st = self._slots[slot]
            if st is not None:
                if st.pages:
                    self._release_pages(st)
                self._slots[slot] = None
        job.holds = []

    def _beam_finish(self, job, ids: np.ndarray,
                     scores: np.ndarray) -> None:
        """All hypotheses frozen or at horizon: free the job's slots and
        complete the request — ``(ids [K, Tp+N], scores [K])`` when the
        request asked for all beams, else the best beam's ids truncated
        after its eos."""
        self._beam_free_slots(job)
        if job in self._beam_jobs:
            self._beam_jobs.remove(job)
        if job.params.return_all:
            result = (ids, scores)
        else:
            best = ids[0]
            plen = int(job.prompt.size)
            if job.eos_id >= 0:
                gen = best[plen:]
                hits = np.nonzero(gen == job.eos_id)[0]
                if hits.size:
                    best = best[:plen + int(hits[0]) + 1]
            result = best
        latency = time.monotonic() - job.request.enqueue_t
        self._recent.append({
            "beam_size": job.K, "prompt_len": int(job.prompt.size),
            "tokens": job.max_new, "status": "ok",
            "latency_s": round(latency, 6)})
        job.request.future.set_result(result)
        job.request.end_trace(status="ok", beam_size=job.K,
                              latency_s=round(latency, 6))
        self.metrics.inc("completed")
        self.metrics.observe_latency(latency)

    def _beam_abort(self, job, exc) -> None:
        self._beam_free_slots(job)
        if job in self._beam_jobs:
            self._beam_jobs.remove(job)
        job.done = True
        self.metrics.inc("cache_exhausted")
        job.request.end_trace(status="cache_exhausted")
        job.request.future.set_exception(exc)

    def _beam_maintenance(self) -> bool:
        """Retry pool-parked beam jobs; a job that can NEVER get its
        pages (nothing else runs and eviction already failed) aborts
        typed instead of parking forever."""
        from .errors import CacheExhaustedError

        did = False
        for job in list(self._beam_jobs):
            if not job.waiting:
                continue
            if job.retry():
                did = True
                continue
            others = any(
                st is not None and st.beam_job is not job
                for st in self._slots)
            if not others and not self._deferred:
                self._beam_abort(job, CacheExhaustedError(
                    f"beam_size {job.K} cannot get its fork pages "
                    f"({self.pool.available()} available and nothing "
                    "else in flight) — shrink the beam or grow n_pages",
                    pages_needed=job.K, pages_free=self.pool.available()))
        return did

    def generate_beam(self, prompt, beam_size: int = 4,
                      max_new_tokens: Optional[int] = None,
                      eos_id: Optional[int] = None,
                      length_penalty: float = 0.0,
                      return_all: bool = True):
        """Synchronous beam search through the engine loop. Returns
        ``(ids [K, Tp+N] best-first, scores [K])`` (``return_all=False``:
        the best beam's ids). Token-exact against
        ``transformer_stack_beam_search`` over the same weights."""
        req = Request({"prompt": prompt},
                      {"max_new_tokens": (max_new_tokens
                                          or self.default_max_new_tokens),
                       "eos_id": eos_id, "beam_size": int(beam_size),
                       "length_penalty": float(length_penalty),
                       "return_beams": bool(return_all)}, None)
        self._drive([req])
        return req.future.result(timeout=0.1)

    def _gauges(self):
        super()._gauges()
        self.metrics.set_gauge("mem/kv_pages_in_use",
                               self.pool.pages_in_use())
        self.metrics.set_gauge("mem/kv_pages_free",
                               self.pool.available())
        self.metrics.set_gauge("beam_active_jobs", len(self._beam_jobs))
        if self.prefix_index is not None:
            self.metrics.set_gauge("kv_prefix_entries",
                                   len(self.prefix_index))

    def flight_state(self) -> dict:
        state = super().flight_state()
        state["pool"] = self.pool.stats()
        state["deferred"] = len(self._deferred)
        if self.prefix_index is not None:
            state["prefix_index"] = self.prefix_index.stats()
        return state

    def cache_stats(self) -> dict:
        """Compile-cache counters (base contract) plus the page pool and
        prefix index, flattened to numbers so the server can export
        every key as a gauge."""
        stats = dict(super().cache_stats())
        for k, v in self.pool.stats().items():
            stats[f"kv_pages_{k}"] = v
        if self.prefix_index is not None:
            for k, v in self.prefix_index.stats().items():
                stats[f"kv_prefix_{k}"] = v
        return stats

    def swap_params(self, source, *, strict: bool = True):
        """Rolling weight update (see the base contract) PLUS prefix-
        cache invalidation: cached prefix pages hold K/V computed with
        the OLD weights — serving them after a swap would be silently
        stale, so every index entry is dropped (pages still referenced
        by in-flight slots stay resident until those requests finish)."""
        stats = super().swap_params(source, strict=strict)
        if self.prefix_index is not None:
            dropped = self.prefix_index.clear()
            if dropped:
                self.metrics.inc("prefix_entries_invalidated", dropped)
            self._gauges()
        return stats

    # -- mid-stream chaos --------------------------------------------------
    def _abort_slot_resources(self, st) -> None:
        if st.pages:
            self._release_pages(st)

    def kill(self, reason: str = "chaos") -> int:
        """Paged kill: beam jobs and the deferred queue die with the
        slots (every future fails retryable), pages go back to the
        pool."""
        exc = ConnectionError(
            f"replica killed mid-stream ({reason}); in-flight "
            "generations are resumable from their lineage")
        failed = 0
        for job in list(self._beam_jobs):
            self._beam_free_slots(job)
            self._beam_jobs.remove(job)
            job.done = True
            job.request.end_trace(status="killed")
            if not job.request.future.done():
                job.request.future.set_exception(exc)
                failed += 1
        failed += super().kill(reason)
        while self._deferred:
            req = self._deferred.popleft()[0]
            req.end_trace(status="killed")
            if not req.future.done():
                req.future.set_exception(exc)
                failed += 1
        return failed

    # -- prefill/decode disaggregation: KV handoff -------------------------
    def handoff_ready(self) -> List[int]:
        """Slots eligible to migrate to a decode pool: prompt K/V fully
        cached, next step a plain decode tick. Beam-owned slots stay
        (their job holds engine-local state) and seq2seq slots stay
        (their cross-KV row is engine-resident)."""
        out = []
        for i in range(self.slots):
            st = self._slots[i]
            if st is not None and st.state == "decode" \
                    and st.role == "normal" and st.beam_job is None \
                    and getattr(st, "xrow", None) is None:
                out.append(i)
        return out

    def export_slot(self, slot: int) -> dict:
        """Migrate one decoding slot OUT of this engine. The slot-table
        entry is vacated but the pages keep their refcounts — the
        returned handoff owns them. Same-process: :meth:`adopt_slot` on
        an engine built with ``share_cache_with=`` transfers by
        refcount; cross-process: ``disagg.serialize_handoff`` moves the
        page bytes. Either way the migration is the block table + pages
        — never a prefill recompute."""
        st = self._slots[slot]
        if st is None or st.state != "decode" or st.beam_job is not None \
                or getattr(st, "xrow", None) is not None:
            raise ValueError(f"slot {slot} is not handoff-eligible")
        self._slots[slot] = None
        self.metrics.inc("kv_handoffs_out")
        self.metrics.inc("kv_handoff_pages", len(st.pages))
        self._gauges()
        return {"st": st, "tok": int(self._tok[slot]),
                "pos": int(self._pos[slot]), "pool": self.pool}

    def adopt_slot(self, handoff: dict) -> int:
        """Install a migrated slot (same-process leg). This engine must
        share the exporter's page pool (``share_cache_with=``) — the
        pages' refcounts simply transfer with the block table. Returns
        the slot index; decode resumes on the next tick, bit-identically
        (copy-on-write still guards any page the prefix index shares)."""
        if handoff.get("pool") is not self.pool:
            raise ValueError(
                "same-process adoption needs a shared page pool — build "
                "the decode engine with share_cache_with=<prefill "
                "engine> (cross-process migration goes through "
                "disagg.serialize_handoff)")
        if self.free_slots == 0:
            raise RuntimeError("no free slot to adopt the handoff into")
        slot = self._slots.index(None)
        self._slots[slot] = handoff["st"]
        self._tok[slot] = handoff["tok"]
        self._pos[slot] = handoff["pos"]
        self.metrics.inc("kv_handoffs_in")
        self._gauges()
        return slot

    # -- server-driver interface ------------------------------------------
    def serve_step(self, batcher,
                   idle_wait_s: Optional[float] = None) -> bool:
        if self._killed:
            return self._drain_killed(batcher)
        did = self._beam_maintenance()
        did = self._admit_deferred() > 0 or did
        free = self.free_slots
        if free and not self._deferred:
            wait = 0 if (self.active or did) else idle_wait_s
            reqs = batcher.next_batch(max_n=free, wait_s=wait)
            if reqs:
                did = self.admit(reqs) > 0 or did
        did = self.prefill_tick() or did
        did = self.decode_tick() or did
        return did

    def _drive(self, reqs: List[Request]) -> None:
        """Run the engine loop until every given request completes (the
        in-process analogue of a loaded server, beam jobs included)."""
        pending = list(reqs)
        while pending or self.active or self._deferred or self._beam_jobs:
            if pending and self.free_slots and not self._deferred:
                k = min(len(pending), self.free_slots)
                self.admit(pending[:k])
                pending = pending[k:]
            self._beam_maintenance()
            self._admit_deferred()
            self.prefill_tick()
            self.decode_tick()

    def generate_all(self, prompts: Sequence[Sequence[int]],
                     max_new_tokens: Optional[int] = None,
                     eos_id: Optional[int] = None,
                     sampling=None) -> List[np.ndarray]:
        """``sampling``: one SamplingParams for every prompt, or a list
        (one per prompt) — mixed policies ride one continuous batch."""
        max_new = max_new_tokens or self.default_max_new_tokens
        if sampling is None or isinstance(sampling, SamplingParams):
            sampling = [sampling] * len(list(prompts))
        reqs = [Request({"prompt": p},
                        {"max_new_tokens": max_new, "eos_id": eos_id,
                         "sampling_params": sp}, None)
                for p, sp in zip(prompts, sampling)]
        self._drive(reqs)
        return [r.future.result(timeout=0.1) for r in reqs]

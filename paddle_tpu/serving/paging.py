"""Host-side KV page accounting: the allocator and the prefix index.

The device holds one page pool ``[L, n_pages, Hkv, page_size, dh]`` per
K/V (generation.py owns those tensors); THIS module owns the metadata —
which physical pages are free, how many holders reference each page, and
which pages cache which prompt prefixes. Everything here is plain Python
over numpy ints: no device traffic, no locks (the engine is single-
threaded per tick, like the slot table before it).

Two invariants the engine relies on:

- **Reservation-before-admission.** A request reserves every page it can
  ever need (prompt + max_new_tokens, plus one copy-on-write spare when
  it shares a page it will later write) at admission, so decode never
  allocates — pool pressure surfaces as a typed admission signal
  (:class:`~paddle_tpu.serving.errors.CacheExhaustedError` /
  deferral), never as a mid-decode failure.
- **Write-implies-exclusive.** A page with refcount > 1 is never written;
  the engine copies it first (``kv_cache_page_copy``) and redirects the
  writer's block table to the copy. The prefix index counts as a holder,
  so cached prefixes are immutable by construction.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

SCRAP_PAGE = 0  # padding rows / vacant decode slots write here


class PagePool:
    """Free-list + refcount allocator over ``n_pages`` physical pages.

    Page 0 is the scrap page — permanently pinned, never handed out.
    ``reserve``/``release_reservation`` implement admission-time holds:
    reserved pages are not yet assigned, but they are subtracted from
    :meth:`available` so concurrent admissions cannot oversubscribe, and
    ``alloc(reserved=True)`` draws a physical page out of the hold.
    """

    def __init__(self, n_pages: int, page_size: int):
        if n_pages < 2:
            raise ValueError("need at least 2 pages (one is scrap)")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        # LIFO free list: recently-freed pages are re-used first
        self._free: List[int] = list(range(self.n_pages - 1, 0, -1))
        self._ref = np.zeros(self.n_pages, np.int32)
        self._ref[SCRAP_PAGE] = 1  # pinned
        self._reserved = 0

    # -- accounting --------------------------------------------------------
    @property
    def capacity(self) -> int:
        """Allocatable pages (everything but scrap)."""
        return self.n_pages - 1

    def available(self) -> int:
        """Pages allocatable right now (free minus admission holds)."""
        return len(self._free) - self._reserved

    def pages_in_use(self) -> int:
        return self.capacity - len(self._free)

    def refcount(self, page: int) -> int:
        return int(self._ref[page])

    # -- reservation holds -------------------------------------------------
    def reserve(self, n: int) -> None:
        """Hold ``n`` free pages for a future ``alloc(reserved=True)``."""
        if n < 0:
            raise ValueError("negative reservation")
        if self.available() < n:
            raise RuntimeError(
                f"reserve({n}) with only {self.available()} available")
        self._reserved += n

    def release_reservation(self, n: int) -> None:
        if n > self._reserved:
            raise RuntimeError("releasing more pages than reserved")
        self._reserved -= n

    # -- alloc/ref ---------------------------------------------------------
    def alloc(self, reserved: bool = False) -> int:
        """Pop a free page (refcount 1). ``reserved=True`` consumes one
        unit of a prior :meth:`reserve` hold."""
        if reserved:
            if self._reserved < 1:
                raise RuntimeError("alloc(reserved=True) without a hold")
            self._reserved -= 1
        elif self.available() < 1:
            raise RuntimeError("page pool exhausted")
        page = self._free.pop()
        self._ref[page] = 1
        return page

    def alloc_many(self, n: int) -> List[int]:
        """Pop ``n`` free pages atomically: either all allocate or a
        RuntimeError leaves the pool untouched (a multi-page claim — a
        migrated-in KV handoff — must never half-land)."""
        if self.available() < n:
            raise RuntimeError(
                f"alloc_many({n}) with only {self.available()} available")
        return [self.alloc() for _ in range(n)]

    def incref(self, page: int) -> None:
        if page == SCRAP_PAGE or self._ref[page] < 1:
            raise RuntimeError(f"incref of unallocated page {page}")
        self._ref[page] += 1

    def decref(self, page: int) -> bool:
        """Drop one reference; returns True when the page was freed."""
        if page == SCRAP_PAGE:
            raise RuntimeError("decref of the scrap page")
        if self._ref[page] < 1:
            raise RuntimeError(f"decref of free page {page}")
        self._ref[page] -= 1
        if self._ref[page] == 0:
            self._free.append(page)
            return True
        return False

    def stats(self) -> dict:
        return {"n_pages": self.n_pages, "page_size": self.page_size,
                "in_use": self.pages_in_use(), "free": len(self._free),
                "reserved": self._reserved,
                "shared": int(np.sum(self._ref[1:] > 1))}


def chain_key(parent: Optional[bytes], tokens: Sequence[int]) -> bytes:
    """Content-derived prefix key: digest of (parent key, page tokens).
    Two prompts share page i iff their first i pages carry identical
    tokens — the digest chain makes the whole-prefix comparison O(1)
    per page regardless of depth."""
    h = hashlib.blake2b(digest_size=16)
    h.update(parent or b"\x00")
    h.update(np.asarray(tokens, np.int64).tobytes())
    return h.digest()


class PrefixIndex:
    """LRU map from prompt-prefix keys to cached pages.

    Each entry holds ONE pool reference on its page, so cached prefixes
    survive the requests that produced them — the next request with the
    same system prompt skips that prefill. Entries are content-keyed by
    :func:`chain_key`, walked page-by-page from the prompt's first page;
    the final PARTIAL page may be cached too (keyed by its shorter token
    tuple), which is what makes a full-prompt hit — and therefore a real
    copy-on-write divergence — possible.

    ``evict_until`` frees least-recently-used entries until the pool can
    satisfy an allocation; entries whose page is still held by a live
    request drop only the index's reference (the page stays resident
    under the request and is freed when it finishes).
    """

    def __init__(self, pool: PagePool):
        self._pool = pool
        self._entries: "OrderedDict[bytes, int]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def lookup(self, prompt: np.ndarray) -> Tuple[int, List[int], bytes]:
        """Longest cached prefix of ``prompt``: returns
        ``(shared_tokens, page_ids, last_matched_key)``. Walks full
        pages, then tries the exact partial tail; ``shared_tokens`` is a
        page multiple except on a full-prompt hit. Does NOT take
        references — the caller increfs the pages it decides to use."""
        ps = self._pool.page_size
        key: Optional[bytes] = None
        pages: List[int] = []
        shared = 0
        n_full = len(prompt) // ps
        for i in range(n_full):
            k = chain_key(key, prompt[i * ps:(i + 1) * ps])
            page = self._entries.get(k)
            if page is None:
                self.misses += 1
                return shared, pages, key or b""
            self._entries.move_to_end(k)
            self.hits += 1
            key = k
            pages.append(page)
            shared += ps
        tail = prompt[n_full * ps:]
        if len(tail):
            k = chain_key(key, tail)
            page = self._entries.get(k)
            if page is not None:
                self._entries.move_to_end(k)
                self.hits += 1
                key = k
                pages.append(page)
                shared += len(tail)
            else:
                self.misses += 1
        return shared, pages, key or b""

    def insert(self, parent_key: bytes, tokens: Sequence[int],
               page: int) -> bytes:
        """Cache ``page`` as the prefix continuation ``tokens`` of
        ``parent_key`` (b"" for the first page). Takes one pool
        reference; a no-op (key returned) when already cached."""
        k = chain_key(parent_key or None, tokens)
        if k not in self._entries:
            self._pool.incref(page)
            self._entries[k] = page
        self._entries.move_to_end(k)
        return k

    def evict_until(self, pages_needed: int) -> int:
        """Drop LRU entries until ``pool.available() >= pages_needed``
        (or the index is empty). Returns entries evicted."""
        n = 0
        while (self._pool.available() < pages_needed and self._entries):
            _, page = self._entries.popitem(last=False)
            self._pool.decref(page)
            self.evictions += 1
            n += 1
        return n

    def clear(self) -> int:
        return self.evict_until(self._pool.n_pages + 1)

    def stats(self) -> dict:
        return {"entries": len(self._entries), "hits": self.hits,
                "misses": self.misses, "evictions": self.evictions}

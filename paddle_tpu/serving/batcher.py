"""Dynamic request batching with admission control.

Clipper-style adaptive batching in front of the shape-bucketed engines:
requests queue; the dispatch loop coalesces them into the smallest warm
bucket that covers the backlog, waiting at most ``max_wait_ms`` past the
OLDEST queued request before dispatching a partial batch. Admission is
bounded (``max_queue``) and rejection is a typed error (QueueFullError) —
overload degrades into fast failures, not unbounded latency. Each request
carries a deadline; requests that expire while queued (or after a
fault-injected batch was dropped back) complete with RequestTimeoutError
instead of occupying a bucket row.

The ``fault_hook`` is the test seam: a callable invoked with each formed
batch right before it is handed to the engine. It may sleep (delaying the
batch) or return ``"drop"`` to push the batch back onto the queue front —
simulating a lost dispatch so tests can pin the timeout/retry semantics.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, List, Optional, Sequence

from .. import trace
from .errors import EngineClosedError, QueueFullError, RequestTimeoutError


class Future:
    """Minimal completion handle: ``result(timeout)`` blocks for the
    value or re-raises the failure set by the serving loop."""

    def __init__(self):
        self._done = threading.Event()
        self._result = None
        self._exc: Optional[BaseException] = None

    def set_result(self, value) -> None:
        self._result = value
        self._done.set()

    def set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("result not ready")
        if self._exc is not None:
            raise self._exc
        return self._result


class Request:
    """One queued unit of work: an opaque payload plus scheduling state.

    ``span``/``queue_span`` carry the request's trace: the request span
    opens at admission and closes at completion (whichever thread that
    happens on); the queue span covers admission -> dispatch and records
    the queue-wait attribute. Both are None with tracing off.
    """

    __slots__ = ("payload", "meta", "future", "enqueue_t", "deadline",
                 "span", "queue_span")

    def __init__(self, payload: Any, meta: dict,
                 timeout_ms: Optional[float]):
        self.payload = payload
        self.meta = meta
        self.future = Future()
        self.enqueue_t = time.monotonic()
        self.deadline = (self.enqueue_t + timeout_ms / 1e3
                         if timeout_ms else None)
        self.span = None
        self.queue_span = None

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now or time.monotonic()) >= self.deadline)

    def begin_trace(self) -> None:
        """Open the request + queue spans (detached: they cross threads
        and are ended explicitly by the dispatch/completion path). A
        ``traceparent`` key in ``meta`` (injected by the fleet router or
        an upstream HTTP client) RESUMES that trace — the request's
        spans join the caller's trace id instead of starting a fresh
        one; malformed headers fall back to a fresh trace."""
        parent = trace.extract((self.meta or {}).get("traceparent"))
        self.span = trace.start_span(
            "serving/request", detached=True, parent=parent,
            timeout_ms=(None if self.deadline is None
                        else round((self.deadline - self.enqueue_t) * 1e3)))
        if self.span is not None:
            self.queue_span = trace.start_span(
                "serving/queue", parent=self.span, detached=True)

    def mark_dispatched(self, batch_size: int) -> None:
        """Close the queue span, recording the queue wait."""
        wait_s = time.monotonic() - self.enqueue_t
        if self.queue_span is not None:
            self.queue_span.finish(queue_wait_s=round(wait_s, 6),
                                   batch_size=batch_size)
            self.queue_span = None
        if self.span is not None:
            self.span.set_attr("queue_wait_s", round(wait_s, 6))

    def end_trace(self, status: str = "ok", **attrs) -> None:
        """Close the request span (and a still-open queue span) — called
        from whichever thread completes the request."""
        if self.queue_span is not None:
            self.queue_span.finish(status=status)
            self.queue_span = None
        if self.span is not None:
            self.span.finish(status=status, **attrs)
            self.span = None


class DynamicBatcher:
    """Bounded request queue + bucket-deadline batch former.

    buckets: ascending batch-size buckets the engine keeps warm; a batch
    is dispatched once the backlog covers the largest bucket or the
    oldest request has waited ``max_wait_ms``.
    """

    def __init__(self, buckets: Sequence[int] = (1, 2, 4, 8),
                 max_wait_ms: float = 5.0, max_queue: int = 256,
                 default_timeout_ms: Optional[float] = None,
                 metrics=None,
                 fault_hook: Optional[Callable[[List[Request]], Any]] = None):
        if not buckets:
            raise ValueError("need at least one batch bucket")
        self.buckets = sorted(set(int(b) for b in buckets))
        self.max_wait_s = max_wait_ms / 1e3
        self.max_queue = int(max_queue)
        self.default_timeout_ms = default_timeout_ms
        self.metrics = metrics
        self.fault_hook = fault_hook
        self._q: deque = deque()
        self._cond = threading.Condition()
        self._closed = False

    # -- admission ---------------------------------------------------------
    def submit(self, payload: Any, timeout_ms: Optional[float] = None,
               **meta) -> Future:
        """Enqueue a request; raises QueueFullError at capacity (the
        backpressure contract) and EngineClosedError after close()."""
        req = Request(payload, meta,
                      timeout_ms if timeout_ms is not None
                      else self.default_timeout_ms)
        req.begin_trace()
        with self._cond:
            if self._closed:
                req.end_trace(status="closed")
                raise EngineClosedError("batcher is closed")
            if len(self._q) >= self.max_queue:
                if self.metrics:
                    self.metrics.inc("rejected_queue_full")
                req.end_trace(status="rejected_queue_full")
                raise QueueFullError(
                    f"queue at capacity ({self.max_queue}); retry with "
                    "backoff")
            self._q.append(req)
            if self.metrics:
                self.metrics.inc("requests")
                self.metrics.set_gauge("queue_depth", len(self._q))
            self._cond.notify_all()
        return req.future

    def bucket_for(self, n: int) -> int:
        """Smallest warm bucket covering ``n`` (the largest bucket when
        ``n`` exceeds them all — callers chunk)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    # -- dispatch ----------------------------------------------------------
    def _expire_locked(self, now: float) -> None:
        kept = deque()
        for req in self._q:
            if req.expired(now):
                self._fail_timeout(req)
            else:
                kept.append(req)
        self._q = kept

    def _fail_timeout(self, req: Request) -> None:
        if self.metrics:
            self.metrics.inc("timeouts")
        req.end_trace(status="timeout")
        req.future.set_exception(RequestTimeoutError(
            "request deadline expired before execution"))

    def next_batch(self, max_n: Optional[int] = None,
                   wait_s: Optional[float] = None) -> List[Request]:
        """Form the next batch, blocking up to ``wait_s`` (default: the
        bucket deadline) for work. Returns [] when nothing is ready —
        the serving loop's idle signal, never an error."""
        cap = self.buckets[-1] if max_n is None else min(
            max_n, self.buckets[-1])
        if cap <= 0:
            return []
        with self._cond:
            deadline0 = time.monotonic() + (
                wait_s if wait_s is not None else self.max_wait_s)
            while not self._q and not self._closed:
                remaining = deadline0 - time.monotonic()
                if remaining <= 0:
                    return []
                self._cond.wait(remaining)
            if not self._q:
                return []
            # bucket deadline: measured from the OLDEST request's arrival.
            # wait_s == 0 is the continuous-batching poll: grab whatever
            # is queued NOW (mid-flight joins must not stall decode ticks).
            if wait_s != 0:
                batch_deadline = self._q[0].enqueue_t + self.max_wait_s
                while (len(self._q) < cap and not self._closed
                       and time.monotonic() < batch_deadline):
                    self._cond.wait(batch_deadline - time.monotonic())
            now = time.monotonic()
            self._expire_locked(now)
            batch = []
            while self._q and len(batch) < cap:
                batch.append(self._q.popleft())
            if self.metrics:
                self.metrics.set_gauge("queue_depth", len(self._q))
        if not batch:
            return []
        if self.fault_hook is not None:
            action = self.fault_hook(batch)
            if action == "drop":
                # simulate a lost dispatch: requeue at the FRONT so a
                # later batch retries them (deadlines keep counting down)
                if self.metrics:
                    self.metrics.inc("batches_dropped")
                self.requeue(batch)
                return []
            # a hook that merely slept may have pushed requests past
            # their deadlines — honor them before dispatch
            now = time.monotonic()
            live = [r for r in batch if not r.expired(now)]
            for r in batch:
                if r.expired(now):
                    self._fail_timeout(r)
            batch = live
            if not batch:
                return []
        if self.metrics:
            self.metrics.inc("batches")
            self.metrics.inc("batched_requests", len(batch))
        for req in batch:
            req.mark_dispatched(len(batch))
        return batch

    def requeue(self, requests: List[Request]) -> None:
        """Push requests back to the queue front (oldest first)."""
        with self._cond:
            for req in reversed(requests):
                if req.span is not None and req.queue_span is None:
                    # back in the queue: reopen a queue segment
                    req.queue_span = trace.start_span(
                        "serving/queue", parent=req.span, detached=True,
                        requeued=True)
                self._q.appendleft(req)
            if self.metrics:
                self.metrics.set_gauge("queue_depth", len(self._q))
            self._cond.notify_all()

    def kick(self) -> None:
        """Wake any waiter (used when slots free up mid-wait)."""
        with self._cond:
            self._cond.notify_all()

    @property
    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    def close(self, drain: bool = False) -> None:
        """Stop admitting. Default fails everything still queued;
        ``drain=True`` keeps queued requests so the dispatch loop can
        finish them (the graceful-shutdown path — call again without
        ``drain`` to fail whatever could not be drained in time)."""
        with self._cond:
            self._closed = True
            pending = [] if drain else list(self._q)
            if not drain:
                self._q.clear()
            self._cond.notify_all()
        for req in pending:
            req.end_trace(status="closed")
            req.future.set_exception(EngineClosedError("server stopped"))

"""paddle_tpu.serving — TPU-native model serving.

The deployment story past the one-shot C-API machine: a saved inference
model becomes a production server loop with

- :class:`InferenceEngine` — bucketed, pre-warmed one-shot inference
  (zero compiles on the serving path after warmup), data-parallel across
  local devices via a ``parallel.make_mesh`` mesh;
- :class:`GenerationEngine` — Orca-style continuous batching for
  autoregressive decode over a slot-table KV cache (requests join and
  leave mid-flight; one compiled decode step in steady state);
- :class:`DynamicBatcher` — Clipper-style deadline batching with bounded
  admission and typed backpressure errors;
- :class:`Server` — the dispatch thread plus an in-process ``submit()``
  API and a stdlib JSON HTTP endpoint;
- :class:`MetricsRegistry` — QPS / queue depth / batch occupancy /
  latency quantiles / compile-cache hits as a plain dict snapshot,
  publishable into :mod:`paddle_tpu.profiler`.

See demos/serving_lm.py for the end-to-end walkthrough.
"""
from .batcher import DynamicBatcher, Future, Request
from .engine import InferenceEngine
from .errors import (BadRequestError, EngineClosedError, QueueFullError,
                     RequestTimeoutError, ServingError)
from .generation import GenerationEngine, LMSpec, spec_from_program_dict
from .metrics import MetricsRegistry
from .server import Server

__all__ = [
    "DynamicBatcher", "Future", "Request",
    "InferenceEngine", "GenerationEngine", "LMSpec",
    "spec_from_program_dict", "MetricsRegistry", "Server",
    "ServingError", "QueueFullError", "RequestTimeoutError",
    "BadRequestError", "EngineClosedError",
]

"""paddle_tpu.serving — TPU-native model serving.

The deployment story past the one-shot C-API machine: a saved inference
model becomes a production server loop with

- :class:`InferenceEngine` — bucketed, pre-warmed one-shot inference
  (zero compiles on the serving path after warmup), data-parallel across
  local devices via a ``parallel.make_mesh`` mesh;
- :class:`GenerationEngine` — Orca-style continuous batching for
  autoregressive decode over a slot-table KV cache (requests join and
  leave mid-flight; one compiled decode step in steady state);
- :class:`DynamicBatcher` — Clipper-style deadline batching with bounded
  admission and typed backpressure errors;
- :class:`Server` — the dispatch thread plus an in-process ``submit()``
  API and a stdlib JSON HTTP endpoint;
- :class:`MetricsRegistry` — QPS / queue depth / batch occupancy /
  latency quantiles / compile-cache hits as a plain dict snapshot,
  publishable into :mod:`paddle_tpu.profiler`;
- :class:`Fleet` — the layer above one server: N replicas (in-process
  or remote HTTP) behind a :class:`Router` with per-replica circuit
  breakers, deadline-propagating retries to a different replica,
  tail-latency hedging, typed load shedding, and zero-downtime rolling
  weight updates (``Fleet.update_weights``);
- :class:`ModelRegistry` / :class:`Tenant` / :class:`MultiTenantServer`
  — several resident models per replica behind ONE ``/v1`` surface,
  routed on the request's ``model``/``tenant`` field, with per-tenant
  sampling defaults, admission quotas, labeled SLO gauges, and
  tenant-scoped weight rolls (the other tenants serve through them);
- :class:`DisaggEngine` + :class:`PrefillPool`/:class:`DecodePool` —
  prefill/decode disaggregation: split engine pools with KV handoff by
  refcounted page migration (same-process) or serialized page ranges
  over ``POST /v1/adopt`` (:class:`RemoteDecodeLeg`) — never a prefill
  recompute;
- :class:`LineageStore` / :class:`LineageRecord` — work-preserving
  recovery: every admitted generation's prompt + pinned sampling policy
  + emitted-tokens-so-far, kept router-side so a replica that dies
  mid-stream triggers a RESUME on a healthy replica (``resume_tokens``
  chunk-prefill, token-exact by (request, seed) determinism) instead of
  a failure.

See demos/serving_lm.py and demos/serving_fleet.py for the end-to-end
walkthroughs.
"""
from .batcher import DynamicBatcher, Future, Request
from .disagg import (DecodePool, DisaggEngine, PrefillPool,
                     RemoteDecodeLeg)
from .engine import InferenceEngine, load_param_arrays, swap_scope_params
from .errors import (BadRequestError, CacheExhaustedError,
                     ConnectionDroppedError, EngineClosedError,
                     FleetOverloadedError, ModelNotFoundError,
                     QueueFullError, ReplicaUnavailableError,
                     RequestTimeoutError, ServingError)
from .fleet import Fleet, HttpReplica, LocalReplica, Replica
from .generation import (GenerationEngine, LMSpec, PagedGenerationEngine,
                         RequestTimeline, spec_from_program_dict)
from .metrics import MetricsRegistry
from .paging import PagePool, PrefixIndex
from .recovery import LineageRecord, LineageStore
from .router import (CircuitBreaker, LeastLoadedPolicy, RoundRobinPolicy,
                     Router, SessionAffinityPolicy)
from .server import Server
from .tenancy import ModelRegistry, MultiTenantServer, Tenant

__all__ = [
    "DynamicBatcher", "Future", "Request",
    "InferenceEngine", "GenerationEngine", "PagedGenerationEngine",
    "LMSpec", "RequestTimeline", "spec_from_program_dict",
    "MetricsRegistry", "Server",
    "PagePool", "PrefixIndex",
    "Fleet", "Replica", "LocalReplica", "HttpReplica",
    "Router", "CircuitBreaker", "RoundRobinPolicy", "LeastLoadedPolicy",
    "SessionAffinityPolicy", "load_param_arrays", "swap_scope_params",
    "ModelRegistry", "Tenant", "MultiTenantServer",
    "DisaggEngine", "PrefillPool", "DecodePool", "RemoteDecodeLeg",
    "LineageStore", "LineageRecord",
    "ServingError", "QueueFullError", "RequestTimeoutError",
    "BadRequestError", "EngineClosedError", "ReplicaUnavailableError",
    "FleetOverloadedError", "CacheExhaustedError", "ModelNotFoundError",
    "ConnectionDroppedError",
]

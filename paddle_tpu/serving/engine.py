"""InferenceEngine: bucketed one-shot inference over a saved model.

The serving half of the whole-block-compile design: the executor compiles
one XLA computation per (program, feed-shape) signature, so a server that
pads every batch to a small set of batch-size (and optional seq-len)
buckets hits the compile cache on EVERY request after warmup — the
reference's per-op interpreter had per-op dispatch cost but no compile
cliff; here the cliff is real and bucketing is the contract that removes
it from the serving path.

Replica dispatch rides :mod:`paddle_tpu.parallel`: pass a ``mesh`` (e.g.
``make_mesh({"dp": n_local_devices})``) and every padded batch is sharded
across the devices by the data-parallel plan — XLA splits the batch, runs
the same weights per device, and the fetch gathers rows back. Without a
mesh, ``place`` pins the engine to one local device so several engines
can serve side by side (one replica per device, each with its own warm
cache).
"""
from __future__ import annotations

import contextlib
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import profiler, trace
from ..core.executor import Executor, TPUPlace
from ..core.scope import Scope
from .errors import BadRequestError, EngineClosedError
from .metrics import MetricsRegistry

DEFAULT_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32)


class PendingInference:
    """Deferred result of :meth:`InferenceEngine.run_async`: one or more
    in-flight padded chunk dispatches (core.executor.RunHandle). The
    engine's batch metrics (execute latency, occupancy) are observed at
    resolve time, covering dispatch->completion of the whole request."""

    def __init__(self, engine: "InferenceEngine", parts):
        self._engine = engine
        self._parts = parts  # [(RunHandle, bucket, rows, t0), ...]
        self._part_outs: List = [None] * len(parts)
        self._result = None

    def done(self) -> bool:
        return all(h.done() for h, _, _, _ in self._parts)

    def result(self) -> List[np.ndarray]:
        """Block until every chunk completes; returns the fetch list
        sliced back to the true batch. Each chunk resolves exactly once —
        a retry after one chunk's failure re-resolves only the failed
        chunks, so the batch metrics observe each chunk once."""
        if self._result is None:
            for i, (h, bucket, n, t0) in enumerate(self._parts):
                if self._part_outs[i] is None:
                    self._part_outs[i] = self._engine._resolve_padded(
                        h, bucket, n, t0)
            outs = self._part_outs
            if len(outs) == 1:
                self._result = outs[0]
            else:
                self._result = [
                    np.concatenate([o[i] for o in outs], axis=0)
                    for i in range(len(self._engine.fetch_names))]
        return self._result


def _round_buckets(buckets: Sequence[int], multiple: int) -> List[int]:
    """Round every bucket up to ``multiple`` (mesh data-parallel needs
    per-device batch divisibility) and dedup, keeping order."""
    return sorted({max(multiple, -(-int(b) // multiple) * multiple)
                   for b in buckets})


def load_param_arrays(source) -> Dict[str, object]:
    """``name -> array`` from any weight source a rolling update can
    publish: a resilience checkpoint directory (the trainer's
    ``CheckpointConfig`` output), a ``save_inference_model`` directory,
    a Scope, or a plain dict of arrays."""
    import os

    from ..core.scope import Scope

    if isinstance(source, dict):
        return dict(source)
    if isinstance(source, Scope):
        return {k: source.get(k) for k in source.keys()}
    dirname = str(source)
    from .. import checkpoint as ckpt_mod

    if os.path.exists(os.path.join(dirname, ckpt_mod.META_NAME)):
        staging = Scope()
        ckpt_mod.load_checkpoint(dirname, scope=staging)
        return {k: staging.get(k) for k in staging.keys()}
    if os.path.exists(os.path.join(dirname, "params", "MANIFEST.json")):
        from ..io import _load_saved_params

        staging = _load_saved_params(dirname)
        return {k: staging.get(k) for k in staging.keys()}
    raise ValueError(
        f"{dirname!r} is neither a checkpoint directory "
        f"({ckpt_mod.META_NAME}) nor a saved inference model "
        f"(params/MANIFEST.json)")


def swap_scope_params(scope, source, *, skip=(), strict: bool = True,
                      device_ctx=None, metrics=None) -> Dict[str, int]:
    """Hot-swap parameter values in a live serving scope.

    Every value whose name exists in both ``source`` and ``scope`` is
    replaced, but ONLY when shape and dtype match exactly — the compile
    caches key on the scope's key set and the program signatures, so a
    same-shape swap costs zero recompiles, and a mismatch (which WOULD
    silently retrace every warm executable) raises instead of degrading
    (``strict=False`` skips mismatches). Donation-safe: old arrays stay
    alive for any outstanding RunHandle that captured them at dispatch;
    new values are fresh device buffers.

    Returns counters: swapped / skipped (not in scope, or in ``skip``) /
    mismatched (strict=False only) / kept (scope keys the source lacks).
    """
    import contextlib

    from ..core.program import RNG_VAR

    skip = set(skip) | {RNG_VAR}
    new = load_param_arrays(source)
    scope_keys = set(scope.keys())
    staged = []
    stats = {"swapped": 0, "skipped": 0, "mismatched": 0, "kept": 0}
    for name in sorted(new):
        if name in skip or name not in scope_keys:
            stats["skipped"] += 1
            continue
        old = scope.get(name)
        arr = new[name]
        old_sig = (tuple(np.shape(old)), str(getattr(old, "dtype", "?")))
        new_sig = (tuple(np.shape(arr)), str(getattr(arr, "dtype", "?")))
        if old_sig != new_sig:
            if strict:
                raise ValueError(
                    f"swap_params: {name!r} is {new_sig} in the source "
                    f"but {old_sig} live — a mismatched swap would "
                    f"retrace every warm executable; publish a "
                    f"same-architecture checkpoint (or pass "
                    f"strict=False to skip)")
            stats["mismatched"] += 1
            continue
        staged.append((name, arr))
    if not staged and strict:
        raise ValueError(
            "swap_params: the source shares no parameter names with the "
            f"live scope (source has {sorted(new)[:5]}..., scope has "
            f"{sorted(scope_keys)[:5]}...) — wrong artifact? (pass "
            "strict=False to no-op)")
    stats["kept"] = len(scope_keys - skip - {n for n, _ in staged})
    # stage fully, then install: a half-applied swap (mid-list error)
    # must not leave the scope serving a chimera of old and new weights
    import jax

    with (device_ctx() if device_ctx is not None
          else contextlib.nullcontext()):
        staged = [(name, jax.device_put(np.asarray(arr)))
                  for name, arr in staged]
    for name, arr in staged:
        scope.set(name, arr)
    stats["swapped"] = len(staged)
    if metrics is not None:
        metrics.inc("param_swaps")
        metrics.set_gauge("param_swap/last_swapped", stats["swapped"])
    return stats


class InferenceEngine:
    """Loads a saved inference model and serves padded-bucket batches.

    Construct from a ``save_inference_model`` directory (``model_dir``)
    or from an already-built (program, feed_names, fetch_names, scope).
    """

    def __init__(self, model_dir: Optional[str] = None, *,
                 program=None, feed_names=None, fetch_names=None,
                 scope: Optional[Scope] = None,
                 batch_buckets: Sequence[int] = DEFAULT_BATCH_BUCKETS,
                 seq_buckets: Optional[Sequence[int]] = None,
                 mesh=None, plan=None, place=None,
                 metrics: Optional[MetricsRegistry] = None,
                 transpile: Optional[bool] = None,
                 mem_budget: Optional[float] = None):
        self.metrics = metrics or MetricsRegistry()
        self.scope = scope or Scope()
        self.model_dir = model_dir  # manifest home (save_manifest/warm_start)
        if mesh is None and plan is not None:
            mesh = plan.mesh  # InferenceEngine(plan=...) — plan carries it
        self.mesh = mesh
        if mesh is not None and plan is None:
            from ..parallel import data_parallel_plan
            plan = data_parallel_plan(mesh, data_axis=mesh.axis_names[0])
        self._place = place
        self.executor = Executor(place or TPUPlace(0), mesh=mesh, plan=plan)
        if model_dir is not None:
            from ..io import load_inference_model
            program, feed_names, fetch_names = load_inference_model(
                model_dir, self.executor, scope=self.scope)
        if program is None or not feed_names or not fetch_names:
            raise ValueError("need model_dir or (program, feed_names, "
                             "fetch_names)")
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        # Transpile before warmup (default only for models we own the copy
        # of, i.e. loaded from disk): the inference pipeline re-runs — a
        # no-op on already-transpiled artifacts, the full rewrite set on
        # raw ones — and its per-pass stats land in the MetricsRegistry.
        if transpile is None:
            transpile = model_dir is not None
        if transpile:
            from ..transpiler import inference_pipeline

            pm = inference_pipeline()
            self.program = pm.run(self.program.clone(), self.feed_names,
                                  self.fetch_names, scope=self.scope,
                                  preserve_state_writes=True)
            for k, v in pm.metrics_dict().items():
                self.metrics.set_gauge(k, v)
        if plan is not None:
            # one sharding plane: annotate the served program's vars with
            # the plan's PartitionSpecs (ShardProgram pass) so lowering,
            # verification, and the memory analysis all read the same
            # per-var specs the executor jits with
            from ..transpiler import shard_program

            shard_program(self.program, plan, self.feed_names,
                          self.fetch_names, scope=self.scope)
        from ..flags import FLAGS

        if FLAGS.verify_program:
            # verify the program actually served (transpiled or raw)
            # before warmup compiles it — a corrupted artifact fails here
            # with op/slot context instead of mid-warmup
            from .. import analysis

            analysis.check_program(self.program, self.feed_names,
                                   self.fetch_names, scope=self.scope,
                                   annotate=False)
        if mesh is not None:
            dp = int(np.prod(mesh.devices.shape))
            batch_buckets = _round_buckets(batch_buckets, dp)
        self.batch_buckets = sorted(set(int(b) for b in batch_buckets))
        self.seq_buckets = (sorted(set(int(s) for s in seq_buckets))
                            if seq_buckets else None)
        if mem_budget is not None:
            # build-time gate at the WORST bucket (largest batch the
            # warmup will compile): a model that cannot fit raises a
            # located MemoryBudgetError here, before any compile/OOM
            from .. import analysis

            mem = analysis.check_memory_budget(
                self.program, self.feed_names, self.fetch_names,
                mem_budget, scope=self.scope,
                batch_size=self.batch_buckets[-1],
                what=f"InferenceEngine (bucket "
                     f"{self.batch_buckets[-1]})", plan=plan)
            self.metrics.set_gauge("mem/static_peak_bytes",
                                   mem.peak_bytes)
            self.metrics.set_gauge("mem/resident_bytes",
                                   mem.resident_bytes)
        # graceful-drain state: admissions stop at close(). Synchronous
        # runs in other threads are counted; async dispatches register
        # their RunHandles so close(drain=True) can block on DEVICE
        # completion (never on host-side result(), which only the caller
        # may trigger — waiting for it here would deadlock the closer).
        self._closed = False
        self._released = False
        self._inflight = 0
        self._inflight_cond = threading.Condition()
        self._outstanding: "weakref.WeakSet" = weakref.WeakSet()
        # flight recorder: live engine state rides every crash/SIGUSR1/
        # admin dump (weak registration — never keeps the engine alive)
        from ..trace import flight as trace_flight

        trace_flight.get_recorder().add_source(type(self).__name__,
                                               self.flight_state)

    # ------------------------------------------------------------------
    def flight_state(self) -> dict:
        """Live state for the flight recorder bundle."""
        return {
            "engine": type(self).__name__,
            "closed": self._closed,
            "inflight": self._inflight,
            "batch_buckets": list(self.batch_buckets),
            "feed_names": list(self.feed_names),
            "cache_stats": dict(self.cache_stats()),
        }

    # ------------------------------------------------------------------
    def _device_ctx(self):
        if self.mesh is None and self._place is not None:
            import jax
            return jax.default_device(self._place.device())
        return contextlib.nullcontext()

    def bucket_for(self, n: int) -> int:
        for b in self.batch_buckets:
            if n <= b:
                return b
        return self.batch_buckets[-1]

    def _feed_template(self, name: str):
        block = self.program.global_block
        if not block.has_var(name):
            return None, None
        v = block.var(name)
        return list(v.shape or []), v.dtype

    # ------------------------------------------------------------------
    def _validated_arrays(self, feed: Dict[str, np.ndarray]):
        missing = [n for n in self.feed_names if n not in feed]
        if missing:
            raise BadRequestError(f"missing feeds: {missing}")
        arrays = {n: np.asarray(feed[n]) for n in self.feed_names}
        ns = {n: a.shape[0] for n, a in arrays.items()}
        if len(set(ns.values())) != 1:
            raise BadRequestError(f"inconsistent batch sizes: {ns}")
        n = next(iter(ns.values()))
        if n == 0:
            raise BadRequestError("empty batch")
        return arrays, n

    def _admit(self):
        if self._closed:
            raise EngineClosedError(
                "engine is closed (draining or released); no new batches")

    def _track(self, delta: int) -> None:
        with self._inflight_cond:
            self._inflight += delta
            if delta < 0:
                self._inflight_cond.notify_all()

    def run(self, feed: Dict[str, np.ndarray]) -> List[np.ndarray]:
        """Execute one user batch: pads the leading dim to the nearest
        bucket (chunking batches beyond the largest), runs the compiled
        program, and returns the fetches sliced back to the true batch.
        Assumes every feed and fetch carries the batch on axis 0 — the
        save_inference_model feed contract."""
        self._admit()
        arrays, n = self._validated_arrays(feed)
        outs: List[List[np.ndarray]] = []
        start = 0
        while start < n:
            chunk = min(n - start, self.batch_buckets[-1])
            outs.append(self._run_padded(
                {k: a[start:start + chunk] for k, a in arrays.items()},
                chunk))
            start += chunk
        if len(outs) == 1:
            return outs[0]
        return [np.concatenate([o[i] for o in outs], axis=0)
                for i in range(len(self.fetch_names))]

    def run_async(self, feed: Dict[str, np.ndarray]) -> PendingInference:
        """Non-blocking :meth:`run`: dispatches every padded chunk via
        ``Executor.run_async`` and returns a :class:`PendingInference`
        handle. The batcher uses this to pipeline consecutive buckets —
        bucket k+1's padding/stacking and dispatch overlap bucket k's
        device execution — and ``serve_step`` resolves in dispatch
        order."""
        self._admit()
        arrays, n = self._validated_arrays(feed)
        parts = []
        start = 0
        while start < n:
            chunk = min(n - start, self.batch_buckets[-1])
            parts.append(self._dispatch_padded(
                {k: a[start:start + chunk] for k, a in arrays.items()},
                chunk))
            start += chunk
        return PendingInference(self, parts)

    def _pad_feed(self, arrays: Dict[str, np.ndarray], n: int):
        bucket = self.bucket_for(n)
        pad = bucket - n
        fed = {}
        for name, a in arrays.items():
            if pad:
                # replicate the last row: numerically safe for any model
                # (an all-zeros row can hit log/div landmines)
                a = np.concatenate([a, np.repeat(a[-1:], pad, axis=0)])
            fed[name] = a
        return fed, bucket

    def _dispatch_padded(self, arrays: Dict[str, np.ndarray], n: int):
        fed, bucket = self._pad_feed(arrays, n)
        t0 = time.perf_counter()
        with self._device_ctx(), \
                trace.span("serving/dispatch_batch", bucket=bucket,
                           rows=n):
            handle = self.executor.run_async(
                self.program, feed=fed, fetch_list=self.fetch_names,
                scope=self.scope)
        self._outstanding.add(handle)
        return handle, bucket, n, t0

    def _resolve_padded(self, handle, bucket: int, n: int, t0: float):
        with profiler.timer("serving/infer_batch"), \
                trace.span("serving/resolve_batch", bucket=bucket, rows=n):
            res = handle.result()
        self.metrics.observe_latency(
            time.perf_counter() - t0, name="batch_execute")
        self.metrics.inc("batches_executed")
        self.metrics.set_gauge("batch_occupancy", n / bucket)
        return [np.asarray(r)[:n] for r in res]

    def _run_padded(self, arrays: Dict[str, np.ndarray], n: int):
        fed, bucket = self._pad_feed(arrays, n)
        t0 = time.perf_counter()
        self._track(+1)
        try:
            with self._device_ctx(), \
                    profiler.timer("serving/infer_batch"), \
                    trace.span("serving/infer_batch", bucket=bucket,
                               rows=n):
                res = self.executor.run(self.program, feed=fed,
                                        fetch_list=self.fetch_names,
                                        scope=self.scope)
        finally:
            self._track(-1)
        self.metrics.observe_latency(
            time.perf_counter() - t0, name="batch_execute")
        self.metrics.inc("batches_executed")
        self.metrics.set_gauge("batch_occupancy", n / bucket)
        return [np.asarray(r)[:n] for r in res]

    # ------------------------------------------------------------------
    def warmup(self) -> int:
        """Compile every configured bucket shape up front with dummy
        feeds so live traffic never pays a compile. Returns the number
        of (batch, seq) combinations warmed; feeds with a dynamic
        non-batch dim need ``seq_buckets`` configured or they are
        skipped (and counted in the 'warmup_skipped' metric)."""
        combos = 0
        seqs = self.seq_buckets or [None]
        for b in self.batch_buckets:
            for s in seqs:
                feed = {}
                ok = True
                for name in self.feed_names:
                    shape, dtype = self._feed_template(name)
                    if shape is None:
                        ok = False
                        break
                    dims = [b]
                    for d in shape[1:]:
                        if d in (-1, None):
                            if s is None:
                                ok = False
                                break
                            dims.append(s)
                        else:
                            dims.append(int(d))
                    if not ok:
                        break
                    feed[name] = np.zeros(dims, dtype=dtype)
                if not ok:
                    self.metrics.inc("warmup_skipped")
                    continue
                with self._device_ctx():
                    self.executor.run(self.program, feed=feed,
                                      fetch_list=self.fetch_names,
                                      scope=self.scope)
                combos += 1
        self.metrics.inc("warmup_compiles", combos)
        self.save_manifest()
        return combos

    # -- cold-start plane ----------------------------------------------
    def save_manifest(self, dirname: Optional[str] = None) -> Optional[str]:
        """Persist the executor's recorded compile signatures next to the
        saved model (``warmup_manifest.json``) so the next replica can
        AOT-replay them (:meth:`warm_from_manifest`) instead of paying
        fresh compiles. No-op (returns None) without a model directory or
        before anything compiled."""
        dirname = dirname or self.model_dir
        if dirname is None or len(self.executor.manifest) == 0:
            return None
        try:
            return self.executor.manifest.save(dirname)
        except OSError:  # read-only artifact volume: serving still works
            return None

    def warm_from_manifest(self,
                           dirname: Optional[str] = None) -> Optional[int]:
        """AOT-replay a saved warmup manifest: ``.lower().compile()`` of
        every recorded signature of this engine's program, concurrently,
        WITHOUT executing anything. Returns the number of signatures now
        warm, or None when no manifest exists (caller falls back to the
        execute-based :meth:`warmup`). With ``--compilation_cache_dir``
        the compiles are disk restores and the first request is a pure
        in-process cache hit."""
        from ..core import manifest as manifest_mod

        dirname = dirname or self.model_dir
        if dirname is None:
            return None
        manifest = manifest_mod.try_load(dirname)
        if manifest is None:
            return None
        stats = manifest_mod.replay(
            self.executor, [self.program], scope=self.scope,
            manifest=manifest, device_ctx=self._device_ctx)
        self.metrics.inc("warmup_replayed", stats["compiled"])
        if stats["skipped"]:
            self.metrics.inc("warmup_manifest_skipped", stats["skipped"])
        return stats["compiled"] + stats["already"]

    def warm_start(self) -> int:
        """Boot path: manifest replay when available (AOT, concurrent, no
        execution), else execute-based :meth:`warmup`; either way a fresh
        manifest lands next to the model so the NEXT replica boots warm.
        A stale/foreign manifest degrades into ``warmup()`` instead of
        failing the boot."""
        import warnings as warnings_mod

        from ..core.manifest import ManifestError

        warmed = None
        try:
            warmed = self.warm_from_manifest()
        except ManifestError as exc:
            warnings_mod.warn(f"ignoring warmup manifest: {exc}",
                              RuntimeWarning, stacklevel=2)
        if warmed is None:
            warmed = self.warmup()
        self.save_manifest()
        return warmed

    def cache_stats(self) -> dict:
        return self.executor.cache_stats()

    def swap_params(self, source, *, strict: bool = True) -> Dict[str, int]:
        """Zero-recompile param hot-swap (the rolling-update payload
        step): replace this engine's weights in place from ``source`` (a
        trainer checkpoint dir, a saved-model dir, a Scope, or a dict).
        Shapes/dtypes must match the live values — the compile cache
        keys on the scope's key set, so a same-signature swap keeps
        every warm executable. Outstanding async dispatches keep the old
        arrays alive until they resolve (donation-safe)."""
        return swap_scope_params(self.scope, source, strict=strict,
                                 device_ctx=self._device_ctx,
                                 metrics=self.metrics)

    # ------------------------------------------------------------------
    @property
    def state(self) -> str:
        """``ready`` | ``draining`` (closed, in-flight work finishing) |
        ``closed`` — the /healthz vocabulary."""
        if not self._closed:
            return "ready"
        return "closed" if self._released else "draining"

    def close(self, drain: bool = True,
              timeout: Optional[float] = 30.0) -> None:
        """Graceful release: stop admissions (``run``/``run_async``/
        ``serve_step`` raise :class:`EngineClosedError` from now on),
        then — with ``drain`` — wait for every in-flight batch before
        releasing the compile cache: synchronous runs on other threads
        finish, and async dispatches complete ON DEVICE (their callers
        can still ``result()`` afterwards — the fetched arrays outlive
        the engine). Idempotent."""
        with self._inflight_cond:
            self._closed = True
        if drain:
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            with self._inflight_cond:
                while self._inflight > 0:  # sync runs in other threads
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        break  # bounded wait
                    self._inflight_cond.wait(remaining)
            for handle in list(self._outstanding):
                try:
                    handle.block()  # device completion, no host fetch
                except Exception:  # noqa: BLE001 - failed batch: done too
                    pass
        self._released = True
        self.executor.close()

    # ------------------------------------------------------------------
    # Server-driver interface
    # ------------------------------------------------------------------
    def serve_step(self, batcher, idle_wait_s: Optional[float] = None) -> bool:
        """Pull one batch from the batcher and execute it. Request
        payloads are per-row feed dicts (no batch dim); rows with
        identical shapes coalesce into one padded run. Shape groups are
        dispatched non-blocking (``run_async``) before any is resolved,
        so consecutive buckets pipeline: group k+1's stacking/padding and
        dispatch overlap group k's device execution. Returns True when
        work was done."""
        self._admit()
        reqs = batcher.next_batch(wait_s=idle_wait_s)
        if not reqs:
            return False
        groups: Dict[tuple, list] = {}
        for req in reqs:
            try:
                rows = {n: np.asarray(req.payload[n])
                        for n in self.feed_names}
            except (KeyError, TypeError) as exc:
                req.end_trace(status="bad_request")
                req.future.set_exception(BadRequestError(
                    f"payload must be a dict with feeds "
                    f"{self.feed_names}: {exc}"))
                continue
            sig = tuple((n, rows[n].shape) for n in self.feed_names)
            groups.setdefault(sig, []).append((req, rows))

        def fail(members, t0, exc):
            t1 = time.perf_counter()
            for req, _ in members:
                if req.span is not None:  # keep sampling decisions
                    trace.record("serving/execute", t0, t1,
                                 parent=req.span, batch=len(members),
                                 error=repr(exc)[:200])
                req.end_trace(status="error", error=repr(exc)[:200])
                req.future.set_exception(exc)

        dispatched = []
        for _, members in groups.items():
            feed = {n: np.stack([rows[n] for _, rows in members])
                    for n in self.feed_names}
            t0 = time.perf_counter()
            try:
                pending = self.run_async(feed)
            except Exception as exc:  # engine failure fails the batch
                fail(members, t0, exc)
                continue
            dispatched.append((members, t0, pending))
        for members, t0, pending in dispatched:
            try:
                fetched = pending.result()
            except Exception as exc:
                fail(members, t0, exc)
                continue
            t1 = time.perf_counter()
            now = time.monotonic()
            for i, (req, _) in enumerate(members):
                # attribute the shared batch execution to each rider
                # (skipped for unsampled requests: a root 'execute' span
                # would defeat the per-request sampling decision)
                if req.span is not None:
                    trace.record("serving/execute", t0, t1,
                                 parent=req.span, batch=len(members),
                                 row=i)
                req.future.set_result([f[i] for f in fetched])
                req.end_trace(status="ok",
                              latency_s=round(now - req.enqueue_t, 6))
                self.metrics.inc("completed")
                self.metrics.observe_latency(now - req.enqueue_t)
        return True

"""Multi-tenant model serving: several resident models, one ``/v1``.

One replica process often has room for more than one model (or more
than one weight generation of the same model) — small rerankers riding
next to the headline LM, or a canary generation serving 5% of traffic.
This module is the composition layer that makes that a first-class
deployment shape instead of N separate ports:

- :class:`Tenant` — one resident model: its engines, its OWN admission
  queue (quota = the queue bound, so per-tenant backpressure is the
  same typed :class:`~.errors.QueueFullError` contract as everywhere
  else), its own :class:`~paddle_tpu.trace.slo.SLOTracker`, sampling
  defaults, and compile-cache/warmup-manifest namespace (the engine's
  ``namespace`` → ``warmup_manifest.<tenant>.json``, so tenants warm
  and verify independently).
- :class:`ModelRegistry` — the name -> Tenant map behind the request's
  ``model``/``tenant`` field. Unknown names are a typed
  :class:`~.errors.ModelNotFoundError` (HTTP 404), never a silent
  fall-through to the default model.
- :class:`MultiTenantServer` — a :class:`~.server.Server` whose
  dispatch loop round-robins (engine, tenant-queue) pairs, so one
  tenant's burst queues against ITS quota while the others keep their
  latency. Tenant-scoped rolling updates
  (``swap_params(tenant=...)``) drain only that tenant's queue and
  engines — the other tenants serve straight through the roll.

Per-tenant observability rides the labeled-gauge plane:
``tenant_queue_depth{tenant=...}``, ``weights_version{tenant=...}``,
and — via ``SLOTracker.publish_gauges(..., tenant=...)`` — one full
SLO burn-rate plane per tenant. ``fleetctl status`` renders the
per-tenant table from ``/fleet/status``'s ``tenants`` block.
"""
from __future__ import annotations

import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

from ..trace.slo import SLOTracker
from .batcher import DynamicBatcher, Future
from .errors import EngineClosedError, ModelNotFoundError
from .metrics import MetricsRegistry
from .server import Server


class Tenant:
    """One resident model inside a multi-tenant replica.

    name:        the id requests select with their ``model``/``tenant``
                 field (and the engine's compile-cache/manifest
                 namespace when the engine doesn't already have one).
    engines:     the engine (or engines) serving this tenant. They keep
                 their own Scope/Executor/page pool — tenancy is
                 composition, not sharing.
    sampling:    optional :class:`~paddle_tpu.decoding.SamplingParams`
                 installed as the tenant's engine-wide default (request
                 fields still win field-by-field).
    max_pending: admission quota — the bound of the tenant's OWN queue;
                 beyond it submits fail typed (QueueFullError/429), so
                 one tenant's burst can never consume another's queue.
    slo:         optional :class:`~paddle_tpu.trace.slo.SLO` evaluated
                 over THIS tenant's engine metrics only.
    weights_dir: checkpoint dir a tenant-scoped Publisher watches
                 (informational here; the Publisher drives the rolls).
    """

    def __init__(self, name: str, engines, *, sampling=None,
                 max_pending: int = 256,
                 batch_buckets: Sequence[int] = (1, 2, 4, 8),
                 max_wait_ms: float = 5.0,
                 default_timeout_ms: Optional[float] = None,
                 slo=None, weights_dir: Optional[str] = None):
        if not name:
            raise ValueError("a tenant needs a non-empty name")
        self.name = str(name)
        self.engines = list(engines) if isinstance(
            engines, (list, tuple)) else [engines]
        if not self.engines:
            raise ValueError(f"tenant {name!r} needs at least one engine")
        # the tenant's own admission queue: its bound IS the quota
        self.batcher = DynamicBatcher(
            buckets=batch_buckets, max_wait_ms=max_wait_ms,
            max_queue=max_pending, default_timeout_ms=default_timeout_ms,
            metrics=self.engines[0].metrics)
        self.max_pending = int(max_pending)
        self.slo_tracker = SLOTracker(slo) if slo is not None else None
        self.weights_dir = weights_dir
        self.paused = False          # tenant-scoped drain (rolling update)
        self.weights_version = 0.0   # bumped by note_swap / Publisher
        self.swaps = 0
        for eng in self.engines:
            # manifest/compile-cache namespace: tenants on one replica
            # must not clobber each other's warmup_manifest.json
            if not getattr(eng, "namespace", ""):
                eng.namespace = self.name
        if sampling is not None:
            for eng in self.engines:
                vocab = getattr(getattr(eng, "spec", None),
                                "vocab_size", None)
                sampling.validate(vocab)
                eng.default_sampling = sampling
                # keep the deprecated engine-wide mirrors coherent
                eng.temperature = float(sampling.temperature)
                eng.top_k = int(sampling.top_k)
        self.sampling = sampling

    # -- metrics -----------------------------------------------------------
    @property
    def metrics(self) -> MetricsRegistry:
        return self.engines[0].metrics

    def snapshot(self) -> dict:
        """This tenant's metrics view: one engine's snapshot, or the
        bucket-sum merge across a multi-engine tenant (the same merge
        the fleet uses, so SLO attainment stays exact)."""
        if len(self.engines) == 1:
            return self.engines[0].metrics.snapshot()
        return MetricsRegistry.merge(
            {f"e{i}": e.metrics.snapshot()
             for i, e in enumerate(self.engines)})

    def active(self) -> int:
        return sum(getattr(e, "active", 0) for e in self.engines)

    def pages_in_use(self) -> int:
        return sum(e.pool.pages_in_use() for e in self.engines
                   if getattr(e, "pool", None) is not None)

    def note_swap(self, source) -> None:
        """Record a completed weight swap: the version gauge follows the
        checkpoint step when the source carries one (a Publisher's
        pinned generation), else a monotonic roll counter."""
        self.swaps += 1
        step = getattr(source, "step", None)
        self.weights_version = (float(step) if step is not None
                                else float(self.swaps))

    def status(self) -> dict:
        """One row of the ``tenants`` block on ``/fleet/status``."""
        snap = self.snapshot()
        counters = snap.get("counters") or {}
        slo_status = (self.slo_tracker.status(snap)
                      if self.slo_tracker is not None else None)
        max_burn = 0.0
        if slo_status is not None:
            for obj in slo_status["objectives"].values():
                for win in obj["burn"].values():
                    max_burn = max(max_burn, win["burn_rate"])
        return {
            "tenant": self.name,
            "engines": len(self.engines),
            "paused": self.paused,
            "queue_depth": self.batcher.depth,
            "max_pending": self.max_pending,
            "active": self.active(),
            "pages_in_use": self.pages_in_use(),
            "weights_version": self.weights_version,
            "completed": int(counters.get("completed", 0)),
            "failed": int(counters.get("failed", 0)
                          + counters.get("bad_requests", 0)
                          + counters.get("timeouts", 0)),
            "slo": slo_status,
            "slo_max_burn": round(max_burn, 4),
            "slo_alerting": bool(slo_status and slo_status["alerting"]),
        }


class ModelRegistry:
    """Name -> :class:`Tenant` map — the routing table behind the
    request's ``model``/``tenant`` field. The first registered tenant
    is the default (requests without a model field); an unknown name is
    a typed :class:`ModelNotFoundError`, by contract never a fallback."""

    def __init__(self):
        self._tenants: "OrderedDict[str, Tenant]" = OrderedDict()

    def register(self, name: str, engines=None, *,
                 tenant: Optional[Tenant] = None, **kwargs) -> Tenant:
        """Add a tenant: either a prebuilt :class:`Tenant` or engines +
        Tenant kwargs. Duplicate names are an error — re-registering a
        live tenant would strand its queue."""
        if name in self._tenants:
            raise ValueError(f"tenant {name!r} already registered")
        if tenant is None:
            if engines is None:
                raise ValueError("register() needs engines or tenant=")
            tenant = Tenant(name, engines, **kwargs)
        elif tenant.name != name:
            raise ValueError(f"tenant name mismatch: {tenant.name!r} "
                             f"registered as {name!r}")
        self._tenants[name] = tenant
        return tenant

    def get(self, name: str) -> Tenant:
        t = self._tenants.get(name)
        if t is None:
            raise ModelNotFoundError(
                f"unknown model/tenant {name!r}: this replica serves "
                f"{sorted(self._tenants)}")
        return t

    def resolve(self, name: Optional[str]) -> Tenant:
        """The admission-path lookup: None selects the default tenant,
        anything else must match exactly."""
        if name is None:
            return self.default
        return self.get(name)

    @property
    def default(self) -> Tenant:
        if not self._tenants:
            raise ValueError("empty registry has no default tenant")
        return next(iter(self._tenants.values()))

    def names(self) -> tuple:
        return tuple(self._tenants)

    def __iter__(self):
        return iter(self._tenants.values())

    def __len__(self) -> int:
        return len(self._tenants)

    def __contains__(self, name) -> bool:
        return name in self._tenants


class MultiTenantServer(Server):
    """One dispatch loop, N resident models, one ``/v1`` surface.

    Requests route on their ``model``/``tenant`` field into the named
    tenant's own queue (quota, typed backpressure) and engines; the
    shared dispatch thread round-robins every (engine, tenant-queue)
    pair, so tenants share compute fairly but never share a queue.
    ``swap_params(tenant=...)`` is the tenant-scoped rolling update:
    only that tenant drains — the others serve through the roll.

    The server's own registry carries the cross-tenant labeled gauges
    (``tenant_queue_depth{tenant=...}``, ``weights_version{tenant=...}``,
    per-tenant SLO burn rates); each tenant's engine registry stays its
    private single-tenant view.
    """

    def __init__(self, registry: ModelRegistry, *,
                 metrics: Optional[MetricsRegistry] = None,
                 serve_retry=None, warmup=False, slo=None):
        if len(registry) == 0:
            raise ValueError("a MultiTenantServer needs >= 1 tenant")
        engines = [eng for t in registry for eng in t.engines]
        super().__init__(
            engines, batcher=registry.default.batcher,
            metrics=metrics or MetricsRegistry(),
            serve_retry=serve_retry, warmup=warmup, slo=slo,
            model_ids=registry.names())
        self.registry = registry

    # -- dispatch plumbing -------------------------------------------------
    def _batchers(self):
        return [t.batcher for t in self.registry]

    def _dispatch_pairs(self):
        return [(eng, t.batcher)
                for t in self.registry for eng in t.engines]

    # -- admission ---------------------------------------------------------
    def submit(self, payload, timeout_ms: Optional[float] = None,
               **meta) -> Future:
        """Route into the named tenant's queue. ``meta['model']`` (the
        ``model``/``tenant`` request field) picks the tenant; absent
        means the default tenant. Unknown ids raise ModelNotFoundError
        (404) — and a tenant mid-roll answers like a draining replica
        (EngineClosedError), which the fleet retries elsewhere."""
        if self._paused:
            raise EngineClosedError(
                "server is draining (paused for a rolling update); "
                "route to another replica")
        model = meta.pop("model", None)
        try:
            tenant = self.registry.resolve(model)
        except ModelNotFoundError:
            self.metrics.inc("model_not_found")
            raise
        if tenant.paused:
            raise EngineClosedError(
                f"tenant {tenant.name!r} is draining for a rolling "
                "update on this replica; route to another replica")
        fut = tenant.batcher.submit(payload, timeout_ms=timeout_ms,
                                    **meta)
        # impressions carry the RESOLVED tenant name (default routing
        # included), so the joined examples are per-model attributable
        return self._feedback_tap(fut, payload, tenant.name)

    # -- tenant-scoped rolling updates -------------------------------------
    def pause_tenant(self, name: str, wait: bool = True,
                     timeout: float = 30.0) -> Tenant:
        """Drain ONE tenant: its submits start failing retryable, its
        queue and engines run dry; every other tenant keeps serving on
        the same dispatch thread. The safe point for a tenant-scoped
        ``swap_params``."""
        tenant = self.registry.get(name)
        tenant.paused = True
        if wait:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if tenant.batcher.depth == 0 and tenant.active() == 0:
                    break
                time.sleep(0.005)
        return tenant

    def resume_tenant(self, name: str) -> None:
        self.registry.get(name).paused = False

    def swap_params(self, source, *, strict: bool = True,
                    tenant: Optional[str] = None) -> dict:
        """Hot-swap params. With ``tenant=`` this is the whole
        tenant-scoped roll — drain that tenant, swap its engines, note
        the new generation, resume — while other tenants serve
        uninterrupted (their queues never pause, their compiled
        programs and KV pages are untouched). Without ``tenant`` every
        engine swaps; the caller owns the whole-server drain, exactly
        like the base class."""
        if tenant is None:
            stats = super().swap_params(source, strict=strict)
            for t in self.registry:
                t.note_swap(source)
            return stats
        t = self.pause_tenant(tenant)
        try:
            stats: Dict[str, int] = {}
            for eng in t.engines:
                for k, v in eng.swap_params(source,
                                            strict=strict).items():
                    stats[k] = stats.get(k, 0) + v
            t.note_swap(source)
            self.metrics.inc("tenant_swaps")
        finally:
            self.resume_tenant(tenant)
        return stats

    # -- observability -----------------------------------------------------
    def publish_tenant_gauges(self) -> None:
        """Export every tenant's plane as labeled series on the shared
        registry: queue/active/pages/weights gauges plus — when the
        tenant declares an SLO — its full burn-rate plane
        (``slo_burn_rate{objective=...,tenant=...,window=...}``)."""
        for t in self.registry:
            self.metrics.set_labeled("tenant_queue_depth",
                                     t.batcher.depth, tenant=t.name)
            self.metrics.set_labeled("tenant_active_slots", t.active(),
                                     tenant=t.name)
            self.metrics.set_labeled("tenant_kv_pages_in_use",
                                     t.pages_in_use(), tenant=t.name)
            self.metrics.set_labeled("weights_version",
                                     t.weights_version, tenant=t.name)
            if t.slo_tracker is not None:
                t.slo_tracker.publish_gauges(
                    self.metrics,
                    t.slo_tracker.status(t.snapshot()),
                    tenant=t.name)

    def tenant_status(self) -> List[dict]:
        """The ``tenants`` block of ``/fleet/status`` (and the rows of
        ``fleetctl status``'s TENANTS table)."""
        self.publish_tenant_gauges()
        return [t.status() for t in self.registry]

    def metrics_snapshot(self) -> dict:
        self.publish_tenant_gauges()
        snap = super().metrics_snapshot()
        snap["tenants"] = [t.status() for t in self.registry]
        return snap

    def metrics_prometheus(self) -> str:
        self.publish_tenant_gauges()
        return super().metrics_prometheus()

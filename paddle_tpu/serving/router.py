"""Routing policies + per-replica circuit breakers for the serving fleet.

The reference's Go master routes work around dead pservers by lease
expiry; a serving fleet needs the request-path analogue: a
:class:`Router` that picks a replica per attempt (round-robin, least
loaded, or session-affine) and a :class:`CircuitBreaker` per replica
that converts an outcome stream into an availability decision:

    closed ──consecutive failures / error rate──► open
    open ──recovery timer + /healthz probe──► half_open
    half_open ──probe success──► closed   (probe failure ──► open)

The breaker is driven from BOTH ends: request outcomes
(``record_success``/``record_failure``) and the replica's ``/healthz``
(a not-ready probe keeps an open breaker open without burning a real
request). Every state transition emits a ``fleet/breaker`` trace record
and a labeled gauge, so Prometheus shows exactly when each replica
tripped and recovered.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from .. import trace

#: breaker state -> the value exported as the labeled Prometheus gauge
BREAKER_GAUGE = {"closed": 0.0, "open": 1.0, "half_open": 2.0}


class CircuitBreaker:
    """Availability state machine for one replica.

    failure_threshold:  consecutive failures that trip closed -> open.
    error_rate:         alternative trip: failure fraction over the last
                        ``window`` outcomes (needs >= ``min_outcomes``).
    recovery_s:         open -> half-open probe eligibility delay.
    on_transition:      ``fn(old_state, new_state, reason)`` hook (the
                        router wires metrics + trace through it).
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold: int = 3,
                 error_rate: float = 0.5, window: int = 20,
                 min_outcomes: int = 10, recovery_s: float = 0.25,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[Callable] = None):
        self.failure_threshold = int(failure_threshold)
        self.error_rate = float(error_rate)
        self.min_outcomes = int(min_outcomes)
        self.recovery_s = float(recovery_s)
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive = 0
        self._outcomes: deque = deque(maxlen=int(window))
        self._opened_at = 0.0
        self._probe_inflight = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition(self, new: str, reason: str) -> None:
        old, self._state = self._state, new
        if new == self.OPEN:
            self._opened_at = self._clock()
            self._probe_inflight = False
        if old != new and self._on_transition is not None:
            self._on_transition(old, new, reason)

    # -- request path ------------------------------------------------------
    def allow(self) -> bool:
        """May a request be sent to this replica right now? In half-open
        exactly ONE in-flight probe is allowed; in open, the recovery
        timer promotes to half-open (the caller should then healthz-gate
        the probe via :meth:`probe_eligible`)."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._clock() - self._opened_at < self.recovery_s:
                    return False
                self._transition(self.HALF_OPEN, "recovery timer")
            # half-open: one probe at a time
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def probe_eligible(self) -> bool:
        """True when the breaker is open and the recovery delay has
        elapsed — the moment a /healthz check is worth making."""
        with self._lock:
            return (self._state == self.OPEN
                    and self._clock() - self._opened_at >= self.recovery_s)

    # -- outcome stream ----------------------------------------------------
    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._outcomes.append(True)
            self._probe_inflight = False
            if self._state != self.CLOSED:
                self._transition(self.CLOSED, "probe success")

    def record_failure(self, reason: str = "error") -> None:
        with self._lock:
            self._consecutive += 1
            self._outcomes.append(False)
            self._probe_inflight = False
            if self._state == self.HALF_OPEN:
                self._transition(self.OPEN, f"probe failed: {reason}")
                return
            if self._state != self.CLOSED:
                return
            n = len(self._outcomes)
            failures = sum(1 for ok in self._outcomes if not ok)
            if self._consecutive >= self.failure_threshold:
                self._transition(
                    self.OPEN, f"{self._consecutive} consecutive failures")
            elif n >= self.min_outcomes \
                    and failures / n > self.error_rate:
                self._transition(
                    self.OPEN, f"error rate {failures}/{n}")

    def release_probe(self) -> None:
        """An attempt admitted as the half-open probe was ABANDONED
        without an outcome (hedge loser, deadline expiry): free the
        probe slot so the breaker doesn't wedge waiting for a verdict
        that will never arrive."""
        with self._lock:
            self._probe_inflight = False

    def force_open(self, reason: str = "healthz") -> None:
        """Trip the breaker from the health prober (a dead /healthz must
        stop traffic without burning ``failure_threshold`` requests)."""
        with self._lock:
            if self._state != self.OPEN:
                self._transition(self.OPEN, reason)
            else:
                self._opened_at = self._clock()  # restart recovery timer

    def seconds_until_probe(self) -> float:
        with self._lock:
            if self._state != self.OPEN:
                return 0.0
            return max(0.0, self.recovery_s
                       - (self._clock() - self._opened_at))


# ---------------------------------------------------------------------------
# pick policies
# ---------------------------------------------------------------------------
class RoundRobinPolicy:
    """Rotate through the candidates — the baseline fair spread."""

    def __init__(self):
        self._i = 0
        self._lock = threading.Lock()

    def pick(self, candidates: Sequence, meta: dict):
        with self._lock:
            self._i += 1
            return candidates[(self._i - 1) % len(candidates)]


class LeastLoadedPolicy:
    """Pick the candidate with the fewest in-flight requests (ties break
    round-robin) — absorbs heterogeneous replicas better than rotation."""

    def __init__(self):
        self._rr = RoundRobinPolicy()

    def pick(self, candidates: Sequence, meta: dict):
        loads = [getattr(c, "inflight", 0) for c in candidates]
        low = min(loads)
        best = [c for c, l in zip(candidates, loads) if l == low]
        return self._rr.pick(best, meta)


class SessionAffinityPolicy:
    """Hash ``meta["session"]`` to a stable preferred replica (KV-cache /
    prefix locality); sessions fall back to ``base`` when their preferred
    replica is not a candidate (drained, crashed, breaker-open) — and so
    do requests without a session."""

    def __init__(self, base=None):
        self.base = base or LeastLoadedPolicy()

    def pick(self, candidates: Sequence, meta: dict):
        session = (meta or {}).get("session")
        if session is not None:
            # stable across processes (hash() is salted): FNV-1a
            h = 2166136261
            for byte in str(session).encode():
                h = ((h ^ byte) * 16777619) & 0xFFFFFFFF
            preferred = [c for c in candidates
                         if getattr(c, "index", 0) == h % max(
                             1, getattr(c, "fleet_size", len(candidates)))]
            if preferred:
                return preferred[0]
        return self.base.pick(candidates, meta)


class Router:
    """Replica picker + breaker bank for one fleet.

    ``route(meta, exclude)`` returns a replica that is routable (not
    draining/crashed) and whose breaker admits traffic, or None; the
    half-open probe is /healthz-gated — an open breaker whose recovery
    timer elapsed first asks the replica's healthz, and only a ready
    answer lets the probe request through.
    """

    def __init__(self, replicas: Sequence, policy=None,
                 breaker_kwargs: Optional[dict] = None, metrics=None):
        self.replicas = list(replicas)
        self.policy = policy or LeastLoadedPolicy()
        self.metrics = metrics
        self.breakers: Dict[str, CircuitBreaker] = {}
        for r in self.replicas:
            self.breakers[r.name] = CircuitBreaker(
                on_transition=self._transition_hook(r.name),
                **(breaker_kwargs or {}))

    def _transition_hook(self, name: str):
        def hook(old: str, new: str, reason: str) -> None:
            now = time.perf_counter()
            trace.record("fleet/breaker", now, now, replica=name,
                         from_state=old, to_state=new, reason=reason)
            # breaker trips are exactly the events a 3am flight bundle
            # needs — record them even when span tracing is off
            trace.get_recorder().note("breaker", replica=name,
                                      from_state=old, to_state=new,
                                      reason=reason)
            if self.metrics is not None:
                if new == CircuitBreaker.OPEN:
                    self.metrics.inc("breaker_opens")
                elif new == CircuitBreaker.CLOSED and old != new:
                    self.metrics.inc("breaker_closes")
                self.metrics.set_labeled("fleet_breaker_state",
                                         BREAKER_GAUGE[new], replica=name)
        return hook

    # ------------------------------------------------------------------
    def route(self, meta: Optional[dict] = None,
              exclude: Sequence[str] = ()):
        """Pick a replica for one attempt. ``exclude`` lists replica
        names already tried for this request (retries go to a DIFFERENT
        replica)."""
        exclude = set(exclude)
        candidates = []
        for r in self.replicas:
            if r.name in exclude or not r.routable:
                continue
            br = self.breakers[r.name]
            if br.state == CircuitBreaker.CLOSED:
                candidates.append(r)
                continue
            # open/half-open: /healthz-gated probe admission
            if br.probe_eligible():
                health = r.healthz()
                if health.get("state") != "ready":
                    br.force_open("healthz not ready")
                    continue
            if br.allow():
                return r  # the probe request — route it immediately
        if not candidates:
            return None
        return self.policy.pick(candidates, meta or {})

    def record(self, replica, ok: bool, reason: str = "error") -> None:
        br = self.breakers[replica.name]
        if ok:
            br.record_success()
        else:
            br.record_failure(reason)

    def release(self, replica) -> None:
        """Abandoned attempt (no outcome): free a possible probe slot."""
        self.breakers[replica.name].release_probe()

    def quarantine(self, replica, reason: str = "quarantine") -> None:
        """Trip the replica's breaker open NOW, without waiting for
        ``failure_threshold`` outcomes. A connection that died
        MID-STREAM (tokens already emitted, then reset) is a far
        stronger death signal than one refused connect — the fleet's
        recovery path uses this so resumed re-admissions never route
        back to the replica that just dropped them."""
        self.breakers[replica.name].force_open(reason)

    def any_routable(self) -> bool:
        """At least one replica could accept traffic now (or is due a
        probe) — False means admission should shed before queueing."""
        return any(
            r.routable and (self.breakers[r.name].state
                            != CircuitBreaker.OPEN
                            or self.breakers[r.name].probe_eligible())
            for r in self.replicas)

    def min_recovery_s(self) -> float:
        """Soonest any open breaker becomes probe-eligible — the
        Retry-After hint for shed responses."""
        waits = [self.breakers[r.name].seconds_until_probe()
                 for r in self.replicas]
        return min(waits) if waits else 1.0

    def breaker_states(self) -> Dict[str, str]:
        return {name: br.state for name, br in self.breakers.items()}

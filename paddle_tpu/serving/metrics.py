"""Serving metrics registry: counters, gauges, latency quantiles.

One process-local registry per Server/engine (no global singleton — tests
and multi-engine processes keep their numbers separate). Everything is
exported as a plain dict snapshot (JSON-safe: the HTTP front end serves it
verbatim at /metrics) and can be published into :mod:`paddle_tpu.profiler`'s
StatSet plane so ``print_all_status`` shows serving timers next to the
training timers.
"""
from __future__ import annotations

import bisect
import threading
import time
from collections import defaultdict, deque
from typing import Dict, List, Optional, Sequence

# Latency reservoir size: enough for stable p99 at demo scale without
# unbounded growth under sustained traffic (oldest samples fall off).
_RESERVOIR = 4096
# Sliding window for the QPS gauge.
_QPS_WINDOW_S = 10.0

#: Fixed log-spaced histogram bucket bounds (seconds): 100 µs .. 100 s,
#: four buckets per decade (upper/lower ratio ~1.78, so any quantile read
#: from the buckets is within ~33% of the true value). FIXED and shared
#: by every registry on purpose: cross-replica aggregation then SUMS
#: bucket counts, which — unlike merging per-replica quantile summaries —
#: is mathematically exact, so fleet-level P99s are correct.
HIST_BUCKET_BOUNDS: List[float] = [
    round(1e-4 * 10 ** (k / 4.0), 10) for k in range(25)]


def _quantile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def hist_quantile(counts: Sequence[int], q: float,
                  bounds: Sequence[float] = None) -> float:
    """Quantile (seconds) from per-bucket counts, linearly interpolated
    inside the owning bucket. ``counts`` has ``len(bounds) + 1`` entries
    (the last is the overflow bucket, read as its lower bound)."""
    bounds = HIST_BUCKET_BOUNDS if bounds is None else bounds
    total = sum(counts)
    if total <= 0:
        return 0.0
    target = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if cum + c >= target:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i] if i < len(bounds) else bounds[-1]
            frac = min(1.0, max(0.0, (target - cum) / c))
            return lo + frac * (hi - lo)
        cum += c
    return bounds[-1]


class MetricsRegistry:
    """Thread-safe counters/gauges/latency-histograms for one server."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = defaultdict(int)
        self._gauges: Dict[str, float] = {}
        self._latencies: Dict[str, deque] = defaultdict(
            lambda: deque(maxlen=_RESERVOIR))
        # cumulative fixed-bucket histograms (HIST_BUCKET_BOUNDS + one
        # overflow bucket): never truncated, mergeable by summation —
        # the fleet-correct twin of the bounded quantile reservoirs
        self._hist: Dict[str, List[int]] = {}
        self._hist_sum: Dict[str, float] = defaultdict(float)
        # total observations ever pushed per reservoir (reservoirs drop
        # old samples; this never decreases) + the publish high-water
        # mark, so publish_to_profiler is incremental across calls.
        self._observed: Dict[str, int] = defaultdict(int)
        self._published: Dict[str, int] = defaultdict(int)
        self._completions = deque()  # timestamps for the QPS window
        # labeled gauge series: name -> {(("k","v"),...) -> value}. The
        # fleet plane's per-replica health/breaker/inflight live here and
        # export as proper labeled Prometheus series.
        self._labeled: Dict[str, Dict[tuple, float]] = defaultdict(dict)
        self._t0 = time.monotonic()

    # -- write side --------------------------------------------------------
    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] += n

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def set_labeled(self, name: str, value: float, **labels) -> None:
        """Set one sample of a labeled gauge series, e.g.
        ``set_labeled("fleet_replica_health", 1, replica="r0")``."""
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            self._labeled[name][key] = float(value)

    def _hist_observe_locked(self, name: str, seconds: float) -> None:
        counts = self._hist.get(name)
        if counts is None:
            counts = self._hist[name] = [0] * (len(HIST_BUCKET_BOUNDS) + 1)
        counts[bisect.bisect_left(HIST_BUCKET_BOUNDS, seconds)] += 1
        self._hist_sum[name] += seconds

    def observe_hist(self, name: str, seconds: float) -> None:
        """Observe one duration into the fixed-bucket histogram plane
        (TTFT / TPOT / queue-wait land here without joining the
        ``request`` QPS window)."""
        with self._lock:
            self._hist_observe_locked(name, float(seconds))

    def observe_latency(self, seconds: float, name: str = "request") -> None:
        now = time.monotonic()
        with self._lock:
            self._latencies[name].append(float(seconds))
            self._observed[name] += 1
            self._hist_observe_locked(name, float(seconds))
            if name == "request":
                self._completions.append(now)
                cutoff = now - _QPS_WINDOW_S
                while self._completions and self._completions[0] < cutoff:
                    self._completions.popleft()

    # -- read side ---------------------------------------------------------
    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> dict:
        """Plain-dict export: counters, gauges, per-name latency quantiles
        (ms), windowed QPS, uptime. JSON-serializable by construction."""
        now = time.monotonic()
        with self._lock:
            lat = {}
            for name, buf in self._latencies.items():
                vals = sorted(buf)
                lat[name + "_ms"] = {
                    "count": len(vals),
                    "mean": (sum(vals) / len(vals) * 1e3) if vals else 0.0,
                    "p50": _quantile(vals, 0.50) * 1e3,
                    "p95": _quantile(vals, 0.95) * 1e3,
                    "p99": _quantile(vals, 0.99) * 1e3,
                }
            hist = {}
            for name, counts in self._hist.items():
                n = sum(counts)
                hist[name] = {
                    "bounds_ms": [round(b * 1e3, 6)
                                  for b in HIST_BUCKET_BOUNDS],
                    "counts": list(counts),
                    "count": n,
                    "sum_ms": round(self._hist_sum[name] * 1e3, 6),
                    "p50_ms": round(hist_quantile(counts, 0.50) * 1e3, 6),
                    "p95_ms": round(hist_quantile(counts, 0.95) * 1e3, 6),
                    "p99_ms": round(hist_quantile(counts, 0.99) * 1e3, 6),
                }
            cutoff = now - _QPS_WINDOW_S
            qps_n = sum(1 for t in self._completions if t >= cutoff)
            labeled = {name: {"{" + ",".join(f'{k}="{v}"'
                                             for k, v in key) + "}": val
                              for key, val in series.items()}
                       for name, series in self._labeled.items()}
            snap = {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "latency": lat,
                "hist": hist,
                "qps": qps_n / min(max(now - self._t0, 1e-9), _QPS_WINDOW_S),
                "uptime_s": now - self._t0,
            }
            if labeled:
                snap["labeled"] = labeled
            return snap

    @staticmethod
    def merge(snapshots: Dict[str, dict]) -> dict:
        """Fleet-level aggregation over per-replica :meth:`snapshot`
        payloads (keyed by replica name): counters sum, histogram BUCKET
        COUNTS sum (quantiles are then re-derived from the merged
        buckets — the only statistically correct way to get a fleet P99;
        averaging or overwriting per-replica quantile summaries is
        provably wrong for replicas with different latency
        distributions), gauges and per-replica latency summaries keep a
        ``<replica>/<name>`` key, qps sums. The result has the same
        shape as :meth:`snapshot`, so it nests into the fleet /metrics
        body verbatim."""
        counters: Dict[str, int] = defaultdict(int)
        gauges: Dict[str, float] = {}
        latency: Dict[str, dict] = {}
        hists: Dict[str, dict] = {}
        qps = 0.0
        uptime = 0.0
        for rname, snap in sorted(snapshots.items()):
            if not isinstance(snap, dict):
                continue
            for k, v in (snap.get("counters") or {}).items():
                counters[k] += int(v)
            for k, v in (snap.get("gauges") or {}).items():
                gauges[f"{rname}/{k}"] = v
            for k, v in (snap.get("latency") or {}).items():
                latency[f"{rname}/{k}"] = v
            for k, h in (snap.get("hist") or {}).items():
                if not isinstance(h, dict) or "counts" not in h:
                    continue
                agg = hists.get(k)
                if agg is None:
                    hists[k] = {"bounds_ms": list(h.get("bounds_ms") or []),
                                "counts": list(h["counts"]),
                                "sum_ms": float(h.get("sum_ms") or 0.0)}
                elif len(agg["counts"]) == len(h["counts"]) \
                        and agg["bounds_ms"] == (h.get("bounds_ms") or []):
                    agg["counts"] = [a + int(b) for a, b in
                                     zip(agg["counts"], h["counts"])]
                    agg["sum_ms"] += float(h.get("sum_ms") or 0.0)
                else:  # incompatible bounds: keep it per-replica
                    hists[f"{rname}/{k}"] = dict(h)
            qps += float(snap.get("qps") or 0.0)
            uptime = max(uptime, float(snap.get("uptime_s") or 0.0))
        for k, h in hists.items():
            counts = h["counts"]
            bounds = [b / 1e3 for b in h["bounds_ms"]] or None
            h["count"] = sum(counts)
            h["sum_ms"] = round(h["sum_ms"], 6)
            for q, key in ((0.50, "p50_ms"), (0.95, "p95_ms"),
                           (0.99, "p99_ms")):
                h[key] = round(
                    hist_quantile(counts, q, bounds=bounds) * 1e3, 6)
        return {"counters": dict(counters), "gauges": gauges,
                "latency": latency, "hist": hists, "qps": qps,
                "uptime_s": uptime, "replicas": sorted(snapshots.keys())}

    def publish_to_profiler(self, stat_set=None, prefix: str = "serving/"):
        """Push the latency reservoirs into a profiler StatSet (the global
        one by default) so serving quantile sources show up in
        ``profiler.print_all_status`` alongside training timers.

        Incremental: a per-reservoir high-water mark tracks how many
        observations have already been published, so repeated calls (a
        periodic dump loop) add only the NEW samples instead of
        re-pushing the whole reservoir. Samples that aged out of the
        bounded reservoir before a publish are counted but gone — the
        StatSet receives what is still buffered."""
        from .. import profiler

        target = stat_set or profiler.global_stat
        with self._lock:
            items = []
            for name, buf in self._latencies.items():
                new = self._observed[name] - self._published[name]
                if new <= 0:
                    continue
                # the reservoir holds the most recent len(buf) samples;
                # anything beyond that aged out unpublished
                fresh = list(buf)[-min(new, len(buf)):]
                items.append((name, fresh))
                self._published[name] = self._observed[name]
        for name, vals in items:
            for v in vals:
                target.add(prefix + name, v)
        return target

    def update_device_gauges(self) -> None:
        """Refresh the device-memory gauge plane: the legacy flat
        ``mem/device<N>_*`` gauges plus a PROPERLY LABELED
        ``device_memory_bytes{device=...,stat=...}`` series, so sharded
        runs show per-device HBM in ``/metrics?format=prom`` — the
        serving-side twin of ``analyze_memory(plan=...)``'s static
        per-device estimate. No-op on backends reporting nothing."""
        from ..trace import device_memory_stats, per_device_memory_stats

        for name, value in device_memory_stats().items():
            self.set_gauge("mem/" + name, value)
        for dev, stats in per_device_memory_stats().items():
            for stat, value in stats.items():
                self.set_labeled("device_memory_bytes", value,
                                 device=dev, stat=stat)

    def merge_timer_dict(self, timers: Optional[dict]) -> dict:
        """snapshot() + a profiler StatSet.as_dict() payload in one dict
        (the /metrics endpoint body)."""
        snap = self.snapshot()
        if timers:
            snap["timers"] = timers
        return snap

    # -- Prometheus exposition --------------------------------------------
    def prometheus_text(self, timers: Optional[dict] = None,
                        namespace: str = "paddle_tpu") -> str:
        """Render the registry in Prometheus text exposition format
        (v0.0.4): counters as ``<ns>_<name>_total``, gauges as
        ``<ns>_<name>``, latency reservoirs as summaries with
        p50/p95/p99 quantile samples, plus qps/uptime. ``timers`` (a
        StatSet.as_dict payload) export as ``<ns>_timer_seconds`` sum/
        count pairs labelled by timer name."""
        snap = self.snapshot()
        lines = []

        def emit(name, kind, samples, help_str=""):
            if help_str:
                lines.append(f"# HELP {name} {help_str}")
            lines.append(f"# TYPE {name} {kind}")
            for labels, value in samples:
                lines.append(f"{name}{labels} {_prom_num(value)}")

        for cname in sorted(snap["counters"]):
            emit(f"{namespace}_{_prom_name(cname)}_total", "counter",
                 [("", snap["counters"][cname])])
        for gname in sorted(snap["gauges"]):
            emit(f"{namespace}_{_prom_name(gname)}", "gauge",
                 [("", snap["gauges"][gname])])
        for lname in sorted(snap.get("labeled", {})):
            series = snap["labeled"][lname]
            emit(f"{namespace}_{_prom_name(lname)}", "gauge",
                 [(labels, series[labels]) for labels in sorted(series)])
        for lname in sorted(snap["latency"]):
            base = _prom_name(lname[:-3] if lname.endswith("_ms")
                              else lname)
            d = snap["latency"][lname]
            metric = f"{namespace}_{base}_latency_seconds"
            emit(metric, "summary", [
                ('{quantile="0.5"}', d["p50"] / 1e3),
                ('{quantile="0.95"}', d["p95"] / 1e3),
                ('{quantile="0.99"}', d["p99"] / 1e3),
            ], help_str=f"{lname} latency quantiles over the reservoir")
            lines.append(f"{metric}_sum "
                         f"{_prom_num(d['mean'] / 1e3 * d['count'])}")
            lines.append(f"{metric}_count {d['count']}")
        for hname in sorted(snap.get("hist", {})):
            h = snap["hist"][hname]
            metric = f"{namespace}_{_prom_name(hname)}_seconds"
            lines.append(f"# HELP {metric} {hname} fixed log-spaced "
                         "bucket histogram (cumulative, mergeable)")
            lines.append(f"# TYPE {metric} histogram")
            cum = 0
            for bound_ms, c in zip(h.get("bounds_ms", []), h["counts"]):
                cum += c
                lines.append(f'{metric}_bucket{{le="'
                             f'{_prom_num(bound_ms / 1e3)}"}} {cum}')
            lines.append(f'{metric}_bucket{{le="+Inf"}} {h["count"]}')
            lines.append(f"{metric}_sum {_prom_num(h['sum_ms'] / 1e3)}")
            lines.append(f"{metric}_count {h['count']}")
        emit(f"{namespace}_qps", "gauge", [("", snap["qps"])],
             help_str="completions per second (sliding window)")
        emit(f"{namespace}_uptime_seconds", "gauge",
             [("", snap["uptime_s"])])
        if timers:
            metric = f"{namespace}_timer_seconds"
            lines.append(f"# TYPE {metric} summary")
            for tname in sorted(timers):
                d = timers[tname]
                label = _prom_label(tname)
                lines.append(f'{metric}_sum{{name="{label}"}} '
                             f"{_prom_num(d['total_ms'] / 1e3)}")
                lines.append(f'{metric}_count{{name="{label}"}} '
                             f"{d['calls']}")
        return "\n".join(lines) + "\n"


def _prom_name(name: str) -> str:
    """Sanitize to a legal Prometheus metric-name fragment."""
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    return ("_" + out) if out and out[0].isdigit() else out


def _prom_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def _prom_num(v) -> str:
    f = float(v)
    return repr(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)

"""Serving metrics registry: counters, gauges, latency quantiles.

One process-local registry per Server/engine (no global singleton — tests
and multi-engine processes keep their numbers separate). Everything is
exported as a plain dict snapshot (JSON-safe: the HTTP front end serves it
verbatim at /metrics) and can be published into :mod:`paddle_tpu.profiler`'s
StatSet plane so ``print_all_status`` shows serving timers next to the
training timers.
"""
from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from typing import Dict, Optional

# Latency reservoir size: enough for stable p99 at demo scale without
# unbounded growth under sustained traffic (oldest samples fall off).
_RESERVOIR = 4096
# Sliding window for the QPS gauge.
_QPS_WINDOW_S = 10.0


def _quantile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


class MetricsRegistry:
    """Thread-safe counters/gauges/latency-histograms for one server."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = defaultdict(int)
        self._gauges: Dict[str, float] = {}
        self._latencies: Dict[str, deque] = defaultdict(
            lambda: deque(maxlen=_RESERVOIR))
        # total observations ever pushed per reservoir (reservoirs drop
        # old samples; this never decreases) + the publish high-water
        # mark, so publish_to_profiler is incremental across calls.
        self._observed: Dict[str, int] = defaultdict(int)
        self._published: Dict[str, int] = defaultdict(int)
        self._completions = deque()  # timestamps for the QPS window
        # labeled gauge series: name -> {(("k","v"),...) -> value}. The
        # fleet plane's per-replica health/breaker/inflight live here and
        # export as proper labeled Prometheus series.
        self._labeled: Dict[str, Dict[tuple, float]] = defaultdict(dict)
        self._t0 = time.monotonic()

    # -- write side --------------------------------------------------------
    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] += n

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def set_labeled(self, name: str, value: float, **labels) -> None:
        """Set one sample of a labeled gauge series, e.g.
        ``set_labeled("fleet_replica_health", 1, replica="r0")``."""
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            self._labeled[name][key] = float(value)

    def observe_latency(self, seconds: float, name: str = "request") -> None:
        now = time.monotonic()
        with self._lock:
            self._latencies[name].append(float(seconds))
            self._observed[name] += 1
            if name == "request":
                self._completions.append(now)
                cutoff = now - _QPS_WINDOW_S
                while self._completions and self._completions[0] < cutoff:
                    self._completions.popleft()

    # -- read side ---------------------------------------------------------
    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> dict:
        """Plain-dict export: counters, gauges, per-name latency quantiles
        (ms), windowed QPS, uptime. JSON-serializable by construction."""
        now = time.monotonic()
        with self._lock:
            lat = {}
            for name, buf in self._latencies.items():
                vals = sorted(buf)
                lat[name + "_ms"] = {
                    "count": len(vals),
                    "mean": (sum(vals) / len(vals) * 1e3) if vals else 0.0,
                    "p50": _quantile(vals, 0.50) * 1e3,
                    "p95": _quantile(vals, 0.95) * 1e3,
                    "p99": _quantile(vals, 0.99) * 1e3,
                }
            cutoff = now - _QPS_WINDOW_S
            qps_n = sum(1 for t in self._completions if t >= cutoff)
            labeled = {name: {"{" + ",".join(f'{k}="{v}"'
                                             for k, v in key) + "}": val
                              for key, val in series.items()}
                       for name, series in self._labeled.items()}
            snap = {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "latency": lat,
                "qps": qps_n / min(max(now - self._t0, 1e-9), _QPS_WINDOW_S),
                "uptime_s": now - self._t0,
            }
            if labeled:
                snap["labeled"] = labeled
            return snap

    @staticmethod
    def merge(snapshots: Dict[str, dict]) -> dict:
        """Fleet-level aggregation over per-replica :meth:`snapshot`
        payloads (keyed by replica name): counters sum, gauges and
        latency quantiles keep a per-replica ``<replica>/<name>`` key
        (quantiles cannot be merged exactly from summaries), qps sums.
        The result has the same shape as :meth:`snapshot`, so it nests
        into the fleet /metrics body verbatim."""
        counters: Dict[str, int] = defaultdict(int)
        gauges: Dict[str, float] = {}
        latency: Dict[str, dict] = {}
        qps = 0.0
        uptime = 0.0
        for rname, snap in sorted(snapshots.items()):
            if not isinstance(snap, dict):
                continue
            for k, v in (snap.get("counters") or {}).items():
                counters[k] += int(v)
            for k, v in (snap.get("gauges") or {}).items():
                gauges[f"{rname}/{k}"] = v
            for k, v in (snap.get("latency") or {}).items():
                latency[f"{rname}/{k}"] = v
            qps += float(snap.get("qps") or 0.0)
            uptime = max(uptime, float(snap.get("uptime_s") or 0.0))
        return {"counters": dict(counters), "gauges": gauges,
                "latency": latency, "qps": qps, "uptime_s": uptime,
                "replicas": sorted(snapshots.keys())}

    def publish_to_profiler(self, stat_set=None, prefix: str = "serving/"):
        """Push the latency reservoirs into a profiler StatSet (the global
        one by default) so serving quantile sources show up in
        ``profiler.print_all_status`` alongside training timers.

        Incremental: a per-reservoir high-water mark tracks how many
        observations have already been published, so repeated calls (a
        periodic dump loop) add only the NEW samples instead of
        re-pushing the whole reservoir. Samples that aged out of the
        bounded reservoir before a publish are counted but gone — the
        StatSet receives what is still buffered."""
        from .. import profiler

        target = stat_set or profiler.global_stat
        with self._lock:
            items = []
            for name, buf in self._latencies.items():
                new = self._observed[name] - self._published[name]
                if new <= 0:
                    continue
                # the reservoir holds the most recent len(buf) samples;
                # anything beyond that aged out unpublished
                fresh = list(buf)[-min(new, len(buf)):]
                items.append((name, fresh))
                self._published[name] = self._observed[name]
        for name, vals in items:
            for v in vals:
                target.add(prefix + name, v)
        return target

    def update_device_gauges(self) -> None:
        """Refresh the device-memory gauge plane (jax live-bytes per
        local device) — a no-op on backends without allocator stats."""
        from ..trace import device_memory_stats

        for name, value in device_memory_stats().items():
            self.set_gauge("mem/" + name, value)

    def merge_timer_dict(self, timers: Optional[dict]) -> dict:
        """snapshot() + a profiler StatSet.as_dict() payload in one dict
        (the /metrics endpoint body)."""
        snap = self.snapshot()
        if timers:
            snap["timers"] = timers
        return snap

    # -- Prometheus exposition --------------------------------------------
    def prometheus_text(self, timers: Optional[dict] = None,
                        namespace: str = "paddle_tpu") -> str:
        """Render the registry in Prometheus text exposition format
        (v0.0.4): counters as ``<ns>_<name>_total``, gauges as
        ``<ns>_<name>``, latency reservoirs as summaries with
        p50/p95/p99 quantile samples, plus qps/uptime. ``timers`` (a
        StatSet.as_dict payload) export as ``<ns>_timer_seconds`` sum/
        count pairs labelled by timer name."""
        snap = self.snapshot()
        lines = []

        def emit(name, kind, samples, help_str=""):
            if help_str:
                lines.append(f"# HELP {name} {help_str}")
            lines.append(f"# TYPE {name} {kind}")
            for labels, value in samples:
                lines.append(f"{name}{labels} {_prom_num(value)}")

        for cname in sorted(snap["counters"]):
            emit(f"{namespace}_{_prom_name(cname)}_total", "counter",
                 [("", snap["counters"][cname])])
        for gname in sorted(snap["gauges"]):
            emit(f"{namespace}_{_prom_name(gname)}", "gauge",
                 [("", snap["gauges"][gname])])
        for lname in sorted(snap.get("labeled", {})):
            series = snap["labeled"][lname]
            emit(f"{namespace}_{_prom_name(lname)}", "gauge",
                 [(labels, series[labels]) for labels in sorted(series)])
        for lname in sorted(snap["latency"]):
            base = _prom_name(lname[:-3] if lname.endswith("_ms")
                              else lname)
            d = snap["latency"][lname]
            metric = f"{namespace}_{base}_latency_seconds"
            emit(metric, "summary", [
                ('{quantile="0.5"}', d["p50"] / 1e3),
                ('{quantile="0.95"}', d["p95"] / 1e3),
                ('{quantile="0.99"}', d["p99"] / 1e3),
            ], help_str=f"{lname} latency quantiles over the reservoir")
            lines.append(f"{metric}_sum "
                         f"{_prom_num(d['mean'] / 1e3 * d['count'])}")
            lines.append(f"{metric}_count {d['count']}")
        emit(f"{namespace}_qps", "gauge", [("", snap["qps"])],
             help_str="completions per second (sliding window)")
        emit(f"{namespace}_uptime_seconds", "gauge",
             [("", snap["uptime_s"])])
        if timers:
            metric = f"{namespace}_timer_seconds"
            lines.append(f"# TYPE {metric} summary")
            for tname in sorted(timers):
                d = timers[tname]
                label = _prom_label(tname)
                lines.append(f'{metric}_sum{{name="{label}"}} '
                             f"{_prom_num(d['total_ms'] / 1e3)}")
                lines.append(f'{metric}_count{{name="{label}"}} '
                             f"{d['calls']}")
        return "\n".join(lines) + "\n"


def _prom_name(name: str) -> str:
    """Sanitize to a legal Prometheus metric-name fragment."""
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    return ("_" + out) if out and out[0].isdigit() else out


def _prom_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def _prom_num(v) -> str:
    f = float(v)
    return repr(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)

"""Serving metrics registry: counters, gauges, latency quantiles.

One process-local registry per Server/engine (no global singleton — tests
and multi-engine processes keep their numbers separate). Everything is
exported as a plain dict snapshot (JSON-safe: the HTTP front end serves it
verbatim at /metrics) and can be published into :mod:`paddle_tpu.profiler`'s
StatSet plane so ``print_all_status`` shows serving timers next to the
training timers.
"""
from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from typing import Dict, Optional

# Latency reservoir size: enough for stable p99 at demo scale without
# unbounded growth under sustained traffic (oldest samples fall off).
_RESERVOIR = 4096
# Sliding window for the QPS gauge.
_QPS_WINDOW_S = 10.0


def _quantile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


class MetricsRegistry:
    """Thread-safe counters/gauges/latency-histograms for one server."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = defaultdict(int)
        self._gauges: Dict[str, float] = {}
        self._latencies: Dict[str, deque] = defaultdict(
            lambda: deque(maxlen=_RESERVOIR))
        self._completions = deque()  # timestamps for the QPS window
        self._t0 = time.monotonic()

    # -- write side --------------------------------------------------------
    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] += n

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def observe_latency(self, seconds: float, name: str = "request") -> None:
        now = time.monotonic()
        with self._lock:
            self._latencies[name].append(float(seconds))
            if name == "request":
                self._completions.append(now)
                cutoff = now - _QPS_WINDOW_S
                while self._completions and self._completions[0] < cutoff:
                    self._completions.popleft()

    # -- read side ---------------------------------------------------------
    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> dict:
        """Plain-dict export: counters, gauges, per-name latency quantiles
        (ms), windowed QPS, uptime. JSON-serializable by construction."""
        now = time.monotonic()
        with self._lock:
            lat = {}
            for name, buf in self._latencies.items():
                vals = sorted(buf)
                lat[name + "_ms"] = {
                    "count": len(vals),
                    "mean": (sum(vals) / len(vals) * 1e3) if vals else 0.0,
                    "p50": _quantile(vals, 0.50) * 1e3,
                    "p95": _quantile(vals, 0.95) * 1e3,
                    "p99": _quantile(vals, 0.99) * 1e3,
                }
            cutoff = now - _QPS_WINDOW_S
            qps_n = sum(1 for t in self._completions if t >= cutoff)
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "latency": lat,
                "qps": qps_n / min(max(now - self._t0, 1e-9), _QPS_WINDOW_S),
                "uptime_s": now - self._t0,
            }

    def publish_to_profiler(self, stat_set=None, prefix: str = "serving/"):
        """Push the latency reservoirs into a profiler StatSet (the global
        one by default) so serving quantile sources show up in
        ``profiler.print_all_status`` alongside training timers."""
        from .. import profiler

        target = stat_set or profiler.global_stat
        with self._lock:
            items = [(n, list(buf)) for n, buf in self._latencies.items()]
        for name, vals in items:
            for v in vals:
                target.add(prefix + name, v)
        return target

    def merge_timer_dict(self, timers: Optional[dict]) -> dict:
        """snapshot() + a profiler StatSet.as_dict() payload in one dict
        (the /metrics endpoint body)."""
        snap = self.snapshot()
        if timers:
            snap["timers"] = timers
        return snap
